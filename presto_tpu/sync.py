"""Named synchronization primitives + opt-in lock instrumentation.

The runtime half of the concurrency sanitizer
(presto_tpu/analysis/concurrency.py is the static half).  Engine
modules create their locks through :func:`named_lock` /
:func:`named_condition` instead of bare ``threading.Lock()`` so every
lock carries a stable name (``module.Class.attr`` — the same naming
scheme the static analyzer derives from the AST).  In normal operation
the factories return the plain stdlib primitives: zero per-acquisition
overhead, one extra function call at construction.

With ``PRESTO_TPU_LOCK_SANITIZER=1`` (resolved once via the
:class:`~presto_tpu.envflag.EnvFlag` contract; ``set_lock_sanitizer``
overrides for tests) the factories return instrumented wrappers that
record, per lock NAME:

- acquisition counts, wait time, and hold time;
- the **observed acquisition-order graph**: an edge ``A -> B`` for
  every acquire of ``B`` while ``A`` is held on the same thread;
- **lock-order inversions**, detected online: acquiring ``B`` while
  holding ``A`` when a ``B -> ... -> A`` path was already observed
  means two threads can deadlock — recorded with both stacks' names.

``WATCHER.report()`` returns the whole picture; ``tools/
lock_sanitizer.py`` cross-checks it against the static lock graph
(confirming or refuting each statically-possible cycle) and the
``sanitizer.*`` gauges surface the totals through the metrics catalog.

The watcher's own bookkeeping uses a bare ``threading.Lock`` — the
instrumentation must never instrument itself.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Set, Tuple

from presto_tpu.envflag import EnvFlag

#: opt-in: instrumented locks are for sanitizer runs/tests, never the
#: serving default (they add two perf_counter reads per acquisition)
_LOCK_SANITIZER = EnvFlag("PRESTO_TPU_LOCK_SANITIZER", default=False)


def lock_sanitizer_enabled() -> bool:
    return _LOCK_SANITIZER()


def set_lock_sanitizer(value: Optional[bool]) -> None:
    """Test/tool override (``None`` re-resolves from the environment).
    Only affects locks constructed AFTER the call — module-level locks
    created at import time need the env var set before the process
    imports presto_tpu (tools/lock_sanitizer.py does exactly that)."""
    _LOCK_SANITIZER.set(value)


class _LockStats:
    __slots__ = ("acquisitions", "wait_s", "hold_s", "max_hold_s",
                 "contentions")

    def __init__(self):
        self.acquisitions = 0
        self.wait_s = 0.0
        self.hold_s = 0.0
        self.max_hold_s = 0.0
        self.contentions = 0


class LockWatcher:
    """Process-global recorder of lock acquisition order and timing.

    Per-thread held stacks live in a ``threading.local``; the shared
    edge graph / stats / inversion list are guarded by a bare
    (uninstrumented) lock.  Everything aggregates by lock NAME, so two
    instances of the same class feed one node — the granularity
    deadlock analysis needs (a cycle between instances of classes A
    and B exists iff it exists between their name nodes)."""

    #: inversion records kept (each is a distinct (a, b) pair anyway)
    MAX_INVERSIONS = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        # (holder_name, acquired_name) -> observation count
        self.edges: Dict[Tuple[str, str], int] = {}
        self.stats: Dict[str, _LockStats] = {}
        self.inversions: List[dict] = []
        self._inverted_pairs: Set[Tuple[str, str]] = set()

    # -- per-thread stack ---------------------------------------------------
    def _stack(self) -> List[list]:
        s = getattr(self._tls, "stack", None)
        if s is None:
            s = self._tls.stack = []
        return s

    # -- recording ----------------------------------------------------------
    def on_acquired(self, name: str, waited: float) -> None:
        stack = self._stack()
        held = [entry[0] for entry in stack]
        stack.append([name, time.perf_counter()])
        with self._lock:
            st = self.stats.get(name)
            if st is None:
                st = self.stats[name] = _LockStats()
            st.acquisitions += 1
            st.wait_s += waited
            if waited > 1e-4:
                st.contentions += 1
            for h in held:
                if h == name:
                    continue  # re-acquire of the same name: not an edge
                key = (h, name)
                fresh = key not in self.edges
                self.edges[key] = self.edges.get(key, 0) + 1
                if fresh and self._path_exists(name, h):
                    self._record_inversion(h, name, held)

    def on_released(self, name: str) -> None:
        stack = self._stack()
        # LIFO is the common case but out-of-order release is legal
        for i in range(len(stack) - 1, -1, -1):
            if stack[i][0] == name:
                _, t0 = stack.pop(i)
                held = time.perf_counter() - t0
                with self._lock:
                    st = self.stats.get(name)
                    if st is not None:
                        st.hold_s += held
                        if held > st.max_hold_s:
                            st.max_hold_s = held
                return

    def _path_exists(self, src: str, dst: str) -> bool:
        """True when dst is reachable from src in the observed edge
        graph (caller holds self._lock).  Graphs here are tens of
        nodes; BFS is plenty."""
        if src == dst:
            return True
        seen = {src}
        frontier = [src]
        while frontier:
            nxt = []
            for n in frontier:
                for (a, b) in self.edges:
                    if a == n and b not in seen:
                        if b == dst:
                            return True
                        seen.add(b)
                        nxt.append(b)
            frontier = nxt
        return False

    def _record_inversion(self, held: str, acquired: str,
                          held_stack: List[str]) -> None:
        pair = (held, acquired) if held <= acquired else (acquired, held)
        if pair in self._inverted_pairs:
            return
        self._inverted_pairs.add(pair)
        if len(self.inversions) < self.MAX_INVERSIONS:
            self.inversions.append({
                "held": held,
                "acquired": acquired,
                "held_stack": list(held_stack),
                "thread": threading.current_thread().name,
            })

    # -- reporting ----------------------------------------------------------
    def report(self) -> dict:
        _wire_gauges()
        with self._lock:
            return {
                "locks": {
                    name: {
                        "acquisitions": st.acquisitions,
                        "contentions": st.contentions,
                        "wait_s": round(st.wait_s, 6),
                        "hold_s": round(st.hold_s, 6),
                        "max_hold_s": round(st.max_hold_s, 6),
                    }
                    for name, st in sorted(self.stats.items())
                },
                "edges": sorted(
                    [a, b, n] for (a, b), n in self.edges.items()),
                "inversions": list(self.inversions),
            }

    def reset(self) -> None:
        with self._lock:
            self.edges.clear()
            self.stats.clear()
            self.inversions.clear()
            self._inverted_pairs.clear()

    # -- totals (sanitizer.* gauges sample these) ----------------------------
    def total(self, field: str) -> float:
        with self._lock:
            if field == "inversions":
                return float(len(self.inversions))
            if field == "locks":
                return float(len(self.stats))
            if field == "edges":
                return float(len(self.edges))
            return float(sum(getattr(st, field) for st in
                             self.stats.values()))


#: the process-wide watcher (inert until an instrumented lock feeds it)
WATCHER = LockWatcher()

_GAUGES_WIRED = False


def _wire_gauges() -> None:
    """Attach the ``sanitizer.*`` gauge callbacks to the watcher.
    Deferred (not at import): obs imports must not run while this
    module loads, or a metrics->sync->obs->metrics cycle deadlocks the
    import machinery.  Idempotent; called on the first instrumented
    construction and from report()."""
    global _GAUGES_WIRED
    if _GAUGES_WIRED:
        return
    try:
        from presto_tpu.obs import METRICS
    except ImportError:
        return
    _GAUGES_WIRED = True
    METRICS.gauge("sanitizer.lock_acquisitions").set_fn(
        lambda: WATCHER.total("acquisitions"))
    METRICS.gauge("sanitizer.lock_wait_seconds").set_fn(
        lambda: WATCHER.total("wait_s"))
    METRICS.gauge("sanitizer.lock_hold_seconds").set_fn(
        lambda: WATCHER.total("hold_s"))
    METRICS.gauge("sanitizer.lock_inversions").set_fn(
        lambda: WATCHER.total("inversions"))
    METRICS.gauge("sanitizer.locks_tracked").set_fn(
        lambda: WATCHER.total("locks"))
    METRICS.gauge("sanitizer.edges_observed").set_fn(
        lambda: WATCHER.total("edges"))


class _SanLock:
    """Instrumented mutex: the ``threading.Lock`` surface the engine
    uses (acquire/release/context manager/locked)."""

    __slots__ = ("name", "_inner")

    def __init__(self, name: str):
        self.name = name
        self._inner = threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        t0 = time.perf_counter()
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            WATCHER.on_acquired(self.name, time.perf_counter() - t0)
        return ok

    def release(self) -> None:
        WATCHER.on_released(self.name)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self) -> bool:
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()


class _SanCondition:
    """Instrumented condition variable.  ``wait()`` releases the
    underlying lock while blocked, and the held-stack must reflect
    that — otherwise every waiter would fabricate edges from a lock it
    does not actually hold."""

    __slots__ = ("name", "_lock", "_inner")

    def __init__(self, name: str, lock: Optional[_SanLock] = None):
        self.name = name
        self._lock = lock if lock is not None else _SanLock(name)
        self._inner = threading.Condition(self._lock._inner)

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        return self._lock.acquire(blocking, timeout)

    def release(self) -> None:
        self._lock.release()

    def __enter__(self) -> bool:
        return self._lock.__enter__()

    def __exit__(self, *exc) -> None:
        self._lock.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None) -> bool:
        WATCHER.on_released(self._lock.name)
        t0 = time.perf_counter()
        try:
            return self._inner.wait(timeout)
        finally:
            WATCHER.on_acquired(self._lock.name, time.perf_counter() - t0)

    def wait_for(self, predicate, timeout: Optional[float] = None):
        result = predicate()
        if timeout is None:
            while not result:
                self.wait()
                result = predicate()
            return result
        deadline = time.monotonic() + timeout
        while not result:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            self.wait(remaining)
            result = predicate()
        return result

    def notify(self, n: int = 1) -> None:
        self._inner.notify(n)

    def notify_all(self) -> None:
        self._inner.notify_all()


def named_lock(name: str):
    """A mutex named for the sanitizer.  Plain ``threading.Lock`` when
    the sanitizer is off (the default); the name must follow the
    static analyzer's scheme — ``<module>.<Class>.<attr>`` for
    instance locks, ``<module>.<NAME>`` at module scope — so runtime
    edges line up with static ones in the cross-check."""
    if not _LOCK_SANITIZER():
        return threading.Lock()
    _wire_gauges()
    return _SanLock(name)


def named_condition(name: str, lock=None):
    """A condition variable named for the sanitizer.  ``lock`` may be
    a :func:`named_lock` result (instrumented or plain) so a
    Lock+Condition pair shares one underlying mutex either way."""
    if not _LOCK_SANITIZER():
        if isinstance(lock, _SanLock):  # mixed construction windows
            return threading.Condition(lock._inner)
        return threading.Condition(lock)
    if isinstance(lock, _SanLock) or lock is None:
        _wire_gauges()
        return _SanCondition(name, lock)
    # a plain lock created before the override flipped on: wrap it
    # un-instrumented rather than splitting the mutex in two
    return threading.Condition(lock)
