"""Generic function-signature resolution.

Reference analog: ``metadata/FunctionRegistry.java:349`` +
``SignatureBinder`` — functions declare signatures over type variables
and parameterized containers (``array(T)``, ``map(K,V)``), and a call
site resolves by unifying argument types against them, falling back to
implicit coercions (common_super_type) when no exact match binds.

The engine's scalar dispatch is largely name-switched in
``expr/ir.infer_type`` (the JIT specializes per plan, so there is no
runtime dispatch to optimize); THIS module is the declarative layer
over it: signatures unify, produce a type-variable binding, and yield
the return type.  ``infer_type`` consults it for registered names, and
new functions can be added as data instead of switch arms.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DOUBLE,
    VARCHAR,
    Type,
    common_super_type,
)


@dataclasses.dataclass(frozen=True)
class TypePattern:
    """One parameter slot: a concrete type name ('bigint'), a type
    variable ('T', 'K', 'V'), or a container over patterns
    ('array(T)', 'map(K,V)')."""

    kind: str  # 'concrete' | 'var' | 'array' | 'map'
    name: str = ""
    element: Optional["TypePattern"] = None
    key: Optional["TypePattern"] = None


def _parse_pattern(s: str) -> TypePattern:
    s = s.strip()
    if s.startswith("array(") and s.endswith(")"):
        return TypePattern("array", element=_parse_pattern(s[6:-1]))
    if s.startswith("map(") and s.endswith(")"):
        inner = s[4:-1]
        depth = 0
        for i, ch in enumerate(inner):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
            elif ch == "," and depth == 0:
                return TypePattern(
                    "map", key=_parse_pattern(inner[:i]),
                    element=_parse_pattern(inner[i + 1:]))
        raise ValueError(f"bad map pattern {s}")
    if len(s) == 1 and s.isupper():
        return TypePattern("var", name=s)
    return TypePattern("concrete", name=s)


@dataclasses.dataclass(frozen=True)
class Signature:
    """fn(arg_patterns...) -> return_pattern; ``variadic`` repeats the
    last parameter (concat(T, T, ...))."""

    name: str
    args: Tuple[TypePattern, ...]
    returns: TypePattern
    variadic: bool = False

    @staticmethod
    def of(name: str, arg_specs: Sequence[str], return_spec: str,
           variadic: bool = False) -> "Signature":
        return Signature(
            name, tuple(_parse_pattern(a) for a in arg_specs),
            _parse_pattern(return_spec), variadic)


def _unify(pattern: TypePattern, t: Type, binding: Dict[str, Type],
           coerce: bool) -> bool:
    if pattern.kind == "var":
        bound = binding.get(pattern.name)
        if bound is None:
            binding[pattern.name] = t
            return True
        if bound == t:
            return True
        if coerce:
            try:
                binding[pattern.name] = common_super_type(bound, t)
                return True
            except TypeError:
                return False
        return False
    if pattern.kind == "concrete":
        if t.name == pattern.name:
            return True
        if coerce:
            try:
                target = _concrete_type(pattern.name)
            except ValueError:
                return False
            try:
                return common_super_type(t, target) == target
            except TypeError:
                return False
        return False
    if pattern.kind == "array":
        return t.is_array and _unify(pattern.element, t.element, binding, coerce)
    if pattern.kind == "map":
        return (t.is_map and _unify(pattern.key, t.key_element, binding, coerce)
                and _unify(pattern.element, t.element, binding, coerce))
    return False


def _concrete_type(name: str) -> Type:
    from presto_tpu.types import parse_type

    return parse_type(name)


def _instantiate(pattern: TypePattern, binding: Dict[str, Type],
                 args: Sequence[Type]) -> Type:
    if pattern.kind == "var":
        return binding[pattern.name]
    if pattern.kind == "concrete":
        return _concrete_type(pattern.name)
    if pattern.kind == "array":
        from presto_tpu.types import ArrayType

        elem = _instantiate(pattern.element, binding, args)
        # preserve the argument's slot capacity when a container arg
        # flows through (static shapes: capacity is part of the type)
        cap = next((a.max_elems for a in args if a.is_array or a.is_map), 8)
        return ArrayType(elem, cap)
    if pattern.kind == "map":
        from presto_tpu.types import MapType

        cap = next((a.max_elems for a in args if a.is_map), 8)
        return MapType(_instantiate(pattern.key, binding, args),
                       _instantiate(pattern.element, binding, args), cap)
    raise ValueError(pattern)


class SignatureRegistry:
    def __init__(self):
        self._by_name: Dict[str, List[Signature]] = {}

    def register(self, sig: Signature) -> None:
        self._by_name.setdefault(sig.name, []).append(sig)

    def names(self):
        return self._by_name.keys()

    def resolve(self, name: str, arg_types: Sequence[Type]) -> Optional[Type]:
        """Return type for the call, or None if the name is unknown.
        Raises TypeError when the name is known but no signature binds
        (exact pass first, then a coercion pass — the reference's
        two-phase resolution)."""
        sigs = self._by_name.get(name)
        if sigs is None:
            return None
        for coerce in (False, True):
            for sig in sigs:
                n = len(sig.args)
                if sig.variadic:
                    if len(arg_types) < n:
                        continue
                    padded = list(sig.args) + [sig.args[-1]] * (
                        len(arg_types) - n)
                else:
                    if len(arg_types) != n:
                        continue
                    padded = list(sig.args)
                binding: Dict[str, Type] = {}
                if all(_unify(p, t, binding, coerce)
                       for p, t in zip(padded, arg_types)):
                    return _instantiate(sig.returns, binding, arg_types)
        raise TypeError(
            f"no signature of {name} matches ({', '.join(map(repr, arg_types))})")


REGISTRY = SignatureRegistry()

# Generic container functions — the signatures the reference declares
# with @TypeParameter in operator/scalar/ (e.g. ArrayMaxFunction
# "array(T) -> T").  expr/ir.infer_type consults the registry FIRST
# for these names; the old switch arms are gone, so this is the single
# source of truth for their typing.
for _sig in [
    Signature.of("greatest", ["T", "T"], "T", variadic=True),
    Signature.of("least", ["T", "T"], "T", variadic=True),
    Signature.of("subscript", ["array(T)", "bigint"], "T"),
    Signature.of("subscript", ["map(K,V)", "K"], "V"),
    Signature.of("element_at", ["array(T)", "bigint"], "T"),
    Signature.of("element_at", ["map(K,V)", "K"], "V"),
    Signature.of("cardinality", ["array(T)"], "bigint"),
    Signature.of("cardinality", ["map(K,V)"], "bigint"),
    Signature.of("contains", ["array(T)", "T"], "boolean"),
    Signature.of("array_position", ["array(T)", "T"], "bigint"),
    Signature.of("array_min", ["array(T)"], "T"),
    Signature.of("array_max", ["array(T)"], "T"),
    Signature.of("array_sort", ["array(T)"], "array(T)"),
    Signature.of("array_distinct", ["array(T)"], "array(T)"),
    Signature.of("map_keys", ["map(K,V)"], "array(K)"),
    Signature.of("map_values", ["map(K,V)"], "array(V)"),
    # KMV set digests (type/setdigest/SetDigestFunctions.java)
    Signature.of("jaccard_index", ["setdigest", "setdigest"], "double"),
    Signature.of("intersection_cardinality", ["setdigest", "setdigest"],
                 "bigint"),
    Signature.of("hash_counts", ["setdigest"], "map(bigint,bigint)"),
]:
    REGISTRY.register(_sig)
