"""Query event pipeline.

Reference analog: ``event/query/QueryMonitor.java:114`` emitting
QueryCreated/QueryCompleted/SplitCompleted events to the
``EventListener`` SPI (``spi/eventlistener/EventListener.java``) via
``EventListenerManager`` — the hook warehouses use for query logging.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import List, Optional


@dataclasses.dataclass
class QueryCreatedEvent:
    query_id: str
    sql: str
    user: str
    create_time: float
    # request-correlation token propagated end to end (reference:
    # X-Presto-Trace-Token, server/GenerateTraceTokenRequestFilter.java:29)
    trace_token: Optional[str] = None


@dataclasses.dataclass
class QueryCompletedEvent:
    query_id: str
    sql: str
    user: str
    state: str  # FINISHED | FAILED
    create_time: float
    end_time: float
    rows: int = 0
    error: Optional[str] = None
    trace_token: Optional[str] = None
    # distributed-tier outcome (VERDICT r3: fallbacks must be loud):
    # mesh stages executed, and the reason when execution fell back to
    # the coordinator despite SET SESSION distributed = true
    dist_stages: Optional[int] = None
    dist_fallback: Optional[str] = None
    # lifecycle stage times from the obs span spine (QueryStats.java's
    # analysisTime/planningTime/executionTime): planning covers
    # bind+optimize+validate, compile is the XLA compile seconds the
    # tracer attributed (None when the query did not trace), execution
    # is the run itself.  All NULL-safe — consumers must handle None.
    planning_ms: Optional[float] = None
    compile_ms: Optional[float] = None
    execution_ms: Optional[float] = None
    # serving tier (serving/cache.py): True when the result was served
    # from the structural result cache without executing; None for
    # statements the cache does not apply to (writes, DDL)
    cache_hit: Optional[bool] = None
    # admission-plane waits (serving/admission.py annotates the query
    # timeline; the runner copies them here): time queued for a
    # concurrency slot, and time blocked on memory headroom AFTER
    # admission.  NULL-safe — None when the query bypassed admission.
    queued_ms: Optional[float] = None
    memory_blocked_ms: Optional[float] = None
    # ranked doctor findings (obs/doctor.py as_dict rows) — the query
    # log's bottleneck attribution; None when diagnosis did not run
    findings: Optional[list] = None
    # estimate-vs-actual plane (obs/history.py worst_estimate): the
    # query's worst per-operator misestimate factor (>= 1.0); None when
    # stats collection was off or nothing was comparable
    worst_estimate_ratio: Optional[float] = None


@dataclasses.dataclass
class QueryQueuedEvent:
    """The admission controller queued a query (serving/admission.py) —
    the group it landed in and its live queue position, so the query
    log shows WHERE each query waited, not just that it was slow."""

    query_id: str
    user: str
    group: Optional[str]
    position: Optional[int]
    queue_time: float  # epoch seconds (event timestamp)


@dataclasses.dataclass
class QueryAdmittedEvent:
    """The admission controller dispatched a queued query: how long it
    waited and the memory projection it was admitted under — together
    with QueryQueuedEvent this reconstructs every admission decision
    from the log alone."""

    query_id: str
    group: Optional[str]
    queued_ms: float
    projected_bytes: int
    admit_time: float  # epoch seconds (event timestamp)


@dataclasses.dataclass
class MemoryKillEvent:
    """The cluster low-memory killer chose a victim (the reference logs
    this from ClusterMemoryManager's kill path).  Emitted in ADDITION
    to the victim's eventual QueryCompletedEvent — the kill decision
    (pool pressure at decision time, bytes freed) is information the
    completion event cannot carry."""

    query_id: str
    freed_bytes: int
    reserved_bytes: int  # pool reservation at the decision
    limit_bytes: int
    kill_time: float  # epoch seconds (event timestamp, not a duration)


@dataclasses.dataclass
class QueryKilledEvent:
    """The coordinator killed a query for a policy reason (deadline,
    admission, operator action) — emitted in ADDITION to the victim's
    completion/failure line, carrying the DECISION: the reason code
    and the limit that was exceeded (the reference's
    QueryMonitor.queryImmediateFailureEvent + killed-query log)."""

    query_id: str
    reason: str  # e.g. EXCEEDED_TIME_LIMIT
    message: str
    limit_s: Optional[float]  # the configured limit, when one applies
    elapsed_s: Optional[float]
    kill_time: float  # epoch seconds (event timestamp, not a duration)


@dataclasses.dataclass
class WorkerStateChangeEvent:
    """The failure detector moved a worker between states
    (alive/suspect/dead/recovered) — the cluster-membership half of
    the query log (HeartbeatFailureDetector's state-change logging,
    made a first-class event)."""

    uri: str
    old_state: str
    new_state: str
    reason: Optional[str]
    change_time: float  # epoch seconds


def new_trace_token() -> str:
    return "trace_" + uuid.uuid4().hex[:16]


class EventListener:
    """Subclass and override (EventListener SPI analog)."""

    def query_created(self, event: QueryCreatedEvent) -> None:  # pragma: no cover
        pass

    def query_completed(self, event: QueryCompletedEvent) -> None:  # pragma: no cover
        pass

    def memory_killed(self, event: MemoryKillEvent) -> None:  # pragma: no cover
        pass

    def query_killed(self, event: QueryKilledEvent) -> None:  # pragma: no cover
        pass

    def worker_state_changed(
            self, event: WorkerStateChangeEvent) -> None:  # pragma: no cover
        pass

    def query_queued(self, event: QueryQueuedEvent) -> None:  # pragma: no cover
        pass

    def query_admitted(
            self, event: QueryAdmittedEvent) -> None:  # pragma: no cover
        pass


class EventListenerManager:
    def __init__(self):
        self._listeners: List[EventListener] = []

    def add(self, listener: EventListener) -> None:
        self._listeners.append(listener)

    def query_created(self, event: QueryCreatedEvent) -> None:
        for l in self._listeners:
            l.query_created(event)

    def query_completed(self, event: QueryCompletedEvent) -> None:
        for l in self._listeners:
            l.query_completed(event)

    def memory_killed(self, event: MemoryKillEvent) -> None:
        for l in self._listeners:
            l.memory_killed(event)

    def query_killed(self, event: QueryKilledEvent) -> None:
        for l in self._listeners:
            l.query_killed(event)

    def worker_state_changed(self, event: WorkerStateChangeEvent) -> None:
        for l in self._listeners:
            l.worker_state_changed(event)

    def query_queued(self, event: QueryQueuedEvent) -> None:
        for l in self._listeners:
            l.query_queued(event)

    def query_admitted(self, event: QueryAdmittedEvent) -> None:
        for l in self._listeners:
            l.query_admitted(event)


def new_query_id() -> str:
    """Presto-style query id: yyyymmdd_hhmmss_ncccc_xxxxx."""
    return time.strftime("%Y%m%d_%H%M%S") + "_" + uuid.uuid4().hex[:5]
