"""Resolve-once boolean environment flags with override hooks.

The engine_lint ``env-read`` contract: an ``os.environ`` read belongs
at import/construction time or behind a resolve-once helper — never in
a per-page/per-query path (a dict lookup per page, and program choice
that flips mid-process with the environment).  Every A/B escape hatch
(``PRESTO_TPU_PAD_SCAN``, ``PRESTO_TPU_AGG_TOWER``, ...) shares this
one implementation instead of hand-rolling the getter/setter pair.

Usage::

    _PAD_SCAN = EnvFlag("PRESTO_TPU_PAD_SCAN", default=True)
    if _PAD_SCAN(): ...
    _PAD_SCAN.set(False)   # test override; .set(None) re-resolves
"""

from __future__ import annotations

from typing import Optional


def _resolve_env_flag(name: str, default: bool) -> bool:
    import os

    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false")


def _resolve_env_int(name: str, default: int) -> int:
    import os

    raw = os.environ.get(name)
    try:
        return default if raw is None or not raw.strip() else int(raw)
    except ValueError:
        return default


class EnvFlag:
    """A boolean env var resolved ONCE per process, with an override
    hook for tests/tools (``set(True/False)``; ``set(None)``
    re-resolves from the environment on next read)."""

    __slots__ = ("name", "default", "_value")

    def __init__(self, name: str, default: bool = True):
        self.name = name
        self.default = default
        self._value: Optional[bool] = None

    def __call__(self) -> bool:
        if self._value is None:
            self._value = _resolve_env_flag(self.name, self.default)
        return self._value

    def set(self, value: Optional[bool]) -> None:
        self._value = value


class EnvInt:
    """An integer env var resolved ONCE per process, same contract as
    :class:`EnvFlag` (``set(n)`` overrides; ``set(None)`` re-resolves).
    Values clamp to ``floor`` so a malformed/negative setting can never
    produce an unbounded or zero-width pool."""

    __slots__ = ("name", "default", "floor", "_value")

    def __init__(self, name: str, default: int, floor: int = 0):
        self.name = name
        self.default = int(default)
        self.floor = int(floor)
        self._value: Optional[int] = None

    def __call__(self) -> int:
        if self._value is None:
            self._value = max(self.floor,
                              _resolve_env_int(self.name, self.default))
        return self._value

    def set(self, value: Optional[int]) -> None:
        self._value = None if value is None else max(self.floor, int(value))
