"""Worker task server.

Reference analog: the worker side of the task protocol —
``server/TaskResource.java:120`` (POST /v1/task/{taskId} with the
serialized fragment + splits, results served from output buffers) and
``execution/SqlTaskManager.java:339``.  Collapsed for the
request/response model: a task executes its fragment synchronously and
returns the serialized result pages in the response body (the pull
buffer protocol is unnecessary when the coordinator is the only
consumer and fragments end in bounded partial states).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from presto_tpu import __version__
from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import LocalRunner
from presto_tpu.server.serde import plan_from_json, serialize_page


class WorkerServer:
    """Executes plan fragments against the worker's own catalog.

    POST /v1/task   body: {"fragment": <plan json>}
                    response: concatenated serialized pages
                    (4-byte count prefix, then length-prefixed pages)
    GET  /v1/info   liveness + version (heartbeat endpoint)
    """

    def __init__(self, catalog: Catalog, host: str = "127.0.0.1", port: int = 0):
        self.catalog = catalog
        self.runner = LocalRunner(catalog)
        self.tasks_executed = 0
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/v1/info":
                    body = json.dumps(
                        {"nodeVersion": {"version": __version__},
                         "coordinator": False, "state": "ACTIVE",
                         "tasks": outer.tasks_executed}
                    ).encode()
                    self.send_response(200)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_response(404)
                    self.end_headers()

            def do_POST(self):
                if self.path != "/v1/task":
                    self.send_response(404)
                    self.end_headers()
                    return
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n).decode())
                try:
                    fragment = plan_from_json(req["fragment"], outer.catalog)
                    pages = [serialize_page(p) for p in outer.runner._pages(fragment)]
                    outer.tasks_executed += 1
                    body = len(pages).to_bytes(4, "little") + b"".join(
                        len(p).to_bytes(8, "little") + p for p in pages
                    )
                    self.send_response(200)
                    self.send_header("Content-Type", "application/octet-stream")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except Exception as e:
                    body = json.dumps({"error": f"{type(e).__name__}: {e}"}).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def parse_task_response(raw: bytes):
    npages = int.from_bytes(raw[:4], "little")
    off = 4
    out = []
    for _ in range(npages):
        ln = int.from_bytes(raw[off : off + 8], "little")
        off += 8
        out.append(raw[off : off + ln])
        off += ln
    return out
