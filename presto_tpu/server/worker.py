"""Worker task server.

Reference analog: the worker side of the task protocol —
``server/TaskResource.java`` (POST /v1/task/{taskId} creating the task
at :124, GET .../results/{bufferId}/{token} long-poll at :239, token
acknowledge at :298, DELETE abort) executed by
``execution/SqlTaskManager.java:339``.  A task runs its fragment in a
background thread, streaming serialized pages into a bounded
:class:`TaskOutputBuffer`; consumers long-poll with token
acknowledgement (at-least-once + client dedupe) and the producer blocks
on unacknowledged bytes — pull-side backpressure end to end.

The legacy one-shot ``POST /v1/task`` (fragment in, all pages out) is
kept for small control-plane uses.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from presto_tpu import __version__
from presto_tpu.catalog import Catalog
from presto_tpu.exec.local import LocalRunner
from presto_tpu.server.buffers import BufferAborted, TaskOutputBuffer
from presto_tpu.server.serde import plan_from_json, serialize_page
from presto_tpu.sync import named_lock

# /v1/task/{id}/results/{token} (single-stream, buffer 0) or
# /v1/task/{id}/results/{buffer}/{token} (partitioned output — the
# reference's bufferId path, server/TaskResource.java:239)
_RESULTS_RE = re.compile(
    r"^/v1/task/([\w-]+)/results/(\d+)(?:/(\d+))?(/acknowledge)?$")
_TASK_RE = re.compile(r"^/v1/task/([\w-]+)$")

# task states (execution/TaskState.java:21 — PLANNED/RUNNING/FINISHED/
# CANCELED/ABORTED/FAILED collapsed to the ones a pull consumer observes)
RUNNING, FINISHED, FAILED, ABORTED = "RUNNING", "FINISHED", "FAILED", "ABORTED"


class _Task:
    def __init__(self, task_id: str, buffer_bytes: int, n_buffers: int = 1):
        import time

        self.task_id = task_id
        # one buffer per output partition (PartitionedOutputBuffer's
        # ClientBuffer-per-partition layout; n_buffers=1 = TaskOutput)
        self.buffers = [
            TaskOutputBuffer(max_bytes=max(buffer_bytes // n_buffers, 1 << 20))
            for _ in range(n_buffers)
        ]
        self.state = RUNNING
        self.error: Optional[str] = None
        self.last_access = time.monotonic()
        # per-operator actuals of the fragment (QueryStats.to_wire),
        # set once at FINISHED — the task-completion half of the
        # estimate-vs-actual roll-up (None when the coordinator did
        # not ask: recording costs one device sync per page)
        self.stats_wire: Optional[list] = None

    @property
    def buffer(self) -> TaskOutputBuffer:
        return self.buffers[0]

    def touch(self) -> None:
        import time

        self.last_access = time.monotonic()


class WorkerServer:
    """Executes plan fragments against the worker's own catalog.

    POST   /v1/task/{id}  body: {"fragment": ...} -> task status JSON;
                          pages stream into the task's output buffer
    GET    /v1/task/{id}/results/{token}[?maxsize=N] -> page batch
                          (binary, X-Next-Token / X-Complete headers)
    GET    /v1/task/{id}/results/{token}/acknowledge -> frees < token
    DELETE /v1/task/{id}  abort + drop buffers
    POST   /v1/task       legacy one-shot (all pages in the response)
    GET    /v1/info       liveness + version (heartbeat endpoint)
    """

    def __init__(self, catalog: Catalog, host: str = "127.0.0.1", port: int = 0,
                 buffer_bytes: int = 64 << 20, task_ttl: float = 300.0,
                 memory_pool=None, task_threads: int = 4,
                 task_concurrency: Optional[int] = None, faults=None):
        from presto_tpu.executor import TaskExecutor

        self.catalog = catalog
        # all runners in this worker process (and any co-resident
        # coordinator executor) share ONE program registry — the
        # process-wide default: a fragment shape compiled for task A
        # is a cache hit for task B.  Worker fragments run their scan
        # splits through the morsel split scheduler (exec/tasks.py);
        # None = process default (query.task-concurrency / env)
        self.runner = LocalRunner(catalog, memory_pool=memory_pool,
                                  task_concurrency=task_concurrency)
        # cooperative scheduler: page-granularity quanta over a
        # multilevel feedback queue (execution/executor/TaskExecutor.java)
        self.executor = TaskExecutor(num_threads=task_threads)
        self.tasks_executed = 0
        self.buffer_bytes = buffer_bytes
        # abandoned-task expiry: a consumer that dies mid-pull must not
        # leak its buffer + blocked producer forever (the reference
        # expires tasks via TaskManagerConfig.infoMaxAge/clientTimeout)
        self.task_ttl = task_ttl
        self._tasks: Dict[str, _Task] = {}
        self._tasks_lock = named_lock("worker.WorkerServer._tasks_lock")
        self.draining = False
        # deterministic fault injection (testing_faults.py): the
        # process-global registry is inert unless a test/CI leg armed
        # it, so the per-request gate below is one attribute read in
        # production.  _fault_dead = the simulated mid-query crash of
        # worker.die_after_n_pages: once set, every request is dropped.
        from presto_tpu.testing_faults import FAULTS

        self.faults = faults if faults is not None else FAULTS
        self._fault_dead = False
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _faulted(self) -> bool:
                """Fault-injection gate for every request: a dead
                worker (worker.die_after_n_pages fired) or a firing
                worker.refuse_connect drops the connection without a
                response; worker.slow_response_ms delays it.
                Heartbeat probes (GET /v1/info) are EXEMPT from the
                request-gated points: background detector probes fire
                on wall-clock timers and would otherwise race query
                traffic for after=N/count=K schedule slots, breaking
                the harness's byte-for-byte determinism.  A DEAD
                worker still drops everything — the detector must see
                the death."""
                f = outer.faults
                if not f.enabled and not outer._fault_dead:
                    return False
                if outer._fault_dead:
                    self.close_connection = True
                    return True
                if self.path.split("?")[0] == "/v1/info":
                    return False
                if f.should_fire(
                        "worker.refuse_connect", outer.node_id) is not None:
                    # no response at all: the connection closes when the
                    # handler returns, so the client sees the peer drop
                    # mid-request (RemoteDisconnected — transient)
                    self.close_connection = True
                    return True
                spec = f.should_fire("worker.slow_response_ms",
                                     outer.node_id)
                if spec is not None and spec.ms > 0:
                    import time

                    time.sleep(spec.ms / 1000.0)
                return False

            def _send(self, code: int, body: bytes, ctype="application/json",
                      headers=()):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in headers:
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self._faulted():
                    return
                if self.path == "/v1/info":
                    info = {"nodeVersion": {"version": __version__},
                            "coordinator": False,
                            "state": "SHUTTING_DOWN" if outer.draining else "ACTIVE",
                            "tasks": outer.tasks_executed}
                    pool = outer.runner.memory_pool
                    if pool is not None:
                        # per-query tagged breakdown rides along so the
                        # coordinator's killer decisions are reproducible
                        # from scraped data alone (not just pool totals)
                        from presto_tpu.cluster_memory import query_reservations

                        info["memory"] = {
                            "reserved": pool.reserved,
                            "peak": pool.peak,
                            "limit": pool.limit,
                            "query_reservations": query_reservations(pool),
                        }
                    self._send(200, json.dumps(info).encode())
                    return
                if self.path.split("?")[0] == "/v1/metrics":
                    from presto_tpu.obs import openmetrics

                    if "format=json" in self.path:
                        self._send(200, json.dumps(openmetrics.json_form(
                            outer.node_id)).encode())
                    else:
                        self._send(200, openmetrics.render().encode(),
                                   ctype=openmetrics.CONTENT_TYPE)
                    return
                if self.path.split("?")[0] == "/v1/metrics/history":
                    from presto_tpu.obs.timeseries import HISTORY

                    self._send(200, json.dumps({
                        "node": outer.node_id,
                        "intervalMs": HISTORY.interval_ms,
                        "ticks": HISTORY.tick_count(),
                        "rows": [[ts, n, v]
                                 for ts, n, v in HISTORY.rows()],
                    }).encode())
                    return
                m = _RESULTS_RE.match(self.path.split("?")[0])
                if m:
                    outer._expire_tasks()
                    task = outer._tasks.get(m.group(1))
                    if task is None:
                        self._send(404, b"{}")
                        return
                    task.touch()
                    if m.group(3) is not None:  # /results/{buffer}/{token}
                        buffer_id, token = int(m.group(2)), int(m.group(3))
                    else:  # legacy /results/{token} = buffer 0
                        buffer_id, token = 0, int(m.group(2))
                    if buffer_id >= len(task.buffers):
                        self._send(404, json.dumps(
                            {"error": f"no buffer {buffer_id}"}).encode())
                        return
                    buf = task.buffers[buffer_id]
                    if m.group(4):  # acknowledge
                        # scoped by URI like the client-side net.*
                        # point, so one node key addresses both ends
                        if outer.faults.enabled and outer.faults.should_fire(
                                "net.drop_ack", outer.uri) is not None:
                            # the ack is "lost en route": respond OK
                            # without applying it — unacked pages
                            # re-serve at the same token and a later,
                            # higher ack supersedes (the client's
                            # seq dedupe keeps delivery exactly-once)
                            self._send(200, b"{}")
                            return
                        buf.acknowledge(token)
                        self._send(200, b"{}")
                        return
                    maxsize = 8 << 20
                    if "maxsize=" in self.path:
                        maxsize = int(self.path.split("maxsize=")[1].split("&")[0])
                    try:
                        pages, nxt, done, err = buf.get(token, maxsize)
                    except BufferAborted:
                        # aborted concurrently with this GET: same
                        # answer an expired/deleted task gives
                        self._send(404, b"{}")
                        return
                    if err is not None:
                        self._send(500, json.dumps({"error": err}).encode())
                        return
                    from presto_tpu.server.serde import encode_page_batch

                    self._send(200, encode_page_batch(pages),
                               "application/octet-stream",
                               headers=[("X-Next-Token", str(nxt)),
                                        ("X-Complete", "1" if done else "0")])
                    return
                m = _TASK_RE.match(self.path)
                if m:
                    task = outer._tasks.get(m.group(1))
                    if task is None:
                        self._send(404, b"{}")
                        return
                    self._send(200, json.dumps(
                        {"taskId": task.task_id, "state": task.state,
                         "error": task.error,
                         "stats": task.stats_wire}).encode())
                    return
                self._send(404, b"{}")

            def do_PUT(self):
                if self._faulted():
                    return
                # PUT /v1/info/state "SHUTTING_DOWN" triggers a drain in
                # the background (server/GracefulShutdownHandler.java:43)
                if self.path == "/v1/info/state":
                    n = int(self.headers.get("Content-Length", "0"))
                    want = self.rfile.read(n).decode().strip().strip('"')
                    if want == "SHUTTING_DOWN":
                        outer.draining = True
                        threading.Thread(target=outer.drain, daemon=True,
                                         name="worker-drain").start()
                        self._send(200, b"{}")
                    else:
                        self._send(400, json.dumps(
                            {"error": f"invalid state {want!r}"}).encode())
                    return
                self._send(404, b"{}")

            def do_POST(self):
                if self._faulted():
                    return
                n = int(self.headers.get("Content-Length", "0"))
                req = json.loads(self.rfile.read(n).decode())
                m = _TASK_RE.match(self.path)
                if m:
                    if outer.draining:
                        # a draining worker accepts no new tasks
                        self._send(503, json.dumps(
                            {"error": "worker is shutting down"}).encode())
                        return
                    tid = m.group(1)
                    task = outer._create_task(
                        tid, req["fragment"], req.get("output"),
                        trace_token=self.headers.get("X-Presto-Trace-Token"),
                        collect_stats=bool(req.get("collect_stats")))
                    self._send(200, json.dumps(
                        {"taskId": tid, "state": task.state}).encode())
                    return
                if self.path == "/v1/task":  # legacy one-shot
                    if outer.draining:
                        self._send(503, json.dumps(
                            {"error": "worker is shutting down"}).encode())
                        return
                    try:
                        fragment = plan_from_json(req["fragment"], outer.catalog)
                        pages = [serialize_page(p)
                                 for p in outer.runner._pages(fragment)]
                        outer.tasks_executed += 1
                        from presto_tpu.server.serde import encode_page_batch

                        self._send(200, encode_page_batch(pages),
                                   "application/octet-stream")
                    except Exception as e:
                        self._send(500, json.dumps(
                            {"error": f"{type(e).__name__}: {e}"}).encode())
                    return
                self._send(404, b"{}")

            def do_DELETE(self):
                if self._faulted():
                    return
                m = _TASK_RE.match(self.path)
                if m:
                    outer._abort_task(m.group(1))
                    self._send(200, b"{}")
                    return
                self._send(404, b"{}")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        # node identity for the metrics plane (the coordinator labels
        # this worker's system_metrics rows with it).  Hostname-
        # qualified: two containers both on :8080 must not collapse
        # into one rollup key
        import socket

        self.node_id = f"worker-{socket.gethostname()}-{self.port}"
        if memory_pool is not None:
            from presto_tpu.memory import wire_pool_gauges

            wire_pool_gauges(memory_pool)
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="worker-http")

    # ------------------------------------------------------------------
    def _create_task(self, task_id: str, fragment_json: dict,
                     output_spec: Optional[dict] = None,
                     trace_token: Optional[str] = None,
                     collect_stats: bool = False) -> _Task:
        """``output_spec``: ``{"partitions": K, "key_indices": [...],
        "domains": [[lo,hi]|null...]}`` routes each produced page's rows
        into K per-partition buffers by key hash (the
        PartitionedOutputOperator + PartitionedOutputBuffer write path);
        absent = single-stream output (TaskOutputOperator).
        ``trace_token`` (X-Presto-Trace-Token) attaches this task's
        spans to the originating query's tracer — the same object when
        coordinator and worker share a process, a per-node tracer
        retrievable by token otherwise."""
        from presto_tpu import obs

        n_buffers = int(output_spec["partitions"]) if output_spec else 1
        with self._tasks_lock:
            existing = self._tasks.get(task_id)
            if existing is not None:  # idempotent create (client retry)
                return existing
            task = _Task(task_id, self.buffer_bytes, n_buffers)
            self._tasks[task_id] = task
        obs.TASKS.start(task_id, "worker", trace_token=trace_token)
        tracer = (obs.tracer_for(trace_token, create=True)
                  if trace_token else None)

        mem_ctx = None
        if self.runner.memory_pool is not None:
            from presto_tpu.memory import QueryMemoryContext

            mem_ctx = QueryMemoryContext(self.runner.memory_pool, task_id)

        def steps():
            """One yield per produced page: the cooperative quantum
            boundary (PrioritizedSplitRunner.process analog).  Runner
            threads can change between quanta, so the thread-local
            memory context re-binds around every step."""
            try:
                fragment = plan_from_json(fragment_json, self.catalog)
                # per-task stats sink, rebound around every quantum
                # like the memory context (runner threads can change
                # between steps).  Keys are the stable structural ids,
                # so the wire snapshot merges onto the coordinator's
                # entries even though this plan was rebuilt from JSON.
                tstats = None
                if collect_stats:
                    from presto_tpu.exec.local import QueryStats

                    tstats = QueryStats()
                    tstats.register_plan(fragment)
                partition_fn = None
                check_partial_mg = None
                if output_spec is not None:
                    from presto_tpu.exec.spill import make_bucket_fn
                    from presto_tpu.expr.ir import ColumnRef

                    chans = fragment.channels
                    keys = [ColumnRef(type=chans[i].type, index=i)
                            for i in output_spec.get("key_indices", [])]
                    domains = [tuple(d) if d else None
                               for d in output_spec.get("domains", [])] or None
                    partition_fn = make_bucket_fn(keys, domains, n_buffers,
                                                  jit=self.runner.jit)
                    # a truncated partial-agg page scatters its mg live
                    # states across partitions, hiding the overflow from
                    # every downstream capacity check — so the PRODUCER
                    # detects it (LocalRunner._check_overflow's role at
                    # the exchange boundary) and fails for a retry
                    from presto_tpu.planner.plan import AggregationNode

                    if (isinstance(fragment, AggregationNode)
                            and fragment.step == "partial"
                            and fragment.group_exprs):
                        check_partial_mg = fragment.max_groups
                        # exact-capacity aggs legitimately fill every
                        # slot (domain product <= capacity): live == mg
                        # is completeness there, not truncation
                        kd = fragment.key_domains
                        if kd and all(d is not None for d in kd):
                            prod = 1
                            for lo, hi in kd:
                                prod *= hi - lo + 2
                            if prod <= fragment.max_groups:
                                check_partial_mg = None
                gen = self.runner._pages(fragment)
                while True:
                    if self.faults.enabled and self.faults.should_fire(
                            "worker.die_after_n_pages",
                            self.node_id) is not None:
                        # simulated mid-query crash: stop producing and
                        # drop every subsequent request — the consumer
                        # sees a dead socket, never a task error
                        self._fault_dead = True
                        raise BufferAborted()
                    if mem_ctx is not None:
                        self.runner._mem = mem_ctx
                    if tstats is not None:
                        self.runner.stats = tstats
                    try:
                        # tracer re-binds around every quantum exactly
                        # like the memory context: runner threads can
                        # change between steps
                        with obs.tracing(tracer):
                            p = next(gen)
                    except StopIteration:
                        break
                    finally:
                        if mem_ctx is not None:
                            self.runner._mem = None
                        if tstats is not None:
                            self.runner.stats = None
                    if partition_fn is None:
                        raw = serialize_page(p)
                        if self.faults.enabled:
                            raw = self.faults.maybe_corrupt_page(
                                raw, self.node_id)
                        task.buffer.enqueue(raw)
                    else:
                        from presto_tpu.exec.spill import partition_to_host
                        from presto_tpu.server.serde import serialize_host_page

                        parts = partition_to_host(p, partition_fn(p), n_buffers)
                        live = sum(hp.num_rows for hp in parts if hp is not None)
                        if check_partial_mg is not None and live >= check_partial_mg:
                            raise RuntimeError(
                                f"GroupCapacityExceeded: partial aggregation "
                                f"truncated at {check_partial_mg} groups")
                        for k, hp in enumerate(parts):
                            if hp is not None:
                                raw = serialize_host_page(hp)
                                if self.faults.enabled:
                                    raw = self.faults.maybe_corrupt_page(
                                        raw, self.node_id)
                                task.buffers[k].enqueue(raw)
                    yield
                if tstats is not None:
                    # publish BEFORE the state flip: a consumer that
                    # observes FINISHED must find the stats attached
                    task.stats_wire = tstats.to_wire()
                task.state = FINISHED
                for buf in task.buffers:
                    buf.set_complete()
                self.tasks_executed += 1
                obs.TASKS.finish(task_id, FINISHED)
            except BufferAborted:
                task.state = ABORTED
                obs.TASKS.finish(task_id, ABORTED)
            except Exception as e:
                task.state = FAILED
                task.error = f"{type(e).__name__}: {e}"
                for buf in task.buffers:
                    buf.fail(task.error)
                obs.TASKS.finish(task_id, FAILED, error=task.error)
            finally:
                if mem_ctx is not None:
                    mem_ctx.release_all()

        self.executor.submit(steps())
        return task

    def _abort_task(self, task_id: str) -> None:
        with self._tasks_lock:
            task = self._tasks.pop(task_id, None)
        if task is not None:
            for buf in task.buffers:
                buf.abort()
            if task.state == RUNNING:
                task.state = ABORTED

    def _expire_tasks(self) -> None:
        """Drop tasks untouched for task_ttl (lazy sweep per request)."""
        import time

        now = time.monotonic()
        with self._tasks_lock:
            dead = [tid for tid, t in self._tasks.items()
                    if now - t.last_access > self.task_ttl]
        for tid in dead:
            self._abort_task(tid)

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread.start()
        # serving processes keep a metrics-history ring by default
        # (1s cadence unless PRESTO_TPU_METRICS_HISTORY_MS overrides);
        # the process singleton may already be armed by a co-resident
        # coordinator — only the server that armed it stops it
        from presto_tpu.obs.timeseries import HISTORY

        with self._tasks_lock:
            self._history_owner = (not HISTORY.running
                                   and HISTORY.start(default_ms=1000))

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self.executor.shutdown(wait=False)
        # stop() may run from a drain thread while start() ran on the
        # coordinator thread — settle ownership under the lock
        from presto_tpu.obs.timeseries import HISTORY

        with self._tasks_lock:
            owner = getattr(self, "_history_owner", False)
            self._history_owner = False
        if owner:
            HISTORY.stop()

    def drain(self, timeout: float = 30.0) -> bool:
        """Graceful shutdown: refuse visibility as ACTIVE, wait for
        running tasks to finish, then stop
        (server/GracefulShutdownHandler.java:73)."""
        import time

        self.draining = True
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._tasks_lock:
                if all(t.state != RUNNING for t in self._tasks.values()):
                    break
            time.sleep(0.05)
        drained = all(t.state != RUNNING for t in self._tasks.values())
        self.stop()
        return drained

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"


def parse_task_response(raw: bytes):
    from presto_tpu.server.serde import parse_page_batch

    return parse_page_batch(raw)
