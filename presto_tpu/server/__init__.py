from presto_tpu.server.coordinator import CoordinatorServer  # noqa: F401
