"""Authenticating reverse proxy for the statement protocol.

Reference analog: ``presto-proxy`` (ProxyResource.java — forwards the
V1 REST protocol to a backing coordinator, authenticating callers and
rewriting nextUri links so clients keep talking to the proxy).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional
from urllib import request as _request
from urllib.error import HTTPError


class ProxyServer:
    """Forwards /v1/* to ``backend_uri``; optional bearer-token check.

    nextUri values in JSON responses rewrite from the backend authority
    to the proxy's, so paging clients never learn the backend address
    (ProxyResource's rewriteUri)."""

    def __init__(self, backend_uri: str, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None,
                 authenticate: Optional[Callable[[str], bool]] = None,
                 public_host: Optional[str] = None):
        self.backend = backend_uri.rstrip("/")
        self.token = token
        self.authenticate = authenticate
        # the authority clients reach the proxy at — used by nextUri
        # rewriting; a 0.0.0.0 bind must supply its public name
        self._public_host = public_host or (
            host if host not in ("0.0.0.0", "::") else None)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reject(self, code: int, msg: str) -> None:
                body = json.dumps({"error": msg}).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _authorized(self) -> bool:
                if outer.token is None and outer.authenticate is None:
                    return True
                auth = self.headers.get("Authorization", "")
                got = auth[len("Bearer "):] if auth.startswith("Bearer ") else ""
                if outer.authenticate is not None:
                    return outer.authenticate(got)
                return got == outer.token

            def _forward(self, method: str) -> None:
                if not self.path.startswith("/v1/"):
                    self._reject(404, "not found")
                    return
                if not self._authorized():
                    self._reject(401, "unauthorized")
                    return
                n = int(self.headers.get("Content-Length", "0") or 0)
                body = self.rfile.read(n) if n else None
                req = _request.Request(outer.backend + self.path, data=body,
                                       method=method)
                for h in ("Content-Type", "X-Presto-User", "X-Trace-Token"):
                    if self.headers.get(h):
                        req.add_header(h, self.headers[h])
                try:
                    with _request.urlopen(req, timeout=60) as resp:
                        payload = resp.read()
                        ctype = resp.headers.get("Content-Type", "application/json")
                        code = resp.status
                except HTTPError as e:
                    payload = e.read()
                    ctype = e.headers.get("Content-Type", "application/json")
                    code = e.code
                if b"nextUri" in payload:
                    payload = payload.replace(
                        outer.backend.encode(), outer.uri.encode())
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._forward("GET")

            def do_POST(self):
                self._forward("POST")

            def do_DELETE(self):
                self._forward("DELETE")

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="proxy-http")

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def uri(self) -> str:
        if self._public_host is None:
            raise ValueError(
                "proxy bound to a wildcard address needs public_host= for "
                "client-facing nextUri rewriting")
        return f"http://{self._public_host}:{self.port}"
