"""Plan-fragment and Page wire serialization.

Reference analogs: the JSON-serialized ``PlanFragment`` shipped in
``TaskUpdateRequest`` (server/TaskUpdateRequest.java — the coordinator
POSTs the whole fragment to workers) and the binary page format of
``execution/buffer/PagesSerde.java:39`` (block-encoded pages on the
shuffle wire).  Fragments are JSON over expression/plan dataclasses;
pages are a JSON header + raw little-endian column bytes (dictionary
columns travel as codes — both ends resolve values from their own
catalog, like the reference's dictionary-block encodings).

Table handles serialize by (connector, table) name: the receiving
worker re-resolves against its own catalog, mirroring how reference
workers deserialize connector handles via their own plugin codecs.
"""

from __future__ import annotations

import base64
import json
from typing import Dict, List, Optional

import numpy as np

from presto_tpu.catalog import Catalog
from presto_tpu.expr.ir import AggCall, Call, ColumnRef, Expr, Literal
from presto_tpu.ops.window import WindowFunc
from presto_tpu.page import Block, Page
from presto_tpu.planner.plan import (
    AggregationNode,
    Channel,
    CrossSingleNode,
    FilterNode,
    JoinNode,
    LimitNode,
    OutputNode,
    PlanNode,
    PrecomputedNode,
    ProjectNode,
    RemoteSourceNode,
    SortNode,
    TableScanNode,
    TopNNode,
    ValuesNode,
    WindowNode,
)
from presto_tpu.types import (
    BIGINT, BOOLEAN, DATE, DOUBLE, INTEGER, TIMESTAMP, VARCHAR, DecimalType, Type,
)

# ---------------------------------------------------------------------------
# types
# ---------------------------------------------------------------------------

from presto_tpu.types import REAL, SMALLINT, TIME, TINYINT  # noqa: E402

_BASIC = {t.name: t for t in (BIGINT, INTEGER, SMALLINT, TINYINT, DOUBLE,
                              REAL, BOOLEAN, DATE, TIMESTAMP, TIME, VARCHAR)}


def type_to_json(t: Type) -> dict:
    out = {"name": t.name, "scale": t.scale, "precision": t.precision}
    if t.is_raw_string:
        out["raw"] = True
    if t.element is not None:
        out["element"] = type_to_json(t.element)
    if t.key_element is not None:
        out["key"] = type_to_json(t.key_element)
    return out


def type_from_json(d: dict) -> Type:
    if d["name"] == "array":
        from presto_tpu.types import ArrayType

        return ArrayType(type_from_json(d["element"]), d["precision"] or 8)
    if d["name"] == "map":
        from presto_tpu.types import MapType

        return MapType(type_from_json(d["key"]), type_from_json(d["element"]),
                       d["precision"] or 8)
    if d["name"] == "hll":
        from presto_tpu.types import HllType

        return HllType()
    if d["name"] == "setdigest":
        from presto_tpu.types import SetDigestType

        return SetDigestType()
    if d["name"] == "decimal":
        return DecimalType(d["precision"], d["scale"])
    if d.get("raw"):
        from presto_tpu.types import VarcharType

        return VarcharType(d["precision"] or 32, raw=True)
    if d["name"] == "varbinary":
        from presto_tpu.types import VarbinaryType

        return VarbinaryType(d["precision"] or 32)
    if d["name"] == "char":
        from presto_tpu.types import CharType

        return CharType(d["precision"] or 32)
    return _BASIC[d["name"]]


# ---------------------------------------------------------------------------
# expressions
# ---------------------------------------------------------------------------

def expr_to_json(e: Optional[Expr]) -> Optional[dict]:
    if e is None:
        return None
    if isinstance(e, ColumnRef):
        return {"k": "col", "i": e.index, "t": type_to_json(e.type), "n": e.name}
    if isinstance(e, Literal):
        return {"k": "lit", "v": e.value, "t": type_to_json(e.type)}
    if isinstance(e, Call):
        return {
            "k": "call", "fn": e.fn, "t": type_to_json(e.type),
            "args": [expr_to_json(a) for a in e.args],
        }
    raise TypeError(type(e))


def expr_from_json(d: Optional[dict]) -> Optional[Expr]:
    if d is None:
        return None
    if d["k"] == "col":
        return ColumnRef(type=type_from_json(d["t"]), index=d["i"], name=d.get("n", ""))
    if d["k"] == "lit":
        return Literal(type=type_from_json(d["t"]), value=d["v"])
    if d["k"] == "call":
        return Call(
            type=type_from_json(d["t"]), fn=d["fn"],
            args=tuple(expr_from_json(a) for a in d["args"]),
        )
    raise KeyError(d["k"])


def _agg_to_json(a: AggCall) -> dict:
    return {
        "fn": a.fn, "arg": expr_to_json(a.arg), "t": type_to_json(a.type),
        "distinct": a.distinct, "filter": expr_to_json(a.filter),
        "arg2": expr_to_json(a.arg2),
        "arg3": expr_to_json(a.arg3),
    }


def _agg_from_json(d: dict) -> AggCall:
    return AggCall(
        fn=d["fn"], arg=expr_from_json(d["arg"]), type=type_from_json(d["t"]),
        distinct=d["distinct"], filter=expr_from_json(d["filter"]),
        arg2=expr_from_json(d.get("arg2")),
        arg3=expr_from_json(d.get("arg3")),
    )


# ---------------------------------------------------------------------------
# plan nodes
# ---------------------------------------------------------------------------

def plan_to_json(node: PlanNode) -> dict:
    if isinstance(node, TableScanNode):
        return {
            "k": "scan",
            "connector": node.handle.connector_name,
            "table": node.handle.table,
            "columns": list(node.columns),
            "splits": node.splits,
            "constraints": [list(c) for c in node.constraints],
            "limit": node.limit,
            "sample": list(node.sample) if node.sample else None,
        }
    if isinstance(node, FilterNode):
        return {"k": "filter", "src": plan_to_json(node.source),
                "pred": expr_to_json(node.predicate)}
    if isinstance(node, ProjectNode):
        return {"k": "project", "src": plan_to_json(node.source),
                "projections": [expr_to_json(e) for e in node.projections],
                "names": list(node.names)}
    if isinstance(node, AggregationNode):
        return {
            "k": "agg", "src": plan_to_json(node.source),
            "group": [expr_to_json(e) for e in node.group_exprs],
            "group_names": list(node.group_names),
            "aggs": [_agg_to_json(a) for a in node.aggs],
            "agg_names": list(node.agg_names),
            "step": node.step, "max_groups": node.max_groups,
        }
    if isinstance(node, JoinNode):
        return {
            "k": "join", "left": plan_to_json(node.left), "right": plan_to_json(node.right),
            "lk": [expr_to_json(e) for e in node.left_keys],
            "rk": [expr_to_json(e) for e in node.right_keys],
            "kind": node.kind, "unique": node.unique_build,
            "null_safe": node.null_safe_keys,
            "na": node.null_aware,
        }
    if isinstance(node, CrossSingleNode):
        return {"k": "cross1", "left": plan_to_json(node.left),
                "right": plan_to_json(node.right)}
    if isinstance(node, PrecomputedNode):
        # a materialized intermediate travels INSIDE the fragment: how
        # the DCN tier re-chunks one stage's output across the next
        # stage's workers (the data-bearing half of the reference's
        # RemoteSourceNode + exchange, for coordinator-pushed chunks)
        import base64

        return {
            "k": "pre",
            "page": base64.b64encode(serialize_page(node.page)).decode(),
            "channels": [
                {"name": c.name, "type": type_to_json(c.type),
                 "dict": (list(c.dictionary.values)
                          if c.dictionary is not None else None),
                 "domain": list(c.domain) if c.domain else None}
                for c in node.channel_list
            ],
        }
    if isinstance(node, SortNode):
        return {"k": "sort", "src": plan_to_json(node.source),
                "keys": [expr_to_json(e) for e in node.sort_exprs],
                "asc": list(node.ascending), "nf": node.nulls_first}
    if isinstance(node, TopNNode):
        return {"k": "topn", "src": plan_to_json(node.source),
                "keys": [expr_to_json(e) for e in node.sort_exprs],
                "asc": list(node.ascending), "count": node.count, "nf": node.nulls_first}
    if isinstance(node, LimitNode):
        return {"k": "limit", "src": plan_to_json(node.source), "count": node.count}
    if isinstance(node, WindowNode):
        return {
            "k": "window", "src": plan_to_json(node.source),
            "partition": [expr_to_json(e) for e in node.partition_exprs],
            "order": [expr_to_json(e) for e in node.order_exprs],
            "asc": list(node.ascending),
            "funcs": [
                {"kind": f.kind, "arg": expr_to_json(f.arg), "offset": f.offset,
                 "frame": list(f.frame) if f.frame else None,
                 "ignore_nulls": f.ignore_nulls}
                for f in node.funcs
            ],
            "names": list(node.func_names),
        }
    if isinstance(node, ValuesNode):
        return {"k": "values", "names": list(node.names),
                "types": [type_to_json(t) for t in node.types],
                "rows": [list(r) for r in node.rows]}
    if isinstance(node, OutputNode):
        return {"k": "output", "src": plan_to_json(node.source), "names": list(node.names)}
    if isinstance(node, RemoteSourceNode):
        return {
            "k": "remote",
            # the upstream fragment travels for its channel layout only
            "producer": plan_to_json(node.producer),
            "tasks": [[u, t] for u, t in node.tasks],
            "buffer": node.buffer_id,
        }
    raise TypeError(f"unserializable plan node {type(node).__name__}")


def plan_from_json(d: dict, catalog: Catalog) -> PlanNode:
    k = d["k"]
    if k == "scan":
        handle = catalog.resolve(d["table"])
        return TableScanNode(
            handle, list(d["columns"]), d.get("splits"),
            constraints=[tuple(c) for c in d.get("constraints", [])],
            limit=d.get("limit"),
            sample=tuple(d["sample"]) if d.get("sample") else None,
        )
    if k == "filter":
        return FilterNode(plan_from_json(d["src"], catalog), expr_from_json(d["pred"]))
    if k == "project":
        return ProjectNode(
            plan_from_json(d["src"], catalog),
            [expr_from_json(e) for e in d["projections"]], list(d["names"]),
        )
    if k == "agg":
        return AggregationNode(
            plan_from_json(d["src"], catalog),
            [expr_from_json(e) for e in d["group"]], list(d["group_names"]),
            [_agg_from_json(a) for a in d["aggs"]], list(d["agg_names"]),
            step=d["step"], max_groups=d["max_groups"],
        )
    if k == "join":
        return JoinNode(
            plan_from_json(d["left"], catalog), plan_from_json(d["right"], catalog),
            [expr_from_json(e) for e in d["lk"]], [expr_from_json(e) for e in d["rk"]],
            kind=d["kind"], unique_build=d["unique"],
            null_safe_keys=d.get("null_safe", False),
            null_aware=d.get("na", False),
        )
    if k == "cross1":
        return CrossSingleNode(
            plan_from_json(d["left"], catalog), plan_from_json(d["right"], catalog)
        )
    if k == "pre":
        from presto_tpu.page import Dictionary

        channels = []
        for c in d["channels"]:
            dic = Dictionary(c["dict"]) if c.get("dict") is not None else None
            channels.append(Channel(
                name=c["name"], type=type_from_json(c["type"]),
                dictionary=dic,
                domain=tuple(c["domain"]) if c.get("domain") else None))
        page = deserialize_page(base64.b64decode(d["page"]),
                                [c.dictionary for c in channels])
        # chunk row counts are data-dependent (round(i*n/k) splits) and
        # the wire format compacts live rows, so pad HERE to bucketed
        # capacity — otherwise every chunk shape costs the worker a
        # fresh XLA compile of its chain program
        from presto_tpu.exec.local import pad_page_pow2

        return PrecomputedNode(page=pad_page_pow2(page),
                               channel_list=channels)
    if k == "sort":
        return SortNode(
            plan_from_json(d["src"], catalog),
            [expr_from_json(e) for e in d["keys"]], list(d["asc"]), d.get("nf"),
        )
    if k == "topn":
        return TopNNode(
            plan_from_json(d["src"], catalog),
            [expr_from_json(e) for e in d["keys"]], list(d["asc"]),
            d["count"], d.get("nf"),
        )
    if k == "limit":
        return LimitNode(plan_from_json(d["src"], catalog), d["count"])
    if k == "window":
        return WindowNode(
            plan_from_json(d["src"], catalog),
            [expr_from_json(e) for e in d["partition"]],
            [expr_from_json(e) for e in d["order"]],
            list(d["asc"]),
            [WindowFunc(kind=f["kind"], arg=expr_from_json(f["arg"]), offset=f["offset"],
                        frame=tuple(f["frame"]) if f.get("frame") else None,
                        ignore_nulls=f.get("ignore_nulls", False))
             for f in d["funcs"]],
            list(d["names"]),
        )
    if k == "values":
        return ValuesNode(
            list(d["names"]), [type_from_json(t) for t in d["types"]],
            [tuple(r) for r in d["rows"]],
        )
    if k == "output":
        return OutputNode(plan_from_json(d["src"], catalog), list(d["names"]))
    if k == "remote":
        return RemoteSourceNode(
            producer=plan_from_json(d["producer"], catalog),
            tasks=[(u, t) for u, t in d["tasks"]],
            buffer_id=d["buffer"],
        )
    raise KeyError(k)


# ---------------------------------------------------------------------------
# pages (shuffle wire format)
# ---------------------------------------------------------------------------

def _encode_page(columns, n: int, compress: bool) -> bytes:
    """Shared page frame: JSON header + column payload, zlib-compressed
    when that shrinks it (the reference's optional LZ4 page compression,
    execution/buffer/PagesSerde.java:66 + exchange_compression).
    ``columns`` yields (np data, np valid, Type) already trimmed to n
    rows — the single implementation both serialize paths share so the
    wire format cannot drift."""
    import zlib

    header = {"types": [], "n": n}
    payload = b""
    for data, valid, t in columns:
        header["types"].append(
            {"t": type_to_json(t), "dtype": str(data.dtype),
             "shape": list(data.shape[1:])}
        )
        payload += np.ascontiguousarray(data).tobytes()
        payload += np.packbits(valid).tobytes()
    if compress:
        z = zlib.compress(payload, 1)
        if len(z) < len(payload):
            header["z"] = len(payload)  # uncompressed size
            payload = z
    # integrity: CRC32 over the ON-WIRE payload (post-compression), so
    # a consumer verifies without decompressing and damage anywhere in
    # the frame body is caught before rows are trusted (the reference
    # ships page checksums in its serialized-page wire format too)
    header["crc"] = zlib.crc32(payload)
    hjson = json.dumps(header).encode()
    return len(hjson).to_bytes(4, "little") + hjson + payload


def _count_exchange(direction: str, nbytes: int) -> None:
    from presto_tpu.obs import METRICS

    METRICS.counter(f"exchange.pages_{direction}").inc()
    METRICS.counter(f"exchange.bytes_{direction}").inc(nbytes)


def serialize_page(page: Page, compress: bool = True) -> bytes:
    """Compact live rows and encode (device page path)."""
    p = page.compact_host()
    n = int(np.asarray(p.row_mask).sum())
    cols = ((np.asarray(b.data)[:n], np.asarray(b.valid)[:n], b.type)
            for b in p.blocks)
    out = _encode_page(cols, n, compress)
    _count_exchange("serialized", len(out))
    return out


def serialize_host_page(hp, compress: bool = True) -> bytes:
    """serialize_page for a spill-tier HostPage (numpy-backed, already
    compacted) — the partitioned-output write path serializes each
    bucket straight from host RAM without a device round trip."""
    n = int(hp.mask.sum())
    cols = ((data, valid, t) for data, valid, t, _dic in hp.columns)
    out = _encode_page(cols, n, compress)
    _count_exchange("serialized", len(out))
    return out


def encode_page_batch(pages) -> bytes:
    """[npages u32][len u64][bytes]... framing of a page batch (the
    task-results response body)."""
    return len(pages).to_bytes(4, "little") + b"".join(
        len(p).to_bytes(8, "little") + p for p in pages)


def parse_page_batch(raw: bytes):
    """Inverse of encode_page_batch."""
    npages = int.from_bytes(raw[:4], "little")
    off = 4
    out = []
    for _ in range(npages):
        ln = int.from_bytes(raw[off:off + 8], "little")
        off += 8
        out.append(raw[off:off + ln])
        off += ln
    return out


def verify_page(raw: bytes) -> None:
    """Check a serialized page's CRC without decoding it; raises
    PageIntegrityError (classified TRANSIENT — the fragment is pure,
    so recomputation is safe) on damage.  Pages from older producers
    without a crc field pass."""
    import zlib

    from presto_tpu.net import PageIntegrityError

    try:
        hlen = int.from_bytes(raw[:4], "little")
        header = json.loads(raw[4: 4 + hlen].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise PageIntegrityError(f"page frame header unreadable: {e}")
    crc = header.get("crc")
    if crc is not None and zlib.crc32(raw[4 + hlen:]) != crc:
        raise PageIntegrityError(
            f"page payload CRC mismatch ({len(raw)} bytes)")


def deserialize_page(raw: bytes, dictionaries=None,
                     verify: bool = True) -> Page:
    """``verify=False`` skips the CRC pass for bytes already checked
    at their pull/ingest boundary (WorkerClient.pull_results) or
    produced in-process — one checksum per page, not two."""
    import zlib

    from presto_tpu.net import PageIntegrityError

    _count_exchange("deserialized", len(raw))
    hlen = int.from_bytes(raw[:4], "little")
    try:
        header = json.loads(raw[4 : 4 + hlen].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise PageIntegrityError(f"page frame header unreadable: {e}")
    if verify:
        # CRC folded into the decode's single header parse (the hot
        # exchange path); verify_page stays for pull-boundary callers
        # that check without decoding
        crc = header.get("crc")
        if crc is not None and zlib.crc32(raw[4 + hlen:]) != crc:
            raise PageIntegrityError(
                f"page payload CRC mismatch ({len(raw)} bytes)")
    n = header["n"]
    if header.get("z"):
        raw = raw[: 4 + hlen] + zlib.decompress(raw[4 + hlen :])
    off = 4 + hlen
    blocks = []
    import jax.numpy as jnp

    for i, tinfo in enumerate(header["types"]):
        dtype = np.dtype(tinfo["dtype"])
        vshape = tuple(tinfo.get("shape", ()))
        vcount = int(np.prod(vshape)) if vshape else 1
        nbytes = n * vcount * dtype.itemsize
        data = np.frombuffer(raw[off : off + nbytes], dtype=dtype).reshape((n,) + vshape)
        off += nbytes
        vbytes = (n + 7) // 8
        valid = np.unpackbits(
            np.frombuffer(raw[off : off + vbytes], dtype=np.uint8)
        )[:n].astype(bool)
        off += vbytes
        t = type_from_json(tinfo["t"])
        dic = dictionaries[i] if dictionaries is not None else None
        cap = max(n, 1)
        d = np.zeros((cap,) + vshape, dtype=dtype)
        d[:n] = data
        v = np.zeros(cap, dtype=bool)
        v[:n] = valid
        blocks.append(Block(jnp.asarray(d), jnp.asarray(v), t, dic))
    mask = np.zeros(max(n, 1), dtype=bool)
    mask[:n] = True
    return Page(tuple(blocks), jnp.asarray(mask))
