"""Shuffle read client: pull one task output buffer over HTTP.

Reference analog: ``operator/HttpPageBufferClient.java:88`` — the
long-poll GET of ``/v1/task/{id}/results/{buffer}/{token}`` with token
acknowledgement (``server/TaskResource.java:239,298``), at-least-once
delivery de-duplicated by the client-held token, plus a no-progress
deadline so a wedged producer fails the pull instead of hanging it.
Transient transport faults ride the shared classification plane
(net.py, the RequestErrorTracker analog): a token GET is idempotent,
so brief connection blips retry in place with backoff, while a worker
that stays dead fails the pull within a few hundred milliseconds —
fast enough for the caller's fragment failover.

Used by BOTH tiers of the DCN exchange: the coordinator pulling a root
stage, and a worker's RemoteSource leaf pulling an upstream stage's
partition buffer (worker-to-worker shuffle — the ExchangeOperator.java:36
consumption path).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Iterator

from presto_tpu.analysis.protocols import RECORDER
from presto_tpu.server.serde import parse_page_batch as _parse_batch
from presto_tpu.testing_faults import FAULTS

#: consecutive transient transport failures tolerated per token before
#: the pull is abandoned (the caller's failover takes over) — small on
#: purpose: a dead producer must fail fast, not ride the no-progress
#: deadline
MAX_TRANSIENT_RETRIES = 3


class TaskPullFailed(Exception):
    """The producing task reported FAILED (deterministic query error:
    the failure travels; the worker is not to blame)."""


def _task_error(uri: str, task_id: str) -> str:
    from presto_tpu.net import request_json

    try:
        info = request_json(f"{uri}/v1/task/{task_id}", timeout=5.0)
        if info.get("state") == "FAILED":
            return info.get("error") or "task failed"
    except Exception:
        # the status probe is best-effort context for an error we are
        # ALREADY raising; its own failure is classified by request_json
        pass
    return ""


def pull_pages(uri: str, task_id: str, buffer_id: int = 0,
               timeout: float = 300.0, poll_timeout: float = 30.0,
               ) -> Iterator[bytes]:
    """Yield serialized pages from one buffer of a remote task until
    the producer marks it complete.  Raises TaskPullFailed on producer
    task failure, TimeoutError after ``timeout`` with no progress, and
    the classified transport error after MAX_TRANSIENT_RETRIES
    consecutive transient failures."""
    from presto_tpu.net import count_error, is_transient

    uri = uri.rstrip("/")
    token = 0
    pkey = f"pull:{uri}/{task_id}/{buffer_id}"
    last_progress = time.monotonic()
    transient_failures = 0
    while True:
        if time.monotonic() - last_progress > timeout:
            raise TimeoutError(
                f"buffer {buffer_id} of task {task_id} on {uri} made no "
                f"progress for {timeout}s")
        rtoken = token
        try:
            with urllib.request.urlopen(
                f"{uri}/v1/task/{task_id}/results/{buffer_id}/{token}",
                timeout=poll_timeout,
            ) as resp:
                batch = _parse_batch(resp.read())
                nxt = int(resp.headers.get("X-Next-Token", token))
                complete = resp.headers.get("X-Complete") == "1"
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                detail = ""
            detail = detail or _task_error(uri, task_id)
            if detail:
                raise TaskPullFailed(detail)
            raise
        except TimeoutError:
            continue  # long-poll expiry, not lack of progress
        except Exception as e:
            count_error(e)
            transient_failures += 1
            if not is_transient(e) \
                    or transient_failures > MAX_TRANSIENT_RETRIES:
                raise
            # the token GET is idempotent (unacknowledged pages re-serve
            # at the same token): retry in place with a short backoff
            time.sleep(min(0.05 * (2 ** transient_failures), 0.5))
            continue
        transient_failures = 0
        responses = [(rtoken, batch, nxt, complete)]
        if FAULTS.enabled and FAULTS.should_fire(
                "net.duplicate_page", uri) is not None:
            # the delayed duplicate reply of a token GET the client
            # retried (both responses eventually arrive): the seq-based
            # dedupe below must swallow the repeated pages
            responses.append((rtoken, batch, nxt, complete))
        for r_tok, r_batch, r_nxt, r_done in responses:
            if RECORDER.enabled:
                RECORDER.record("exchange", pkey, "recv",
                                token=r_tok, next=r_nxt, done=r_done)
            for i, raw in enumerate(r_batch):
                seq = r_tok + i
                if seq < token:
                    # dedupe by sequence number: a duplicated or stale
                    # response (client retry whose first reply was not
                    # lost after all) re-carries pages already yielded —
                    # at-least-once delivery becomes exactly-once HERE
                    continue
                if RECORDER.enabled:
                    RECORDER.record("exchange", pkey, "deliver", seq=seq)
                yield raw
            if r_nxt > token:
                token = r_nxt
                last_progress = time.monotonic()
                try:
                    urllib.request.urlopen(
                        f"{uri}/v1/task/{task_id}/results/{buffer_id}"
                        f"/{token}/acknowledge",
                        timeout=poll_timeout,
                    ).close()
                except Exception as e:
                    # best-effort: an ack only frees buffered pages below
                    # `token` — a later ack at a higher token supersedes a
                    # lost one, and a truly dead producer surfaces at the
                    # next results GET with proper triage.  Aborting the
                    # pull (and recomputing the whole task) over an ack
                    # blip would be strictly worse.
                    count_error(e)
        if complete:
            return
