"""Shuffle read client: pull one task output buffer over HTTP.

Reference analog: ``operator/HttpPageBufferClient.java:88`` — the
long-poll GET of ``/v1/task/{id}/results/{buffer}/{token}`` with token
acknowledgement (``server/TaskResource.java:239,298``), at-least-once
delivery de-duplicated by the client-held token, plus a no-progress
deadline so a wedged producer fails the pull instead of hanging it.

Used by BOTH tiers of the DCN exchange: the coordinator pulling a root
stage, and a worker's RemoteSource leaf pulling an upstream stage's
partition buffer (worker-to-worker shuffle — the ExchangeOperator.java:36
consumption path).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Iterator, List


from presto_tpu.server.serde import parse_page_batch as _parse_batch


class TaskPullFailed(Exception):
    """The producing task reported FAILED (deterministic query error:
    the failure travels; the worker is not to blame)."""


def _task_error(uri: str, task_id: str) -> str:
    try:
        with urllib.request.urlopen(f"{uri}/v1/task/{task_id}", timeout=5.0) as r:
            info = json.load(r)
        if info.get("state") == "FAILED":
            return info.get("error") or "task failed"
    except Exception:
        pass
    return ""


def pull_pages(uri: str, task_id: str, buffer_id: int = 0,
               timeout: float = 300.0, poll_timeout: float = 30.0,
               ) -> Iterator[bytes]:
    """Yield serialized pages from one buffer of a remote task until
    the producer marks it complete.  Raises TaskPullFailed on producer
    task failure, TimeoutError after ``timeout`` with no progress."""
    uri = uri.rstrip("/")
    token = 0
    last_progress = time.monotonic()
    while True:
        if time.monotonic() - last_progress > timeout:
            raise TimeoutError(
                f"buffer {buffer_id} of task {task_id} on {uri} made no "
                f"progress for {timeout}s")
        try:
            with urllib.request.urlopen(
                f"{uri}/v1/task/{task_id}/results/{buffer_id}/{token}",
                timeout=poll_timeout,
            ) as resp:
                batch = _parse_batch(resp.read())
                nxt = int(resp.headers.get("X-Next-Token", token))
                complete = resp.headers.get("X-Complete") == "1"
        except urllib.error.HTTPError as e:
            try:
                detail = json.loads(e.read()).get("error", "")
            except Exception:
                detail = ""
            detail = detail or _task_error(uri, task_id)
            if detail:
                raise TaskPullFailed(detail)
            raise
        except TimeoutError:
            continue  # long-poll expiry, not lack of progress
        yield from batch
        if nxt > token:
            token = nxt
            last_progress = time.monotonic()
            urllib.request.urlopen(
                f"{uri}/v1/task/{task_id}/results/{buffer_id}/{token}/acknowledge",
                timeout=poll_timeout,
            ).close()
        if complete:
            return
