"""Coordinator REST server.

Reference analog: the V1 statement protocol —
``server/protocol/StatementResource.java:82`` (POST /v1/statement
creates a query; results are paged via GET nextUri with token
acknowledgement; DELETE cancels) plus the info/status resources
(``server/ServerInfoResource``, ``QueryResource``).  stdlib
http.server stands in for airlift/jetty; query execution runs on a
worker thread per query with paged result buffers.

Protocol (JSON):
  POST /v1/statement            body = SQL
  GET  /v1/statement/{id}/{tok} next page
  DELETE /v1/statement/{id}     cancel
  GET  /v1/info                 server info
  GET  /v1/query                finished/running query summaries
Responses carry: id, columns [{name, type}], data [[row...]...],
stats {state, rows}, error?, nextUri?.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from presto_tpu import __version__
from presto_tpu.runner import QueryRunner
from presto_tpu.sync import named_lock

PAGE_ROWS = 1000

# Minimal cluster console (the reference serves a React app from
# presto-main/src/main/resources/webapp/; this single inline page covers
# the same first-stop view — cluster tiles + live query list + a
# per-query detail view (stage progress table + span timeline) — from
# the same REST resources).
_UI_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>presto-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#16181d;color:#e8e8e8}
 h1{font-size:1.3rem} h2{font-size:1.05rem;color:#9aa0ab}
 .tiles{display:flex;gap:1rem;margin:1rem 0}
 .tile{background:#23262e;border-radius:8px;padding:1rem 1.5rem;min-width:8rem}
 .tile .v{font-size:1.8rem;font-weight:600} .tile .l{color:#9aa0ab;font-size:.8rem}
 table{border-collapse:collapse;width:100%;margin-top:1rem}
 th,td{text-align:left;padding:.4rem .6rem;border-bottom:1px solid #2e323b;font-size:.85rem}
 th{color:#9aa0ab;font-weight:500}
 .FINISHED{color:#6fcf97}.RUNNING{color:#56ccf2}.FAILED,.CANCELED{color:#eb5757}
 .QUEUED{color:#f2c94c} td.q{font-family:ui-monospace,monospace;max-width:40rem;
 overflow:hidden;text-overflow:ellipsis;white-space:nowrap}
 tr.row{cursor:pointer} tr.row:hover{background:#1c1f26}
 #detail{display:none;background:#23262e;border-radius:8px;padding:1rem 1.5rem;margin:1rem 0}
 .lane{position:relative;height:18px;margin:2px 0;background:#1a1d23}
 .sp{position:absolute;height:14px;top:2px;background:#56ccf2;border-radius:2px;
  overflow:hidden;font-size:.65rem;color:#0b0d10;white-space:nowrap;padding:0 2px}
 .sp.lifecycle{background:#6fcf97}.sp.compile{background:#f2c94c}
 .sp.exchange{background:#bb6bd9}.sp.device{background:#eb5757}
 .bar{background:#1a1d23;border-radius:4px;height:8px;margin-top:2px}
 .bar>div{background:#56ccf2;border-radius:4px;height:8px}
</style></head><body>
<h1>presto-tpu cluster console</h1>
<div class="tiles" id="tiles"></div>
<div id="workers"></div>
<div id="detail"></div>
<table><thead><tr><th>query id</th><th>state</th><th>progress</th><th>rows</th><th>sql</th></tr></thead>
<tbody id="queries"></tbody></table>
<script>
let selected = null;
async function refresh(){
  const c = await (await fetch('/v1/cluster')).json();
  document.getElementById('tiles').innerHTML =
    ['runningQueries','queuedQueries','finishedQueries','failedQueries']
    .map(k=>`<div class="tile"><div class="v">${c[k]??0}</div><div class="l">${k.replace('Queries',' queries')}</div></div>`).join('')
    + (c.totalBytes?`<div class="tile"><div class="v">${(100*c.reservedBytes/c.totalBytes).toFixed(1)}%</div><div class="l">pool reserved</div></div>`:'');
  const ws = await (await fetch('/v1/worker')).json();
  document.getElementById('workers').innerHTML = !ws.length ? '' :
    '<h2>workers</h2><table><thead><tr><th>worker</th><th>detector state</th>'+
    '<th>consecutive failures</th><th>last heartbeat</th></tr></thead><tbody>'+
    ws.map(w=>{
      const cls = {ALIVE:'FINISHED',RECOVERED:'FINISHED',SUSPECT:'QUEUED',
                   DEAD:'FAILED'}[w.state]||'';
      const hb = w.last_heartbeat_ms==null?'never'
                 :(w.last_heartbeat_ms/1000).toFixed(1)+'s ago';
      return `<tr><td>${w.uri}</td><td class="${cls}">${w.state}</td>`+
             `<td>${w.consecutive_failures}</td><td>${hb}</td></tr>`;
    }).join('')+'</tbody></table>';
  const qs = await (await fetch('/v1/query')).json();
  document.getElementById('queries').innerHTML = qs.reverse().map(q=>
    `<tr class="row" onclick="select('${q.id}')"><td>${q.id}</td>`+
    `<td class="${q.state}">${q.state}</td>`+
    `<td>${q.state==='QUEUED'&&q.queuePosition!=null?'queue #'+q.queuePosition
         :q.progress==null?'':q.progress.toFixed(0)+'%'}</td>`+
    `<td>${q.rows}</td><td class="q">${q.query.replace(/</g,'&lt;')}</td></tr>`).join('');
  if (selected) detail(selected);
}
function select(id){ selected = (selected===id)?null:id; detail(selected); }
async function detail(id){
  const box = document.getElementById('detail');
  if (!id){ box.style.display='none'; return; }
  let html = `<h2>query ${id}</h2>`;
  const pr = await fetch(`/v1/query/${id}/progress`);
  if (pr.ok){
    const p = await pr.json();
    html += `<div>progress ${p.progressPercentage}% · ${p.elapsedMs}ms</div>`;
    html += '<table><thead><tr><th>stage</th><th>state</th><th>splits</th>'+
            '<th>rows</th><th>bytes</th><th></th></tr></thead><tbody>';
    for (const s of p.stages){
      const tot = s.splitsTotal, pct = tot?100*s.splitsDone/tot:0;
      html += `<tr><td>${s.stage}</td><td class="${s.state}">${s.state}</td>`+
        `<td>${s.splitsDone}/${tot??'?'}</td><td>${s.rows}</td><td>${s.bytes}</td>`+
        `<td style="min-width:8rem"><div class="bar"><div style="width:${pct.toFixed(0)}%"></div></div></td></tr>`;
    }
    html += '</tbody></table>';
  }
  const tr = await fetch(`/v1/query/${id}/trace`);
  if (tr.ok){
    // span timeline from the trace registry: top spans by duration,
    // one lane per thread, scaled to the trace extent
    const t = await tr.json();
    const evs = t.traceEvents.filter(e=>e.ph==='X');
    if (evs.length){
      const end = Math.max(...evs.map(e=>e.ts+e.dur));
      const top = evs.sort((a,b)=>b.dur-a.dur).slice(0,60);
      const tids = [...new Set(top.map(e=>e.tid))];
      html += `<h2>span timeline (${evs.length} spans, ${(end/1000).toFixed(1)}ms)</h2>`;
      for (const tid of tids){
        html += '<div class="lane">' + top.filter(e=>e.tid===tid).map(e=>
          `<div class="sp ${e.cat}" title="${e.name} ${(e.dur/1000).toFixed(2)}ms"`+
          ` style="left:${(100*e.ts/end).toFixed(2)}%;width:${Math.max(100*e.dur/end,.3).toFixed(2)}%">${e.name}</div>`
        ).join('') + '</div>';
      }
    }
  } else if (!pr.ok) {
    html += '<div>no progress or trace recorded for this query</div>';
  }
  const or_ = await fetch(`/v1/query/${id}/operators`);
  if (or_.ok){
    // per-operator est/actual rows (collect_stats sessions)
    const o = await or_.json();
    if (o.operators && o.operators.length){
      html += '<h2>operators</h2><table><thead><tr><th>operator</th>'+
              '<th>est rows</th><th>actual rows</th><th>ratio</th>'+
              '<th>pages</th><th>wall ms</th></tr></thead><tbody>';
      for (const op of o.operators){
        html += `<tr><td>${op.node}#${op.occ}</td>`+
          `<td>${op.est_rows==null?'':Number(op.est_rows).toFixed(0)}</td>`+
          `<td>${op.rows}</td>`+
          `<td>${op.ratio==null?'':'×'+Number(op.ratio).toFixed(1)}</td>`+
          `<td>${op.pages}</td><td>${op.wall_ms}</td></tr>`;
      }
      html += '</tbody></table>';
    }
  }
  const dr = await fetch(`/v1/query/${id}/doctor`);
  if (dr.ok){
    // post-query diagnosis (obs/doctor.py): ranked bottleneck findings
    const d = await dr.json();
    if (d.findings && d.findings.length){
      html += '<h2>diagnosis</h2><table><thead><tr><th>#</th><th>rule</th>'+
              '<th>score</th><th>summary</th></tr></thead><tbody>';
      d.findings.forEach((f,i)=>{
        html += `<tr><td>${i+1}</td><td>${f.rule}</td>`+
          `<td>${Number(f.score).toFixed(2)}</td>`+
          `<td class="q">${String(f.summary).replace(/</g,'&lt;')}</td></tr>`;
      });
      html += '</tbody></table>';
    }
  }
  box.innerHTML = html; box.style.display='block';
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class _QueryState:
    def __init__(self, qid: str, sql: str):
        self.id = qid
        self.sql = sql
        self.state = "QUEUED"  # QUEUED -> RUNNING -> FINISHED | FAILED | CANCELED
        self.columns: List[dict] = []
        self.rows: List[tuple] = []
        self.error: Optional[str] = None
        self.done = threading.Event()
        # the computation thread: outlives `done` on cancel (DELETE
        # sets done to unblock the client; the thread runs to the end)
        self.thread: Optional[threading.Thread] = None
        # distributed-tier outcome: stage count and (when the query
        # silently ran locally) the fallback reason — surfaced in the
        # statement-protocol stats so clients see fallbacks without
        # querying system_runtime_queries
        self.dist_stages: Optional[int] = None
        self.dist_fallback: Optional[str] = None
        # lifecycle stage times from the obs span spine (NULL-safe)
        self.planning_ms: Optional[float] = None
        self.compile_ms: Optional[float] = None
        self.execution_ms: Optional[float] = None
        # client-supplied request correlation (X-Presto-Trace-Token)
        self.trace_token: Optional[str] = None
        # deadline bookkeeping: the effective limit (None = none) and
        # the monotonic instant execution started
        self.deadline_s: Optional[float] = None
        self.t_running: Optional[float] = None
        # the admission ticket this query holds (serving/admission.py;
        # set while queued) — released once-only through the
        # controller, so a kill frees the slot immediately instead of
        # waiting for the zombie thread
        self.ticket = None
        # statement error code for policy failures (QUERY_QUEUE_FULL /
        # EXCEEDED_QUEUE_TIME / EXCEEDED_TIME_LIMIT); None for generic
        # execution errors
        self.error_code: Optional[str] = None
        # serving-tier result provenance (statement stats cacheHit)
        self.cache_hit: Optional[bool] = None
        # admission-plane waits + doctor findings (NULL-safe, copied
        # off the result like the stage times above)
        self.queued_ms: Optional[float] = None
        self.memory_blocked_ms: Optional[float] = None
        self.findings: Optional[list] = None
        # live queue position served while QUEUED (filled per response)
        self.queue_position: Optional[int] = None

    @property
    def group_released(self) -> bool:
        """Whether the admission slot has been freed (legacy surface of
        the pre-serving-tier flag; now the ticket's released state)."""
        return self.ticket is not None and self.ticket.released

    def summary(self) -> dict:
        from presto_tpu import obs

        prog = obs.progress_for(self.id)
        return {
            "id": self.id,
            "query": self.sql,
            "state": self.state,
            "rows": len(self.rows),
            "progress": (100.0 if self.state == "FINISHED"
                         else prog.percentage() if prog is not None
                         else None),
            "queuePosition": self.queue_position
            if self.state == "QUEUED" else None,
        }


class CoordinatorServer:
    """Embeds a QueryRunner behind the REST protocol.  Queries run on
    daemon threads (the coordinator's query-execution pool); the state
    machine mirrors QueryState.java:21 (trimmed to the states a
    single-process coordinator hits)."""

    def __init__(self, runner: QueryRunner, host: str = "127.0.0.1", port: int = 0,
                 resource_groups=None, worker_uris=(), memory_threshold: float = 0.95,
                 authenticator=None, max_execution_time: float = 0.0,
                 max_queued_time: float = 600.0, deadline_grace: float = 5.0,
                 detector=None, admission=None,
                 admission_memory_fraction: float = 0.9,
                 admission_reserve_bytes: int = 0):
        from presto_tpu.resource_groups import ResourceGroupManager

        # optional PasswordAuthenticator (server/security + the
        # password-authenticator plugins): HTTP Basic on /v1/statement
        self.authenticator = authenticator
        self.runner = runner
        self.queries: Dict[str, _QueryState] = {}
        self.resource_groups = resource_groups or ResourceGroupManager()
        self.worker_uris = list(worker_uris)
        # query deadlines (query.max-execution-time / max-queued-time
        # config keys): the coordinator kills a query that runs past
        # its deadline — frees its memory reservations, emits a
        # QueryKilledEvent(EXCEEDED_TIME_LIMIT), fails the statement.
        # The deadline is OPT-IN (default 0 = none: the legacy 600s
        # was a long-poll bound, not a kill); the queue bound replaces
        # the old hard-coded 600s acquire wait.
        self.max_execution_time = float(max_execution_time)
        self.max_queued_time = float(max_queued_time)
        self.deadline_grace = float(deadline_grace)
        # worker failure detector (parallel/failure.py): background
        # heartbeats with backoff, state machine per worker, surfaced
        # through /v1/worker, system_runtime_workers and the web UI;
        # transitions flow into the event pipeline (query log)
        from presto_tpu.parallel.failure import FailureDetector

        self.failure_detector = detector or FailureDetector(self.worker_uris)
        import time as _time

        from presto_tpu.events import WorkerStateChangeEvent

        self.failure_detector.add_transition_listener(
            lambda uri, old, new, reason:
            runner.events.worker_state_changed(WorkerStateChangeEvent(
                uri=uri, old_state=old, new_state=new, reason=reason,
                change_time=_time.time())))
        self._lock = named_lock("coordinator.CoordinatorServer._lock")
        # serving-tier admission plane (serving/admission.py): every
        # statement passes the memory-aware controller — resource-group
        # concurrency + projected pool headroom — instead of a bare
        # group.acquire; queue positions flow back through the async
        # statement protocol, the CLI and the web UI
        from presto_tpu.serving.admission import AdmissionController

        self.admission = admission or AdmissionController(
            self.resource_groups,
            pool=getattr(runner.executor, "memory_pool", None),
            memory_fraction=admission_memory_fraction,
            reserve_bytes=admission_reserve_bytes,
            events=runner.events)
        # cluster-wide OOM protection (memory/ClusterMemoryManager.java:88):
        # polls local + worker pools, kills the biggest reserver at the
        # threshold. Only active when the executor runs with a pool.
        self.memory_manager = None
        pool = getattr(runner.executor, "memory_pool", None)
        if pool is not None:
            from presto_tpu.cluster_memory import ClusterMemoryManager
            from presto_tpu.memory import wire_pool_gauges

            wire_pool_gauges(pool)
            self.memory_manager = ClusterMemoryManager(
                pool, self._kill_query, worker_uris=worker_uris,
                threshold=memory_threshold, events=runner.events)
        # cluster fan-in wiring: any SystemConnector already registered
        # in this runner's catalog gets the coordinator's worker polls,
        # so system_metrics grows its per-node rows + cluster rollup
        # and system_memory_pools covers the fleet without the caller
        # wiring callbacks by hand (explicitly injected ones win)
        from presto_tpu.connectors.system import SystemConnector

        for conn in runner.catalog._connectors.values():
            if isinstance(conn, SystemConnector):
                if conn.remote_metrics is None:
                    conn.remote_metrics = self.remote_metrics
                if conn.remote_history is None:
                    conn.remote_history = self.remote_history
                if conn.pools is None:
                    conn.pools = self.memory_pool_rows
                if conn.workers is None:
                    conn.workers = self.worker_rows
        # availability-transition logging for the metrics/memory polls:
        # once per state change, never per poll cycle
        from presto_tpu.net import PollHealth

        self._metrics_poll_health = PollHealth("worker metrics")
        self._memory_poll_health = PollHealth("worker memory")
        self._history_poll_health = PollHealth("worker history")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                # default=str: timestamps/decimals render as ISO strings
                # (the reference's JSON protocol does the same)
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, code: int, body: str) -> None:
                raw = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _authenticated(self) -> bool:
                if outer.authenticator is None:
                    return True
                from presto_tpu.security import (
                    AuthenticationError, parse_basic_auth, parse_bearer_auth,
                )

                header = self.headers.get("Authorization", "")
                token = parse_bearer_auth(header)
                if token is not None \
                        and hasattr(outer.authenticator,
                                    "authenticate_token"):
                    try:
                        outer.authenticator.authenticate_token(token)
                        return True
                    except AuthenticationError:
                        pass
                got = parse_basic_auth(header)
                if got is not None:
                    try:
                        outer.authenticator.authenticate(*got)
                        return True
                    except AuthenticationError:
                        pass
                self.send_response(401)
                self.send_header("WWW-Authenticate", "Basic realm=\"presto\"")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return False

            def do_POST(self):
                if self.path != "/v1/statement":
                    self._json(404, {"error": "not found"})
                    return
                if not self._authenticated():
                    return
                n = int(self.headers.get("Content-Length", "0"))
                sql = self.rfile.read(n).decode()
                q = outer._submit(
                    sql,
                    trace_token=self.headers.get("X-Presto-Trace-Token"))
                # X-Presto-Async: the reference protocol's real shape —
                # return immediately with state + progress; the client
                # polls nextUri until the state is terminal.  Without
                # the header the legacy blocking behavior is kept.
                if self.headers.get("X-Presto-Async"):
                    q.done.wait(timeout=0.05)  # fast queries: one page
                else:
                    # config-driven long-poll bound (was a magic 600):
                    # with a deadline set, the deadline killer fires
                    # within limit+grace, so the wait below always
                    # returns a terminal (or pollable) page — a
                    # deadline-exceeding query can never hang the POST
                    q.done.wait(timeout=outer._blocking_wait(q))
                self._json(200, outer._page_response(q, 0))

            def do_GET(self):
                parts = [p for p in self.path.split("/")
                         if p and not p.startswith("?")]
                if parts and parts[-1].split("?")[0] == "metrics" \
                        and parts[0] == "v1" and len(parts) == 2:
                    # OpenMetrics exposition (Prometheus scrape target);
                    # ?format=json serves the machine-to-machine form
                    from presto_tpu.obs import openmetrics

                    if "format=json" in self.path:
                        self._json(200, openmetrics.json_form("local"))
                    else:
                        body = openmetrics.render().encode()
                        self.send_response(200)
                        self.send_header("Content-Type",
                                         openmetrics.CONTENT_TYPE)
                        self.send_header("Content-Length", str(len(body)))
                        self.end_headers()
                        self.wfile.write(body)
                    return
                if len(parts) == 3 and parts[:2] == ["v1", "metrics"] \
                        and parts[2].split("?")[0] == "history":
                    # cluster metrics history: the local ring plus every
                    # worker's, keyed by node (system_metrics_history's
                    # HTTP twin)
                    self._json(200, outer.metrics_history())
                    return
                if parts == ["v1", "info"]:
                    self._json(200, {
                        "nodeVersion": {"version": __version__},
                        "coordinator": True,
                        "state": "ACTIVE",
                    })
                    return
                if parts == ["v1", "query"]:
                    with outer._lock:
                        self._json(200, [q.summary() for q in outer.queries.values()])
                    return
                if parts == ["v1", "cluster"]:
                    self._json(200, outer._cluster_stats())
                    return
                if parts == ["v1", "worker"]:
                    # failure-detector view of the worker fleet (feeds
                    # the web UI worker list; same rows as the
                    # system_runtime_workers table)
                    self._json(200, outer.worker_rows())
                    return
                if parts in ([], ["ui"]):
                    self._html(200, _UI_HTML)
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "query"] \
                        and parts[3] == "progress":
                    # live stage table + monotone percentage for the
                    # web UI's detail view and external pollers
                    from presto_tpu import obs

                    prog = obs.progress_for(parts[2])
                    if prog is None:
                        self._json(404, {"error": "no progress for query "
                                                  f"{parts[2]}"})
                        return
                    self._json(200, prog.snapshot())
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "query"] \
                        and parts[3] == "trace":
                    # per-query Chrome-trace JSON (open in Perfetto /
                    # chrome://tracing); works by query id or trace token
                    from presto_tpu import obs

                    tracer = obs.lookup(parts[2])
                    if tracer is None:
                        self._json(404, {"error": "no trace for query "
                                                  f"{parts[2]} (enable the "
                                                  "trace session property)"})
                        return
                    self._json(200, obs.chrome_trace(tracer))
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "query"] \
                        and parts[3] == "timeline":
                    # per-query resource timeline (obs/timeseries.py):
                    # bounded (ts_ms, metric, value) points + the
                    # annotation dict the doctor consumes
                    from presto_tpu import obs

                    tl = obs.timeline_for(parts[2])
                    if tl is None:
                        self._json(404, {"error": "no timeline for "
                                                  f"query {parts[2]}"})
                        return
                    self._json(200, tl.snapshot())
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "query"] \
                        and parts[3] == "operators":
                    # per-operator est/actual rows annotated at query
                    # completion (SET SESSION collect_stats = true) —
                    # the web UI's operator detail table
                    from presto_tpu import obs

                    tl = obs.timeline_for(parts[2])
                    ops = tl.annotation("operators") if tl is not None \
                        else None
                    if ops is None:
                        self._json(404, {"error": "no operator stats for "
                                                  f"query {parts[2]} (SET "
                                                  "SESSION collect_stats "
                                                  "= true)"})
                        return
                    self._json(200, {"queryId": parts[2],
                                     "operators": ops})
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "query"] \
                        and parts[3] == "doctor":
                    # post-query diagnosis: findings stored at
                    # completion, else a fresh run over the registries
                    from presto_tpu import obs

                    if obs.timeline_for(parts[2]) is None \
                            and obs.lookup(parts[2]) is None \
                            and obs.progress_for(parts[2]) is None:
                        self._json(404, {"error": "no telemetry for "
                                                  f"query {parts[2]}"})
                        return
                    self._json(200, obs.doctor.report(parts[2]))
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "statement"]:
                    qid, token = parts[2], int(parts[3])
                    q = outer.queries.get(qid)
                    if q is None:
                        self._json(404, {"error": "unknown query"})
                        return
                    # async pollers re-fetch the same token while the
                    # query runs; a short wait turns a hot poll loop
                    # into a long-poll without delaying finished pages
                    if not q.done.is_set():
                        q.done.wait(timeout=0.3)
                    self._json(200, outer._page_response(q, token))
                    return
                self._json(404, {"error": "not found"})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) >= 3 and parts[:2] == ["v1", "statement"]:
                    q = outer.queries.get(parts[2])
                    if q is not None:
                        with outer._lock:
                            if q.state in ("QUEUED", "RUNNING"):
                                q.state = "CANCELED"
                                q.done.set()
                        # a queued victim's memory-gate wait exits at
                        # its next wakeup instead of running its bound,
                        # and a RUNNING victim's slot + projected bytes
                        # free immediately (once-only, same as a kill)
                        # rather than when the zombie thread unwinds
                        outer.admission.cancel(q.id)
                        outer._release_group(q)
                    self._json(204, {})
                    return
                self._json(404, {"error": "not found"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True,
                                        name="coordinator-http")

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread.start()
        if self.memory_manager is not None:
            self.memory_manager.start()
        if self.worker_uris:
            self.failure_detector.start()
        # serving processes keep a metrics-history ring by default
        # (1s cadence unless PRESTO_TPU_METRICS_HISTORY_MS overrides);
        # only the server that armed the process singleton stops it
        from presto_tpu.obs.timeseries import HISTORY

        with self._lock:
            self._history_owner = (not HISTORY.running
                                   and HISTORY.start(default_ms=1000))

    def stop(self, drain_timeout: float = 30.0) -> None:
        self.failure_detector.stop()
        if self.memory_manager is not None:
            self.memory_manager.stop()
        from presto_tpu.obs.timeseries import HISTORY

        with self._lock:
            owner = getattr(self, "_history_owner", False)
            self._history_owner = False
        if owner:
            HISTORY.stop()
        if self._thread.is_alive():  # shutdown() blocks unless serving
            self.httpd.shutdown()
        self.httpd.server_close()
        # drain in-flight computation threads: cancellation is
        # cooperative (the thread discards its result but runs to the
        # end), and its per-query pool reservations release only at
        # completion — a stop() that abandons them leaks reservations
        # into whatever runs next in the process
        deadline = time.monotonic() + drain_timeout
        with self._lock:
            pending = [q.thread for q in self.queries.values()
                       if q.thread is not None]
        for t in pending:
            t.join(max(0.0, deadline - time.monotonic()))

    def _release_group(self, q: _QueryState) -> None:
        """Release a query's admission ticket EXACTLY once — callable
        from both the computation thread's finally and a killer (the
        deadline timer / memory manager), so a killed query frees its
        slot immediately instead of holding it until the cooperative
        thread unwinds.  The zombie thread may briefly run past the
        group's concurrency limit; that window is the same one the
        cooperative memory-kill protocol already accepts.  (The
        controller's release is itself once-only and additionally wakes
        memory-gate waiters — a finished query is when headroom
        reappears.)"""
        with self._lock:
            ticket = q.ticket
        self.admission.release(ticket)

    def _kill_query(self, qid: str) -> None:
        """LowMemoryKiller action: cancel through the normal state path
        (the computation thread discards its result on completion)."""
        q = self.queries.get(qid)
        if q is not None:
            with self._lock:
                if q.state in ("QUEUED", "RUNNING"):
                    q.state = "CANCELED"
                    q.error = "query killed by the cluster memory manager"
                    q.done.set()
            # a victim still waiting at the memory gate exits at its
            # next wakeup instead of holding its group slot for the
            # rest of the queue bound (same as the DELETE path)
            self.admission.cancel(qid)
            self._release_group(q)

    # -- deadlines ------------------------------------------------------
    def _effective_deadline(self) -> float:
        """Seconds a query may run: the ``query_max_execution_time``
        session property when set, else the coordinator's
        ``query.max-execution-time`` config default (0 = none)."""
        from presto_tpu.config import parse_duration

        try:
            prop = str(self.runner.session.get("query_max_execution_time"))
        except KeyError:
            prop = ""
        if prop.strip():
            return parse_duration(prop, self.max_execution_time)
        return self.max_execution_time

    def _blocking_wait(self, q: _QueryState) -> Optional[float]:
        """Bound for the legacy blocking POST: deadline + grace when
        that is tighter (the killer resolves the query within it),
        capped at the protocol's 600s long-poll bound — either way the
        response always arrives, carrying nextUri for a query still
        queued or running, so clients with their own socket timeouts
        (StatementClient's 650s default) never starve."""
        # prefer the limit the killer was actually ARMED with (set when
        # the query went RUNNING); fall back to the session-derived
        # value for still-queued queries
        limit = (q.deadline_s if q.deadline_s is not None
                 else self._effective_deadline())
        if limit and limit > 0:
            return min(600.0, limit + self.deadline_grace)
        return 600.0

    def _deadline_kill(self, q: _QueryState, limit: float) -> None:
        """Timer action at deadline expiry: fail the statement with
        EXCEEDED_TIME_LIMIT, free the query's memory reservations
        (poisoning future ones, so the computation thread unwinds at
        its next reservation), and emit the kill event."""
        with self._lock:
            if q.state != "RUNNING":
                return
            q.state = "FAILED"
            q.error_code = "EXCEEDED_TIME_LIMIT"
            q.error = (f"Query exceeded the maximum execution time of "
                       f"{limit:g}s (EXCEEDED_TIME_LIMIT)")
        pool = getattr(self.runner.executor, "memory_pool", None)
        if pool is not None:
            pool.kill_query(q.id)
        from presto_tpu.obs import METRICS

        METRICS.counter("query.killed_deadline").inc()
        elapsed = (round(time.monotonic() - q.t_running, 3)
                   if q.t_running is not None else None)
        try:
            from presto_tpu.events import QueryKilledEvent

            self.runner.events.query_killed(QueryKilledEvent(
                query_id=q.id, reason="EXCEEDED_TIME_LIMIT",
                message=q.error, limit_s=limit, elapsed_s=elapsed,
                kill_time=time.time()))
        except Exception:
            pass  # telemetry must never block the kill
        self._release_group(q)
        q.done.set()

    def worker_rows(self) -> List[dict]:
        """Failure-detector rows for /v1/worker and the
        system_runtime_workers table (NULL-safe columns)."""
        return self.failure_detector.snapshot()

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # ------------------------------------------------------------------
    def _submit(self, sql: str,
                trace_token: Optional[str] = None) -> _QueryState:
        qid = uuid.uuid4().hex[:16]
        q = _QueryState(qid, sql)
        q.trace_token = trace_token
        with self._lock:
            self.queries[qid] = q

        def run():
            from presto_tpu.resource_groups import QueryQueueFullError

            try:
                prio = int(self.runner.session.get("query_priority"))
            except Exception:
                prio = 0
            # the memory-aware admission gate (serving/admission.py):
            # group concurrency + queue quota + projected pool
            # headroom, bounded by query.max-queued-time.  Rejections
            # keep distinct statement error codes (QUERY_QUEUE_FULL /
            # EXCEEDED_QUEUE_TIME) instead of a generic failure.
            try:
                ticket = self.admission.admit(
                    q.id, self.runner.session.user, priority=prio,
                    timeout=(self.max_queued_time
                             if self.max_queued_time > 0 else None),
                    statement_key=sql)
                with self._lock:
                    q.ticket = ticket
            except QueryQueueFullError as e:
                self._admission_failed(q, "QUERY_QUEUE_FULL", e)
                return
            except TimeoutError as e:
                self._admission_failed(q, "EXCEEDED_QUEUE_TIME", e)
                return
            except Exception as e:
                self._admission_failed(q, None, e)
                return
            with self._lock:
                if q.state != "QUEUED":  # canceled while queued
                    pass  # fall through to the release below
                else:
                    q.state = "RUNNING"
                    q.t_running = time.monotonic()
            if q.state != "RUNNING":
                self._release_group(q)
                q.done.set()
                return
            # deadline enforcement (query.max-execution-time config /
            # query_max_execution_time session property): the killer
            # fails the statement, frees the query's memory
            # reservations and emits QueryKilledEvent with reason
            # EXCEEDED_TIME_LIMIT
            limit = self._effective_deadline()
            timer = None
            if limit > 0:
                q.deadline_s = limit
                timer = threading.Timer(
                    limit, self._deadline_kill, args=(q, limit))
                timer.daemon = True
                timer.start()
            try:
                res = self.runner.execute(sql, query_id=q.id,
                                          trace_token=q.trace_token)
                cols = [
                    {"name": n, "type": repr(t)} for n, t in zip(res.names, res.types)
                ]
                # per-run outcome rides the result object — reading the
                # shared runner._dist here would let concurrent queries
                # report each other's stats
                q.dist_stages = getattr(res, "dist_stages", None)
                q.dist_fallback = getattr(res, "dist_fallback", None)
                q.planning_ms = getattr(res, "planning_ms", None)
                q.compile_ms = getattr(res, "compile_ms", None)
                q.execution_ms = getattr(res, "execution_ms", None)
                q.cache_hit = getattr(res, "cache_hit", None)
                q.queued_ms = getattr(res, "queued_ms", None)
                q.memory_blocked_ms = getattr(res, "memory_blocked_ms",
                                              None)
                q.findings = getattr(res, "findings", None)
                # observed peak feeds the admission controller's memory
                # projection for the NEXT run of this statement
                self.admission.record_peak(
                    sql, getattr(res, "peak_bytes", 0) or 0)
                # CANCELED is terminal: a DELETE that raced this query's
                # completion must not be resurrected to FINISHED/FAILED
                with self._lock:
                    if q.state == "RUNNING":
                        q.columns = cols
                        q.rows = res.rows
                        q.state = "FINISHED"
            except Exception as e:  # surfaces to the client as error
                with self._lock:
                    if q.state == "RUNNING":
                        q.error = f"{type(e).__name__}: {e}"
                        q.state = "FAILED"
            finally:
                if timer is not None:
                    timer.cancel()
                self._release_group(q)
                q.done.set()

        t = threading.Thread(target=run, daemon=True,
                             name=f"query-{q.id}")
        t.start()  # started before publication: stop() joins safely
        with self._lock:
            q.thread = t
        return q

    def _admission_failed(self, q: _QueryState, code: Optional[str],
                          e: Exception) -> None:
        """Fail a statement at the admission gate with its policy error
        code, emitting the kill-decision event so the query log records
        WHY the query never ran (queue full vs queue-time expiry)."""
        with self._lock:
            if q.state == "QUEUED":
                q.error = f"{type(e).__name__}: {e}"
                q.error_code = code
                q.state = "FAILED"
        if code is not None:
            try:
                from presto_tpu.events import QueryKilledEvent

                self.runner.events.query_killed(QueryKilledEvent(
                    query_id=q.id, reason=code, message=str(e),
                    limit_s=(self.max_queued_time
                             if code == "EXCEEDED_QUEUE_TIME" else None),
                    elapsed_s=None, kill_time=time.time()))
            except Exception:
                pass  # telemetry must never mask the failure
        q.done.set()

    def _cluster_stats(self) -> dict:
        """ClusterStatsResource analog (feeds the web UI tiles)."""
        with self._lock:
            states = [q.state for q in self.queries.values()]
        out = {
            "runningQueries": states.count("RUNNING"),
            "queuedQueries": states.count("QUEUED"),
            "finishedQueries": states.count("FINISHED"),
            "failedQueries": states.count("FAILED") + states.count("CANCELED"),
        }
        pool = getattr(self.runner.executor, "memory_pool", None)
        if pool is not None:
            out["reservedBytes"] = pool.reserved
            out["totalBytes"] = pool.limit
        return out

    def _page_response(self, q: _QueryState, token: int) -> dict:
        out = {
            "id": q.id,
            "columns": q.columns,
            "stats": {"state": q.state, "rows": len(q.rows)},
        }
        if q.dist_stages is not None:
            out["stats"]["distStages"] = q.dist_stages
        if q.dist_fallback is not None:
            out["stats"]["distFallback"] = q.dist_fallback
        # per-stage lifecycle times (sourced from the obs spans; NULL
        # keys simply absent, matching distStages' convention)
        if q.planning_ms is not None:
            out["stats"]["planningMs"] = q.planning_ms
        if q.compile_ms is not None:
            out["stats"]["compileMs"] = q.compile_ms
        if q.execution_ms is not None:
            out["stats"]["executionMs"] = q.execution_ms
        # serving tier: result provenance (structural result cache)
        if q.cache_hit is not None:
            out["stats"]["cacheHit"] = q.cache_hit
        # admission-plane waits (mirrors system_runtime_queries'
        # queued_ms/memory_blocked_ms columns; absent when NULL)
        if q.queued_ms is not None:
            out["stats"]["queuedMs"] = q.queued_ms
        if q.memory_blocked_ms is not None:
            out["stats"]["memoryBlockedMs"] = q.memory_blocked_ms
        # live queue position while waiting for admission (1-based;
        # also cached on the state object for /v1/query summaries)
        if q.state == "QUEUED":
            pos = self.admission.queue_position(q.id)
            q.queue_position = pos
            if pos is not None:
                out["stats"]["queuePosition"] = pos
        # Presto-style live progress (StatementStats.progressPercentage
        # + a per-stage split table).  Monotone by construction: the
        # progress object reports a running maximum, and a FINISHED
        # query always reads 100.
        from presto_tpu import obs

        prog = obs.progress_for(q.id)
        if q.state == "FINISHED":
            out["stats"]["progressPercentage"] = 100.0
        elif prog is not None:
            out["stats"]["progressPercentage"] = prog.percentage()
        if prog is not None:
            snap = prog.snapshot()
            out["stats"]["stages"] = snap["stages"]
            out["stats"]["elapsedMs"] = snap["elapsedMs"]
        if q.error:
            out["error"] = q.error
            # distinct statement error codes for policy failures
            # (QUERY_QUEUE_FULL / EXCEEDED_QUEUE_TIME /
            # EXCEEDED_TIME_LIMIT); generic failures carry none
            if q.error_code is not None:
                out["errorCode"] = q.error_code
            return out
        if q.state in ("QUEUED", "RUNNING"):
            # async page: no data yet — the client re-polls this token
            out["nextUri"] = f"{self.uri}/v1/statement/{q.id}/{token}"
            return out
        start = token * PAGE_ROWS
        chunk = q.rows[start : start + PAGE_ROWS]
        out["data"] = [list(r) for r in chunk]
        if start + PAGE_ROWS < len(q.rows):
            out["nextUri"] = f"{self.uri}/v1/statement/{q.id}/{token + 1}"
        return out

    # ------------------------------------------------------------------
    def remote_metrics(self) -> Dict[str, List]:
        """Poll every worker's ``/v1/metrics?format=json`` concurrently
        (net.poll_each; failures are classified, counted and
        transition-logged there — a dead worker's liveness itself is
        the failure detector's job) — the fan-in behind
        system_metrics' per-node rows and cluster rollup."""
        from presto_tpu.net import poll_each, request_json

        payloads = poll_each(
            self.worker_uris,
            lambda uri: request_json(
                f"{uri}/v1/metrics?format=json", timeout=2.0,
                site="cluster.metrics_poll_errors"),
            health=self._metrics_poll_health)
        return {
            payload.get("node") or uri: [
                (n, float(v)) for n, v in payload.get("metrics", [])]
            for uri, payload in payloads.items()
        }

    def remote_history(self) -> Dict[str, List]:
        """Poll every worker's ``/v1/metrics/history`` concurrently —
        the fan-in behind system_metrics_history's per-node rows and
        the coordinator's merged history endpoint."""
        from presto_tpu.net import poll_each, request_json

        payloads = poll_each(
            self.worker_uris,
            lambda uri: request_json(
                f"{uri}/v1/metrics/history", timeout=2.0,
                site="cluster.metrics_poll_errors"),
            health=self._history_poll_health)
        return {
            payload.get("node") or uri: [
                (float(ts), str(n), float(v))
                for ts, n, v in payload.get("rows", [])]
            for uri, payload in payloads.items()
        }

    def metrics_history(self) -> dict:
        """``GET /v1/metrics/history``: the local ring plus every
        worker's, keyed by node id (the cluster-merged twin of the
        worker endpoint's single-node body)."""
        from presto_tpu.obs.timeseries import HISTORY

        nodes: Dict[str, List] = {
            "local": [[ts, n, v] for ts, n, v in HISTORY.rows()]}
        if self.worker_uris:
            for node, rows in self.remote_history().items():
                nodes[node] = [[ts, n, v] for ts, n, v in rows]
        return {"intervalMs": HISTORY.interval_ms, "nodes": nodes}

    def memory_pool_rows(self) -> List[dict]:
        """system_memory_pools rows for this cluster: the local pool +
        every worker's ``/v1/info`` memory section (net.poll_each —
        same classification/transition-log contract as the metrics
        poll)."""
        from presto_tpu.connectors.system import pool_row
        from presto_tpu.net import poll_each, request_json

        rows: List[dict] = []
        pool = getattr(self.runner.executor, "memory_pool", None)
        if pool is not None:
            rows.append(pool_row("local", pool))
        infos = poll_each(
            self.worker_uris,
            lambda uri: request_json(f"{uri}/v1/info", timeout=2.0,
                                     site="cluster.memory_poll_errors"),
            health=self._memory_poll_health)
        for uri, info in infos.items():
            mem = info.get("memory") or {}
            rows.append({
                "node": uri,
                "reserved": int(mem.get("reserved", 0)),
                "peak": int(mem.get("peak", 0)),
                "limit": int(mem.get("limit", 0)),
                "queries": len(mem.get("query_reservations") or {}),
            })
        return rows
