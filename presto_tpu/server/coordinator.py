"""Coordinator REST server.

Reference analog: the V1 statement protocol —
``server/protocol/StatementResource.java:82`` (POST /v1/statement
creates a query; results are paged via GET nextUri with token
acknowledgement; DELETE cancels) plus the info/status resources
(``server/ServerInfoResource``, ``QueryResource``).  stdlib
http.server stands in for airlift/jetty; query execution runs on a
worker thread per query with paged result buffers.

Protocol (JSON):
  POST /v1/statement            body = SQL
  GET  /v1/statement/{id}/{tok} next page
  DELETE /v1/statement/{id}     cancel
  GET  /v1/info                 server info
  GET  /v1/query                finished/running query summaries
Responses carry: id, columns [{name, type}], data [[row...]...],
stats {state, rows}, error?, nextUri?.
"""

from __future__ import annotations

import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from presto_tpu import __version__
from presto_tpu.runner import QueryRunner

PAGE_ROWS = 1000


class _QueryState:
    def __init__(self, qid: str, sql: str):
        self.id = qid
        self.sql = sql
        self.state = "QUEUED"  # QUEUED -> RUNNING -> FINISHED | FAILED | CANCELED
        self.columns: List[dict] = []
        self.rows: List[tuple] = []
        self.error: Optional[str] = None
        self.done = threading.Event()

    def summary(self) -> dict:
        return {
            "id": self.id,
            "query": self.sql,
            "state": self.state,
            "rows": len(self.rows),
        }


class CoordinatorServer:
    """Embeds a QueryRunner behind the REST protocol.  Queries run on
    daemon threads (the coordinator's query-execution pool); the state
    machine mirrors QueryState.java:21 (trimmed to the states a
    single-process coordinator hits)."""

    def __init__(self, runner: QueryRunner, host: str = "127.0.0.1", port: int = 0,
                 resource_groups=None):
        from presto_tpu.resource_groups import ResourceGroupManager

        self.runner = runner
        self.queries: Dict[str, _QueryState] = {}
        self.resource_groups = resource_groups or ResourceGroupManager()
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                if self.path != "/v1/statement":
                    self._json(404, {"error": "not found"})
                    return
                n = int(self.headers.get("Content-Length", "0"))
                sql = self.rfile.read(n).decode()
                q = outer._submit(sql)
                q.done.wait(timeout=600)
                self._json(200, outer._page_response(q, 0))

            def do_GET(self):
                parts = [p for p in self.path.split("/") if p]
                if parts == ["v1", "info"]:
                    self._json(200, {
                        "nodeVersion": {"version": __version__},
                        "coordinator": True,
                        "state": "ACTIVE",
                    })
                    return
                if parts == ["v1", "query"]:
                    with outer._lock:
                        self._json(200, [q.summary() for q in outer.queries.values()])
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "statement"]:
                    qid, token = parts[2], int(parts[3])
                    q = outer.queries.get(qid)
                    if q is None:
                        self._json(404, {"error": "unknown query"})
                        return
                    self._json(200, outer._page_response(q, token))
                    return
                self._json(404, {"error": "not found"})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) >= 3 and parts[:2] == ["v1", "statement"]:
                    q = outer.queries.get(parts[2])
                    if q is not None:
                        with outer._lock:
                            if q.state in ("QUEUED", "RUNNING"):
                                q.state = "CANCELED"
                                q.done.set()
                    self._json(204, {})
                    return
                self._json(404, {"error": "not found"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # ------------------------------------------------------------------
    def _submit(self, sql: str) -> _QueryState:
        qid = uuid.uuid4().hex[:16]
        q = _QueryState(qid, sql)
        with self._lock:
            self.queries[qid] = q

        def run():
            group = self.resource_groups.group_for(self.runner.session.user)
            try:
                group.acquire(timeout=600)
            except Exception as e:
                with self._lock:
                    if q.state == "QUEUED":
                        q.error = f"{type(e).__name__}: {e}"
                        q.state = "FAILED"
                q.done.set()
                return
            with self._lock:
                if q.state != "QUEUED":  # canceled while queued
                    group.release()
                    q.done.set()
                    return
                q.state = "RUNNING"
            try:
                res = self.runner.execute(sql)
                cols = [
                    {"name": n, "type": repr(t)} for n, t in zip(res.names, res.types)
                ]
                # CANCELED is terminal: a DELETE that raced this query's
                # completion must not be resurrected to FINISHED/FAILED
                with self._lock:
                    if q.state == "RUNNING":
                        q.columns = cols
                        q.rows = res.rows
                        q.state = "FINISHED"
            except Exception as e:  # surfaces to the client as error
                with self._lock:
                    if q.state == "RUNNING":
                        q.error = f"{type(e).__name__}: {e}"
                        q.state = "FAILED"
            finally:
                group.release()
                q.done.set()

        threading.Thread(target=run, daemon=True).start()
        return q

    def _page_response(self, q: _QueryState, token: int) -> dict:
        out = {
            "id": q.id,
            "columns": q.columns,
            "stats": {"state": q.state, "rows": len(q.rows)},
        }
        if q.error:
            out["error"] = q.error
            return out
        start = token * PAGE_ROWS
        chunk = q.rows[start : start + PAGE_ROWS]
        out["data"] = [list(r) for r in chunk]
        if start + PAGE_ROWS < len(q.rows):
            out["nextUri"] = f"{self.uri}/v1/statement/{q.id}/{token + 1}"
        return out
