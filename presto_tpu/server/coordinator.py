"""Coordinator REST server.

Reference analog: the V1 statement protocol —
``server/protocol/StatementResource.java:82`` (POST /v1/statement
creates a query; results are paged via GET nextUri with token
acknowledgement; DELETE cancels) plus the info/status resources
(``server/ServerInfoResource``, ``QueryResource``).  stdlib
http.server stands in for airlift/jetty; query execution runs on a
worker thread per query with paged result buffers.

Protocol (JSON):
  POST /v1/statement            body = SQL
  GET  /v1/statement/{id}/{tok} next page
  DELETE /v1/statement/{id}     cancel
  GET  /v1/info                 server info
  GET  /v1/query                finished/running query summaries
Responses carry: id, columns [{name, type}], data [[row...]...],
stats {state, rows}, error?, nextUri?.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from presto_tpu import __version__
from presto_tpu.runner import QueryRunner

PAGE_ROWS = 1000

# Minimal cluster console (the reference serves a React app from
# presto-main/src/main/resources/webapp/; this single inline page covers
# the same first-stop view — cluster tiles + live query list — from the
# same REST resources).
_UI_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>presto-tpu</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#16181d;color:#e8e8e8}
 h1{font-size:1.3rem} .tiles{display:flex;gap:1rem;margin:1rem 0}
 .tile{background:#23262e;border-radius:8px;padding:1rem 1.5rem;min-width:8rem}
 .tile .v{font-size:1.8rem;font-weight:600} .tile .l{color:#9aa0ab;font-size:.8rem}
 table{border-collapse:collapse;width:100%;margin-top:1rem}
 th,td{text-align:left;padding:.4rem .6rem;border-bottom:1px solid #2e323b;font-size:.85rem}
 th{color:#9aa0ab;font-weight:500}
 .FINISHED{color:#6fcf97}.RUNNING{color:#56ccf2}.FAILED,.CANCELED{color:#eb5757}
 .QUEUED{color:#f2c94c} td.q{font-family:ui-monospace,monospace;max-width:40rem;
 overflow:hidden;text-overflow:ellipsis;white-space:nowrap}
</style></head><body>
<h1>presto-tpu cluster console</h1>
<div class="tiles" id="tiles"></div>
<table><thead><tr><th>query id</th><th>state</th><th>rows</th><th>sql</th></tr></thead>
<tbody id="queries"></tbody></table>
<script>
async function refresh(){
  const c = await (await fetch('/v1/cluster')).json();
  document.getElementById('tiles').innerHTML =
    ['runningQueries','queuedQueries','finishedQueries','failedQueries']
    .map(k=>`<div class="tile"><div class="v">${c[k]??0}</div><div class="l">${k.replace('Queries',' queries')}</div></div>`).join('')
    + (c.totalBytes?`<div class="tile"><div class="v">${(100*c.reservedBytes/c.totalBytes).toFixed(1)}%</div><div class="l">pool reserved</div></div>`:'');
  const qs = await (await fetch('/v1/query')).json();
  document.getElementById('queries').innerHTML = qs.reverse().map(q=>
    `<tr><td>${q.id}</td><td class="${q.state}">${q.state}</td><td>${q.rows}</td><td class="q">${q.query.replace(/</g,'&lt;')}</td></tr>`).join('');
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


class _QueryState:
    def __init__(self, qid: str, sql: str):
        self.id = qid
        self.sql = sql
        self.state = "QUEUED"  # QUEUED -> RUNNING -> FINISHED | FAILED | CANCELED
        self.columns: List[dict] = []
        self.rows: List[tuple] = []
        self.error: Optional[str] = None
        self.done = threading.Event()
        # the computation thread: outlives `done` on cancel (DELETE
        # sets done to unblock the client; the thread runs to the end)
        self.thread: Optional[threading.Thread] = None
        # distributed-tier outcome: stage count and (when the query
        # silently ran locally) the fallback reason — surfaced in the
        # statement-protocol stats so clients see fallbacks without
        # querying system_runtime_queries
        self.dist_stages: Optional[int] = None
        self.dist_fallback: Optional[str] = None
        # lifecycle stage times from the obs span spine (NULL-safe)
        self.planning_ms: Optional[float] = None
        self.compile_ms: Optional[float] = None
        self.execution_ms: Optional[float] = None
        # client-supplied request correlation (X-Presto-Trace-Token)
        self.trace_token: Optional[str] = None

    def summary(self) -> dict:
        return {
            "id": self.id,
            "query": self.sql,
            "state": self.state,
            "rows": len(self.rows),
        }


class CoordinatorServer:
    """Embeds a QueryRunner behind the REST protocol.  Queries run on
    daemon threads (the coordinator's query-execution pool); the state
    machine mirrors QueryState.java:21 (trimmed to the states a
    single-process coordinator hits)."""

    def __init__(self, runner: QueryRunner, host: str = "127.0.0.1", port: int = 0,
                 resource_groups=None, worker_uris=(), memory_threshold: float = 0.95,
                 authenticator=None):
        from presto_tpu.resource_groups import ResourceGroupManager

        # optional PasswordAuthenticator (server/security + the
        # password-authenticator plugins): HTTP Basic on /v1/statement
        self.authenticator = authenticator
        self.runner = runner
        self.queries: Dict[str, _QueryState] = {}
        self.resource_groups = resource_groups or ResourceGroupManager()
        self._lock = threading.Lock()
        # cluster-wide OOM protection (memory/ClusterMemoryManager.java:88):
        # polls local + worker pools, kills the biggest reserver at the
        # threshold. Only active when the executor runs with a pool.
        self.memory_manager = None
        pool = getattr(runner.executor, "memory_pool", None)
        if pool is not None:
            from presto_tpu.cluster_memory import ClusterMemoryManager

            self.memory_manager = ClusterMemoryManager(
                pool, self._kill_query, worker_uris=worker_uris,
                threshold=memory_threshold)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _json(self, code: int, obj) -> None:
                # default=str: timestamps/decimals render as ISO strings
                # (the reference's JSON protocol does the same)
                body = json.dumps(obj, default=str).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _html(self, code: int, body: str) -> None:
                raw = body.encode()
                self.send_response(code)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(raw)))
                self.end_headers()
                self.wfile.write(raw)

            def _authenticated(self) -> bool:
                if outer.authenticator is None:
                    return True
                from presto_tpu.security import (
                    AuthenticationError, parse_basic_auth, parse_bearer_auth,
                )

                header = self.headers.get("Authorization", "")
                token = parse_bearer_auth(header)
                if token is not None \
                        and hasattr(outer.authenticator,
                                    "authenticate_token"):
                    try:
                        outer.authenticator.authenticate_token(token)
                        return True
                    except AuthenticationError:
                        pass
                got = parse_basic_auth(header)
                if got is not None:
                    try:
                        outer.authenticator.authenticate(*got)
                        return True
                    except AuthenticationError:
                        pass
                self.send_response(401)
                self.send_header("WWW-Authenticate", "Basic realm=\"presto\"")
                self.send_header("Content-Length", "0")
                self.end_headers()
                return False

            def do_POST(self):
                if self.path != "/v1/statement":
                    self._json(404, {"error": "not found"})
                    return
                if not self._authenticated():
                    return
                n = int(self.headers.get("Content-Length", "0"))
                sql = self.rfile.read(n).decode()
                q = outer._submit(
                    sql,
                    trace_token=self.headers.get("X-Presto-Trace-Token"))
                q.done.wait(timeout=600)
                self._json(200, outer._page_response(q, 0))

            def do_GET(self):
                parts = [p for p in self.path.split("/") if p]
                if parts == ["v1", "info"]:
                    self._json(200, {
                        "nodeVersion": {"version": __version__},
                        "coordinator": True,
                        "state": "ACTIVE",
                    })
                    return
                if parts == ["v1", "query"]:
                    with outer._lock:
                        self._json(200, [q.summary() for q in outer.queries.values()])
                    return
                if parts == ["v1", "cluster"]:
                    self._json(200, outer._cluster_stats())
                    return
                if parts in ([], ["ui"]):
                    self._html(200, _UI_HTML)
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "query"] \
                        and parts[3] == "trace":
                    # per-query Chrome-trace JSON (open in Perfetto /
                    # chrome://tracing); works by query id or trace token
                    from presto_tpu import obs

                    tracer = obs.lookup(parts[2])
                    if tracer is None:
                        self._json(404, {"error": "no trace for query "
                                                  f"{parts[2]} (enable the "
                                                  "trace session property)"})
                        return
                    self._json(200, obs.chrome_trace(tracer))
                    return
                if len(parts) == 4 and parts[:2] == ["v1", "statement"]:
                    qid, token = parts[2], int(parts[3])
                    q = outer.queries.get(qid)
                    if q is None:
                        self._json(404, {"error": "unknown query"})
                        return
                    self._json(200, outer._page_response(q, token))
                    return
                self._json(404, {"error": "not found"})

            def do_DELETE(self):
                parts = [p for p in self.path.split("/") if p]
                if len(parts) >= 3 and parts[:2] == ["v1", "statement"]:
                    q = outer.queries.get(parts[2])
                    if q is not None:
                        with outer._lock:
                            if q.state in ("QUEUED", "RUNNING"):
                                q.state = "CANCELED"
                                q.done.set()
                    self._json(204, {})
                    return
                self._json(404, {"error": "not found"})

        self.httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(target=self.httpd.serve_forever, daemon=True)

    # ------------------------------------------------------------------
    def start(self) -> None:
        self._thread.start()
        if self.memory_manager is not None:
            self.memory_manager.start()

    def stop(self, drain_timeout: float = 30.0) -> None:
        if self.memory_manager is not None:
            self.memory_manager.stop()
        if self._thread.is_alive():  # shutdown() blocks unless serving
            self.httpd.shutdown()
        self.httpd.server_close()
        # drain in-flight computation threads: cancellation is
        # cooperative (the thread discards its result but runs to the
        # end), and its per-query pool reservations release only at
        # completion — a stop() that abandons them leaks reservations
        # into whatever runs next in the process
        deadline = time.monotonic() + drain_timeout
        with self._lock:
            pending = [q.thread for q in self.queries.values()
                       if q.thread is not None]
        for t in pending:
            t.join(max(0.0, deadline - time.monotonic()))

    def _kill_query(self, qid: str) -> None:
        """LowMemoryKiller action: cancel through the normal state path
        (the computation thread discards its result on completion)."""
        q = self.queries.get(qid)
        if q is not None:
            with self._lock:
                if q.state in ("QUEUED", "RUNNING"):
                    q.state = "CANCELED"
                    q.error = "query killed by the cluster memory manager"
                    q.done.set()

    @property
    def uri(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # ------------------------------------------------------------------
    def _submit(self, sql: str,
                trace_token: Optional[str] = None) -> _QueryState:
        qid = uuid.uuid4().hex[:16]
        q = _QueryState(qid, sql)
        q.trace_token = trace_token
        with self._lock:
            self.queries[qid] = q

        def run():
            group = self.resource_groups.group_for(self.runner.session.user)
            try:
                try:
                    prio = int(self.runner.session.get("query_priority"))
                except Exception:
                    prio = 0
                group.acquire(timeout=600, priority=prio)
            except Exception as e:
                with self._lock:
                    if q.state == "QUEUED":
                        q.error = f"{type(e).__name__}: {e}"
                        q.state = "FAILED"
                q.done.set()
                return
            with self._lock:
                if q.state != "QUEUED":  # canceled while queued
                    group.release()
                    q.done.set()
                    return
                q.state = "RUNNING"
            try:
                res = self.runner.execute(sql, query_id=q.id,
                                          trace_token=q.trace_token)
                cols = [
                    {"name": n, "type": repr(t)} for n, t in zip(res.names, res.types)
                ]
                # per-run outcome rides the result object — reading the
                # shared runner._dist here would let concurrent queries
                # report each other's stats
                q.dist_stages = getattr(res, "dist_stages", None)
                q.dist_fallback = getattr(res, "dist_fallback", None)
                q.planning_ms = getattr(res, "planning_ms", None)
                q.compile_ms = getattr(res, "compile_ms", None)
                q.execution_ms = getattr(res, "execution_ms", None)
                # CANCELED is terminal: a DELETE that raced this query's
                # completion must not be resurrected to FINISHED/FAILED
                with self._lock:
                    if q.state == "RUNNING":
                        q.columns = cols
                        q.rows = res.rows
                        q.state = "FINISHED"
            except Exception as e:  # surfaces to the client as error
                with self._lock:
                    if q.state == "RUNNING":
                        q.error = f"{type(e).__name__}: {e}"
                        q.state = "FAILED"
            finally:
                group.release()
                q.done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()  # started before publication: stop() joins safely
        with self._lock:
            q.thread = t
        return q

    def _cluster_stats(self) -> dict:
        """ClusterStatsResource analog (feeds the web UI tiles)."""
        with self._lock:
            states = [q.state for q in self.queries.values()]
        out = {
            "runningQueries": states.count("RUNNING"),
            "queuedQueries": states.count("QUEUED"),
            "finishedQueries": states.count("FINISHED"),
            "failedQueries": states.count("FAILED") + states.count("CANCELED"),
        }
        pool = getattr(self.runner.executor, "memory_pool", None)
        if pool is not None:
            out["reservedBytes"] = pool.reserved
            out["totalBytes"] = pool.limit
        return out

    def _page_response(self, q: _QueryState, token: int) -> dict:
        out = {
            "id": q.id,
            "columns": q.columns,
            "stats": {"state": q.state, "rows": len(q.rows)},
        }
        if q.dist_stages is not None:
            out["stats"]["distStages"] = q.dist_stages
        if q.dist_fallback is not None:
            out["stats"]["distFallback"] = q.dist_fallback
        # per-stage lifecycle times (sourced from the obs spans; NULL
        # keys simply absent, matching distStages' convention)
        if q.planning_ms is not None:
            out["stats"]["planningMs"] = q.planning_ms
        if q.compile_ms is not None:
            out["stats"]["compileMs"] = q.compile_ms
        if q.execution_ms is not None:
            out["stats"]["executionMs"] = q.execution_ms
        if q.error:
            out["error"] = q.error
            return out
        start = token * PAGE_ROWS
        chunk = q.rows[start : start + PAGE_ROWS]
        out["data"] = [list(r) for r in chunk]
        if start + PAGE_ROWS < len(q.rows):
            out["nextUri"] = f"{self.uri}/v1/statement/{q.id}/{token + 1}"
        return out
