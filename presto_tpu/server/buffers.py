"""Task output buffers: the shuffle server's acked page store.

Reference analog: ``execution/buffer/OutputBuffer.java`` (``get(bufferId,
token, maxSize)`` at :65, ``enqueue`` at :86) with ``ClientBuffer``'s
token protocol and ``OutputBufferMemoryManager``'s bounded footprint:

* pages are identified by a monotonically increasing token (their
  sequence number); a GET at token t returns pages [t, t+k) plus the
  next token — re-GETs of an unacknowledged token return the same pages
  (at-least-once delivery with client-side dedupe by token);
* acknowledge(t) frees all pages below t;
* the producer blocks when unacknowledged bytes exceed the buffer's
  cap — pull-side backpressure, the deadlock-free flow control the
  reference gets from bounded OutputBufferMemoryManager.

The payload is opaque: the HTTP tier stores serialized ``bytes`` (size
= len), the in-process streaming exchange (parallel/streams.py) stores
live Page objects with an explicit ``nbytes`` — ONE token/ack/
backpressure protocol for both transports.  ``producers`` > 1 turns
``set_complete`` into a countdown, so N concurrent producer threads
(UNION legs, per-worker pullers) can share one buffer and the consumer
sees completion only when the last one finishes.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional, Tuple

from presto_tpu.analysis.protocols import RECORDER
from presto_tpu.sync import named_condition, named_lock


class BufferAborted(Exception):
    pass


class TaskOutputBuffer:
    """One task's serialized-page output buffer."""

    def __init__(self, max_bytes: int = 64 << 20, producers: int = 1):
        self.max_bytes = max_bytes
        self._lock = named_lock("buffers.TaskOutputBuffer._lock")
        self._cond = named_condition("buffers.TaskOutputBuffer._lock",
                                     self._lock)
        self._pages: List[Optional[object]] = []  # None = acknowledged/freed
        self._sizes: List[int] = []  # parallel byte sizes (payload-agnostic)
        self._acked = 0  # tokens below this are freed
        self._bytes = 0  # unacknowledged payload bytes
        self._producers = producers  # set_complete calls until complete
        self._complete = False
        self._aborted = False
        self._error: Optional[str] = None
        # conformance identity: one spec-automaton run per buffer
        self._pkey = f"buf:{id(self):x}"
        # stage-overlap evidence (perf_counter): when the first page
        # landed vs when production finished — the A/B harness proves a
        # consumer's first pull preceded producer completion from these
        self.first_page_at: Optional[float] = None
        self.completed_at: Optional[float] = None
        # time producers spent blocked on the byte cap (backpressure)
        self.stall_seconds = 0.0

    # -- producer side ------------------------------------------------------
    def enqueue(self, page: object, nbytes: Optional[int] = None) -> None:
        size = len(page) if nbytes is None else int(nbytes)
        with self._cond:
            stalled = None
            while self._bytes >= self.max_bytes and not self._aborted:
                if stalled is None:
                    stalled = time.perf_counter()
                self._cond.wait(timeout=1.0)
            if stalled is not None:
                waited = time.perf_counter() - stalled
                self.stall_seconds += waited
                from presto_tpu.obs import METRICS

                METRICS.counter(
                    "exchange.producer_stall_seconds_total").inc(waited)
            if self._aborted:
                raise BufferAborted()
            if self.first_page_at is None:
                self.first_page_at = time.perf_counter()
            self._pages.append(page)
            self._sizes.append(size)
            self._bytes += size
            if RECORDER.enabled:
                RECORDER.record("exchange", self._pkey, "enqueue",
                                seq=len(self._pages) - 1)
            self._cond.notify_all()

    def set_complete(self) -> None:
        with self._cond:
            self._producers -= 1
            if self._producers <= 0:
                self._complete = True
                if self.completed_at is None:
                    self.completed_at = time.perf_counter()
                if RECORDER.enabled:
                    RECORDER.record("exchange", self._pkey, "complete")
            self._cond.notify_all()

    def fail(self, message: str) -> None:
        with self._cond:
            self._error = message
            self._complete = True
            if RECORDER.enabled:
                RECORDER.record("exchange", self._pkey, "fail")
            self._cond.notify_all()

    def abort(self) -> bool:
        """Tear down the buffer, waking blocked producers/consumers.

        Idempotent and drain-safe: a second abort, or an abort racing
        a consumer's final acknowledge (complete stream, every page
        acked), is a no-op — a deadline/cancel kill arriving after the
        query already delivered everything must not retroactively fail
        it (INV exchange.abort-after-drain-noop).  Returns whether
        this call actually aborted the buffer.
        """
        with self._cond:
            drained = (self._complete and self._bytes == 0
                       and self._acked >= len(self._pages))
            changed = not self._aborted and not drained
            if RECORDER.enabled:
                RECORDER.record("exchange", self._pkey, "abort",
                                changed=changed, drained=drained)
            if not changed:
                return False
            self._aborted = True
            self._pages = []
            self._sizes = []
            self._bytes = 0
            self._cond.notify_all()
            return True

    # -- consumer side ------------------------------------------------------
    def get(self, token: int, max_bytes: int = 8 << 20,
            timeout: float = 10.0) -> Tuple[List[object], int, bool, Optional[str]]:
        """(pages, next_token, buffer_complete, error): long-polls up to
        ``timeout`` for data at ``token``; tokens below the acknowledged
        watermark cannot be replayed (the client already saw them)."""
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        with self._cond:
            if self._aborted:
                raise BufferAborted()
            if token < self._acked:
                raise KeyError(f"token {token} already acknowledged")
            if not self._complete and token >= len(self._pages):
                self._cond.wait(timeout=deadline)
            if self._aborted:
                raise BufferAborted()
            out: List[object] = []
            t = token
            size = 0
            while t < len(self._pages):
                p = self._pages[t]
                if p is None:  # freed (should not happen above _acked)
                    t += 1
                    continue
                if out and size + self._sizes[t] > max_bytes:
                    break
                out.append(p)
                size += self._sizes[t]
                t += 1
            done = self._complete and t >= len(self._pages)
            if RECORDER.enabled:
                RECORDER.record("exchange", self._pkey, "get",
                                token=token, served_to=t, done=done)
            return out, t, done, self._error

    def acknowledge(self, token: int) -> None:
        with self._cond:
            for i in range(self._acked, min(token, len(self._pages))):
                if self._pages[i] is not None:
                    self._bytes -= self._sizes[i]
                    self._pages[i] = None
            self._acked = max(self._acked, token)
            if RECORDER.enabled:
                RECORDER.record("exchange", self._pkey, "ack",
                                token=token, acked=self._acked)
            self._cond.notify_all()

    @property
    def acked_token(self) -> int:
        with self._lock:
            return self._acked

    @property
    def aborted(self) -> bool:
        with self._lock:
            return self._aborted

    @property
    def unacked_bytes(self) -> int:
        with self._lock:
            return self._bytes
