"""Task output buffers: the shuffle server's acked page store.

Reference analog: ``execution/buffer/OutputBuffer.java`` (``get(bufferId,
token, maxSize)`` at :65, ``enqueue`` at :86) with ``ClientBuffer``'s
token protocol and ``OutputBufferMemoryManager``'s bounded footprint:

* pages are identified by a monotonically increasing token (their
  sequence number); a GET at token t returns pages [t, t+k) plus the
  next token — re-GETs of an unacknowledged token return the same pages
  (at-least-once delivery with client-side dedupe by token);
* acknowledge(t) frees all pages below t;
* the producer blocks when unacknowledged bytes exceed the buffer's
  cap — pull-side backpressure, the deadlock-free flow control the
  reference gets from bounded OutputBufferMemoryManager.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple


class BufferAborted(Exception):
    pass


class TaskOutputBuffer:
    """One task's serialized-page output buffer."""

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pages: List[Optional[bytes]] = []  # None = acknowledged/freed
        self._acked = 0  # tokens below this are freed
        self._bytes = 0  # unacknowledged payload bytes
        self._complete = False
        self._aborted = False
        self._error: Optional[str] = None

    # -- producer side ------------------------------------------------------
    def enqueue(self, page: bytes) -> None:
        with self._cond:
            while self._bytes >= self.max_bytes and not self._aborted:
                self._cond.wait(timeout=1.0)
            if self._aborted:
                raise BufferAborted()
            self._pages.append(page)
            self._bytes += len(page)
            self._cond.notify_all()

    def set_complete(self) -> None:
        with self._cond:
            self._complete = True
            self._cond.notify_all()

    def fail(self, message: str) -> None:
        with self._cond:
            self._error = message
            self._complete = True
            self._cond.notify_all()

    def abort(self) -> None:
        with self._cond:
            self._aborted = True
            self._pages = []
            self._bytes = 0
            self._cond.notify_all()

    # -- consumer side ------------------------------------------------------
    def get(self, token: int, max_bytes: int = 8 << 20,
            timeout: float = 10.0) -> Tuple[List[bytes], int, bool, Optional[str]]:
        """(pages, next_token, buffer_complete, error): long-polls up to
        ``timeout`` for data at ``token``; tokens below the acknowledged
        watermark cannot be replayed (the client already saw them)."""
        deadline = threading.TIMEOUT_MAX if timeout is None else timeout
        with self._cond:
            if token < self._acked:
                raise KeyError(f"token {token} already acknowledged")
            if not self._complete and token >= len(self._pages):
                self._cond.wait(timeout=deadline)
            out: List[bytes] = []
            t = token
            size = 0
            while t < len(self._pages):
                p = self._pages[t]
                if p is None:  # freed (should not happen above _acked)
                    t += 1
                    continue
                if out and size + len(p) > max_bytes:
                    break
                out.append(p)
                size += len(p)
                t += 1
            done = self._complete and t >= len(self._pages)
            return out, t, done, self._error

    def acknowledge(self, token: int) -> None:
        with self._cond:
            for i in range(self._acked, min(token, len(self._pages))):
                p = self._pages[i]
                if p is not None:
                    self._bytes -= len(p)
                    self._pages[i] = None
            self._acked = max(self._acked, token)
            self._cond.notify_all()

    @property
    def unacked_bytes(self) -> int:
        with self._lock:
            return self._bytes
