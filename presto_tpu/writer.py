"""Scaled table writers.

Reference analog: ``scheduler/ScaledWriterScheduler.java`` +
``SystemPartitioningHandle.SCALED_WRITER`` — writer tasks are added
dynamically while producers outpace the writers.  Here the expensive
per-page write work (device->host transfer, compaction, dictionary
recoding) runs on a thread pool that grows one writer at a time
whenever the queue backs up, and the staged results publish atomically
at finish (TableFinishOperator's commit role).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, List, Optional

from presto_tpu.sync import named_lock


class ScaledWriter:
    """Submit pages; ``finish()`` returns the processed results.

    ``write_fn(page) -> result`` runs on writer threads.  One writer
    starts immediately; another is added (up to ``max_writers``)
    whenever a submit observes more than ``scale_depth`` queued pages —
    the produced-data-rate trigger of ScaledWriterScheduler.
    """

    def __init__(self, write_fn: Callable, max_writers: int = 4,
                 scale_depth: int = 2):
        self._write = write_fn
        self.max_writers = max_writers
        self.scale_depth = scale_depth
        # bounded (sanitizer unbounded-queue): a producer outrunning
        # every writer blocks in submit() — backpressure — instead of
        # growing the staged-page queue without limit.  Capacity scales
        # with the pool so the scale-up trigger (qsize > scale_depth)
        # still has room to observe backlog, and finish()/abort() can
        # always enqueue one stop marker per writer.
        self._q: "queue.Queue" = queue.Queue(
            maxsize=max(2 * scale_depth, 2) * max(max_writers, 1))
        self._seq = 0
        self._results: List = []
        self._errors: List[BaseException] = []
        self._lock = named_lock("writer.ScaledWriter._lock")
        self._threads: List[threading.Thread] = []
        self._stop = object()
        self._spawn()

    # -- internals ----------------------------------------------------------
    def _spawn(self) -> None:
        t = threading.Thread(target=self._run, daemon=True,
                             name=f"scaled-writer-{len(self._threads)}")
        t.start()
        self._threads.append(t)

    def _run(self) -> None:
        while True:
            item = self._q.get()
            if item is self._stop:
                return
            seq, page = item
            try:
                out = self._write(page)
                with self._lock:
                    self._results.append((seq, out))
            except BaseException as e:  # surfaced by finish()
                with self._lock:
                    self._errors.append(e)

    # -- public -------------------------------------------------------------
    @property
    def writer_count(self) -> int:
        return len(self._threads)

    def submit(self, page) -> None:
        with self._lock:
            seq = self._seq
            self._seq += 1
        self._q.put((seq, page))
        if (self._q.qsize() > self.scale_depth
                and len(self._threads) < self.max_writers):
            self._spawn()

    def finish(self) -> List:
        """Drain, join writers, and return results in submit order."""
        for _ in self._threads:
            self._q.put(self._stop)
        for t in self._threads:
            t.join()
        if self._errors:
            raise self._errors[0]
        return [r for _, r in sorted(self._results, key=lambda x: x[0])]

    def abort(self) -> None:
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        for _ in self._threads:
            self._q.put(self._stop)
        for t in self._threads:
            t.join()
