"""Memory accounting against the device's HBM budget.

Reference analog: the hierarchical memory system —
``presto-memory-context`` (AggregatedMemoryContext/LocalMemoryContext),
``memory/MemoryPool.java:43`` (tagged reservations, listeners) and the
per-query limit enforcement of ``memory/QueryContext.java``.  The
reference tracks JVM heap bytes and kills/spills on pressure; here the
scarce resource is HBM, and the accountable objects are materialized
device intermediates (join builds, aggregation accumulators,
concatenated pages).  Exceeding the query limit raises
ExceededMemoryLimitError — the executor's capacity-retry machinery and
(future) host-offload chunking are the spill analogs.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from presto_tpu.sync import named_lock


class QueryKilledError(Exception):
    """Raised at the next reservation of a query the cluster memory
    manager killed — the execution thread's interruption point."""


class ExceededMemoryLimitError(Exception):
    def __init__(self, tag: str, requested: int, reserved: int, limit: int):
        super().__init__(
            f"query exceeded memory limit: {tag} requested {requested} bytes, "
            f"{reserved} reserved, limit {limit}"
        )
        self.tag = tag
        self.requested = requested
        self.reserved = reserved
        self.limit = limit


def page_bytes(page) -> int:
    """Accountable HBM footprint of a Page."""
    total = 0
    for b in page.blocks:
        total += b.data.size * b.data.dtype.itemsize
        total += b.valid.size  # bool byte each
    total += page.row_mask.size
    return total


class MemoryPool:
    """Tagged byte reservations with a hard limit (MemoryPool.java
    semantics minus the GENERAL/RESERVED two-pool OOM dance — a single
    chip has one HBM)."""

    def __init__(self, limit_bytes: int):
        self.limit = int(limit_bytes)
        self._lock = named_lock("memory.MemoryPool._lock")
        self._tagged: Dict[str, int] = {}
        self.reserved = 0
        self.peak = 0
        self._killed: set = set()

    def reserve(self, tag: str, nbytes: int, enforce: bool = True) -> None:
        """``enforce=False`` counts the bytes (peak/attribution) without
        failing on over-limit — for transient streaming state that
        cannot be spilled or retried (in-flight scan pages), bounded by
        split capacity rather than by the pool."""
        with self._lock:
            qid = tag.split("/", 1)[0]
            if qid in self._killed:
                raise QueryKilledError(f"query {qid} killed by the memory manager")
            if enforce and self.reserved + nbytes > self.limit:
                raise ExceededMemoryLimitError(tag, nbytes, self.reserved, self.limit)
            self._tagged[tag] = self._tagged.get(tag, 0) + nbytes
            self.reserved += nbytes
            self.peak = max(self.peak, self.reserved)

    def kill_query(self, query_id: str) -> int:
        """Free a query's reservations immediately and fail its future
        reserves (ClusterMemoryManager's actual relief mechanism — the
        execution thread dies at its next reservation)."""
        freed = 0
        with self._lock:
            self._killed.add(query_id)
            for tag in [t for t in self._tagged if t.split("/", 1)[0] == query_id]:
                freed += self._tagged.pop(tag)
            self.reserved -= freed
        # abort the query's streaming-exchange buffers too: a producer
        # thread blocked in enqueue (backpressure) never reaches its
        # next pool reservation, so without this it would leak
        try:
            from presto_tpu.parallel.streams import abort_query

            abort_query(query_id)
        except Exception:
            pass  # kill must still free memory if streams are torn down
        return freed

    def free(self, tag: str) -> None:
        with self._lock:
            n = self._tagged.pop(tag, 0)
            self.reserved -= n

    def free_all(self) -> None:
        with self._lock:
            self._tagged.clear()
            self.reserved = 0

    def tags(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._tagged)


class QueryMemoryContext:
    """Per-query view over a pool (QueryContext analog): unique tags
    per allocation site, freed together at query end.  Tracks its own
    reserved/peak so QueryStats can report per-query peak bytes, and
    per-SITE current/peak bytes (site = the ``what`` string, which for
    operator reservations embeds the plan-node id) so EXPLAIN ANALYZE
    can print per-operator peak memory from the tagged reservations.

    Thread-safe: the morsel split scheduler (exec/tasks.py) reserves
    and frees per-split tags from producer/worker threads while the
    consumer thread charges breaker state, so the context is SHARED
    per query rather than confined to one thread — a lock keeps the
    reserved/peak/site books consistent (the pool has its own lock;
    this one covers the query-local accounting)."""

    def __init__(self, pool: MemoryPool, query_id: str = "q"):
        self.pool = pool
        self.query_id = query_id
        self._lock = named_lock("memory.QueryMemoryContext._lock")
        self._seq = 0
        self.reserved = 0
        self.peak = 0
        self._tag_site: Dict[str, tuple] = {}  # tag -> (site, nbytes)
        self._site_current: Dict[str, int] = {}
        self.site_peak: Dict[str, int] = {}
        # per-query resource timeline, captured at construction on the
        # query thread: reserve/free also run on split-scheduler worker
        # threads, where the recording thread-local is not inherited
        from presto_tpu.obs.timeseries import current_timeline

        self._timeline = current_timeline()

    def _record_reserved(self, reserved_now: int) -> None:
        tl = self._timeline
        if tl is not None:
            tl.record("memory.reserved_bytes", float(reserved_now))

    def reserve(self, what: str, nbytes: int, enforce: bool = True) -> str:
        with self._lock:
            self._seq += 1
            tag = f"{self.query_id}/{what}#{self._seq}"
        # pool reservation outside the context lock: the pool enforces
        # its own limit under its own lock, and a kill/limit error must
        # not leave this context locked
        self.pool.reserve(tag, nbytes, enforce=enforce)
        with self._lock:
            self.reserved += nbytes
            self.peak = max(self.peak, self.reserved)
            reserved_now = self.reserved
            self._tag_site[tag] = (what, nbytes)
            cur = self._site_current.get(what, 0) + nbytes
            self._site_current[what] = cur
            if cur > self.site_peak.get(what, 0):
                self.site_peak[what] = cur
        self._record_reserved(reserved_now)
        return tag

    def reserve_page(self, what: str, page) -> str:
        return self.reserve(what, page_bytes(page))

    def free(self, tag: str) -> None:
        n = self.pool.tags().get(tag, 0)
        self.pool.free(tag)
        with self._lock:
            self.reserved -= n
            reserved_now = self.reserved
            entry = self._tag_site.pop(tag, None)
            if entry is not None:
                site, nbytes = entry
                self._site_current[site] = (
                    self._site_current.get(site, 0) - nbytes)
        self._record_reserved(reserved_now)

    def headroom(self) -> int:
        """Pool bytes still available — the split scheduler's
        backpressure probe (dispatch defers while a further in-flight
        split would not fit)."""
        return self.pool.limit - self.pool.reserved

    def release_all(self) -> None:
        for tag in list(self.pool.tags()):
            if tag.startswith(self.query_id + "/"):
                self.pool.free(tag)
        with self._lock:
            self.reserved = 0
            self._tag_site.clear()
            self._site_current.clear()


# ---------------------------------------------------------------------------
# default (always-on) pool
# ---------------------------------------------------------------------------

_DEFAULT_POOL: Optional[MemoryPool] = None
_DEFAULT_LOCK = named_lock("memory._DEFAULT_LOCK")


def detected_memory_limit() -> int:
    """Accountable-memory budget for the default pool: 90% of the
    device's reported HBM on an accelerator, half of host RAM on the
    CPU backend.  PRESTO_TPU_MEMORY_LIMIT_BYTES overrides (testing and
    deployments with reserved headroom)."""
    import os

    env = os.environ.get("PRESTO_TPU_MEMORY_LIMIT_BYTES")
    if env:
        return int(env)
    try:
        import jax

        stats = jax.devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit")
        if limit:
            return int(limit * 0.9)
    except Exception:
        pass
    try:
        with open("/proc/meminfo") as f:
            kb = int(next(ln for ln in f if ln.startswith("MemTotal")).split()[1])
        return kb * 1024 // 2
    except Exception:
        return 16 << 30


def default_memory_pool() -> MemoryPool:
    """Process-wide pool shared by every runner that doesn't bring its
    own — the single-HBM worker pool (memory/LocalMemoryManager.java
    role).  Accounting is unconditional: an untracked path that works
    at SF0.01 OOMs silently at SF100."""
    global _DEFAULT_POOL
    with _DEFAULT_LOCK:
        if _DEFAULT_POOL is None:
            _DEFAULT_POOL = MemoryPool(detected_memory_limit())
            wire_pool_gauges(_DEFAULT_POOL)
        return _DEFAULT_POOL


def wire_pool_gauges(pool: MemoryPool) -> None:
    """Attach the ``memory.pool_*`` gauges (pre-registered in the
    obs catalog) to ``pool``.  Gauges sample through callbacks at
    snapshot/scrape time, so they always read the live pool state.
    Process semantics: ONE accountable pool per process (the default
    pool, or a server's injected one) — the most recently wired pool
    wins, which lets tests swap pools freely."""
    from presto_tpu.obs import METRICS

    METRICS.gauge("memory.pool_reserved_bytes").set_fn(
        lambda: pool.reserved)
    METRICS.gauge("memory.pool_peak_bytes").set_fn(lambda: pool.peak)
    METRICS.gauge("memory.pool_limit_bytes").set_fn(lambda: pool.limit)
    METRICS.gauge("memory.pool_queries").set_fn(
        lambda: len({t.split("/", 1)[0] for t in pool.tags()}))
