#!/usr/bin/env python
"""TPU/JAX anti-pattern linter for the engine codebase.

Static AST pass over ``presto_tpu/`` that flags the recompile- and
crash-hazard patterns the execution tier cannot tolerate.  The
validator (presto_tpu/analysis/) checks *plans* at query time; this
tool checks the *source* at CI time — the two halves of the static
tier ``EXPLAIN (TYPE VALIDATE)`` anchors.

Rules
-----
raw-capacity        An ``int(...)``/``len(...)``-derived value used as
                    a page/array capacity argument without routing
                    through the shape ladder (``bucket_capacity`` /
                    pow2 helpers).  Every off-ladder capacity is a
                    distinct XLA program — the cold-start storm the
                    program registry exists to prevent.
env-read            ``os.environ`` / ``os.getenv`` read inside a
                    function body.  Env reads belong at import time or
                    in a resolve-once helper with an override hook
                    (ops/join.resolve_direct_join is the model); a
                    read in a per-page/per-build path re-pays a dict
                    lookup per page and makes program choice
                    env-timing-dependent.
traced-branch       Python ``if``/``while`` branching directly on a
                    ``jnp`` expression.  Under jit this is a tracer
                    error; outside jit it is an implicit device sync
                    per evaluation.  (dtype predicates like
                    ``jnp.issubdtype`` are static and exempt.)
device-sync         ``int(jnp...)``/``float(jnp...)``/``bool(jnp...)``
                    or ``.item()`` — each is a blocking host transfer;
                    batch values into one array and transfer once
                    (exec/local._extent_live is the model).
                    ``jnp.iinfo``/``jnp.finfo`` are metadata, exempt.
block-until-ready   ``block_until_ready`` in operator/connector code.
                    Synchronization belongs to the executor's timing
                    boundaries (EXPLAIN ANALYZE), not inside kernels.
bare-except         ``except:`` — swallows KeyboardInterrupt and masks
                    engine bugs.
spi-exception       ``raise KeyError/IndexError/AssertionError`` in
                    the SQL frontend (``sql/``, ``expr/ir.py``).  User
                    statements must fail with typed errors (BindError
                    / SyntaxError / TypeError with a message) — the
                    r5 raw ``KeyError: frozenset()`` leak class.
wallclock           ``time.time()`` inside +/- arithmetic — duration
                    or deadline math on the wall clock, which steps
                    under NTP and skews bench/trace numbers.  Durations
                    must use ``time.perf_counter()``, deadlines
                    ``time.monotonic()``.  Genuine epoch arithmetic
                    (JWT expiry claims) carries an allow comment.
metric-catalog      ``.counter("name")`` / ``.gauge`` / ``.histogram``
                    with a string literal NOT in the pre-registered
                    catalog (obs/metrics.py ``_preregister``).  The
                    catalog in docs/observability.md is authoritative;
                    ad-hoc names silently fork it and break dashboards.
                    Deliberately dynamic instruments carry a
                    ``# metrics: allow`` comment.
naked-urlopen       ``urlopen(...)`` without an explicit ``timeout=``
                    argument.  The stdlib default is no timeout at
                    all: one wedged peer hangs the calling thread
                    forever — the exact hang class the fault-tolerance
                    plane (net.py http_retry, failure detector, query
                    deadlines) exists to prevent.
thread-pool         ``ThreadPoolExecutor`` without a ``max_workers``
                    argument (unbounded default), with an int-literal
                    worker count, or a ``Thread`` constructed inside a
                    ``for``/comprehension over ``range(<literal>)``.
                    Pool widths must be bounded AND config-derived
                    (task_concurrency / a constructor parameter — the
                    exec/tasks.py contract): a hard-coded pool ignores
                    the host, and an unbounded one is a fork bomb under
                    concurrent queries.
rule-purity         An optimizer ``Rule.apply`` body that mutates its
                    *input* — attribute/subscript assignment on the
                    matched node or anything reachable from it, or a
                    mutating container method (``.append``/``.extend``/
                    ``.sort``…) on one of its fields — or reads the
                    process environment.  Rules must be pure functions
                    of the matched subtree that build replacement
                    nodes: an in-place edit corrupts the shared DAG
                    behind the optimizer's back (the rewrite-soundness
                    gate in analysis/soundness.py can only compare
                    before/after trees that are actually distinct).
                    Locals built fresh (``list(node.projections)``,
                    ``dataclasses.replace``) are exempt — taint follows
                    aliases of the input only.
narrow-cast         A literal narrow integer width (``jnp.int32``/
                    ``np.int16``/``"int8"``, via ``astype`` or a
                    ``dtype=`` keyword) in kernel code (``ops/``,
                    ``expr/``).  Int64-lane column values silently
                    truncate through such casts — the overflow class
                    the kernel-soundness analyzer
                    (analysis/kernel_soundness.py) proves absent.
                    Lane widths must come from the declared type map
                    (``Type.np_dtype``); a proven-safe narrow (bounded
                    codes, counts, field ranges) carries
                    ``# lint: allow(narrow-cast)``.
protocol-state      Direct assignment to a model-checked protocol
                    state attribute outside its owning transition
                    method.  The protocol-soundness tier
                    (analysis/protocols.py, analysis/mcheck.py) proves
                    invariants over the exchange/detector/retry/
                    admission state machines assuming ALL transitions
                    flow through the audited methods — a write from
                    anywhere else (``h.state = DEAD`` in a helper,
                    ``ticket.released = True`` in a caller) bypasses
                    both the invariant guards and the conformance
                    trace.  The owner map is ``_PROTOCOL_STATE``;
                    extend it when a protocol grows a new transition
                    method.

Concurrency check
-----------------
The same entry point also runs the concurrency sanitizer's static
detectors (``presto_tpu/analysis/concurrency.py`` — whole-repo
lock-order cycles, blocking-in-lock, untimed waits, shared-state
races, thread/executor/queue/server lifecycle, unnamed threads; see
its docstring for the catalog).  ``--rule`` filters apply across both
checks; ``--skip-concurrency`` / ``--only-concurrency`` select one.

Suppression
-----------
Two mechanisms share one contract — every suppression carries a
justification:

- inline: append ``# lint: allow(<rule>)`` to the offending line
  (comma-separate multiple rules; ``# metrics: allow`` for the
  metric-catalog rule) — for fixtures and truly line-local exceptions;
- the shared suppression file (``tools/lint_suppressions.txt``,
  ``--suppressions`` overrides): one ``path | rule | line-substring |
  justification`` entry per reviewed exception, matched on path
  suffix + rule + source-line content so entries survive line drift.
  A malformed or justification-less entry is itself a finding.

Allow-listed helper shapes (resolve-once functions, ``__init__``
constructors, module scope) are exempt from ``env-read``
automatically.

Exit codes (``--check``): 0 clean; bit 1 set = engine anti-pattern
findings; bit 2 set = concurrency findings (so 1, 2, or 3).

Usage::

    python tools/engine_lint.py --check presto_tpu tools  # CI mode
    python tools/engine_lint.py --json presto_tpu/exec/local.py
    python tools/engine_lint.py --rule lock-order --check presto_tpu
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import re
import sys
from typing import Dict, Iterator, List, NamedTuple, Optional, Set, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_concurrency():
    """Import the concurrency analyzer (dependency-free stdlib-ast
    module) without requiring presto_tpu to be importable as a whole —
    the linter must run on machines without jax."""
    import importlib.util

    path = os.path.join(_REPO_ROOT, "presto_tpu", "analysis",
                        "concurrency.py")
    spec = importlib.util.spec_from_file_location(
        "presto_tpu_concurrency_lint", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class Finding(NamedTuple):
    path: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


_ALLOW_RE = re.compile(r"#\s*lint:\s*allow\(([^)]*)\)")

#: env-read is legal in functions that resolve once / construct / set
#: up — by naming convention (the resolve-once pattern of
#: ops/join.resolve_direct_join) or constructor role.
_ENV_OK_FN = re.compile(
    r"^(resolve_|maybe_|enable_|default_|detected_|_resolve|main$|"
    r"__init__$|host_cache_dir$|from_etc$)|_enabled$")

#: jnp attributes that are static metadata, not traced values
_STATIC_JNP = {"issubdtype", "iinfo", "finfo", "dtype", "bool_", "int32",
               "int64", "float32", "float64", "uint32", "uint8", "ndim",
               "floating", "integer", "signedinteger", "inexact", "shape"}

#: callables whose argument is a page/array CAPACITY (positional index
#: or keyword); int()/len() flowing in raw is a ladder bypass
_CAPACITY_SINKS: Dict[str, Tuple[Optional[int], Optional[str]]] = {
    # fn name -> (positional index of capacity arg, keyword name)
    "pad_page_to": (1, None),
    "from_arrays": (None, "capacity"),
    "page_for_split": (None, "capacity"),
    "empty": (1, "capacity"),  # Page.empty(types, capacity)
}

#: names that mark a value as already ladder-routed
_LADDER_MARKERS = {"bucket_capacity", "_cap", "cap", "cap_hi", "capacity",
                   "mg", "max_groups", "MIN_CAP", "out_cap", "tgt",
                   "bucket", "split_capacity"}

#: raise types the SQL frontend must not leak to users
_SPI_RAW_RAISES = {"KeyError", "IndexError", "AssertionError"}

#: protocol-state: the owner map of the model-checked state machines
#: (analysis/protocols.py).  Key = (owning-file path suffix, attribute
#: name); value = the transition methods allowed to assign it.  The
#: attribute names are deliberately scoped to their owning file —
#: ``.state`` and ``.canceled`` name unrelated machines elsewhere
#: (coordinator query lifecycle, executor futures).
_PROTOCOL_STATE: Dict[Tuple[str, str], frozenset] = {
    # failure detector: WorkerHealth.state only moves via _transition
    ("parallel/failure.py", "state"): frozenset({"__init__", "_transition"}),
    # admission tickets: QUEUED -> ADMITTED happens inside the
    # _wait_for_memory critical section; RELEASED only via release()
    ("serving/admission.py", "state"): frozenset(
        {"__init__", "_wait_for_memory", "release"}),
    ("serving/admission.py", "released"): frozenset({"__init__", "release"}),
    ("serving/admission.py", "canceled"): frozenset({"__init__", "cancel"}),
    # exchange buffer: ack watermark / abort / completion flags
    ("server/buffers.py", "_acked"): frozenset({"__init__", "acknowledge"}),
    ("server/buffers.py", "_aborted"): frozenset({"__init__", "abort"}),
    ("server/buffers.py", "_complete"): frozenset(
        {"__init__", "set_complete", "fail"}),
}

#: metric-catalog: the ``# metrics: allow`` opt-out comment
_METRICS_ALLOW_RE = re.compile(r"#\s*metrics:\s*allow")

#: registry methods whose string-literal argument names an instrument
_METRIC_METHODS = {"counter", "gauge", "histogram"}

_CATALOG_CACHE: Dict[str, Optional[frozenset]] = {}


def _metric_catalog_for(path: str) -> Optional[frozenset]:
    """The pre-registered metric catalog governing ``path``: walk up
    from the file to the repo root holding ``presto_tpu/obs/metrics.py``
    and collect every string constant in its ``_preregister`` function.
    Returns None (rule disabled) when no catalog is in scope — fixture
    snippets in temp dirs lint without it."""
    d = os.path.dirname(os.path.abspath(path))
    probed = []
    while True:
        cached = _CATALOG_CACHE.get(d)
        if cached is not None or d in _CATALOG_CACHE:
            catalog = cached
            break
        probed.append(d)
        candidate = os.path.join(d, "presto_tpu", "obs", "metrics.py")
        if os.path.isfile(candidate):
            catalog = _parse_catalog(candidate)
            break
        parent = os.path.dirname(d)
        if parent == d:
            catalog = None
            break
        d = parent
    for p in probed:
        _CATALOG_CACHE[p] = catalog
    return catalog


def _parse_catalog(metrics_py: str) -> Optional[frozenset]:
    try:
        with open(metrics_py, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_preregister":
            return frozenset(
                c.value for c in ast.walk(node)
                if isinstance(c, ast.Constant) and isinstance(c.value, str))
    return None


def _suppressed(source_lines: List[str], lineno: int, rule: str) -> bool:
    if 1 <= lineno <= len(source_lines):
        m = _ALLOW_RE.search(source_lines[lineno - 1])
        if m:
            allowed = {r.strip() for r in m.group(1).split(",")}
            return rule in allowed or "all" in allowed
    return False


def _is_jnp_value(node: ast.AST) -> bool:
    """expression rooted at jnp.<traced fn>(...) (not static metadata)."""
    if isinstance(node, ast.Call):
        return _is_jnp_value(node.func)
    if isinstance(node, ast.Attribute):
        base = node.value
        if isinstance(base, ast.Name) and base.id in ("jnp", "jax"):
            return node.attr not in _STATIC_JNP
        return _is_jnp_value(base)
    if isinstance(node, ast.BinOp):
        return _is_jnp_value(node.left) or _is_jnp_value(node.right)
    if isinstance(node, ast.Compare):
        return _is_jnp_value(node.left) or any(
            _is_jnp_value(c) for c in node.comparators)
    if isinstance(node, ast.BoolOp):
        return any(_is_jnp_value(v) for v in node.values)
    if isinstance(node, ast.UnaryOp):
        return _is_jnp_value(node.operand)
    return False


def _contains_call_to(node: ast.AST, names: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            fn = sub.func
            n = fn.id if isinstance(fn, ast.Name) else (
                fn.attr if isinstance(fn, ast.Attribute) else None)
            if n in names:
                return True
        elif isinstance(sub, ast.Name) and sub.id in names:
            return True
    return False


_NARROW_INTS = {"int8", "int16", "int32"}


def _narrow_dtype_name(node: ast.AST) -> Optional[str]:
    """``jnp.int32`` / ``np.int16`` / ``"int8"`` — a literal narrow
    integer width.  Widths routed through the type map (``t.np_dtype``,
    ``block.data.dtype``) resolve dynamically and are exempt."""
    if isinstance(node, ast.Attribute) and node.attr in _NARROW_INTS \
            and isinstance(node.value, ast.Name) \
            and node.value.id in ("jnp", "np", "jax", "numpy"):
        return node.attr
    if isinstance(node, ast.Constant) and node.value in _NARROW_INTS:
        return node.value
    return None


def _call_name(call: ast.Call) -> Optional[str]:
    fn = call.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return None


class _Linter(ast.NodeVisitor):
    def __init__(self, path: str, tree: ast.Module, source: str,
                 rules: Set[str], metric_catalog: Optional[frozenset] = None):
        self.path = path
        self.tree = tree
        self.lines = source.splitlines()
        self.rules = rules
        self.metric_catalog = metric_catalog
        self.findings: List[Finding] = []
        # stack of enclosing function names
        self._fn_stack: List[str] = []
        self._in_sql_frontend = (
            f"{os.sep}sql{os.sep}" in path
            or path.endswith(os.path.join("expr", "ir.py")))
        self._is_operator_code = any(
            f"{os.sep}{d}{os.sep}" in path
            for d in ("ops", "connectors", "storage"))
        # the narrow-cast rule covers KERNEL code: the expression
        # compiler and the vectorized operators, where a literal narrow
        # width truncates column lanes
        self._is_kernel_code = any(
            f"{os.sep}{d}{os.sep}" in path for d in ("ops", "expr"))
        # names the time MODULE is bound to in this file (import time /
        # import time as _time, at any scope) — the wallclock rule must
        # not fire on unrelated .time() methods
        self._time_aliases = {
            alias.asname or alias.name
            for stmt in ast.walk(tree) if isinstance(stmt, ast.Import)
            for alias in stmt.names if alias.name == "time"}
        # names the time.time FUNCTION is bound to (from time import
        # time [as now]) — bare calls through these are wall clocks too
        self._time_funcs = {
            alias.asname or alias.name
            for stmt in ast.walk(tree) if isinstance(stmt, ast.ImportFrom)
            if stmt.module == "time"
            for alias in stmt.names if alias.name == "time"}
        # depth of enclosing for-loops/comprehensions whose iterable is
        # range(<int literal>) — a Thread() built there is a pool of
        # hard-coded width (the thread-pool rule)
        self._literal_range_depth = 0
        # protocol-state: the attribute -> allowed-methods map for THIS
        # file (empty outside the owning modules)
        norm = path.replace(os.sep, "/")
        self._protocol_attrs = {
            attr: allowed for (suffix, attr), allowed
            in _PROTOCOL_STATE.items() if norm.endswith(suffix)}

    # -- helpers -----------------------------------------------------------
    def _emit(self, node: ast.AST, rule: str, message: str) -> None:
        if rule not in self.rules:
            return
        if _suppressed(self.lines, node.lineno, rule):
            return
        self.findings.append(Finding(self.path, node.lineno, rule, message))

    # -- visitors ----------------------------------------------------------
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._fn_stack.append(node.name)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call) -> None:
        name = _call_name(node)
        txt_fn = ast.unparse(node.func) if node.func is not None else ""

        # env-read ---------------------------------------------------------
        if self._fn_stack and (
                txt_fn.endswith("environ.get") or txt_fn.endswith("getenv")):
            fn = self._fn_stack[-1]
            if not _ENV_OK_FN.search(fn):
                self._emit(
                    node, "env-read",
                    f"os.environ read inside {fn}() — resolve once at "
                    "import/construction (with an override hook) instead "
                    "of per call")

        # device-sync: int(jnp...)/float(jnp...)/bool(jnp...) ---------------
        if name in ("int", "float", "bool") and len(node.args) == 1 \
                and _is_jnp_value(node.args[0]):
            self._emit(
                node, "device-sync",
                f"{name}(jnp...) forces a blocking host transfer — stack "
                "values and transfer once (see exec/local._extent_live)")

        # device-sync: .item() ----------------------------------------------
        if isinstance(node.func, ast.Attribute) and node.func.attr == "item" \
                and not node.args:
            self._emit(node, "device-sync",
                       ".item() forces a blocking host transfer")

        # metric-catalog -----------------------------------------------------
        if (self.metric_catalog is not None
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_METHODS
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            metric = node.args[0].value
            line = (self.lines[node.lineno - 1]
                    if 1 <= node.lineno <= len(self.lines) else "")
            if metric not in self.metric_catalog \
                    and not _METRICS_ALLOW_RE.search(line):
                self._emit(
                    node, "metric-catalog",
                    f"metric {metric!r} is not in the pre-registered "
                    "catalog (obs/metrics.py _preregister, documented in "
                    "docs/observability.md) — add it there, or mark a "
                    "deliberately dynamic instrument with "
                    "`# metrics: allow`")

        # thread-pool --------------------------------------------------------
        if name == "ThreadPoolExecutor":
            width = None
            if node.args:
                width = node.args[0]
            for k in node.keywords:
                if k.arg == "max_workers":
                    width = k.value
            if width is None:
                self._emit(
                    node, "thread-pool",
                    "ThreadPoolExecutor without max_workers defaults to "
                    "an unbounded-ish pool — pass a bounded, "
                    "config-derived worker count (task_concurrency / a "
                    "constructor parameter)")
            elif isinstance(width, ast.Constant) \
                    and isinstance(width.value, int):
                self._emit(
                    node, "thread-pool",
                    f"hard-coded ThreadPoolExecutor width "
                    f"{width.value} — derive the worker count from "
                    "config (task_concurrency / a constructor "
                    "parameter) so deployments can size it")
        if name == "Thread" and self._literal_range_depth > 0:
            self._emit(
                node, "thread-pool",
                "Thread constructed in a range(<literal>) loop is a "
                "pool of hard-coded width — derive the count from "
                "config (task_concurrency / a constructor parameter)")

        # naked-urlopen ------------------------------------------------------
        if name == "urlopen":
            # urlopen(url, data=None, timeout=...) — timeout is the
            # third positional or the keyword
            has_timeout = len(node.args) >= 3 or any(
                k.arg == "timeout" for k in node.keywords)
            if not has_timeout:
                self._emit(
                    node, "naked-urlopen",
                    "urlopen without an explicit timeout= blocks its "
                    "thread forever on a wedged peer — pass a bounded "
                    "timeout (or use presto_tpu.net.request_json/"
                    "request_bytes)")

        # narrow-cast --------------------------------------------------------
        # kernel code (ops/, expr/) narrowing lanes to a literal int8/
        # int16/int32 width: silent truncation of int64-lane values (the
        # overflow class analysis/kernel_soundness.py proves absent).
        # Widths must come from the declared type map (Type.np_dtype) or
        # carry `# lint: allow(narrow-cast)` with the reason nearby.
        if self._is_kernel_code:
            narrow = None
            if name == "astype" and node.args:
                narrow = _narrow_dtype_name(node.args[0])
            elif name in ("asarray", "array", "full_like", "zeros_like",
                          "ones_like"):
                # conversions of EXISTING values; fresh constructions
                # (arange/zeros/ones) narrow nothing and are exempt
                for k in node.keywords:
                    if k.arg == "dtype":
                        narrow = _narrow_dtype_name(k.value)
            if narrow is not None:
                self._emit(
                    node, "narrow-cast",
                    f"literal {narrow} narrowing in kernel code — derive "
                    "the lane width from the declared type map "
                    "(Type.np_dtype), or mark a proven-safe narrow with "
                    "`# lint: allow(narrow-cast)`")

        # block-until-ready --------------------------------------------------
        if name == "block_until_ready" and self._is_operator_code:
            self._emit(
                node, "block-until-ready",
                "block_until_ready in operator code — synchronization "
                "belongs to the executor's timing boundaries")

        # raw-capacity -------------------------------------------------------
        sink = _CAPACITY_SINKS.get(name or "")
        if sink is not None:
            pos, kw = sink
            cand: List[ast.AST] = []
            if pos is not None and len(node.args) > pos:
                cand.append(node.args[pos])
            for k in node.keywords:
                if kw is not None and k.arg == kw:
                    cand.append(k.value)
            for v in cand:
                if _contains_call_to(v, {"int", "len"}) \
                        and not _contains_call_to(v, _LADDER_MARKERS):
                    self._emit(
                        node, "raw-capacity",
                        f"data-dependent capacity {ast.unparse(v)!r} "
                        f"feeds {name}() without the shape ladder — "
                        "wrap in bucket_capacity() so program "
                        "signatures stay finite")

        self.generic_visit(node)

    # -- protocol-state ----------------------------------------------------
    def _check_protocol_write(self, node: ast.AST,
                              targets: List[ast.AST]) -> None:
        """Assignment targets hitting a model-checked protocol state
        attribute (``_PROTOCOL_STATE``) outside its owning transition
        methods — such a write bypasses the invariant guards and the
        conformance trace of the protocol-soundness tier."""
        while targets:
            t = targets.pop()
            if isinstance(t, (ast.Tuple, ast.List)):
                targets.extend(t.elts)
                continue
            if not isinstance(t, ast.Attribute):
                continue
            allowed = self._protocol_attrs.get(t.attr)
            if allowed is None:
                continue
            fn = self._fn_stack[-1] if self._fn_stack else "<module>"
            if fn not in allowed:
                self._emit(
                    node, "protocol-state",
                    f"direct write to protocol state "
                    f"{ast.unparse(t)} in {fn}() — transitions must go "
                    f"through {'/'.join(sorted(allowed - {'__init__'}))}"
                    " so the model-checked invariants and the "
                    "conformance trace stay sound")

    def visit_Assign(self, node: ast.Assign) -> None:
        if self._protocol_attrs:
            self._check_protocol_write(node, list(node.targets))
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        if self._protocol_attrs:
            self._check_protocol_write(node, [node.target])
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if self._protocol_attrs and node.value is not None:
            self._check_protocol_write(node, [node.target])
        self.generic_visit(node)

    def visit_BinOp(self, node: ast.BinOp) -> None:
        # wallclock: time.time() feeding +/- arithmetic is duration or
        # deadline math on a clock that steps under NTP
        if isinstance(node.op, (ast.Add, ast.Sub)) \
                and (self._is_walltime(node.left)
                     or self._is_walltime(node.right)):
            self._emit(
                node, "wallclock",
                "time.time() in duration/deadline arithmetic — the wall "
                "clock steps under NTP; use time.perf_counter() for "
                "durations, time.monotonic() for deadlines (epoch math "
                "needs # lint: allow(wallclock))")
        self.generic_visit(node)

    def _is_walltime(self, node: ast.AST) -> bool:
        """expression containing a ``time.time()`` call on the time
        MODULE (including aliases like ``_time.time()``) — other
        ``.time()`` methods are not clocks.  BinOp operands are NOT
        descended into: they visit and report themselves, and walking
        through them double-reported chained arithmetic
        (``time.time() + a + b``)."""
        stack = [node]
        while stack:
            sub = stack.pop()
            if isinstance(sub, ast.BinOp):
                continue
            if isinstance(sub, ast.Call):
                fn = sub.func
                if isinstance(fn, ast.Attribute) and fn.attr == "time" \
                        and isinstance(fn.value, ast.Name) \
                        and fn.value.id in self._time_aliases:
                    return True
                if isinstance(fn, ast.Name) and fn.id in self._time_funcs:
                    return True
            stack.extend(ast.iter_child_nodes(sub))
        return False

    def _check_branch(self, node) -> None:
        if _is_jnp_value(node.test):
            kind = "if" if isinstance(node, ast.If) else "while"
            self._emit(
                node, "traced-branch",
                f"python `{kind}` branches on a jnp expression — a "
                "tracer error under jit, an implicit device sync "
                "outside it (use jnp.where / lax.cond)")

    @staticmethod
    def _is_literal_range(it: ast.AST) -> bool:
        """``range`` whose STOP argument is an int literal — the
        hard-coded pool-width iterable of the thread-pool rule.  Only
        the stop argument decides: ``range(0, concurrency)`` is
        config-derived despite its literal start."""
        if not (isinstance(it, ast.Call)
                and isinstance(it.func, ast.Name) and it.func.id == "range"
                and it.args):
            return False
        stop = it.args[0] if len(it.args) == 1 else it.args[1]
        return isinstance(stop, ast.Constant) and isinstance(stop.value, int)

    def _visit_in_range_scope(self, node, iters) -> None:
        bump = any(self._is_literal_range(it) for it in iters)
        self._literal_range_depth += bump
        self.generic_visit(node)
        self._literal_range_depth -= bump

    def visit_For(self, node: ast.For) -> None:
        self._visit_in_range_scope(node, [node.iter])

    def visit_ListComp(self, node: ast.ListComp) -> None:
        self._visit_in_range_scope(node, [g.iter for g in node.generators])

    def visit_SetComp(self, node: ast.SetComp) -> None:
        self._visit_in_range_scope(node, [g.iter for g in node.generators])

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        self._visit_in_range_scope(node, [g.iter for g in node.generators])

    def visit_If(self, node: ast.If) -> None:
        self._check_branch(node)
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        self._check_branch(node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._emit(node, "bare-except",
                       "bare `except:` swallows KeyboardInterrupt and "
                       "masks engine bugs — name the exception types")
        self.generic_visit(node)

    # -- rule-purity -------------------------------------------------------
    #: container methods that mutate their receiver in place
    _MUTATORS = {"append", "extend", "insert", "add", "update", "remove",
                 "pop", "popitem", "clear", "setdefault", "sort", "reverse",
                 "discard"}

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        if any((isinstance(b, ast.Name) and b.id == "Rule")
               or (isinstance(b, ast.Attribute) and b.attr == "Rule")
               for b in node.bases):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and item.name == "apply":
                    self._check_rule_purity(item)
        self.generic_visit(node)

    def _check_rule_purity(self, fn: ast.FunctionDef) -> None:
        """``Rule.apply`` must be a pure function of the matched
        subtree: no in-place mutation of the input node or anything
        reachable from it, no environment reads.  Taint starts at the
        node parameter and follows plain aliases (``x = node.source``,
        ``for arm in node.inputs``); calls build fresh objects and
        clear taint (``list(node.projections)``)."""
        params = [a.arg for a in fn.args.args if a.arg != "self"]
        if not params:
            return
        tainted = {params[0]}

        def root(e: ast.AST) -> Optional[str]:
            while isinstance(e, (ast.Attribute, ast.Subscript)):
                e = e.value
            return e.id if isinstance(e, ast.Name) else None

        def aliases_input(e: ast.AST) -> bool:
            # bare names / attribute / subscript chains alias existing
            # objects; anything routed through a Call is fresh
            if isinstance(e, (ast.Tuple, ast.List)):
                return any(aliases_input(x) for x in e.elts)
            if isinstance(e, (ast.Name, ast.Attribute, ast.Subscript)):
                return root(e) in tainted
            return False

        changed = True
        while changed:  # alias fixpoint (chains like a = node; b = a.left)
            changed = False
            for sub in ast.walk(fn):
                names: List[str] = []
                if isinstance(sub, ast.Assign) \
                        and aliases_input(sub.value):
                    names = [t.id for t in sub.targets
                             if isinstance(t, ast.Name)]
                elif isinstance(sub, ast.AnnAssign) \
                        and sub.value is not None \
                        and aliases_input(sub.value) \
                        and isinstance(sub.target, ast.Name):
                    names = [sub.target.id]
                elif isinstance(sub, ast.For) \
                        and aliases_input(sub.iter) \
                        and isinstance(sub.target, ast.Name):
                    names = [sub.target.id]
                elif isinstance(sub, ast.comprehension) \
                        and aliases_input(sub.iter) \
                        and isinstance(sub.target, ast.Name):
                    names = [sub.target.id]
                for n in names:
                    if n not in tainted:
                        tainted.add(n)
                        changed = True

        for sub in ast.walk(fn):
            targets: List[ast.AST] = []
            if isinstance(sub, ast.Assign):
                targets = list(sub.targets)
            elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                targets = [sub.target]
            for t in targets:
                if isinstance(t, ast.Tuple):
                    targets.extend(t.elts)
                    continue
                if isinstance(t, (ast.Attribute, ast.Subscript)) \
                        and root(t) in tainted:
                    self._emit(
                        sub, "rule-purity",
                        f"Rule.apply mutates its input: assignment to "
                        f"{ast.unparse(t)} — rules must build "
                        "replacement nodes, not edit the matched "
                        "subtree in place")
            if isinstance(sub, ast.Call):
                f = sub.func
                if isinstance(f, ast.Attribute) \
                        and f.attr in self._MUTATORS \
                        and aliases_input(f.value):
                    self._emit(
                        sub, "rule-purity",
                        f"Rule.apply mutates its input via "
                        f".{f.attr}() on {ast.unparse(f.value)} — "
                        "rules must build replacement nodes, not edit "
                        "the matched subtree in place")
            elif isinstance(sub, ast.Attribute) \
                    and sub.attr in ("environ", "getenv") \
                    and isinstance(sub.value, ast.Name) \
                    and sub.value.id == "os":
                self._emit(
                    sub, "rule-purity",
                    "Rule.apply reads the process environment — rule "
                    "behavior must be a pure function of the matched "
                    "subtree (resolve config at rule construction)")

    def visit_Raise(self, node: ast.Raise) -> None:
        if self._in_sql_frontend and node.exc is not None:
            exc = node.exc
            name = None
            if isinstance(exc, ast.Call):
                name = _call_name(exc)
            elif isinstance(exc, ast.Name):
                name = exc.id
            if name in _SPI_RAW_RAISES:
                self._emit(
                    node, "spi-exception",
                    f"raise {name} in the SQL frontend leaks an internal "
                    "exception across the SPI boundary — raise BindError "
                    "(with the source position) instead")
        self.generic_visit(node)


ALL_RULES = {"raw-capacity", "env-read", "traced-branch", "device-sync",
             "block-until-ready", "bare-except", "spi-exception",
             "wallclock", "metric-catalog", "thread-pool",
             "naked-urlopen", "rule-purity", "narrow-cast",
             "protocol-state"}

#: the concurrency sanitizer's detector names (the second check); kept
#: in sync with analysis/concurrency.CONCURRENCY_RULES by the tests
CONCURRENCY_RULES = {
    "lock-order", "blocking-in-lock", "untimed-wait", "shared-state-race",
    "thread-leak", "executor-leak", "unbounded-queue", "unnamed-thread",
    "server-leak",
}

DEFAULT_SUPPRESSIONS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "lint_suppressions.txt")


class Suppression(NamedTuple):
    path: str     # path suffix (repo-relative, / separators)
    rule: str
    match: str    # substring the finding's source line must contain
    reason: str   # mandatory justification

    def covers(self, finding: "Finding", line_text: str) -> bool:
        norm = finding.path.replace(os.sep, "/")
        return (norm.endswith(self.path) and finding.rule == self.rule
                and (not self.match or self.match in line_text))


def load_suppressions(path: str) -> Tuple[List[Suppression], List[Finding]]:
    """Parse the shared suppression file.  Format (one per line)::

        path | rule | line-substring | justification

    ``#`` comments and blank lines are skipped.  A malformed entry or
    an empty justification is returned as a finding against the file
    itself — an unexplained suppression is a defect."""
    entries: List[Suppression] = []
    problems: List[Finding] = []
    try:
        with open(path, encoding="utf-8") as f:
            raw = f.read().splitlines()
    except OSError:
        return entries, problems
    for i, line in enumerate(raw, 1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        parts = [p.strip() for p in stripped.split("|")]
        if len(parts) != 4 or not all(parts[:2]) or not parts[3]:
            problems.append(Finding(
                path, i, "suppression-format",
                "suppression entries are `path | rule | line-substring"
                " | justification` with a non-empty justification"))
            continue
        entries.append(Suppression(parts[0].replace(os.sep, "/"),
                                   parts[1], parts[2], parts[3]))
    return entries, problems


def _cached_lines(path: str, cache: Dict[str, List[str]]) -> List[str]:
    """Source lines of ``path``, read once per lint run (shared by the
    suppression matcher and the concurrency adapter so encoding/error
    behavior cannot diverge between them)."""
    lines = cache.get(path)
    if lines is None:
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            lines = []
        cache[path] = lines
    return lines


def apply_suppressions(findings: List[Finding],
                       entries: List[Suppression]) -> List[Finding]:
    if not entries:
        return findings
    out: List[Finding] = []
    line_cache: Dict[str, List[str]] = {}
    for f in findings:
        lines = _cached_lines(f.path, line_cache)
        text = lines[f.line - 1] if 1 <= f.line <= len(lines) else ""
        if not any(s.covers(f, text) for s in entries):
            out.append(f)
    return out


def lint_concurrency(paths, rules: Optional[Set[str]] = None) \
        -> Tuple[List[Finding], dict]:
    """Run the whole-repo concurrency sanitizer and adapt its findings
    to this linter's Finding type (inline ``# lint: allow`` comments
    honored the same way)."""
    conc = _load_concurrency()
    raw, report = conc.analyze(paths)
    findings: List[Finding] = []
    line_cache: Dict[str, List[str]] = {}
    for f in raw:
        if rules is not None and f.rule not in rules:
            continue
        lines = _cached_lines(f.path, line_cache)
        if _suppressed(lines, f.line, f.rule):
            continue
        findings.append(Finding(f.path, f.line, f.rule, f.message))
    return findings, report

#: sentinel: discover the catalog by walking up from the linted file
_AUTO = object()


def lint_file(path: str, rules: Set[str] = ALL_RULES,
              metric_catalog=_AUTO) -> List[Finding]:
    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 0, "parse", str(e))]
    if metric_catalog is _AUTO:
        metric_catalog = _metric_catalog_for(path)
    linter = _Linter(path, tree, source, rules, metric_catalog=metric_catalog)
    linter.visit(tree)
    return linter.findings


def iter_targets(root: str) -> Iterator[str]:
    if os.path.isfile(root):
        yield root
        return
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                yield os.path.join(dirpath, fn)


def lint_paths(paths, rules: Set[str] = ALL_RULES) -> List[Finding]:
    findings: List[Finding] = []
    for root in paths:
        for path in iter_targets(root):
            findings.extend(lint_file(path, rules))
    return findings


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="+", help="files or directories")
    ap.add_argument("--check", action="store_true",
                    help="CI mode: nonzero exit on findings (bit 1 = "
                         "engine anti-patterns, bit 2 = concurrency)")
    ap.add_argument("--rule", action="append", default=None,
                    help="restrict to specific rule(s), either check")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings on stdout")
    ap.add_argument("--skip-concurrency", action="store_true",
                    help="run only the engine anti-pattern check")
    ap.add_argument("--only-concurrency", action="store_true",
                    help="run only the concurrency sanitizer check")
    ap.add_argument("--suppressions", default=DEFAULT_SUPPRESSIONS,
                    help="shared suppression file (path | rule | "
                         "line-substring | justification)")
    args = ap.parse_args(argv)
    known = ALL_RULES | CONCURRENCY_RULES
    rules = set(args.rule) if args.rule else known
    unknown = rules - known
    if unknown:
        ap.error(f"unknown rule(s): {sorted(unknown)} "
                 f"(known: {sorted(known)})")
    run_engine = not args.only_concurrency and bool(rules & ALL_RULES)
    run_conc = not args.skip_concurrency and bool(rules & CONCURRENCY_RULES)

    engine_findings: List[Finding] = []
    conc_findings: List[Finding] = []
    if run_engine:
        engine_findings = lint_paths(args.paths, rules & ALL_RULES)
    if run_conc:
        conc_findings, _report = lint_concurrency(
            args.paths, rules & CONCURRENCY_RULES)

    entries, problems = load_suppressions(args.suppressions)
    engine_findings = apply_suppressions(engine_findings, entries)
    conc_findings = apply_suppressions(conc_findings, entries) + problems

    if args.as_json:
        print(json.dumps([
            {"path": f.path, "line": f.line, "rule": f.rule,
             "check": ("concurrency" if f.rule in CONCURRENCY_RULES
                       or f.rule == "suppression-format" else "engine"),
             "message": f.message}
            for f in engine_findings + conc_findings], indent=2))
    else:
        for f in engine_findings + conc_findings:
            print(f)
    print(f"{len(engine_findings)} engine + {len(conc_findings)} "
          "concurrency finding(s)", file=sys.stderr)
    if not args.check:
        return 0
    rc = 0
    if engine_findings:
        rc |= 1
    if conc_findings:
        rc |= 2
    return rc


if __name__ == "__main__":
    sys.exit(main())
