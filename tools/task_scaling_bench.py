#!/usr/bin/env python
"""Split-scheduler scaling microbench: latency-bound splits.

Measures `exec/tasks.SplitScheduler` throughput over splits whose cost
is a host-side STALL (emulating remote-storage fetches / connector
decode latency) rather than CPU — the component the morsel scheduler
can actually overlap regardless of host core count.  On CPU-bound
TPC-H splits the ratio is capped by spare cores (PERF.md round 7); this
bench isolates the scheduler itself.

Usage:
  python tools/task_scaling_bench.py [--splits 16] [--stall-ms 50]
                                     [--concurrency 1,2,4,8] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--splits", type=int, default=16)
    ap.add_argument("--stall-ms", type=float, default=50.0)
    ap.add_argument("--concurrency", default="1,2,4,8",
                    help="comma list of worker-pool widths to measure")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    from presto_tpu.exec.tasks import SplitScheduler

    stall_s = args.stall_ms / 1e3

    def split(i: int) -> int:
        time.sleep(stall_s)  # the latency being overlapped
        return i

    rows = []
    base = None
    for c in (int(x) for x in args.concurrency.split(",")):
        sched = SplitScheduler(concurrency=c, prefetch=2, ordered=True)
        t0 = time.perf_counter()
        out = list(sched.map(range(args.splits), split))
        wall = time.perf_counter() - t0
        assert out == list(range(args.splits)), "ordering violated"
        if base is None:
            base = wall
        row = {
            "concurrency": c,
            "wall_s": round(wall, 3),
            "splits_per_s": round(args.splits / wall, 2),
            "speedup": round(base / wall, 2),
        }
        rows.append(row)
        if args.json:
            print(json.dumps(row), flush=True)
        else:
            print(f"c={c:<3} wall={row['wall_s']:.3f}s "
                  f"splits/s={row['splits_per_s']:.1f} "
                  f"speedup={row['speedup']:.2f}x", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
