"""Device A/B of the direct-address CSR join table on TPC-H Q3.

The direct table (ops/join.py DIRECT_DOMAIN_* path) is gated
accelerator-only because it loses on XLA:CPU; this script produces the
on-device evidence for that gate: it times Q3 with the table forced off
(PRESTO_TPU_DIRECT_JOIN=0, binary-search probes) and forced on (=1,
O(1) CSR gathers) in two child processes, verifies the row results
match, and writes TPU_AB.json next to TPU_MEASURED.json.

Run by tools/tpu_watch.sh when the tunnel recovers; safe to run by hand.
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(HERE, "TPU_AB.json")


def _child(direct: str) -> dict:
    import presto_tpu  # noqa: F401
    import jax

    cache_dir = os.path.join(HERE, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner
    from tests.tpch_queries import QUERIES

    sf = float(os.environ.get("BENCH_SF", "1.0"))
    platform = jax.devices()[0].platform
    tpch = Tpch(sf=sf, split_rows=1 << 23)
    mem = MemoryConnector()
    mem.load_from(tpch, "lineitem", columns=[
        "l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"])
    mem.load_from(tpch, "orders", columns=[
        "o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"])
    mem.load_from(tpch, "customer", columns=["c_custkey", "c_mktsegment"])
    catalog = Catalog()
    catalog.register("mem", mem)
    runner = QueryRunner(catalog)
    rows = mem.row_count("lineitem")

    sql = QUERIES[3]
    res = runner.execute(sql)  # warmup (compile)
    times = []
    for _ in range(3):
        t0 = time.time()
        res = runner.execute(sql)
        times.append(time.time() - t0)
    best = min(times)
    return {
        "platform": platform,
        "direct": direct,
        "seconds": round(best, 4),
        "rows_per_sec": round(rows / best, 1),
        "result_rows": [[str(c) for c in r] for r in res],
    }


def _rows_match(a, b, rel=1e-9) -> bool:
    """All rows, numeric columns compared with relative tolerance: the
    two join paths may feed the float revenue sum in different orders,
    so last-ulp drift must not read as a correctness mismatch."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for ca, cb in zip(ra, rb):
            if ca == cb:
                continue
            try:
                fa, fb = float(ca), float(cb)
            except ValueError:
                return False
            if abs(fa - fb) > rel * max(1.0, abs(fa), abs(fb)):
                return False
    return True


def main() -> int:
    if os.environ.get("AB_MODE") == "child":
        print("AB_RESULT:" + json.dumps(_child(
            os.environ["PRESTO_TPU_DIRECT_JOIN"])), flush=True)
        return 0

    results = {}
    for direct in ("0", "1"):
        env = dict(os.environ)
        env.update({"AB_MODE": "child", "PRESTO_TPU_DIRECT_JOIN": direct})
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                cwd=HERE, timeout=float(os.environ.get("AB_TIMEOUT", "1800")),
                stdout=subprocess.PIPE, stderr=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"direct={direct}: child timed out", file=sys.stderr)
            continue
        for line in proc.stdout.decode().splitlines():
            if line.startswith("AB_RESULT:"):
                results[direct] = json.loads(line[len("AB_RESULT:"):])

    out = {"query": "q3", "sf": float(os.environ.get("BENCH_SF", "1.0")),
           "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    try:
        out["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=HERE,
            stdout=subprocess.PIPE).stdout.decode().strip()
    except Exception:
        pass
    if "0" in results and "1" in results:
        out["off"] = results["0"]
        out["on"] = results["1"]
        out["results_match"] = _rows_match(
            results["0"].pop("result_rows", []),
            results["1"].pop("result_rows", []))
        out["speedup_direct_on_vs_off"] = round(
            results["1"]["rows_per_sec"] / results["0"]["rows_per_sec"], 3)
    else:
        out["partial"] = {k: v for k, v in results.items()}
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print(json.dumps(out))
    return 0 if len(results) == 2 else 1


if __name__ == "__main__":
    sys.exit(main())
