"""Device A/B of the join addressing designs on TPC-H Q3.

Three legs, each a bounded child process on the same data:
  base    PRESTO_TPU_DIRECT_JOIN=0 PRESTO_TPU_UNIQUE_DIRECT=0
          (sorted build + binary-search probes)
  csr     PRESTO_TPU_DIRECT_JOIN=1 PRESTO_TPU_UNIQUE_DIRECT=0
          (sorted build + domain-sized CSR starts: O(1) probes,
          the r3 accelerator-gated design)
  unique  PRESTO_TPU_UNIQUE_DIRECT=1 (r4b: sort-FREE builds for
          planner-proven unique keys — rank by domain prefix count)
Row results are cross-checked with fp tolerance and TPU_AB.json lands
next to TPU_MEASURED.json.

Run by tools/tpu_watch.sh when the tunnel recovers; safe to run by hand.
"""

import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if HERE not in sys.path:  # children launch as tools/<script>.py
    sys.path.insert(0, HERE)
OUT = os.path.join(HERE, "TPU_AB.json")


def _child(direct: str) -> dict:
    # one-shot child process: env IS the experiment arm, resolved
    # once at child startup (resolve-once in spirit)
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # jax may be pre-imported at interpreter startup (axon plugin);
        # jax.config still works until the backend initializes
        import jax

        jax.config.update("jax_platforms", "cpu")
    import presto_tpu  # noqa: F401
    import jax

    cache_dir = os.path.join(HERE, ".jax_cache")
    jax.config.update("jax_compilation_cache_dir", os.path.abspath(cache_dir))
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.ops.join import (
        set_direct_join_override, set_unique_direct_override,
    )
    from presto_tpu.runner import QueryRunner
    from tests.tpch_queries import QUERIES

    # the env vars are resolved once per process now; set the explicit
    # overrides too so a leg flip can never be lost to caching order
    set_direct_join_override(
        os.environ.get("PRESTO_TPU_DIRECT_JOIN") == "1")
    set_unique_direct_override(
        os.environ.get("PRESTO_TPU_UNIQUE_DIRECT") == "1")

    sf = float(os.environ.get("BENCH_SF", "1.0"))
    platform = jax.devices()[0].platform
    tpch = Tpch(sf=sf, split_rows=1 << 23)
    mem = MemoryConnector()
    mem.load_from(tpch, "lineitem", columns=[
        "l_orderkey", "l_extendedprice", "l_discount", "l_shipdate"])
    mem.load_from(tpch, "orders", columns=[
        "o_orderkey", "o_custkey", "o_orderdate", "o_shippriority"])
    mem.load_from(tpch, "customer", columns=["c_custkey", "c_mktsegment"])
    catalog = Catalog()
    catalog.register("mem", mem)
    runner = QueryRunner(catalog)
    rows = mem.row_count("lineitem")

    sql = QUERIES[3]
    res = runner.execute(sql)  # warmup (compile)
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        res = runner.execute(sql)
        times.append(time.perf_counter() - t0)
    best = min(times)
    return {
        "platform": platform,
        "leg": direct,
        "seconds": round(best, 4),
        "rows_per_sec": round(rows / best, 1),
        "result_rows": [[str(c) for c in r] for r in res],
    }


def _rows_match(a, b, rel=1e-9) -> bool:
    """All rows, numeric columns compared with relative tolerance: the
    two join paths may feed the float revenue sum in different orders,
    so last-ulp drift must not read as a correctness mismatch."""
    if len(a) != len(b):
        return False
    for ra, rb in zip(a, b):
        if len(ra) != len(rb):
            return False
        for ca, cb in zip(ra, rb):
            if ca == cb:
                continue
            try:
                fa, fb = float(ca), float(cb)
            except ValueError:
                return False
            if abs(fa - fb) > rel * max(1.0, abs(fa), abs(fb)):
                return False
    return True


LEGS = {
    "base": {"PRESTO_TPU_DIRECT_JOIN": "0", "PRESTO_TPU_UNIQUE_DIRECT": "0"},
    "csr": {"PRESTO_TPU_DIRECT_JOIN": "1", "PRESTO_TPU_UNIQUE_DIRECT": "0"},
    "unique": {"PRESTO_TPU_DIRECT_JOIN": "0",
               "PRESTO_TPU_UNIQUE_DIRECT": "1"},
}


def main() -> int:
    if os.environ.get("AB_MODE") == "child":
        print("AB_RESULT:" + json.dumps(_child(
            os.environ.get("AB_LEG", "?"))), flush=True)
        return 0

    results = {}
    for leg, envs in LEGS.items():
        env = dict(os.environ)
        env.update({"AB_MODE": "child", "AB_LEG": leg})
        env.update(envs)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)], env=env,
                cwd=HERE, timeout=float(os.environ.get("AB_TIMEOUT", "1800")),
                stdout=subprocess.PIPE, stderr=sys.stderr)
        except subprocess.TimeoutExpired:
            print(f"{leg}: child timed out", file=sys.stderr)
            continue
        for line in proc.stdout.decode().splitlines():
            if line.startswith("AB_RESULT:"):
                results[leg] = json.loads(line[len("AB_RESULT:"):])

    out = {"query": "q3", "sf": float(os.environ.get("BENCH_SF", "1.0")),
           "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    try:
        out["commit"] = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=HERE,
            stdout=subprocess.PIPE).stdout.decode().strip()
    except Exception:
        pass
    if "base" in results and len(results) > 1:
        base_rows = results["base"].pop("result_rows", [])
        out["results_match"] = all(
            _rows_match(base_rows, results[k].pop("result_rows", []))
            for k in results if k != "base")
        base_rate = results["base"]["rows_per_sec"]
        for k in results:
            if k != "base":
                out[f"speedup_{k}_vs_base"] = round(
                    results[k]["rows_per_sec"] / base_rate, 3)
        out["legs"] = results
    else:
        for v in results.values():
            v.pop("result_rows", None)
        out["partial"] = results
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1, sort_keys=True)
    print(json.dumps(out))
    return 0 if len(results) == 2 else 1


if __name__ == "__main__":
    sys.exit(main())
