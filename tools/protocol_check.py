#!/usr/bin/env python
"""Protocol-soundness gate: bounded model checking + runtime conformance.

The one-command proof behind the protocol tier
(docs/static-analysis.md, "Protocol soundness"):

1. **Exploration** — runs the deterministic schedule explorer
   (``presto_tpu/analysis/mcheck.py``) over all four protocol models
   (exchange token/ack/abort, failure detector, fragment-retry budget,
   admission tickets) to their pinned depths.  Any reachable invariant
   violation is printed with its replayable counterexample schedule
   and fails the gate — a protocol bug one interleaving away.

2. **Conformance** — arms ``PRESTO_TPU_PROTOCOL_TRACE=1`` **before**
   importing presto_tpu, boots a real 2-worker
   ``DistributedQueryRunner``, and runs a faulted workload: a worker
   dies mid-query (fragment failover + watermark replay), a results
   response is duplicated (``net.duplicate_page`` — the client dedupe
   must swallow it), and acks are dropped (``net.drop_ack`` — replay
   must stay exactly-once).  Every event the implementation emitted is
   then replayed through the spec automata
   (``presto_tpu/analysis/protocols.py``); a rejected trace means the
   implementation and the model diverged — on THIS machine, under the
   pinned fault seed.

Exit status: 0 when exploration is clean AND the runtime trace
conforms; 1 otherwise.

Usage::

    python tools/protocol_check.py            # human summary + verdict
    python tools/protocol_check.py --json     # full machine report
    PRESTO_TPU_FAULT_SEED=1234 python tools/protocol_check.py
"""

import argparse
import json
import os
import sys

# MUST precede any presto_tpu import: the recorder's enable flag is
# resolved when analysis/protocols.py constructs it at import time
os.environ["PRESTO_TPU_PROTOCOL_TRACE"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

#: conformance workload: a multihost aggregation (fragment fan-out,
#: token/ack exchange), a distributed ORDER BY (per-shard sort +
#: merge, multiple buffers), and a coordinator-protocol query (REST
#: statement path -> admission tickets)
WORKLOAD_MULTIHOST = [
    "SELECT count(*) FROM lineitem",
    "SELECT l_returnflag, count(*), sum(l_quantity) FROM lineitem "
    "GROUP BY l_returnflag ORDER BY l_returnflag",
    "SELECT l_orderkey, l_extendedprice FROM lineitem "
    "ORDER BY l_extendedprice DESC, l_orderkey LIMIT 50",
]
WORKLOAD_REST = [
    "SELECT sum(l_extendedprice) FROM lineitem WHERE l_quantity < 24",
]


def run_exploration(seed: int) -> dict:
    from presto_tpu.analysis.mcheck import PINNED_DEPTHS, explore_all

    results = explore_all(seed=seed)
    report = {}
    ok = True
    for name, r in sorted(results.items()):
        report[name] = {
            "depth": PINNED_DEPTHS[name],
            "states": r.states,
            "transitions": r.transitions,
            "hit_state_cap": r.hit_state_cap,
            "counterexamples": [
                {"faults": sorted(cex.faults),
                 "trace": [list(a) for a in cex.trace]}
                for cex in r.counterexamples],
        }
        if not r.ok or r.hit_state_cap:
            ok = False
    report["ok"] = ok
    return report


def run_conformance(n_workers: int, sf: float) -> dict:
    from presto_tpu.analysis.protocols import RECORDER
    from presto_tpu.testing import DistributedQueryRunner
    from presto_tpu.testing_faults import FAULTS, arm_from_env

    arm_from_env()  # PRESTO_TPU_FAULT_SEED / PRESTO_TPU_FAULTS
    RECORDER.reset()
    rig = DistributedQueryRunner(n_workers=n_workers, sf=sf,
                                 split_rows=2048)
    rig.multihost.min_stage_rows = 0  # force breaker stages distributed
    queries = 0
    try:
        # clean pass first: the failover replay below re-pulls from the
        # survivor, and the detector needs a success history to recover
        for sql in WORKLOAD_MULTIHOST:
            rig.execute_multihost(sql)
            queries += 1
        # chaos pass: mid-stream worker death (watermark replay),
        # duplicated results response, dropped acks — the protocol
        # surfaces the models prove invariants over
        rig.arm_fault("worker.die_after_n_pages", worker=0, pages=3)
        rig.execute_multihost(WORKLOAD_MULTIHOST[0])
        queries += 1
        FAULTS.disarm_all()
        # worker 0 is "dead" from the fault above — the net faults go
        # on the SURVIVOR, whose pulls actually happen
        rig.arm_fault("net.duplicate_page", worker=1, after=1, count=2)
        rig.arm_fault("net.drop_ack", worker=1, count=2)
        for sql in WORKLOAD_MULTIHOST[:2]:
            rig.execute_multihost(sql)
            queries += 1
        FAULTS.disarm_all()
        # coordinator/REST path: admission tickets + root-stage pull
        for sql in WORKLOAD_REST:
            rig.execute(sql)
            queries += 1
    finally:
        FAULTS.disarm_all()
        rig.close()

    events = RECORDER.events()
    violations = RECORDER.check()
    by_protocol = {}
    for ev in events:
        by_protocol[ev.protocol] = by_protocol.get(ev.protocol, 0) + 1
    return {
        "queries": queries,
        "events": len(events),
        "events_dropped": RECORDER.dropped,
        "by_protocol": by_protocol,
        "violations": [
            {"invariant": v.invariant, "key": v.key, "seq": v.seq,
             "message": v.message}
            for v in violations],
        "ok": not violations and not RECORDER.dropped,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="TPC-H scale factor for the conformance rig")
    ap.add_argument("--workers", type=int, default=2,
                    help="worker count for the conformance rig")
    ap.add_argument("--seed", type=int, default=0,
                    help="exploration schedule seed (0 = deterministic "
                         "DFS order)")
    ap.add_argument("--skip-conformance", action="store_true",
                    help="exploration only (no rig boot — for "
                         "constrained environments)")
    args = ap.parse_args(argv)

    explore = run_exploration(args.seed)
    conform = None
    if not args.skip_conformance:
        conform = run_conformance(args.workers, args.sf)

    ok = explore["ok"] and (conform is None or conform["ok"])
    if args.as_json:
        print(json.dumps({"exploration": explore, "conformance": conform,
                          "ok": ok}, indent=2))
    else:
        for name, r in sorted(explore.items()):
            if name == "ok":
                continue
            verdict = ("OK" if not r["counterexamples"]
                       and not r["hit_state_cap"] else "FAIL")
            print(f"explore {name:<10} depth={r['depth']:<3} "
                  f"states={r['states']:<7} "
                  f"transitions={r['transitions']:<8} {verdict}")
            for cex in r["counterexamples"]:
                print(f"  counterexample ({len(cex['trace'])} steps): "
                      f"{cex['faults']}")
                for step in cex["trace"]:
                    print(f"    {step}")
        if conform is not None:
            print(f"conformance: {conform['queries']} queries, "
                  f"{conform['events']} events "
                  f"{conform['by_protocol']}, "
                  f"{len(conform['violations'])} violation(s)"
                  + (f", {conform['events_dropped']} DROPPED"
                     if conform["events_dropped"] else ""))
            for v in conform["violations"]:
                print(f"  VIOLATION [{v['invariant']}] {v['key']} "
                      f"seq={v['seq']}: {v['message']}")
        print(f"{'OK' if ok else 'FAIL'}: protocol soundness "
              f"{'holds' if ok else 'violated'}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
