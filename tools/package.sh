#!/bin/bash
# Server package assembly — the presto-server (provisio tarball) /
# presto-server-rpm slot: produce a relocatable
# dist/presto-tpu-<version>.tar.gz containing
#
#   presto-tpu-<version>/
#     bin/launcher            start|stop|restart|status|run wrapper
#     lib/presto_tpu/...      the engine package
#     etc/                    default configs (coordinator role, tpch
#                             catalog) — the reference tarball's
#                             etc/ skeleton
#     docs/ README.md PARITY.md
#
# Unpack anywhere with python+jax available:
#   tar xzf presto-tpu-<v>.tar.gz && presto-tpu-<v>/bin/launcher start
set -euo pipefail
cd "$(dirname "$0")/.."

VERSION=${VERSION:-$(git rev-list --count HEAD 2>/dev/null || echo 0).r4}
NAME="presto-tpu-${VERSION}"
STAGE="dist/${NAME}"

rm -rf "$STAGE"
mkdir -p "$STAGE"/{bin,lib,etc/catalog,docs}

# engine package (no tests, no caches)
rsync -a --exclude '__pycache__' presto_tpu "$STAGE/lib/" 2>/dev/null || {
  mkdir -p "$STAGE/lib"
  tar cf - --exclude '__pycache__' presto_tpu | tar xf - -C "$STAGE/lib"
}
cp README.md PARITY.md "$STAGE/" 2>/dev/null || true
cp -r docs "$STAGE/" 2>/dev/null || true

# default etc/: coordinator role + tpch catalog (reference default
# config.properties/node.properties/catalog/*.properties skeleton)
cat > "$STAGE/etc/config.properties" <<'EOF'
coordinator=true
http-server.http.port=8080
EOF
cat > "$STAGE/etc/node.properties" <<'EOF'
node.environment=production
EOF
cat > "$STAGE/etc/catalog/tpch.properties" <<'EOF'
connector.name=tpch
tpch.scale-factor=0.01
EOF

# launcher wrapper (bin/launcher of the reference tarball)
cat > "$STAGE/bin/launcher" <<'EOF'
#!/bin/bash
set -euo pipefail
HERE="$(cd "$(dirname "$0")/.." && pwd)"
export PYTHONPATH="$HERE/lib${PYTHONPATH:+:$PYTHONPATH}"
CMD="${1:-status}"; shift || true
exec python -m presto_tpu.launcher "$CMD" --etc "$HERE/etc" "$@"
EOF
chmod +x "$STAGE/bin/launcher"

mkdir -p dist
tar czf "dist/${NAME}.tar.gz" -C dist "$NAME"
echo "dist/${NAME}.tar.gz"
