#!/usr/bin/env python
"""Diff the two newest ``BENCH_r*.json`` trajectory files.

The bench driver writes one ``BENCH_rNN.json`` per round but nothing
reads the series — regressions surfaced only when a human opened two
files side by side.  This tool compares the newest round against the
previous one:

- per-query rows/s ratios (TPC-H ``rates`` + TPC-DS ``tpcds_rates``),
  flagging regressions beyond the threshold (default 20%),
- median ± half-spread per query from the ``raw_times`` repeat blocks
  (the variance protocol's evidence), when both rounds carry them, so
  a flagged drop is distinguishable from host noise,
- the geomean ratio over the common query set,
- the query doctor's top finding for each flagged regression, when the
  new round's payload carries a ``doctor`` map (benchmark_driver rows
  include one) — the diagnosed bottleneck prints under the flag,
- the worst estimate-vs-actual ratio for each flagged regression, when
  the new round carries a ``misestimates`` map ({query: ratio};
  benchmark_driver rows ship ``worst_estimate_ratio``) — a planner
  misestimate prints as a candidate cause next to the drop.

Exit code: 0 always in report mode (`tools/ci.sh` runs it as a
non-fatal step); ``--strict`` exits 1 when a regression is flagged.

Usage::

    python tools/bench_compare.py [--dir .] [--threshold 0.2] [--strict]
"""

from __future__ import annotations

import argparse
import json
import math
import os
import re
import sys
from typing import Dict, List, Optional, Tuple

_ROUND_RE = re.compile(r"BENCH_r(\d+)\.json$")


def find_rounds(directory: str) -> List[Tuple[int, str]]:
    out = []
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    for name in names:
        m = _ROUND_RE.match(name)
        if m:
            out.append((int(m.group(1)), os.path.join(directory, name)))
    return sorted(out)


def load_round(path: str) -> Optional[dict]:
    """The bench payload of one trajectory file: the driver wraps the
    child's BENCH line under ``parsed``; a bare payload (rates at top
    level) is accepted too."""
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    payload = doc.get("parsed") if isinstance(doc, dict) else None
    if not isinstance(payload, dict):
        payload = doc if isinstance(doc, dict) and "rates" in doc else None
    if payload is None or not payload.get("rates"):
        return None
    return payload


def _geomean(values: List[float]) -> float:
    vals = [v for v in values if v > 0]
    if not vals:
        return 0.0
    return math.exp(sum(math.log(v) for v in vals) / len(vals))


def _median_spread(times: List[float]) -> Tuple[float, float]:
    ts = sorted(float(t) for t in times)
    n = len(ts)
    med = ts[n // 2] if n % 2 else (ts[n // 2 - 1] + ts[n // 2]) / 2
    return med, (ts[-1] - ts[0]) / 2


def _fmt_rate(v: float) -> str:
    if v >= 1e6:
        return f"{v / 1e6:.2f}M/s"
    if v >= 1e3:
        return f"{v / 1e3:.1f}k/s"
    return f"{v:.0f}/s"


def compare(old: dict, new: dict, threshold: float = 0.2) -> dict:
    """{"queries": [...], "regressions": [...], "geomean_ratio": x}"""
    rows = []
    regressions = []
    for key in ("rates", "tpcds_rates"):
        o, n = old.get(key) or {}, new.get(key) or {}
        for q in sorted(set(o) & set(n)):
            if not o[q]:
                continue
            ratio = n[q] / o[q]
            entry = {"query": q, "old": o[q], "new": n[q],
                     "ratio": round(ratio, 3)}
            for side, payload in (("old", old), ("new", new)):
                raw = (payload.get("raw_times") or {}).get(q)
                if raw:
                    med, spread = _median_spread(raw)
                    entry[f"{side}_median_s"] = round(med, 4)
                    entry[f"{side}_spread_s"] = round(spread, 4)
            if ratio < 1.0 - threshold:
                entry["regression"] = True
                regressions.append(q)
            # the query doctor's top finding for the NEW round, when the
            # payload carries one ({query: {rule, score, summary}}) —
            # a flagged drop arrives with its diagnosed bottleneck
            doc = (new.get("doctor") or {}).get(q)
            if isinstance(doc, dict) and doc.get("rule"):
                entry["doctor"] = doc
            # the worst estimate-vs-actual ratio of the NEW round, when
            # the payload carries a ``misestimates`` map ({query:
            # ratio} — benchmark_driver rows ship worst_estimate_ratio)
            mis = (new.get("misestimates") or {}).get(q)
            if mis is not None:
                try:
                    entry["misestimate"] = round(float(mis), 2)
                except (TypeError, ValueError):
                    pass
            rows.append(entry)
    common_tpch = sorted(set(old.get("rates") or {})
                         & set(new.get("rates") or {}))
    geo = None
    if common_tpch:
        geo = round(
            _geomean([new["rates"][q] for q in common_tpch])
            / _geomean([old["rates"][q] for q in common_tpch]), 3)
    return {"queries": rows, "regressions": regressions,
            "geomean_ratio": geo}


def report(old_path: str, new_path: str, result: dict,
           threshold: float) -> str:
    lines = [f"bench trajectory: {os.path.basename(old_path)} -> "
             f"{os.path.basename(new_path)} "
             f"(regression threshold {threshold:.0%})"]
    for e in result["queries"]:
        delta = (e["ratio"] - 1.0) * 100
        flag = "  ** REGRESSION **" if e.get("regression") else ""
        extra = ""
        if "new_median_s" in e:
            extra = f"  [median {e['new_median_s']}s ±{e['new_spread_s']}s"
            if "old_median_s" in e:
                extra += f" vs {e['old_median_s']}s ±{e['old_spread_s']}s"
            extra += "]"
        lines.append(
            f"  {e['query']:<8} {_fmt_rate(e['old']):>10} -> "
            f"{_fmt_rate(e['new']):>10}  {delta:+6.1f}%{extra}{flag}")
        if e.get("regression") and e.get("doctor"):
            d = e["doctor"]
            lines.append(f"           doctor: {d['rule']} "
                         f"(score {d['score']:.2f}): {d['summary']}")
        if e.get("regression") and e.get("misestimate") is not None:
            lines.append(f"           misestimate: worst est-vs-actual "
                         f"x{e['misestimate']:.1f} — stale stats may "
                         "explain the drop (try feedback_stats)")
    if result["geomean_ratio"] is not None:
        lines.append(f"  geomean ratio (tpch common set): "
                     f"{result['geomean_ratio']:.3f}x")
    if result["regressions"]:
        lines.append(f"  {len(result['regressions'])} regression(s): "
                     + ", ".join(result["regressions"]))
    else:
        lines.append("  no regressions beyond threshold")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=".",
                    help="directory holding BENCH_r*.json (repo root)")
    ap.add_argument("--threshold", type=float, default=0.2,
                    help="per-query rate drop that counts as a "
                         "regression (fraction, default 0.2)")
    ap.add_argument("--strict", action="store_true",
                    help="exit 1 when a regression is flagged "
                         "(default: report-only, exit 0)")
    args = ap.parse_args(argv)

    rounds = find_rounds(args.dir)
    if len(rounds) < 2:
        print(f"bench-compare: need two BENCH_r*.json rounds under "
              f"{args.dir!r}, found {len(rounds)} — nothing to diff")
        return 0
    (r_old, old_path), (r_new, new_path) = rounds[-2], rounds[-1]
    old, new = load_round(old_path), load_round(new_path)
    if old is None or new is None:
        which = old_path if old is None else new_path
        print(f"bench-compare: {which} carries no usable rates "
              "(partial/failed round) — skipping the diff")
        return 0
    result = compare(old, new, threshold=args.threshold)
    print(report(old_path, new_path, result, args.threshold))
    return 1 if (args.strict and result["regressions"]) else 0


if __name__ == "__main__":
    sys.exit(main())
