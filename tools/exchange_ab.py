#!/usr/bin/env python
"""Streamed-vs-materialized exchange A/B (PERF.md protocol).

Boots an in-process multihost rig (coordinator + N HTTP workers, the
DistributedQueryRunner shape), runs the three distributed breaker
shapes — windowed query, large ORDER BY, 3-leg UNION — with the
streaming exchange ON and OFF, ``--repeat`` times each, and reports:

* wall medians +- spread per leg (the --repeat variance protocol);
* stage overlap: the consumer's first-page time vs the last producer's
  completion on the streamed gather (first_page < producers_done means
  stage k+1 consumed while stage k still produced);
* peak exchange memory (unacked bytes high-water vs the buffer cap).

Usage: python tools/exchange_ab.py [--sf 0.05] [--workers 2]
           [--repeat 5] [--split-rows 4096] [--json out.json]
"""

from __future__ import annotations

import argparse
import json
import statistics
import time


QUERIES = {
    "window": ("SELECT o_custkey, o_totalprice, "
               "sum(o_totalprice) OVER (PARTITION BY o_custkey) "
               "FROM orders"),
    "orderby": ("SELECT l_orderkey, l_extendedprice FROM lineitem "
                "ORDER BY l_extendedprice, l_orderkey"),
    "union3": ("SELECT o_orderkey FROM orders "
               "UNION ALL SELECT o_orderkey FROM orders "
               "UNION ALL SELECT l_orderkey FROM lineitem"),
}


def run_leg(mh, local, sql, repeat, streaming):
    mh.exchange_streaming = streaming
    times = []
    overlap = None
    rows = 0
    for _ in range(repeat):
        plan = local.plan(sql)
        t0 = time.perf_counter()
        out = mh.run(plan)
        times.append(time.perf_counter() - t0)
        assert out.dist_fallback is None, out.dist_fallback
        rows = len(out.rows)
        st = dict(mh.last_exchange_stats)
        if streaming and st.get("pages"):
            overlap = {
                "first_page_lead_s": round(
                    st["producers_done_at"] - st["first_page_at"], 4),
                "peak_buffered_bytes": st["peak_buffered_bytes"],
                "pages": st["pages"],
            }
    med = statistics.median(times)
    spread = (max(times) - min(times)) / 2
    return {"median_s": round(med, 4), "spread_s": round(spread, 4),
            "raw_times_s": [round(t, 4) for t in times], "rows": rows,
            "overlap": overlap}


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sf", type=float, default=0.05)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--repeat", type=int, default=5)
    ap.add_argument("--split-rows", type=int, default=4096)
    ap.add_argument("--json", default=None)
    args = ap.parse_args()

    from presto_tpu.testing import DistributedQueryRunner

    rig = DistributedQueryRunner(n_workers=args.workers, sf=args.sf,
                                 split_rows=args.split_rows)
    rig.multihost.min_stage_rows = 0
    report = {"sf": args.sf, "workers": args.workers,
              "repeat": args.repeat, "split_rows": args.split_rows,
              "buffer_bytes": rig.multihost.exchange_buffer_bytes,
              "queries": {}}
    try:
        for name, sql in QUERIES.items():
            # warm both legs once (compile + dictionaries)
            run_leg(rig.multihost, rig.runner, sql, 1, True)
            run_leg(rig.multihost, rig.runner, sql, 1, False)
            streamed = run_leg(rig.multihost, rig.runner, sql,
                               args.repeat, True)
            materialized = run_leg(rig.multihost, rig.runner, sql,
                                   args.repeat, False)
            ratio = (materialized["median_s"] / streamed["median_s"]
                     if streamed["median_s"] else float("nan"))
            report["queries"][name] = {
                "streamed": streamed, "materialized": materialized,
                "speedup_streamed": round(ratio, 3),
            }
            ov = streamed["overlap"] or {}
            print(f"{name:8s} streamed {streamed['median_s']:.3f}s "
                  f"+-{streamed['spread_s']:.3f} | materialized "
                  f"{materialized['median_s']:.3f}s "
                  f"+-{materialized['spread_s']:.3f} | x{ratio:.2f} | "
                  f"first-page lead {ov.get('first_page_lead_s', 0)}s, "
                  f"peak buffered {int(ov.get('peak_buffered_bytes', 0))}B",
                  flush=True)
    finally:
        rig.close()
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
