#!/bin/bash
# Supervise the TPU tunnel for the whole round.  Poll every 2 minutes;
# on recovery, snapshot the last COMMIT (not the mid-edit working tree)
# into .tpu_snap and run, in order:
#   1. SF1 bench            -> TPU_MEASURED.json (sf1)
#   2. direct-join A/B (Q3) -> TPU_AB.json
#   3. SF10 bench           -> TPU_MEASURED.json (sf10)
# The tunnel is re-probed before each step (a mid-sequence death must
# not burn hours of timeouts), and artifacts are copied back to the
# repo root after each step, so a tunnel that dies mid-sequence still
# leaves whatever it finished.  A sequence counts as a capture only if
# TPU_MEASURED.json actually CHANGED (stale carry-forward is not
# success).  After a successful capture the watcher keeps polling and
# re-runs if HEAD has advanced >= 20 commits since.  Log: bench_tpu.log.
cd /root/repo || exit 1
LOG=bench_tpu.log
SNAP=.tpu_snap
ROUNDS=${ROUNDS:-400}
last_capture_commit=""

log() { echo "$(date -u +%FT%TZ) $*" >> "$LOG"; }

probe() {
  timeout 60 python -c "import jax,jax.numpy as jnp; assert jax.default_backend()!='cpu'; print(float(jnp.arange(8).sum()))" >/dev/null 2>&1
}

tpu_sum() { sha256sum TPU_MEASURED.json 2>/dev/null | cut -d' ' -f1; }

snapshot() {
  rm -rf "$SNAP"
  mkdir -p "$SNAP"
  git archive HEAD | tar -x -C "$SNAP" || return 1
  mkdir -p .jax_cache
  ln -sfn "$(pwd)/.jax_cache" "$SNAP/.jax_cache"
  # carry forward accumulated measurements so per-sf entries merge;
  # BASELINE_MEASURED.json comes from git archive (it is committed)
  [ -f TPU_MEASURED.json ] && cp TPU_MEASURED.json "$SNAP/"
  return 0
}

copy_back() {
  for f in TPU_MEASURED.json TPU_AB.json; do
    [ -f "$SNAP/$f" ] && cp "$SNAP/$f" .
  done
  return 0
}

run_sequence() {
  snapshot || { log "snapshot failed"; return 1; }
  local before
  before=$(tpu_sum)
  log "recovery: running SF1 bench"
  (cd "$SNAP" && BENCH_SF=1.0 BENCH_ITERS=3 BENCH_DEADLINE=2700 \
    timeout 3000 python bench.py >> "../$LOG" 2>&1)
  log "SF1 bench rc=$?"; copy_back
  if probe; then
    log "running direct-join A/B"
    (cd "$SNAP" && BENCH_SF=1.0 AB_TIMEOUT=1500 \
      timeout 3200 python tools/tpu_ab_direct_join.py >> "../$LOG" 2>&1)
    log "A/B rc=$?"; copy_back
  else
    log "tunnel died before A/B; skipping rest of sequence"
  fi
  if probe; then
    log "running SF10 bench"
    (cd "$SNAP" && BENCH_SF=10 BENCH_ITERS=2 BENCH_DEADLINE=5000 \
      timeout 5400 python bench.py >> "../$LOG" 2>&1)
    log "SF10 bench rc=$?"; copy_back
  else
    log "tunnel died before SF10; skipping"
  fi
  if [ -f TPU_MEASURED.json ] && [ "$(tpu_sum)" != "$before" ]; then
    last_capture_commit=$(git rev-parse HEAD)
    log "capture complete at $last_capture_commit"
    return 0
  fi
  log "sequence produced no new measurement"
  return 1
}

log "watcher started (pid $$)"
for i in $(seq 1 "$ROUNDS"); do
  if probe; then
    if [ -z "$last_capture_commit" ]; then
      run_sequence
    else
      ahead=$(git rev-list --count "$last_capture_commit"..HEAD 2>/dev/null || echo 0)
      if [ "$ahead" -ge 20 ]; then
        log "HEAD moved $ahead commits since capture; re-running"
        run_sequence
      fi
    fi
    sleep 600
  else
    sleep 120
  fi
done
log "watcher done after $ROUNDS polls"
