#!/bin/bash
# Poll the TPU tunnel; when it answers, run the SF1 benchmark once
# (persisting rates to TPU_MEASURED.json) and exit.
cd /root/repo
for i in $(seq 1 200); do
  if timeout 60 python -c "import jax,jax.numpy as jnp; print(float(jnp.arange(8).sum()))" >/dev/null 2>&1; then
    echo "$(date) tunnel up, running bench" >> bench_tpu.log
    BENCH_SF=${BENCH_SF:-1.0} BENCH_ITERS=3 BENCH_DEADLINE=3000 timeout 3300 python bench.py >> bench_tpu.log 2>&1
    echo "$(date) bench done rc=$?" >> bench_tpu.log
    exit 0
  fi
  sleep 120
done
echo "$(date) gave up waiting for tunnel" >> bench_tpu.log
