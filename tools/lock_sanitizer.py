#!/usr/bin/env python
"""Instrumented-lock runtime verification + static/dynamic cross-check.

The one-command proof behind the concurrency sanitizer
(docs/static-analysis.md):

1. arms ``PRESTO_TPU_LOCK_SANITIZER=1`` **before** importing
   presto_tpu, so module-level locks instrument too;
2. runs a distributed workload — multihost fragment fan-out over real
   HTTP workers (window shuffle, distributed ORDER BY, UNION legs,
   aggregation) plus a coordinator-protocol query — the exact surfaces
   PR 6-8 threaded;
3. collects the observed lock-acquisition graph, hold/wait times, and
   any lock-order **inversions** from ``presto_tpu.sync.WATCHER``;
4. runs the static analyzer (``presto_tpu/analysis/concurrency.py``)
   over the repo and cross-checks: every statically-possible
   lock-order cycle is marked confirmed / refuted / unobserved by the
   runtime evidence.

Exit status: 0 when zero inversions were observed (static cycles may
still be "unobserved"); 1 when the runtime saw an inversion — a real
deadlock one interleaving away.

Usage::

    python tools/lock_sanitizer.py            # human summary + verdict
    python tools/lock_sanitizer.py --json     # full machine report
    python tools/lock_sanitizer.py --sf 0.05  # heavier workload
"""

import argparse
import json
import os
import sys

# MUST precede any presto_tpu import: module-level locks (_REG_LOCK,
# trace/progress registries, the default-pool lock) are created at
# import time and only instrument if the flag is already set
os.environ["PRESTO_TPU_LOCK_SANITIZER"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

#: the distributed/multihost subset: window shuffle (two-stage), large
#: ORDER BY (per-shard sort + k-way merge), UNION legs on one
#: exchange, distributed aggregation, and a LIMIT early-close (the
#: drain-abort path)
WORKLOAD = [
    "SELECT l_orderkey, sum(l_extendedprice) OVER "
    "(PARTITION BY l_orderkey) AS s FROM lineitem ORDER BY l_orderkey, s "
    "LIMIT 20",
    "SELECT l_orderkey, l_extendedprice FROM lineitem "
    "ORDER BY l_extendedprice DESC, l_orderkey LIMIT 50",
    "SELECT l_orderkey AS k FROM lineitem UNION ALL "
    "SELECT o_orderkey AS k FROM orders ORDER BY k LIMIT 30",
    "SELECT l_returnflag, count(*), sum(l_quantity) FROM lineitem "
    "GROUP BY l_returnflag ORDER BY l_returnflag",
    "SELECT count(*) FROM lineitem",
]


def run_workload(sf: float, n_workers: int) -> dict:
    from presto_tpu.testing import DistributedQueryRunner

    executed = []
    with DistributedQueryRunner(n_workers=n_workers, sf=sf) as dqr:
        for sql in WORKLOAD:
            rows = dqr.execute_multihost(sql)
            executed.append({"sql": sql, "rows": len(rows)})
        # the statement protocol path too (coordinator threads + pools)
        rows = dqr.execute("SELECT count(*) FROM orders")
        executed.append({"sql": "count(orders) via REST",
                         "rows": len(rows)})
    return {"queries": executed}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit the full machine-readable report")
    ap.add_argument("--sf", type=float, default=0.01,
                    help="TPC-H scale factor for the workload")
    ap.add_argument("--workers", type=int, default=2,
                    help="HTTP worker count")
    ap.add_argument("--skip-workload", action="store_true",
                    help="cross-check only (whatever the process has "
                         "already observed)")
    args = ap.parse_args(argv)

    import presto_tpu.sync as sync
    from presto_tpu.analysis import concurrency

    workload = {}
    if not args.skip_workload:
        workload = run_workload(args.sf, args.workers)

    runtime = sync.WATCHER.report()
    static_findings, static_report = concurrency.analyze(
        [os.path.join(_REPO, "presto_tpu")])
    xc = concurrency.crosscheck(static_report, runtime)

    report = {
        "workload": workload,
        "runtime": runtime,
        "static": {
            "cycles": static_report["cycles"],
            "findings": [f._asdict() for f in static_findings],
        },
        "crosscheck": xc,
    }
    if args.as_json:
        print(json.dumps(report, indent=2))
    else:
        locks = runtime["locks"]
        total_acq = sum(s["acquisitions"] for s in locks.values())
        print(f"locks observed : {len(locks)} "
              f"({total_acq} acquisitions)")
        print(f"order edges    : {len(runtime['edges'])} observed, "
              f"{len(static_report['edges'])} static")
        for name, s in sorted(locks.items(),
                              key=lambda kv: -kv[1]["hold_s"])[:8]:
            print(f"  {name:48s} acq={s['acquisitions']:<7d} "
                  f"hold={s['hold_s']:.4f}s wait={s['wait_s']:.4f}s")
        print(f"static cycles  : {len(static_report['cycles'])}")
        for c in xc["cycles"]:
            print(f"  {' -> '.join(c['cycle'])} : {c['verdict']} "
                  f"({c['edges_observed']}/{c['edges_total']} edges)")
        print(f"inversions     : {len(runtime['inversions'])}")
        for inv in runtime["inversions"]:
            print(f"  INVERSION {inv['held']} -> {inv['acquired']} "
                  f"on {inv['thread']} (held: {inv['held_stack']})")
    if runtime["inversions"]:
        print("FAIL: lock-order inversion(s) observed", file=sys.stderr)
        return 1
    print("OK: zero lock-order inversions", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
