#!/usr/bin/env bash
# One-command CI gate (the reference's maven verify analog):
#
#   1. engine anti-pattern lint   (tools/engine_lint.py --check, over
#      the engine AND the tools themselves)
#   2. bench trajectory diff      (tools/bench_compare.py — non-fatal
#      report: >20% per-query rate drops between the two newest
#      BENCH_r*.json rounds are flagged, not failed)
#   3. plan-validator corpus      (tests/test_plan_validator.py:
#      every TPC-H/TPC-DS query binds + validates clean, seeded-bug
#      mutations still diagnose)
#   3b. corpus plan-diff          (tools/plan_diff.py --check: golden
#      plan-shape fingerprints for all 22 TPC-H + 99 TPC-DS queries,
#      planned under the rewrite-soundness gate; an optimizer change
#      that moves plans must refresh the goldens with --update)
#   3c. doctor/telemetry smoke    (metrics-history ring armed over real
#      queries: ticks recorded, per-query timelines populated, doctor
#      findings schema-valid, sampler thread stops clean)
#   4. fault-injection leg        (tests/test_fault_tolerance.py under
#      a FIXED fault seed: the chaos schedules — worker death
#      mid-query, refused connects, corrupt pages, deadline kills —
#      reproduce deterministically on every gate)
#   5. tier-1 pytest suite        (the ROADMAP.md verify command)
#
# Usage: tools/ci.sh [extra pytest args]

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== engine lint =============================================="
python tools/engine_lint.py --check presto_tpu tools

echo "== bench trajectory (non-fatal) ============================="
python tools/bench_compare.py || echo "bench-compare failed (non-fatal)"

echo "== plan-validator corpus ===================================="
env JAX_PLATFORMS=cpu python -m pytest tests/test_plan_validator.py -q \
    -p no:cacheprovider

echo "== corpus plan-diff (golden fingerprints) ==================="
env JAX_PLATFORMS=cpu python tools/plan_diff.py --check

echo "== kernel-soundness corpus =================================="
# the expression-tier abstract interpreter over every TPC-H + TPC-DS
# plan (overflow / lossy-cast / division / accumulator / null-policy),
# plus the seeded-bug fixtures that prove each checker still catches
# its bug class (tests/test_kernel_soundness.py)
env JAX_PLATFORMS=cpu python -m pytest tests/test_kernel_soundness.py \
    tests/test_kernel_ranges.py -q -p no:cacheprovider

echo "== range-sanitizer smoke (TPC-H q1/q6) ======================"
# runtime cross-check of the analyzer's predicted intervals: observed
# page min/max outside a predicted interval means a transfer function
# under-approximates — that fails LOUDLY here, not silently in prod
env JAX_PLATFORMS=cpu PRESTO_TPU_RANGE_SANITIZER=1 python - <<'EOF'
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.obs import METRICS
from presto_tpu.runner import QueryRunner
import sys, os
sys.path.insert(0, "tests")
from tpch_queries import QUERIES

catalog = Catalog()
catalog.register("tpch", Tpch(sf=0.01))
runner = QueryRunner(catalog)
for qid in (1, 6):
    res = runner.execute(QUERIES[qid])
    print(f"q{qid}: {len(res.rows)} rows, sanitizer clean")
escapes = METRICS.counter("kernel.sanitizer_escapes").value
assert escapes == 0, f"{escapes} interval escapes"
EOF

echo "== telemetry-history / query-doctor smoke ==================="
# arm the metrics-history ring, run real queries, and assert the whole
# observability loop end-to-end: the ring holds ticks, the per-query
# timeline recorded points, the doctor's findings are schema-valid,
# and the sampler thread does not leak past stop()
env JAX_PLATFORMS=cpu python - <<'EOF'
import threading
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.system import QueryHistory, SystemConnector
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.obs import doctor, timeline_for
from presto_tpu.obs.timeseries import HISTORY
from presto_tpu.runner import QueryRunner

catalog = Catalog()
catalog.register("tpch", Tpch(sf=0.01))
history = QueryHistory()
catalog.register("system", SystemConnector(history))
runner = QueryRunner(catalog)
runner.events.add(history)
assert HISTORY.start(interval_ms=50)
try:
    runner.execute("select l_returnflag, sum(l_quantity) from lineitem"
                   " group by l_returnflag")
    runner.execute("select count(*) from orders where o_totalprice > 1000")
    res = runner.execute("select count(*) from system_metrics_history")
    assert res.rows[0][0] > 0, "history ring empty after armed run"
    for e in history.completed:
        assert e.findings is not None, "completed event missing findings"
        for f in e.findings:
            assert {"rule", "score", "summary", "evidence"} <= set(f), f
            assert 0.0 <= f["score"] <= 1.0, f
        tl = timeline_for(e.query_id)
        assert tl is not None and tl.points(), "timeline recorded nothing"
        rep = doctor.report(e.query_id)
        assert rep["findings"] == e.findings
finally:
    HISTORY.stop()
    HISTORY.clear()
assert not HISTORY.running
names = [t.name for t in threading.enumerate()]
assert "obs-history-sampler" not in names, f"sampler leaked: {names}"
print(f"doctor smoke: {len(history.completed)} queries diagnosed, "
      "ring sampled, sampler stopped clean")
EOF

echo "== estimate-vs-actual / plan-history smoke =================="
# the estimate-vs-actual loop end-to-end: EXPLAIN ANALYZE renders
# est/actual per operator, the plan-history store round-trips across
# a re-open with its incarnation preserved, and the doctor's
# misestimate rule fires on an engineered ratio
env JAX_PLATFORMS=cpu python - <<'EOF'
import tempfile, os
from presto_tpu.obs import doctor
from presto_tpu.obs.history import PlanHistoryStore, history_path, set_default_history
from presto_tpu.obs.timeseries import QueryTimeline
from presto_tpu.testing import LocalQueryRunner

set_default_history(None)
runner = LocalQueryRunner()
res = runner.execute(
    "EXPLAIN ANALYZE select count(*) from lineitem where l_quantity < 10")
text = res.rows[0][0]
assert "est:" in text and "actual:" in text, text

root = tempfile.mkdtemp(prefix="ci_plan_history_")
store = PlanHistoryStore(history_path(root))
store.observe("FilterNode", "abc123", 500, est_rows=10.0)
store.save()
reopened = PlanHistoryStore(history_path(root))
assert reopened.incarnation == store.incarnation, "incarnation lost"
assert reopened.observed_rows("FilterNode", "abc123") == 500.0

tl = QueryTimeline("ci-misest")
tl.annotate("worst_estimate",
            {"ratio": 50.0, "node": "FilterNode", "est": 10.0, "actual": 500})
findings = doctor.diagnose(timeline=tl, wall_ms=100.0)
assert any(f.rule == "misestimate" for f in findings), findings
set_default_history(None)
print("estimate-vs-actual smoke: explain annotated, store round-tripped, "
      "misestimate rule fired")
EOF

echo "== concurrent split-scheduler leg ==========================="
# a fast tier-1 subset under PRESTO_TPU_TASK_CONCURRENCY=4: the morsel
# scheduler's threaded path (scan chains, spill/memory interaction,
# TPC-H end-to-end vs the oracle) is exercised on EVERY gate, not just
# in its dedicated tests
env JAX_PLATFORMS=cpu PRESTO_TPU_TASK_CONCURRENCY=4 python -m pytest \
    tests/test_tasks.py tests/test_tpch.py tests/test_spill.py \
    tests/test_always_on_memory.py tests/test_executor.py -q \
    -p no:cacheprovider

echo "== distributed window/sort/union streaming leg =============="
# the streaming-exchange stage tier on the 8-device CPU mesh: the
# tests force distributed_min_stage_rows=0 so every breaker stage
# (window hash-exchange, per-shard sort + merge, concurrent union
# legs) and the exchange protocol (token/ack, backpressure, replay)
# are exercised on EVERY gate
env JAX_PLATFORMS=cpu \
    XLA_FLAGS="--xla_force_host_platform_device_count=8" \
    python -m pytest tests/test_distributed_stages.py \
    tests/test_streaming_exchange.py -q -p no:cacheprovider

echo "== serving tier leg (lock-sanitized) ========================"
# admission queue/cache locks run INSTRUMENTED: the ISSUE-8 sanitizer
# order-checks every serving-tier lock (sync.named_lock constructions)
# while the admission/cache/coordinator tests exercise them under real
# contention — an observed lock-order inversion fails the gate
env JAX_PLATFORMS=cpu PRESTO_TPU_LOCK_SANITIZER=1 python -m pytest \
    tests/test_serving.py tests/test_resource_groups.py -q \
    -p no:cacheprovider

echo "== fault-injection (chaos) leg =============================="
# fixed seed: the fault schedules (and their jittered backoffs) are
# deterministic, so a chaos failure here reproduces byte-for-byte
env JAX_PLATFORMS=cpu PRESTO_TPU_FAULT_SEED=1234 python -m pytest \
    tests/test_fault_tolerance.py -q -p no:cacheprovider

echo "== protocol-soundness leg ==================================="
# bounded model checking of the exchange/detector/retry/admission
# state machines at pinned depths (any reachable invariant violation
# fails with a replayable counterexample schedule), the seeded-bug
# mutation fixtures (each must be caught by its named invariant), and
# a runtime conformance pass: a faulted 2-worker workload's protocol
# trace replayed through the spec automata
env JAX_PLATFORMS=cpu python -m pytest tests/test_protocol_soundness.py \
    -q -p no:cacheprovider
# replay-from-watermark byte-equality property (q3/q6 under
# net.duplicate_page / net.drop_ack / worker death) — marked slow, so
# it runs here rather than in the tier-1 sweep
env JAX_PLATFORMS=cpu python -m pytest \
    "tests/test_streaming_exchange.py::test_replay_byte_equality_under_net_faults" \
    -q -p no:cacheprovider
env JAX_PLATFORMS=cpu PRESTO_TPU_FAULT_SEED=1234 \
    python tools/protocol_check.py

echo "== tier-1 tests ============================================="
rm -f /tmp/_t1.log
# `|| rc=$?` keeps set -e from aborting before the pass-count
# diagnostic — the line exists precisely for the failing case
rc=0
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly "$@" 2>&1 | tee /tmp/_t1.log || rc=$?
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit "$rc"
