"""Interactive TPC-DS corpus authoring harness: run candidate queries
through BOTH the engine and the sqlite oracle (same env as
tests/test_tpcds_queries.py) and diff.  Usage:

    python tools/dscheck.py file.sql            # engine vs oracle
    python tools/dscheck.py file.sql oracle.sql # separate oracle text

Keeps the loaded catalog + oracle in-process when used via -i.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

import presto_tpu  # noqa: E402,F401
from tests.oracle import assert_rows_match, translate  # noqa: E402

_ENV = None


def env():
    global _ENV
    if _ENV is None:
        # the suite's env fixture is the single source of generator
        # params — reuse it so dscheck always diffs the same dataset
        from tests.test_tpcds_queries import env as suite_env
        runner, oracle = suite_env.__wrapped__()
        _ENV = (runner, oracle)
    return _ENV


def check(sql: str, oracle_sql: str = None, ordered: bool = False):
    runner, oracle = env()
    expected = [tuple(r) for r in
                oracle.execute(translate(oracle_sql or sql)).fetchall()]
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=ordered)
    print(f"MATCH: {len(actual)} rows; head: {actual[:3]}")
    return actual


if __name__ == "__main__":
    sql = open(sys.argv[1]).read()
    osql = open(sys.argv[2]).read() if len(sys.argv) > 2 else None
    check(sql, osql)
