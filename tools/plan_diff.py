#!/usr/bin/env python
"""Corpus plan-diff harness: golden plan-shape fingerprints.

Optimizes every TPC-H (22) + TPC-DS (99) query and fingerprints the
optimized plan's canonical shape (``analysis.soundness.plan_shape_str``
— no stats, estimates, or object identity), then compares against the
committed goldens in ``tools/goldens/plan_fingerprints.json``.  Any
optimizer-rule change shows exactly which query plans moved — the
instrument ROADMAP item 3 (next ~15 rules) chooses rules by.

Modes (mirroring tools/bench_compare.py):

  python tools/plan_diff.py            report-only: print the diff,
                                       exit 0
  python tools/plan_diff.py --check    CI gate: exit 1 on any diff or
                                       missing goldens
  python tools/plan_diff.py --update   rewrite the goldens from the
                                       current planner (commit the
                                       result with the rule change
                                       that moved the plans)

Every query is planned with the rewrite-soundness gate ON, so a
golden refresh can never capture the output of an unsound rewrite.
"""

from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys
from typing import Dict

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

GOLDEN_PATH = os.path.join(REPO, "tools", "goldens",
                           "plan_fingerprints.json")


def fingerprint(shape: str) -> str:
    return hashlib.sha256(shape.encode()).hexdigest()[:16]


def corpus_shapes() -> Dict[str, Dict[str, str]]:
    """``{"tpch/q01": {"fingerprint": ..., "shape": ...}, ...}`` for
    both corpora, planned with rewrite validation forced on."""
    from presto_tpu.analysis.soundness import plan_shape_str
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpcds import Tpcds
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner
    from tests.tpcds_queries import QUERIES as TPCDS
    from tests.tpch_queries import QUERIES as TPCH

    corpora = (
        ("tpch", TPCH, Tpch(sf=0.01)),
        # cd/inventory truncated like the TPC-DS suite fixture: both
        # are sf-independent cross products
        ("tpcds", TPCDS, Tpcds(sf=0.01, split_rows=16384,
                               cd_rows=2 * 5 * 7 * 20, inv_rows=60000)),
    )
    out: Dict[str, Dict[str, str]] = {}
    for name, queries, conn in corpora:
        catalog = Catalog()
        catalog.register(name, conn)
        runner = QueryRunner(catalog)
        runner.session.set("validate_rewrites", True)
        for qid in sorted(queries):
            plan = runner.binder.plan(queries[qid])
            shape = plan_shape_str(plan)
            out[f"{name}/{qid}"] = {"fingerprint": fingerprint(shape),
                                    "shape": shape}
    return out


def diff(golden: Dict[str, Dict[str, str]],
         current: Dict[str, Dict[str, str]]) -> bool:
    """Print per-query changes; True if anything differs."""
    changed = False
    for key in sorted(set(golden) | set(current)):
        g, c = golden.get(key), current.get(key)
        if g is None:
            print(f"NEW     {key}  {c['fingerprint']}")
            changed = True
        elif c is None:
            print(f"REMOVED {key}  {g['fingerprint']}")
            changed = True
        elif g["fingerprint"] != c["fingerprint"]:
            changed = True
            print(f"CHANGED {key}  {g['fingerprint']} -> {c['fingerprint']}")
            old = g.get("shape", "").splitlines()
            new = c.get("shape", "").splitlines()
            import difflib

            for line in difflib.unified_diff(old, new, "golden", "current",
                                             lineterm="", n=1):
                print(f"    {line}")
    return changed


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    mode = ap.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="exit 1 on any diff (the CI gate)")
    mode.add_argument("--update", action="store_true",
                      help="rewrite the goldens from the current planner")
    args = ap.parse_args(argv)

    current = corpus_shapes()

    if args.update:
        os.makedirs(os.path.dirname(GOLDEN_PATH), exist_ok=True)
        with open(GOLDEN_PATH, "w") as f:
            json.dump(current, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {len(current)} fingerprints to {GOLDEN_PATH}")
        return 0

    if not os.path.exists(GOLDEN_PATH):
        print(f"no goldens at {GOLDEN_PATH} — run with --update first")
        return 1 if args.check else 0
    with open(GOLDEN_PATH) as f:
        golden = json.load(f)

    changed = diff(golden, current)
    if not changed:
        print(f"plan fingerprints clean: {len(current)} queries match "
              "the goldens")
        return 0
    print("plan fingerprints moved — review the diff; if intended, "
          "refresh with: python tools/plan_diff.py --update")
    return 1 if args.check else 0


if __name__ == "__main__":
    raise SystemExit(main())
