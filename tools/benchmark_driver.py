#!/usr/bin/env python
"""Benchmark driver: run SQL suites and report wall-clock percentiles.

Reference analog: ``presto-benchmark-driver`` (BenchmarkDriver.java +
docs presto-docs/src/main/sphinx/installation/benchmark-driver.rst) —
a CLI that executes named query suites against an engine and prints
per-query wall/cpu statistics (median, mean, stddev).

Suites are directories of ``.sql`` files (the layout of
presto-benchto-benchmarks/src/main/resources/sql/presto/tpch/) or the
built-in ``tpch``/``tpcds`` corpora from tests/.

Usage:
  python tools/benchmark_driver.py --suite tpch --sf 0.01 --runs 3
  python tools/benchmark_driver.py --suite path/to/dir --catalog tpch
  python tools/benchmark_driver.py --suite tpch --queries q1,q6 --json
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_suite(name: str):
    """-> list of (query_name, sql)."""
    if os.path.isdir(name):
        out = []
        for fn in sorted(os.listdir(name)):
            if fn.endswith(".sql"):
                with open(os.path.join(name, fn)) as f:
                    out.append((fn[:-4], f.read()))
        if not out:
            raise SystemExit(f"no .sql files in {name}")
        return out
    if name == "tpch":
        from tests.tpch_queries import QUERIES

        return [(f"q{i}", sql) for i, sql in sorted(QUERIES.items())]
    if name == "tpcds":
        from tests.tpcds_queries import QUERIES

        return [(f"q{i}", sql) for i, sql in sorted(QUERIES.items())]
    raise SystemExit(f"unknown suite {name!r} (builtin: tpch, tpcds)")


def build_runner(args):
    from presto_tpu.catalog import Catalog
    from presto_tpu.runner import QueryRunner

    catalog = Catalog()
    if args.suite == "tpcds" or args.catalog == "tpcds":
        from presto_tpu.connectors.tpcds import Tpcds

        catalog.register("tpcds", Tpcds(sf=args.sf))
    else:
        from presto_tpu.connectors.tpch import Tpch

        catalog.register("tpch", Tpch(sf=args.sf))
    return QueryRunner(catalog)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--suite", default="tpch",
                    help="builtin suite name (tpch/tpcds) or a directory of .sql files")
    ap.add_argument("--catalog", default=None,
                    help="builtin catalog to register for directory suites")
    ap.add_argument("--sf", type=float, default=0.01, help="generator scale factor")
    ap.add_argument("--runs", type=int, default=3, help="timed runs per query (after 1 warmup)")
    ap.add_argument("--queries", default=None, help="comma list filter, e.g. q1,q6")
    ap.add_argument("--cpu", action="store_true", help="force the XLA CPU backend")
    ap.add_argument("--json", action="store_true", help="one JSON line per query")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import presto_tpu  # noqa: F401  (x64 etc.)

    suite = load_suite(args.suite)
    if args.queries:
        want = set(args.queries.split(","))
        suite = [(n, q) for n, q in suite if n in want]
        if not suite:
            raise SystemExit(f"no queries match {args.queries!r}")

    runner = build_runner(args)

    results = []
    for name, sql in suite:
        try:
            t0 = time.time()
            res = runner.execute(sql)
            warmup = time.time() - t0
            times = []
            for _ in range(args.runs):
                t0 = time.time()
                runner.execute(sql)
                times.append(time.time() - t0)
            row = {
                "query": name,
                "rows": len(res),
                "warmup_s": round(warmup, 3),
                "median_s": round(statistics.median(times), 4),
                "mean_s": round(statistics.mean(times), 4),
                "min_s": round(min(times), 4),
                "max_s": round(max(times), 4),
                "stddev_s": round(statistics.stdev(times), 4) if len(times) > 1 else 0.0,
            }
        except Exception as e:
            row = {"query": name, "error": f"{type(e).__name__}: {e}"}
        results.append(row)
        if args.json:
            print(json.dumps(row), flush=True)
        elif "error" in row:
            print(f"{name:>8}  ERROR {row['error']}", flush=True)
        else:
            print(f"{name:>8}  rows={row['rows']:<8} median={row['median_s']:.4f}s "
                  f"mean={row['mean_s']:.4f}s min={row['min_s']:.4f}s "
                  f"max={row['max_s']:.4f}s (warmup {row['warmup_s']:.1f}s)",
                  flush=True)

    ok = [r for r in results if "error" not in r]
    if ok and not args.json:
        total = sum(r["median_s"] for r in ok)
        print(f"\n{len(ok)}/{len(results)} queries ok; total median wall {total:.2f}s")
    sys.exit(0 if len(ok) == len(results) else 1)


if __name__ == "__main__":
    main()
