#!/usr/bin/env python
"""Benchmark driver: run SQL suites and report wall-clock percentiles.

Reference analog: ``presto-benchmark-driver`` (BenchmarkDriver.java +
docs presto-docs/src/main/sphinx/installation/benchmark-driver.rst) —
a CLI that executes named query suites against an engine and prints
per-query wall/cpu statistics (median, mean, stddev).

Suites are directories of ``.sql`` files (the layout of
presto-benchto-benchmarks/src/main/resources/sql/presto/tpch/) or the
built-in ``tpch``/``tpcds`` corpora from tests/.

Usage:
  python tools/benchmark_driver.py --suite tpch --sf 0.01 --runs 3
  python tools/benchmark_driver.py --suite path/to/dir --catalog tpch
  python tools/benchmark_driver.py --suite tpch --queries q1,q6 --json
  python tools/benchmark_driver.py --suite tpch --streams 4 --runs 2
  python tools/benchmark_driver.py --queries q1,q6,q14 --task-concurrency 4

``--streams N`` switches to concurrent-query THROUGHPUT mode: N client
threads issue the query against the same warm engine and the report
carries aggregate rows/s plus p50/p95 per-execution latency — the
cross-query behavior of the split scheduler measured, not assumed.
``--task-concurrency`` pins the morsel scheduler width for A/B legs
(1 = the serial baseline).

``--hot-cold H:C`` (with ``--streams``) runs the serving-tier workload
mix: of every H+C executions per client, H repeat the suite query
verbatim (the hot dashboard set) and C run a UNIQUE structurally
distinct cold variant — reporting per-class p50/p95 and the result-
cache hit rate.  ``--result-cache on`` enables the structural result
cache (the A/B lever for PERF.md), ``--admit N`` routes every
execution through a serving-tier AdmissionController with per-group
hard concurrency N so enforced limits are part of what's measured.
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_suite(name: str):
    """-> list of (query_name, sql)."""
    if os.path.isdir(name):
        out = []
        for fn in sorted(os.listdir(name)):
            if fn.endswith(".sql"):
                with open(os.path.join(name, fn)) as f:
                    out.append((fn[:-4], f.read()))
        if not out:
            raise SystemExit(f"no .sql files in {name}")
        return out
    if name == "tpch":
        from tests.tpch_queries import QUERIES

        return [(f"q{i}", sql) for i, sql in sorted(QUERIES.items())]
    if name == "tpcds":
        from tests.tpcds_queries import QUERIES

        return [(f"q{i}", sql) for i, sql in sorted(QUERIES.items())]
    raise SystemExit(f"unknown suite {name!r} (builtin: tpch, tpcds)")


def build_runner(args, programs=None):
    from presto_tpu.catalog import Catalog
    from presto_tpu.runner import QueryRunner

    catalog = Catalog()
    if args.suite == "tpcds" or args.catalog == "tpcds":
        from presto_tpu.connectors.tpcds import Tpcds

        catalog.register("tpcds", Tpcds(sf=args.sf))
    else:
        from presto_tpu.connectors.tpch import Tpch

        catalog.register("tpch", Tpch(sf=args.sf))
    return QueryRunner(catalog, programs=programs)


# the standing cold-start protocol (VERDICT checklist #1): scan-heavy
# q6, join+agg q14, wide-agg q1, join-order-sensitive q3 — in that
# order, so cross-query program reuse is part of what's measured
COLD_SEQUENCE = ("q6", "q14", "q1", "q3")


def cold_compile_report(args):
    """--cold-compile-report: run COLD_SEQUENCE with cold in-process
    caches and write per-query warmup seconds + compiled-program
    counts to COMPILE_REPORT.json — compile evidence the bench child
    can commit even when the TPU tunnel is down."""
    import jax

    from presto_tpu.exec.programs import (
        ProgramRegistry, maybe_enable_persistent_cache,
        persistent_cache_stats, structural_sharing_enabled,
    )

    suite = dict(load_suite(args.suite))
    names = list(args.queries.split(",")) if args.queries \
        else list(COLD_SEQUENCE)
    missing = [n for n in names if n not in suite]
    if missing:
        raise SystemExit(f"unknown queries {missing}")

    jax.clear_caches()  # cold in-process compile caches
    cache_dir = maybe_enable_persistent_cache()
    registry = ProgramRegistry()
    runner = build_runner(args, programs=registry)

    def reg_stats():
        # with structural sharing disabled (the A/B baseline) programs
        # land in the executor's private per-node registry instead
        own = getattr(runner.executor, "_own_registry", None)
        return (own or registry).stats()

    queries = []
    prev_programs = prev_compile = 0.0
    for name in names:
        t0 = time.perf_counter()
        res = runner.execute(suite[name])
        warmup = time.perf_counter() - t0
        t0 = time.perf_counter()
        runner.execute(suite[name])
        warm = time.perf_counter() - t0
        s = reg_stats()
        queries.append({
            "query": name,
            "rows": len(res),
            "warmup_s": round(warmup, 3),
            "warm_s": round(warm, 4),
            "programs_total": s["programs"],
            "programs_new": s["programs"] - int(prev_programs),
            "compile_s_new": round(s["compile_s"] - prev_compile, 3),
            "registry_hits": s["hits"],
            "registry_misses": s["misses"],
        })
        prev_programs, prev_compile = s["programs"], s["compile_s"]
        print(f"{name:>6}  warmup={warmup:.2f}s warm={warm:.3f}s "
              f"programs={s['programs']} (+{queries[-1]['programs_new']})",
              flush=True)

    report = {
        "sequence": names,
        "sf": args.sf,
        "backend": jax.default_backend(),
        "structural_sharing": structural_sharing_enabled(),
        "persistent_cache_dir": cache_dir,
        "total_warmup_s": round(sum(q["warmup_s"] for q in queries), 3),
        "distinct_programs": int(prev_programs),
        "registry": reg_stats(),
        "persistent": persistent_cache_stats(),
        "queries": queries,
    }
    out = args.report_out or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "COMPILE_REPORT.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(f"wrote {out}: {report['distinct_programs']} distinct programs, "
          f"total warmup {report['total_warmup_s']}s", flush=True)
    return 0


def run_streams(runner, name: str, sql: str, streams: int, runs: int):
    """Concurrent-query throughput: ``streams`` client threads each
    execute ``sql`` ``runs`` times against the shared warm engine.
    Returns the aggregate row/s + latency-percentile report row."""
    import statistics as stats
    import threading

    warm = runner.execute(sql)
    latencies: list = []
    rows_total = [0]
    errors: list = []
    lock = threading.Lock()

    def client():
        for _ in range(runs):
            t0 = time.perf_counter()
            try:
                res = runner.execute(sql)
            except Exception as e:  # a failing stream must be visible
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
                rows_total[0] += len(res)

    # client-count is CLI-derived (--streams), not hard-coded
    threads = [threading.Thread(target=client, name=f"stream-{i}")
               for i in range(streams)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    if not latencies:
        return {"query": name, "streams": streams,
                "error": errors[0] if errors else "no executions"}
    lat = sorted(latencies)

    def pct(p):
        # nearest-rank (ceil, 1-indexed): floor-indexing returned the
        # MAX for any n <= 20, making "p95" a worst-case outlier report
        return _percentile(lat, p)

    row = {
        "query": name,
        "streams": streams,
        "runs_per_stream": runs,
        "executions": len(lat),
        "rows": len(warm),
        "wall_s": round(wall, 3),
        "queries_per_s": round(len(lat) / wall, 3),
        "rows_per_s": round(rows_total[0] / wall, 1),
        "p50_s": round(stats.median(lat), 4),
        "p95_s": round(pct(95), 4),
        "max_s": round(lat[-1], 4),
    }
    if errors:
        row["errors"] = errors
    return row


def _top_finding(res):
    """The doctor's top-ranked finding riding a MaterializedResult
    (runner attaches the full ranked list), trimmed to what the report
    needs — bench_compare.py prints it next to flagged regressions."""
    findings = getattr(res, "findings", None)
    if not findings:
        return None
    top = findings[0]
    return {"rule": top["rule"], "score": top["score"],
            "summary": top["summary"]}


def _percentile(sorted_vals, p):
    """Nearest-rank percentile (ceil, 1-indexed) — run_streams' pct."""
    import math

    if not sorted_vals:
        return None
    return sorted_vals[min(len(sorted_vals) - 1,
                           max(0, math.ceil(p / 100.0 * len(sorted_vals)) - 1))]


def _cold_variant(sql: str, uid: int) -> str:
    """A structurally distinct sibling of ``sql``: a huge, unique LIMIT
    changes the plan shape (TopN/Limit count is part of the structural
    signature) without changing the rows — so cold variants can never
    hit the hot entry yet stay oracle-comparable."""
    base = sql.strip().rstrip(";")
    if " limit " in base.lower():
        return f"SELECT * FROM ({base}) cold_{uid} LIMIT {9_000_000 + uid}"
    return f"{base} LIMIT {9_000_000 + uid}"


def run_hot_cold(runner, name: str, sql: str, streams: int, runs: int,
                 mix: str, admit: int = 0):
    """Serving-tier workload mix: each of ``streams`` clients runs
    ``runs`` executions scheduled hot:cold by ``mix`` (e.g. ``3:1``).
    Hot = the query verbatim (result-cache candidates); cold = unique
    structural variants (guaranteed misses).  Reports per-class p50/p95
    and the result-cache hit rate over the run; ``--admit N`` funnels
    every execution through an AdmissionController so per-group limits
    are enforced while the percentiles are measured."""
    import statistics as stats
    import threading

    from presto_tpu.obs import METRICS

    h, c = (int(x) for x in mix.split(":"))
    if h <= 0 or c < 0:
        raise SystemExit(f"bad --hot-cold mix {mix!r} (use e.g. 3:1)")
    ctl = None
    if admit > 0:
        from presto_tpu.resource_groups import (
            ResourceGroup, ResourceGroupManager,
        )
        from presto_tpu.serving import AdmissionController

        ctl = AdmissionController(
            ResourceGroupManager(ResourceGroup(
                "bench", hard_concurrency=admit, max_queued=10_000)),
            pool=runner.memory_pool)
    warm = runner.execute(sql)  # plan + compile out of the measurement
    snap0 = dict(METRICS.snapshot())
    lock = threading.Lock()
    lat = {"hot": [], "cold": []}
    queue_waits: list = []
    errors: list = []
    uid_counter = [0]

    def client(ci: int):
        for k in range(runs):
            hot = (k % (h + c)) < h
            if hot:
                stmt = sql
            else:
                with lock:
                    uid_counter[0] += 1
                    uid = uid_counter[0]
                stmt = _cold_variant(sql, uid)
            ticket = None
            t0 = time.perf_counter()
            try:
                if ctl is not None:
                    ticket = ctl.admit(f"{name}-{ci}-{k}", "bench",
                                       timeout=300.0, statement_key=stmt)
                    queue_waits.append(ticket.queued_ms())
                res = runner.execute(stmt)
            except Exception as e:
                with lock:
                    errors.append(f"{type(e).__name__}: {e}")
                return
            finally:
                if ctl is not None:
                    ctl.release(ticket)
            dt = time.perf_counter() - t0
            with lock:
                lat["hot" if hot else "cold"].append(dt)
                del res

    threads = [threading.Thread(target=client, args=(i,),
                                name=f"hotcold-{i}")
               for i in range(streams)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    snap1 = dict(METRICS.snapshot())

    def delta(metric):
        return snap1.get(metric, 0.0) - snap0.get(metric, 0.0)

    hits, misses = delta("cache.result_hits"), delta("cache.result_misses")
    row = {
        "query": name,
        "streams": streams,
        "mix": mix,
        "rows": len(warm),
        "executions": len(lat["hot"]) + len(lat["cold"]),
        "wall_s": round(wall, 3),
        "queries_per_s": round(
            (len(lat["hot"]) + len(lat["cold"])) / wall, 3) if wall else None,
        "cache_result_hits": int(hits),
        "cache_result_misses": int(misses),
        "cache_hit_rate": (round(hits / (hits + misses), 3)
                           if hits + misses else None),
    }
    for cls in ("hot", "cold"):
        vals = sorted(lat[cls])
        row[cls] = {
            "executions": len(vals),
            "p50_s": round(stats.median(vals), 4) if vals else None,
            "p95_s": round(_percentile(vals, 95), 4) if vals else None,
            "max_s": round(vals[-1], 4) if vals else None,
        }
    if ctl is not None:
        qw = sorted(queue_waits)
        row["admit_concurrency"] = admit
        row["queue_wait_p50_ms"] = round(_percentile(qw, 50), 2) if qw else None
        row["queue_wait_p95_ms"] = round(_percentile(qw, 95), 2) if qw else None
    if errors:
        row["errors"] = errors[:5]
    return row


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--suite", default="tpch",
                    help="builtin suite name (tpch/tpcds) or a directory of .sql files")
    ap.add_argument("--catalog", default=None,
                    help="builtin catalog to register for directory suites")
    ap.add_argument("--sf", type=float, default=0.01, help="generator scale factor")
    ap.add_argument("--runs", type=int, default=3, help="timed runs per query (after 1 warmup)")
    ap.add_argument("--repeat", type=int, default=3,
                    help="independent repeats of the timed block; the "
                         "report carries median-of-medians ± spread and "
                         "every raw time (variance protocol)")
    ap.add_argument("--queries", default=None, help="comma list filter, e.g. q1,q6")
    ap.add_argument("--streams", type=int, default=0,
                    help="concurrent-query throughput mode: N client "
                         "threads over the same warm engine (aggregate "
                         "rows/s + p50/p95 latency)")
    ap.add_argument("--task-concurrency", type=int, default=0,
                    help="pin the morsel split-scheduler width for this "
                         "run (session task_concurrency; 1 = serial A/B "
                         "leg, 0 = process default)")
    ap.add_argument("--hot-cold", default=None, metavar="MIX",
                    help="with --streams: hot:cold execution mix per "
                         "client (e.g. 3:1) — repeating hot queries + "
                         "unique cold variants, per-class p50/p95 and "
                         "result-cache hit rate")
    ap.add_argument("--result-cache", default=None, choices=["on", "off"],
                    help="enable/disable the structural result cache "
                         "for this run (default: on for --hot-cold, "
                         "off otherwise)")
    ap.add_argument("--admit", type=int, default=0,
                    help="route every execution through a serving-tier "
                         "AdmissionController with this per-group hard "
                         "concurrency (0 = no admission gate)")
    ap.add_argument("--cpu", action="store_true", help="force the XLA CPU backend")
    ap.add_argument("--json", action="store_true", help="one JSON line per query")
    ap.add_argument("--cold-compile-report", action="store_true",
                    help="run the cold q6>q14>q1>q3 sequence and write "
                         "COMPILE_REPORT.json (warmup seconds + program counts)")
    ap.add_argument("--report-out", default=None,
                    help="output path for --cold-compile-report")
    args = ap.parse_args()

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    import presto_tpu  # noqa: F401  (x64 etc.)

    if args.cold_compile_report:
        sys.exit(cold_compile_report(args))

    suite = load_suite(args.suite)
    if args.queries:
        want = set(args.queries.split(","))
        suite = [(n, q) for n, q in suite if n in want]
        if not suite:
            raise SystemExit(f"no queries match {args.queries!r}")

    runner = build_runner(args)
    if args.task_concurrency:
        runner.execute(
            f"SET SESSION task_concurrency = {args.task_concurrency}")
    cache_mode = args.result_cache or ("on" if args.hot_cold else None)
    if cache_mode is not None:
        runner.execute("SET SESSION result_cache_enabled = "
                       + ("true" if cache_mode == "on" else "false"))

    if args.hot_cold and not args.streams:
        raise SystemExit("--hot-cold requires --streams N")

    if args.streams:
        results = []
        for name, sql in suite:
            try:
                if args.hot_cold:
                    row = run_hot_cold(runner, name, sql, args.streams,
                                       max(args.runs, 1), args.hot_cold,
                                       admit=args.admit)
                else:
                    row = run_streams(runner, name, sql, args.streams,
                                      max(args.runs, 1))
            except Exception as e:
                row = {"query": name, "error": f"{type(e).__name__}: {e}"}
            results.append(row)
            if args.json:
                print(json.dumps(row), flush=True)
            elif "error" in row:
                print(f"{name:>8}  ERROR {row['error']}", flush=True)
            elif args.hot_cold:
                hr = row.get("cache_hit_rate")
                print(f"{name:>8}  mix={row['mix']} "
                      f"hot p50={row['hot']['p50_s']}s "
                      f"p95={row['hot']['p95_s']}s | "
                      f"cold p50={row['cold']['p50_s']}s "
                      f"p95={row['cold']['p95_s']}s | "
                      f"hit rate={'n/a' if hr is None else hr}"
                      + (f" | queue p95={row['queue_wait_p95_ms']}ms"
                         if "queue_wait_p95_ms" in row else ""),
                      flush=True)
            else:
                print(f"{name:>8}  streams={row['streams']} "
                      f"qps={row['queries_per_s']:.2f} "
                      f"rows/s={row['rows_per_s']:.1f} "
                      f"p50={row['p50_s']:.3f}s p95={row['p95_s']:.3f}s",
                      flush=True)
        sys.exit(0 if all("error" not in r for r in results) else 1)

    results = []
    for name, sql in suite:
        try:
            t0 = time.perf_counter()
            # estimate-vs-actual: per-operator stats on the WARMUP run
            # only (session.set, not SET SESSION — an executor rebuild
            # here would discard the warmed compile caches, and the
            # per-page device sync must not perturb the timed runs).
            # The worst misestimate ratio rides the row so
            # bench_compare can print it next to a flagged regression.
            runner.session.set("collect_stats", True)
            try:
                res = runner.execute(sql)
            finally:
                runner.session.set("collect_stats", False)
            warmup = time.perf_counter() - t0
            # variance protocol (VERDICT weak #3): --repeat independent
            # measurement blocks of --runs timed runs each.  The
            # headline is the MEDIAN of per-repeat medians with the
            # spread across repeats, and every raw time is kept, so a
            # regression is distinguishable from host variance.
            raw: list = []
            repeat_medians = []
            last = res
            for _ in range(max(args.repeat, 1)):
                times = []
                for _ in range(args.runs):
                    t0 = time.perf_counter()
                    last = runner.execute(sql)
                    times.append(time.perf_counter() - t0)
                raw.append([round(t, 4) for t in times])
                repeat_medians.append(statistics.median(times))
            # the query doctor's top-ranked finding for the final timed
            # run (obs/doctor.py) — "why is this query slow" travels
            # with the number that says it is
            top = _top_finding(last)
            flat = [t for block in raw for t in block]
            spread = (max(repeat_medians) - min(repeat_medians)) / 2
            row = {
                "query": name,
                "rows": len(res),
                "warmup_s": round(warmup, 3),
                "median_s": round(statistics.median(repeat_medians), 4),
                "spread_s": round(spread, 4),
                "repeat_medians_s": [round(m, 4) for m in repeat_medians],
                "raw_times_s": raw,
                "mean_s": round(statistics.mean(flat), 4),
                "min_s": round(min(flat), 4),
                "max_s": round(max(flat), 4),
                "stddev_s": round(statistics.stdev(flat), 4) if len(flat) > 1 else 0.0,
            }
            if top is not None:
                row["doctor"] = top
            wr = getattr(res, "worst_estimate_ratio", None)
            if wr is not None:
                row["worst_estimate_ratio"] = round(float(wr), 2)
        except Exception as e:
            row = {"query": name, "error": f"{type(e).__name__}: {e}"}
        results.append(row)
        if args.json:
            print(json.dumps(row), flush=True)
        elif "error" in row:
            print(f"{name:>8}  ERROR {row['error']}", flush=True)
        else:
            doc = row.get("doctor")
            print(f"{name:>8}  rows={row['rows']:<8} "
                  f"median={row['median_s']:.4f}s ±{row['spread_s']:.4f} "
                  f"mean={row['mean_s']:.4f}s min={row['min_s']:.4f}s "
                  f"max={row['max_s']:.4f}s (warmup {row['warmup_s']:.1f}s)"
                  + (f"  doctor: {doc['rule']} ({doc['score']:.2f})"
                     if doc else ""),
                  flush=True)

    ok = [r for r in results if "error" not in r]
    if ok and not args.json:
        total = sum(r["median_s"] for r in ok)
        print(f"\n{len(ok)}/{len(results)} queries ok; total median wall {total:.2f}s")
    sys.exit(0 if len(ok) == len(results) else 1)


if __name__ == "__main__":
    main()
