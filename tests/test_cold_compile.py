"""Cold-start compile budget: the shape-canonicalizing program
registry (exec/programs.py) must keep distinct compiled XLA programs
bounded and reuse compiled binaries across program registries and
processes (the persistent cache).  These tests pin the budgets so a
future PR that re-fragments shapes — a stray data-dependent capacity,
a signature that stops matching — fails loudly instead of silently
re-paying the cold-start tax (VERDICT checklist #1)."""

import jax
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.exec.programs import (
    ProgramRegistry, default_registry, disable_persistent_cache,
    enable_persistent_cache, ir_signature, persistent_cache_stats,
)
from presto_tpu.runner import QueryRunner
from tests.tpch_queries import QUERIES


def _fresh_runner(sf=0.01):
    from presto_tpu.connectors.tpch import Tpch

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=sf))
    registry = ProgramRegistry()
    return QueryRunner(catalog, programs=registry), registry


# measured 8 distinct programs for cold q1+q6 at sf 0.01 (chain +
# fold/final per aggregation, projection chain, sort); the pin leaves
# two programs of headroom for planner drift, not for fragmentation
Q1_Q6_PROGRAM_BUDGET = 10


def test_cold_q1_q6_program_budget():
    runner, registry = _fresh_runner()
    runner.execute(QUERIES[1])
    runner.execute(QUERIES[6])
    progs = registry.program_count()
    assert 0 < progs <= Q1_Q6_PROGRAM_BUDGET, (
        f"cold q1+q6 compiled {progs} distinct programs "
        f"(budget {Q1_Q6_PROGRAM_BUDGET}): shapes re-fragmented")


def test_structural_twin_query_shares_programs():
    """A structurally identical query (different SQL text, fresh plan
    nodes) must be a 100% registry hit — zero new programs."""
    runner, registry = _fresh_runner()
    sql = ("SELECT l_returnflag, sum(l_quantity) FROM lineitem "
           "GROUP BY l_returnflag")
    runner.execute(sql)
    before = registry.program_count()
    misses_before = registry.misses
    runner.execute(sql + "  ")  # distinct text -> plan cache miss
    assert registry.program_count() == before
    assert registry.misses == misses_before
    assert registry.hits > 0


def test_rebuilt_executor_keeps_programs():
    """SET SESSION rebuilds the executor; compiled programs survive in
    the shared registry (the seed recompiled everything)."""
    runner, registry = _fresh_runner()
    sql = "SELECT sum(l_quantity) FROM lineitem WHERE l_discount < 0.05"
    runner.execute(sql)
    before = registry.program_count()
    runner.execute("SET SESSION distributed_sort = false")
    runner.execute(sql)
    assert registry.program_count() == before


def test_explain_analyze_verbose_reports_registry():
    runner, _ = _fresh_runner()
    res = runner.execute(
        "EXPLAIN ANALYZE VERBOSE SELECT count(*) FROM nation")
    text = res.rows[0][0]
    assert "program registry:" in text
    assert "hits" in text and "misses" in text and "compile" in text
    assert "compiled XLA programs:" in text


def test_persistent_cache_second_registry_hits(tmp_path):
    """A second registry (fresh jit caches, same cache dir) must
    rehydrate serialized XLA binaries: persistent hits recorded and
    the programs recompile from disk, not from scratch."""
    cache_dir = str(tmp_path / "xla-cache")
    enable_persistent_cache(cache_dir)
    try:
        runner, _ = _fresh_runner()
        runner.execute("SELECT sum(n_regionkey) FROM nation")
        jax.clear_caches()  # drop in-process executables, keep disk
        hits0 = persistent_cache_stats()["persistent_hits"]
        runner2, reg2 = _fresh_runner()
        runner2.execute("SELECT sum(n_regionkey) FROM nation")
        assert persistent_cache_stats()["persistent_hits"] > hits0
        assert reg2.program_count() > 0
    finally:
        disable_persistent_cache()


def test_ir_signature_distinguishes_lossy_reprs():
    """Type repr hides the dictionary flag; signatures must not."""
    from presto_tpu.types import VARCHAR, VarcharType

    raw = VarcharType(16, raw=True)
    assert ir_signature(VARCHAR) != ir_signature(raw)
    assert ir_signature(VARCHAR) == ir_signature(VARCHAR)


def test_ir_signature_dictionary_identity():
    from presto_tpu.page import Dictionary

    d1 = Dictionary(["a", "b"])
    d2 = Dictionary(["a", "b"])
    assert ir_signature(d1) == ir_signature(d1)
    assert ir_signature(d1) != ir_signature(d2)  # identity, not content


def test_registry_disabled_mode_still_executes(monkeypatch):
    monkeypatch.setenv("PRESTO_TPU_PROGRAM_REGISTRY", "0")
    runner, registry = _fresh_runner()
    res = runner.execute("SELECT count(*) FROM region")
    assert res.rows == [(5,)]
    # programs landed in the executor's private per-node registry
    assert registry.program_count() == 0
    own = runner.executor._own_registry
    assert own is not None and own.program_count() > 0


def test_default_registry_is_shared():
    assert default_registry() is default_registry()


def test_stage_signature_sensitivity():
    """Every parameter _build_stage bakes into a chain closure must
    flip the signature (the registry's correctness guarantee); equal
    structure must sign equal across separately planned queries."""
    runner, _ = _fresh_runner()
    ex = runner.executor

    def sig(sql):
        plan = runner.binder.plan(sql)
        # walk to the streaming chain root (under the Output node)
        node = plan
        while not ex._is_chain_member(node) and node.sources:
            node = node.sources[0]
        return ex._stage_signature(node)

    base = "SELECT l_quantity FROM lineitem WHERE l_discount < 0.05"
    assert sig(base) == sig(base.replace("0.05", "0.05"))
    assert sig(base) != sig(base.replace("0.05", "0.06"))  # predicate
    assert sig(base) != sig(base.replace("l_quantity", "l_tax"))  # proj
    agg = ("SELECT l_returnflag, sum(l_quantity) FROM lineitem "
           "GROUP BY l_returnflag")
    assert sig(agg) != sig(agg.replace("sum", "max"))  # agg fn


def test_registry_lru_eviction_bounds_callables():
    """The registry must bound the live-executable arena (XLA:CPU
    segfaults past a few thousand live programs — r5 TPC-DS finding):
    oldest callables evict, recent ones survive."""
    reg = ProgramRegistry(max_callables=4)
    for i in range(10):
        reg.get("k", ("sig", i), lambda: (lambda x: x), jit=False)
    assert reg.callable_count() == 4
    assert reg.evictions == 6
    # the most recent signature is still a hit
    misses = reg.misses
    reg.get("k", ("sig", 9), lambda: (lambda x: x), jit=False)
    assert reg.misses == misses and reg.hits == 1
