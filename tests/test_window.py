"""Window function tests vs the sqlite oracle.

Reference analog: the reference's window coverage
(presto-main/src/test/.../operator/window/, TestWindowOperator,
AbstractTestQueries window sections)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


WINDOW_QUERIES = [
    # ranking over partitions
    """select c_custkey, c_nationkey,
              row_number() over (partition by c_nationkey order by c_acctbal desc) as rn
       from customer""",
    """select o_orderkey, o_custkey,
              rank() over (partition by o_custkey order by o_orderdate) as rnk,
              dense_rank() over (partition by o_custkey order by o_orderdate) as drnk
       from orders""",
    # running aggregates (RANGE UNBOUNDED PRECEDING default frame)
    """select o_orderkey, o_custkey,
              sum(o_totalprice) over (partition by o_custkey order by o_orderdate) as running,
              count(*) over (partition by o_custkey order by o_orderdate) as cnt
       from orders""",
    # whole-partition aggregates (no ORDER BY)
    """select s_suppkey, s_nationkey,
              sum(s_acctbal) over (partition by s_nationkey) as nation_total,
              avg(s_acctbal) over (partition by s_nationkey) as nation_avg,
              min(s_acctbal) over (partition by s_nationkey) as nation_min,
              max(s_acctbal) over (partition by s_nationkey) as nation_max
       from supplier""",
    # lead/lag/first_value
    """select o_orderkey, o_custkey,
              lag(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey) as prev_price,
              lead(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey) as next_price,
              first_value(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey) as first_price
       from orders""",
    # window over aggregation output
    """select c_nationkey, count(*) as cnt,
              rank() over (order by count(*) desc) as rnk
       from customer group by c_nationkey""",
    # unpartitioned window
    """select o_orderkey, row_number() over (order by o_totalprice desc, o_orderkey) as rn
       from orders limit 10000""",
    # ntile / percent_rank / cume_dist
    """select c_custkey,
              ntile(4) over (partition by c_nationkey order by c_acctbal, c_custkey) as quartile,
              percent_rank() over (partition by c_nationkey order by c_acctbal) as pr,
              cume_dist() over (partition by c_nationkey order by c_acctbal) as cd
       from customer""",
    # ROWS BETWEEN n PRECEDING AND CURRENT ROW (moving aggregates)
    """select o_orderkey,
              sum(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey
                                      rows between 2 preceding and current row) as moving_sum,
              avg(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey
                                      rows between 2 preceding and current row) as moving_avg
       from orders""",
    # ROWS with FOLLOWING end and both-sided window
    """select s_suppkey,
              count(*) over (partition by s_nationkey order by s_acctbal, s_suppkey
                             rows between 1 preceding and 1 following) as c3,
              sum(s_acctbal) over (partition by s_nationkey order by s_acctbal, s_suppkey
                                   rows between current row and 2 following) as ahead
       from supplier""",
    # ROWS UNBOUNDED PRECEDING (running, row-based not peer-based)
    """select o_orderkey,
              max(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey
                                      rows between unbounded preceding and current row) as run_max,
              last_value(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey
                                             rows between unbounded preceding and current row) as lv
       from orders""",
    # RANGE BETWEEN UNBOUNDED PRECEDING AND UNBOUNDED FOLLOWING
    """select s_suppkey,
              sum(s_acctbal) over (partition by s_nationkey order by s_acctbal
                                   range between unbounded preceding and unbounded following) as tot
       from supplier""",
    # nth_value over the default frame
    """select o_orderkey,
              nth_value(o_totalprice, 2) over (partition by o_custkey
                                               order by o_orderdate, o_orderkey) as second_price
       from orders""",
]


@pytest.mark.parametrize("i", range(len(WINDOW_QUERIES)))
def test_window_query(env, i):
    runner, oracle = env
    sql = WINDOW_QUERIES[i]
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_window_arg_validation(env):
    from presto_tpu.sql.binder import BindError

    runner, _ = env
    for bad in [
        "select ntile(-2) over (order by n_nationkey) from nation",
        "select ntile(0) over (order by n_nationkey) from nation",
        "select nth_value(n_name, 0) over (order by n_nationkey) from nation",
        "select lag(n_name, -1) over (order by n_nationkey) from nation",
    ]:
        with pytest.raises(BindError):
            runner.execute(bad)


def test_topn_per_group_pattern(env):
    """The classic top-n-per-group derived-table pattern."""
    runner, oracle = env
    sql = """
    select c_nationkey, c_custkey, c_acctbal
    from (
        select c_nationkey, c_custkey, c_acctbal,
               row_number() over (partition by c_nationkey order by c_acctbal desc, c_custkey) as rn
        from customer
    ) as t
    where rn <= 3
    order by c_nationkey, rn
    """
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)
