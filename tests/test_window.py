"""Window function tests vs the sqlite oracle.

Reference analog: the reference's window coverage
(presto-main/src/test/.../operator/window/, TestWindowOperator,
AbstractTestQueries window sections)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


WINDOW_QUERIES = [
    # ranking over partitions
    """select c_custkey, c_nationkey,
              row_number() over (partition by c_nationkey order by c_acctbal desc) as rn
       from customer""",
    """select o_orderkey, o_custkey,
              rank() over (partition by o_custkey order by o_orderdate) as rnk,
              dense_rank() over (partition by o_custkey order by o_orderdate) as drnk
       from orders""",
    # running aggregates (RANGE UNBOUNDED PRECEDING default frame)
    """select o_orderkey, o_custkey,
              sum(o_totalprice) over (partition by o_custkey order by o_orderdate) as running,
              count(*) over (partition by o_custkey order by o_orderdate) as cnt
       from orders""",
    # whole-partition aggregates (no ORDER BY)
    """select s_suppkey, s_nationkey,
              sum(s_acctbal) over (partition by s_nationkey) as nation_total,
              avg(s_acctbal) over (partition by s_nationkey) as nation_avg,
              min(s_acctbal) over (partition by s_nationkey) as nation_min,
              max(s_acctbal) over (partition by s_nationkey) as nation_max
       from supplier""",
    # lead/lag/first_value
    """select o_orderkey, o_custkey,
              lag(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey) as prev_price,
              lead(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey) as next_price,
              first_value(o_totalprice) over (partition by o_custkey order by o_orderdate, o_orderkey) as first_price
       from orders""",
    # window over aggregation output
    """select c_nationkey, count(*) as cnt,
              rank() over (order by count(*) desc) as rnk
       from customer group by c_nationkey""",
    # unpartitioned window
    """select o_orderkey, row_number() over (order by o_totalprice desc, o_orderkey) as rn
       from orders limit 10000""",
]


@pytest.mark.parametrize("i", range(len(WINDOW_QUERIES)))
def test_window_query(env, i):
    runner, oracle = env
    sql = WINDOW_QUERIES[i]
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_topn_per_group_pattern(env):
    """The classic top-n-per-group derived-table pattern."""
    runner, oracle = env
    sql = """
    select c_nationkey, c_custkey, c_acctbal
    from (
        select c_nationkey, c_custkey, c_acctbal,
               row_number() over (partition by c_nationkey order by c_acctbal desc, c_custkey) as rn
        from customer
    ) as t
    where rn <= 3
    order by c_nationkey, rn
    """
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)
