"""Columnar file storage + split pruning tests.

Reference analogs: presto-orc (columnar reader/writer with stripe
stats pruning), presto-raptor (native storage), local-file connector."""

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner
from presto_tpu.storage import FileConnector, write_table


@pytest.fixture()
def stored(tmp_path):
    """TPC-H orders written to disk, one split per generator split."""
    tpch = Tpch(sf=0.002, split_rows=512)
    schema = tpch.schema("orders")
    pages = [tpch.page_for_split("orders", s) for s in range(tpch.num_splits("orders"))]
    write_table(str(tmp_path), "orders_disk", schema, pages)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    catalog.register("file", FileConnector(str(tmp_path)))
    return QueryRunner(catalog), tpch


def test_roundtrip_counts(stored):
    runner, tpch = stored
    a = runner.execute("select count(*), sum(o_totalprice) from orders_disk").rows
    b = runner.execute("select count(*), sum(o_totalprice) from orders").rows
    assert a == b


def test_strings_roundtrip(stored):
    runner, _ = stored
    a = sorted(runner.execute("select o_orderpriority, count(*) from orders_disk group by o_orderpriority").rows)
    b = sorted(runner.execute("select o_orderpriority, count(*) from orders group by o_orderpriority").rows)
    assert a == b


def test_split_pruning(stored):
    runner, _ = stored
    # o_orderkey is monotonically increasing across splits, so a tight
    # key range must prune most splits
    plan = runner.plan("select count(*) from orders_disk where o_orderkey < 100")
    from presto_tpu.planner.plan import TableScanNode

    def find_scan(n):
        if isinstance(n, TableScanNode):
            return n
        for s in n.sources:
            r = find_scan(s)
            if r is not None:
                return r
        return None

    scan = find_scan(plan)
    assert scan.constraints  # pushdown recorded
    res = runner.executor.run(plan)
    expected = runner.execute("select count(*) from orders where o_orderkey < 100").rows
    assert res.rows == expected

    # verify pruning actually skips splits
    conn = runner.catalog.connector("file")
    from presto_tpu.exec.local import _split_pruned

    pruned = sum(
        _split_pruned(scan.constraints, conn.split_stats("orders_disk", s))
        for s in range(conn.num_splits("orders_disk"))
    )
    assert pruned >= conn.num_splits("orders_disk") - 1


def test_domains_from_stats(stored):
    runner, _ = stored
    conn = runner.catalog.connector("file")
    dom = conn.column_domain("orders_disk", "o_orderkey")
    assert dom is not None and dom[0] >= 1
