"""WITH (CTEs), VALUES relations, DELETE.

Reference analogs: sql/tree/With.java + WithQuery (inline expansion),
sql/tree/Values.java, sql/tree/Delete.java + DeleteOperator.
"""

import pytest

from presto_tpu.testing import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(sf=0.001)


def test_cte_basic(runner):
    rows = runner.execute(
        "WITH big AS (SELECT * FROM nation WHERE n_regionkey = 1), "
        "cnt AS (SELECT count(*) AS c FROM big) SELECT c FROM cnt").rows
    assert rows == [(5,)]


def test_cte_referenced_twice(runner):
    rows = runner.execute(
        "WITH r AS (SELECT n_regionkey AS k FROM nation) "
        "SELECT count(*) FROM r a, r b WHERE a.k = b.k").rows
    expect = runner.execute(
        "SELECT count(*) FROM nation a, nation b "
        "WHERE a.n_regionkey = b.n_regionkey").rows
    assert rows == expect


def test_cte_with_aggregation_and_shadowing(runner):
    rows = runner.execute(
        "WITH x AS (SELECT n_regionkey AS k, count(*) AS c FROM nation "
        "GROUP BY n_regionkey) SELECT sum(c) FROM x WHERE k <= 2").rows
    assert rows == [(15,)]
    # a CTE name shadows a catalog table
    rows = runner.execute(
        "WITH nation AS (SELECT 1 AS n) SELECT count(*) FROM nation").rows
    assert rows == [(1,)]


def test_values_relation(runner):
    rows = runner.execute(
        "SELECT a, b FROM (VALUES (1, 'x'), (2, 'y'), (3, NULL)) AS t (a, b) "
        "ORDER BY a").rows
    assert rows == [(1, "x"), (2, "y"), (3, None)]
    assert runner.execute(
        "SELECT sum(x) FROM (VALUES (1.5), (2.5)) AS v (x)").rows == [(4.0,)]
    # joins against real tables
    rows = runner.execute(
        "SELECT n_name FROM nation JOIN (VALUES (0), (3)) AS k (rk) "
        "ON n_nationkey = rk ORDER BY n_name").rows
    assert rows == [("ALGERIA",), ("CANADA",)]


def test_delete(runner):
    runner.execute("CREATE TABLE del_t AS SELECT n_nationkey AS k FROM nation")
    assert runner.execute("DELETE FROM del_t WHERE k >= 20").rows == [(5,)]
    assert runner.execute("SELECT count(*) FROM del_t").rows == [(20,)]
    # re-delete is a no-op; full delete empties
    assert runner.execute("DELETE FROM del_t WHERE k >= 20").rows == [(0,)]
    assert runner.execute("DELETE FROM del_t").rows == [(20,)]
    assert runner.execute("SELECT count(*) FROM del_t").rows == [(0,)]
    runner.execute("DROP TABLE del_t")


def test_delete_null_predicate_keeps_row(runner):
    runner.execute("CREATE TABLE del_n AS SELECT CASE WHEN n_nationkey < 5 "
                   "THEN n_nationkey END AS k FROM nation")
    # k IS NULL rows survive: DELETE removes only TRUE-predicate rows
    assert runner.execute("DELETE FROM del_n WHERE k < 3").rows == [(3,)]
    assert runner.execute("SELECT count(*) FROM del_n").rows == [(22,)]
    runner.execute("DROP TABLE del_n")
