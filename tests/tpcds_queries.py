"""TPC-DS benchmark corpus, engine dialect — 26 queries spanning star
joins, outer/full joins, window frames, ROLLUP, correlated scalar
subqueries and NOT EXISTS.

Authored from the public TPC-DS spec query shapes, adapted to the
generated schema's column subset and data distributions; reference
analog: presto-benchto-benchmarks/src/main/resources/sql/presto/tpcds/.

``QUERIES``: qid -> engine SQL (also valid sqlite unless overridden).
``ORACLE_OVERRIDES``: qid -> sqlite-equivalent SQL for constructs sqlite
lacks (ROLLUP -> UNION ALL expansion).
"""

QUERIES = {
    # official q38 shape: customers present in all three channels
    38: """
select count(*) from (
    select c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where ss_sold_date_sk = d_date_sk and ss_customer_sk = c_customer_sk
        and d_year = 2000
    intersect
    select c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where cs_sold_date_sk = d_date_sk and cs_bill_customer_sk = c_customer_sk
        and d_year = 2000
    intersect
    select c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where ws_sold_date_sk = d_date_sk and ws_bill_customer_sk = c_customer_sk
        and d_year = 2000
) hot_cust
""",
    # official q87 shape: store customers missing from the other channels
    87: """
select count(*) from (
    select c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where ss_sold_date_sk = d_date_sk and ss_customer_sk = c_customer_sk
        and d_year = 2000
    except
    select c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where cs_sold_date_sk = d_date_sk and cs_bill_customer_sk = c_customer_sk
        and d_year = 2000
    except
    select c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where ws_sold_date_sk = d_date_sk and ws_bill_customer_sk = c_customer_sk
        and d_year = 2000
) cool_cust
""",
    # official Q1 shape: CTE referenced twice, one reference correlated
    2: """
with wscs as (
    select sold_date_sk, sales_price
    from (select ws_sold_date_sk as sold_date_sk,
                 ws_ext_sales_price as sales_price
          from web_sales
          union all
          select cs_sold_date_sk, cs_ext_sales_price
          from catalog_sales) x
)
select d_year, d_dow, sum(sales_price) as tot
from wscs, date_dim
where sold_date_sk = d_date_sk
group by d_year, d_dow
order by d_year, d_dow
limit 50
""",
    # Q1 in its official WITH form (the non-CTE rewrite is key 1)
    30: """
with customer_total_return as (
    select sr_customer_sk as ctr_customer_sk,
           sr_store_sk as ctr_store_sk,
           sum(sr_return_amt) as ctr_total_return
    from store_returns, date_dim
    where sr_returned_date_sk = d_date_sk and d_year = 1998
    group by sr_customer_sk, sr_store_sk
)
select ctr_customer_sk, ctr_total_return
from customer_total_return ctr1
where ctr_total_return > (select avg(ctr_total_return) * 1.2
                          from customer_total_return ctr2
                          where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
order by ctr_customer_sk, ctr_total_return
limit 100
""",
    # correlated scalar subquery: customers returning > 1.2x store average
    1: """
select ctr_customer_sk, ctr_total
from (select sr_customer_sk as ctr_customer_sk, sr_store_sk as ctr_store_sk,
             sum(sr_return_amt) as ctr_total
      from store_returns, date_dim
      where sr_returned_date_sk = d_date_sk and d_year = 1998
      group by sr_customer_sk, sr_store_sk) t1
where ctr_total > (select avg(ctr_total2) * 1.2
                   from (select sr_store_sk as ctr_store_sk2,
                                sum(sr_return_amt) as ctr_total2
                         from store_returns, date_dim
                         where sr_returned_date_sk = d_date_sk and d_year = 1998
                         group by sr_customer_sk, sr_store_sk) t2
                   where ctr_store_sk2 = ctr_store_sk)
""",
    3: """
select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manufact_id = 128
    and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, i_brand_id
limit 100
""",
    # correlated scalar subquery against the item dimension
    6: """
select ca_state, count(*) as cnt
from customer_address, customer, store_sales, date_dim, item
where ca_address_sk = c_current_addr_sk
    and c_customer_sk = ss_customer_sk
    and ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and d_year = 2000 and d_moy = 1
    and i_current_price > 1.2 * (select avg(j.i_current_price) from item j
                                 where j.i_category = i_category)
group by ca_state
having count(*) >= 10
order by cnt, ca_state
""",
    7: """
select i_item_id,
    avg(ss_quantity) as agg1,
    avg(ss_list_price) as agg2,
    avg(ss_coupon_amt) as agg3,
    avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_cdemo_sk = cd_demo_sk
    and ss_promo_sk = p_promo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and (p_channel_email = 'N' or p_channel_event = 'N')
    and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # category revenue share via a partitioned window over agg output
    12: """
select i_item_id, i_category, sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100.0
         / sum(sum(ws_ext_sales_price)) over (partition by i_class) as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
    and i_category in ('Sports', 'Books', 'Home')
    and ws_sold_date_sk = d_date_sk
    and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_class, i_category
""",
    15: """
select ca_zip, sum(cs_sales_price) as total
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and (ca_state in ('CA', 'WA', 'GA') or cs_sales_price > 60.00)
    and cs_sold_date_sk = d_date_sk
    and d_qoy = 1 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
""",
    # ROLLUP over customer geography (oracle: UNION ALL expansion)
    18: """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cs_quantity) as agg1,
       avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3
from catalog_sales, customer_demographics, customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
    and cs_item_sk = i_item_sk
    and cs_bill_cdemo_sk = cd_demo_sk
    and cs_bill_customer_sk = c_customer_sk
    and cd_gender = 'F'
    and cd_education_status = 'Unknown'
    and c_current_addr_sk = ca_address_sk
    and d_year = 1998
group by rollup(i_item_id, ca_country, ca_state, ca_county)
""",
    19: """
select i_brand_id, i_brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 8
    and d_moy = 11
    and d_year = 1998
    and ss_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and ss_store_sk = s_store_sk
    and ca_state <> s_state
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, i_brand_id, i_manufact_id
limit 100
""",
    # ROLLUP over the inventory fact (oracle: UNION ALL expansion)
    22: """
select i_category, i_class, i_brand, avg(inv_quantity_on_hand) as qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
    and inv_item_sk = i_item_sk
    and d_month_seq between 1176 and 1187
group by rollup(i_category, i_class, i_brand)
""",
    # three-fact join: sales -> returns -> catalog re-purchase
    25: """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_year = 1998
    and d1.d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_year between 1998 and 1999
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_year between 1998 and 1999
group by i_item_id, i_item_desc, s_store_id, s_store_name
""",
    26: """
select i_item_id,
    avg(cs_quantity) as agg1,
    avg(cs_list_price) as agg2,
    avg(cs_coupon_amt) as agg3,
    avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
    and cs_item_sk = i_item_sk
    and cs_bill_cdemo_sk = cd_demo_sk
    and cs_promo_sk = p_promo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and (p_channel_email = 'N' or p_channel_event = 'N')
    and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # per-ticket counts joined back to customer
    34: """
select c_last_name, c_first_name, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_hdemo_sk = hd_demo_sk
          and (d_dom between 1 and 3 or d_dom between 25 and 28)
          and hd_buy_potential = '>10000'
          and hd_vehicle_count > 0
          and d_year = 1999
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk
    and cnt between 1 and 5
order by c_last_name, c_first_name, ss_ticket_number
""",
    42: """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) as total_sales
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by d_year, i_category_id, i_category
order by total_sales desc, d_year, i_category_id, i_category
limit 100
""",
    # day-of-week pivot via CASE aggregation
    43: """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price end) as sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price end) as mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price end) as tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price end) as wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price end) as thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price end) as fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price end) as sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
    and s_store_sk = ss_store_sk
    and d_year = 1998
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
""",
    # OR'd demographic/price bands over an equi-joined probe
    48: """
select sum(ss_quantity) as total
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
    and ss_sold_date_sk = d_date_sk and d_year = 1999
    and cd_demo_sk = ss_cdemo_sk
    and ((cd_marital_status = 'M' and cd_education_status = '4 yr Degree'
          and ss_sales_price between 100.00 and 150.00)
      or (cd_marital_status = 'D' and cd_education_status = '2 yr Degree'
          and ss_sales_price between 50.00 and 100.00)
      or (cd_marital_status = 'S' and cd_education_status = 'College'
          and ss_sales_price between 150.00 and 200.00))
    and ss_addr_sk = ca_address_sk
    and ca_country = 'United States'
""",
    # cumulative store vs web revenue series, FULL OUTER + ROWS frame
    51: """
select store_d, store_cum, web_cum
from (select ds as store_d, store_cum, web_cum
      from (select d_date as ds,
                   sum(sum(ss_ext_sales_price)) over (order by d_date
                       rows between unbounded preceding and current row) as store_cum
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk and d_year = 2000 and d_moy = 1
            group by d_date) s
      full outer join
           (select d_date as dw,
                   sum(sum(ws_ext_sales_price)) over (order by d_date
                       rows between unbounded preceding and current row) as web_cum
            from web_sales, date_dim
            where ws_sold_date_sk = d_date_sk and d_year = 2000
                and d_moy = 1 and d_dom < 20
            group by d_date) w
      on ds = dw) x
order by store_d
""",
    52: """
select d_year, i_brand_id as brand_id, i_brand as brand, sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
""",
    # manager monthly sums vs their partitioned average (window over agg)
    53: """
select i_manager_id, sum_sales, avg_monthly_sales
from (select i_manager_id, d_moy, sum(ss_sales_price) as sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_manager_id) as avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
          and ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and d_month_seq between 1176 and 1187
      group by i_manager_id, d_moy) tmp
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
""",
    55: """
select i_brand_id as brand_id, i_brand as brand, sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 28
    and d_moy = 11
    and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
""",
    # items under 10% of their store's average revenue (correlated)
    65: """
select s_store_name, i_item_desc, revenue
from store, item,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_month_seq between 1176 and 1179
      group by ss_store_sk, ss_item_sk) sc
where revenue <= (select 0.1 * avg(rev2)
                  from (select ss_store_sk as store2, sum(ss_sales_price) as rev2
                        from store_sales, date_dim
                        where ss_sold_date_sk = d_date_sk
                            and d_month_seq between 1176 and 1179
                        group by ss_store_sk, ss_item_sk) sb
                  where store2 = ss_store_sk)
    and s_store_sk = ss_store_sk
    and i_item_sk = ss_item_sk
""",
    # bought-city vs home-city ticket roll-up
    68: """
select c_last_name, c_first_name, ca_city, bought_city, extended_price
from (select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
             sum(ss_ext_sales_price) as extended_price
      from store_sales, date_dim, store, household_demographics, customer_address
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_hdemo_sk = hd_demo_sk
          and ss_addr_sk = ca_address_sk
          and d_year = 1999
          and (hd_dep_count = 4 or hd_vehicle_count = 3)
      group by ss_ticket_number, ss_customer_sk, ca_city) dn,
     customer, customer_address
where ss_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and ca_city <> bought_city
""",
    # time-of-day traffic counts, cross join of single-row aggregates
    88: """
select h8, h9, h10, h11
from (select count(*) as h8 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 8
          and hd_dep_count = 2 and s_store_name = 'ese') s1,
     (select count(*) as h9 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 9
          and hd_dep_count = 2 and s_store_name = 'ese') s2,
     (select count(*) as h10 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 10
          and hd_dep_count = 2 and s_store_name = 'ese') s3,
     (select count(*) as h11 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 11
          and hd_dep_count = 2 and s_store_name = 'ese') s4
""",
    # LEFT OUTER to returns with reason filter + actual-sale computation
    93: """
select ss_customer_sk, sum(act_sales) as sumsales
from (select ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end as act_sales
      from store_sales left outer join store_returns
           on sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number,
           reason
      where sr_reason_sk = r_reason_sk
          and r_reason_desc = 'Wrong size') t
group by ss_customer_sk
""",
    # NOT EXISTS anti-join on returns
    94: """
select count(*) as order_count, sum(ws_ext_ship_cost) as total_shipping_cost
from web_sales, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-30'
    and ws_ship_date_sk = d_date_sk
    and ws_ship_addr_sk = ca_address_sk
    and ca_state = 'CA'
    and ws_web_site_sk = web_site_sk
    and web_name = 'site_1'
    and not exists (select * from web_returns
                    where ws_order_number = wr_order_number)
""",
    96: """
select count(*) as cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
    and ss_hdemo_sk = hd_demo_sk
    and ss_store_sk = s_store_sk
    and t_hour = 20
    and t_minute >= 30
    and hd_dep_count = 7
    and s_store_name = 'ese'
""",
    # store/catalog buyer overlap via FULL OUTER over grouped facts
    97: """
select sum(case when customer_sk is not null and customer_sk2 is null then 1 else 0 end) as store_only,
       sum(case when customer_sk is null and customer_sk2 is not null then 1 else 0 end) as catalog_only,
       sum(case when customer_sk is not null and customer_sk2 is not null then 1 else 0 end) as store_and_catalog
from (select ss_customer_sk as customer_sk, ss_item_sk as item_sk
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_month_seq between 1176 and 1181
      group by ss_customer_sk, ss_item_sk) ssci
full outer join
     (select cs_bill_customer_sk as customer_sk2, cs_item_sk as item_sk2
      from catalog_sales, date_dim
      where cs_sold_date_sk = d_date_sk and d_month_seq between 1176 and 1181
      group by cs_bill_customer_sk, cs_item_sk) csci
on customer_sk = customer_sk2 and item_sk = item_sk2
""",
}


def _rollup_union(select_cols, aggs, from_where, groups):
    """Expand GROUP BY ROLLUP into sqlite UNION ALL (oracle side)."""
    parts = []
    for level in range(len(groups), -1, -1):
        live = groups[:level]
        cols = ", ".join(c if c in live else f"null as {c}" for c in select_cols)
        gb = f" group by {', '.join(live)}" if live else ""
        parts.append(f"select {cols}, {aggs} {from_where}{gb}")
    return " union all ".join(parts)


_Q18_FW = """
from catalog_sales, customer_demographics, customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
    and cs_item_sk = i_item_sk
    and cs_bill_cdemo_sk = cd_demo_sk
    and cs_bill_customer_sk = c_customer_sk
    and cd_gender = 'F'
    and cd_education_status = 'Unknown'
    and c_current_addr_sk = ca_address_sk
    and d_year = 1998
"""

_Q22_FW = """
from inventory, date_dim, item
where inv_date_sk = d_date_sk
    and inv_item_sk = i_item_sk
    and d_month_seq between 1176 and 1187
"""

ORACLE_OVERRIDES = {
    18: _rollup_union(
        ["i_item_id", "ca_country", "ca_state", "ca_county"],
        "avg(cs_quantity) as agg1, avg(cs_list_price) as agg2, avg(cs_coupon_amt) as agg3",
        _Q18_FW,
        ["i_item_id", "ca_country", "ca_state", "ca_county"],
    ),
    22: _rollup_union(
        ["i_category", "i_class", "i_brand"],
        "avg(inv_quantity_on_hand) as qoh",
        _Q22_FW,
        ["i_category", "i_class", "i_brand"],
    ),
}
