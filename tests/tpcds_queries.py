"""TPC-DS star-join queries (spec defaults), engine dialect.
Authored from the public TPC-DS spec; reference analog: the tpcds SQL
corpus the reference benchmarks (presto-benchto-benchmarks tpcds)."""

QUERIES = {
    3: """
select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manufact_id = 128
    and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, i_brand_id
limit 100
""",
    7: """
select i_item_id,
    avg(ss_quantity) as agg1,
    avg(ss_list_price) as agg2,
    avg(ss_coupon_amt) as agg3,
    avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_cdemo_sk = cd_demo_sk
    and ss_promo_sk = p_promo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and (p_channel_email = 'N' or p_channel_event = 'N')
    and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    42: """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) as total_sales
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by d_year, i_category_id, i_category
order by total_sales desc, d_year, i_category_id, i_category
limit 100
""",
    52: """
select d_year, i_brand_id as brand_id, i_brand as brand, sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
""",
    55: """
select i_brand_id as brand_id, i_brand as brand, sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 28
    and d_moy = 11
    and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
""",
}
