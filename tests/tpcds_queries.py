"""TPC-DS benchmark corpus, engine dialect — 81 queries spanning star
joins, outer/full joins, window frames, ROLLUP, correlated scalar
subqueries, EXISTS under OR (mark joins), mixed DISTINCT aggregates,
scalar subqueries in SELECT position, and NOT EXISTS.

Authored from the public TPC-DS spec query shapes, adapted to the
generated schema's column subset and data distributions; reference
analog: presto-benchto-benchmarks/src/main/resources/sql/presto/tpcds/.

``QUERIES``: qid -> engine SQL (also valid sqlite unless overridden).
``ORACLE_OVERRIDES``: qid -> sqlite-equivalent SQL for constructs sqlite
lacks (ROLLUP -> UNION ALL expansion).
"""

QUERIES = {
    # official q38 shape: customers present in all three channels
    38: """
select count(*) from (
    select c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where ss_sold_date_sk = d_date_sk and ss_customer_sk = c_customer_sk
        and d_year = 2000
    intersect
    select c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where cs_sold_date_sk = d_date_sk and cs_bill_customer_sk = c_customer_sk
        and d_year = 2000
    intersect
    select c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where ws_sold_date_sk = d_date_sk and ws_bill_customer_sk = c_customer_sk
        and d_year = 2000
) hot_cust
""",
    # official q87 shape: store customers missing from the other channels
    87: """
select count(*) from (
    select c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
    where ss_sold_date_sk = d_date_sk and ss_customer_sk = c_customer_sk
        and d_year = 2000
    except
    select c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
    where cs_sold_date_sk = d_date_sk and cs_bill_customer_sk = c_customer_sk
        and d_year = 2000
    except
    select c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
    where ws_sold_date_sk = d_date_sk and ws_bill_customer_sk = c_customer_sk
        and d_year = 2000
) cool_cust
""",
    # official Q1 shape: CTE referenced twice, one reference correlated
    2: """
with wscs as (
    select sold_date_sk, sales_price
    from (select ws_sold_date_sk as sold_date_sk,
                 ws_ext_sales_price as sales_price
          from web_sales
          union all
          select cs_sold_date_sk, cs_ext_sales_price
          from catalog_sales) x
)
select d_year, d_dow, sum(sales_price) as tot
from wscs, date_dim
where sold_date_sk = d_date_sk
group by d_year, d_dow
order by d_year, d_dow
limit 50
""",
    # Q1 in its official WITH form (the non-CTE rewrite is key 1)
    30: """
with customer_total_return as (
    select sr_customer_sk as ctr_customer_sk,
           sr_store_sk as ctr_store_sk,
           sum(sr_return_amt) as ctr_total_return
    from store_returns, date_dim
    where sr_returned_date_sk = d_date_sk and d_year = 1998
    group by sr_customer_sk, sr_store_sk
)
select ctr_customer_sk, ctr_total_return
from customer_total_return ctr1
where ctr_total_return > (select avg(ctr_total_return) * 1.2
                          from customer_total_return ctr2
                          where ctr1.ctr_store_sk = ctr2.ctr_store_sk)
order by ctr_customer_sk, ctr_total_return
limit 100
""",
    # correlated scalar subquery: customers returning > 1.2x store average
    1: """
select ctr_customer_sk, ctr_total
from (select sr_customer_sk as ctr_customer_sk, sr_store_sk as ctr_store_sk,
             sum(sr_return_amt) as ctr_total
      from store_returns, date_dim
      where sr_returned_date_sk = d_date_sk and d_year = 1998
      group by sr_customer_sk, sr_store_sk) t1
where ctr_total > (select avg(ctr_total2) * 1.2
                   from (select sr_store_sk as ctr_store_sk2,
                                sum(sr_return_amt) as ctr_total2
                         from store_returns, date_dim
                         where sr_returned_date_sk = d_date_sk and d_year = 1998
                         group by sr_customer_sk, sr_store_sk) t2
                   where ctr_store_sk2 = ctr_store_sk)
""",
    3: """
select d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) as sum_agg
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manufact_id = 128
    and d_moy = 11
group by d_year, i_brand_id, i_brand
order by d_year, sum_agg desc, i_brand_id
limit 100
""",
    # correlated scalar subquery against the item dimension
    6: """
select ca_state, count(*) as cnt
from customer_address, customer, store_sales, date_dim, item
where ca_address_sk = c_current_addr_sk
    and c_customer_sk = ss_customer_sk
    and ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and d_year = 2000 and d_moy = 1
    and i_current_price > 1.2 * (select avg(j.i_current_price) from item j
                                 where j.i_category = i_category)
group by ca_state
having count(*) >= 10
order by cnt, ca_state
""",
    7: """
select i_item_id,
    avg(ss_quantity) as agg1,
    avg(ss_list_price) as agg2,
    avg(ss_coupon_amt) as agg3,
    avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_cdemo_sk = cd_demo_sk
    and ss_promo_sk = p_promo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and (p_channel_email = 'N' or p_channel_event = 'N')
    and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # category revenue share via a partitioned window over agg output
    12: """
select i_item_id, i_category, sum(ws_ext_sales_price) as itemrevenue,
       sum(ws_ext_sales_price) * 100.0
         / sum(sum(ws_ext_sales_price)) over (partition by i_class) as revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
    and i_category in ('Sports', 'Books', 'Home')
    and ws_sold_date_sk = d_date_sk
    and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_class, i_category
""",
    15: """
select ca_zip, sum(cs_sales_price) as total
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and (ca_state in ('CA', 'WA', 'GA') or cs_sales_price > 60.00)
    and cs_sold_date_sk = d_date_sk
    and d_qoy = 1 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
""",
    # ROLLUP over customer geography (oracle: UNION ALL expansion)
    18: """
select i_item_id, ca_country, ca_state, ca_county,
       avg(cs_quantity) as agg1,
       avg(cs_list_price) as agg2,
       avg(cs_coupon_amt) as agg3
from catalog_sales, customer_demographics, customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
    and cs_item_sk = i_item_sk
    and cs_bill_cdemo_sk = cd_demo_sk
    and cs_bill_customer_sk = c_customer_sk
    and cd_gender = 'F'
    and cd_education_status = 'Unknown'
    and c_current_addr_sk = ca_address_sk
    and d_year = 1998
group by rollup(i_item_id, ca_country, ca_state, ca_county)
""",
    19: """
select i_brand_id, i_brand, i_manufact_id, i_manufact,
       sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 8
    and d_moy = 11
    and d_year = 1998
    and ss_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and ss_store_sk = s_store_sk
    and ca_state <> s_state
group by i_brand_id, i_brand, i_manufact_id, i_manufact
order by ext_price desc, i_brand_id, i_manufact_id
limit 100
""",
    # ROLLUP over the inventory fact (oracle: UNION ALL expansion)
    22: """
select i_category, i_class, i_brand, avg(inv_quantity_on_hand) as qoh
from inventory, date_dim, item
where inv_date_sk = d_date_sk
    and inv_item_sk = i_item_sk
    and d_month_seq between 1176 and 1187
group by rollup(i_category, i_class, i_brand)
""",
    # three-fact join: sales -> returns -> catalog re-purchase
    25: """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_net_profit) as store_sales_profit,
       sum(sr_net_loss) as store_returns_loss,
       sum(cs_net_profit) as catalog_sales_profit
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_year = 1998
    and d1.d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_year between 1998 and 1999
    and sr_customer_sk = cs_bill_customer_sk
    and sr_item_sk = cs_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_year between 1998 and 1999
group by i_item_id, i_item_desc, s_store_id, s_store_name
""",
    26: """
select i_item_id,
    avg(cs_quantity) as agg1,
    avg(cs_list_price) as agg2,
    avg(cs_coupon_amt) as agg3,
    avg(cs_sales_price) as agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
    and cs_item_sk = i_item_sk
    and cs_bill_cdemo_sk = cd_demo_sk
    and cs_promo_sk = p_promo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and (p_channel_email = 'N' or p_channel_event = 'N')
    and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
""",
    # per-ticket counts joined back to customer
    34: """
select c_last_name, c_first_name, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_hdemo_sk = hd_demo_sk
          and (d_dom between 1 and 3 or d_dom between 25 and 28)
          and hd_buy_potential = '>10000'
          and hd_vehicle_count > 0
          and d_year = 1999
      group by ss_ticket_number, ss_customer_sk) dn, customer
where ss_customer_sk = c_customer_sk
    and cnt between 1 and 5
order by c_last_name, c_first_name, ss_ticket_number
""",
    42: """
select d_year, i_category_id, i_category, sum(ss_ext_sales_price) as total_sales
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by d_year, i_category_id, i_category
order by total_sales desc, d_year, i_category_id, i_category
limit 100
""",
    # day-of-week pivot via CASE aggregation
    43: """
select s_store_name, s_store_id,
       sum(case when d_day_name = 'Sunday' then ss_sales_price end) as sun_sales,
       sum(case when d_day_name = 'Monday' then ss_sales_price end) as mon_sales,
       sum(case when d_day_name = 'Tuesday' then ss_sales_price end) as tue_sales,
       sum(case when d_day_name = 'Wednesday' then ss_sales_price end) as wed_sales,
       sum(case when d_day_name = 'Thursday' then ss_sales_price end) as thu_sales,
       sum(case when d_day_name = 'Friday' then ss_sales_price end) as fri_sales,
       sum(case when d_day_name = 'Saturday' then ss_sales_price end) as sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
    and s_store_sk = ss_store_sk
    and d_year = 1998
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
""",
    # OR'd demographic/price bands over an equi-joined probe
    48: """
select sum(ss_quantity) as total
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
    and ss_sold_date_sk = d_date_sk and d_year = 1999
    and cd_demo_sk = ss_cdemo_sk
    and ((cd_marital_status = 'M' and cd_education_status = '4 yr Degree'
          and ss_sales_price between 100.00 and 150.00)
      or (cd_marital_status = 'D' and cd_education_status = '2 yr Degree'
          and ss_sales_price between 50.00 and 100.00)
      or (cd_marital_status = 'S' and cd_education_status = 'College'
          and ss_sales_price between 150.00 and 200.00))
    and ss_addr_sk = ca_address_sk
    and ca_country = 'United States'
""",
    # cumulative store vs web revenue series, FULL OUTER + ROWS frame
    51: """
select store_d, store_cum, web_cum
from (select ds as store_d, store_cum, web_cum
      from (select d_date as ds,
                   sum(sum(ss_ext_sales_price)) over (order by d_date
                       rows between unbounded preceding and current row) as store_cum
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk and d_year = 2000 and d_moy = 1
            group by d_date) s
      full outer join
           (select d_date as dw,
                   sum(sum(ws_ext_sales_price)) over (order by d_date
                       rows between unbounded preceding and current row) as web_cum
            from web_sales, date_dim
            where ws_sold_date_sk = d_date_sk and d_year = 2000
                and d_moy = 1 and d_dom < 20
            group by d_date) w
      on ds = dw) x
order by store_d
""",
    52: """
select d_year, i_brand_id as brand_id, i_brand as brand, sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 1
    and d_moy = 11
    and d_year = 2000
group by d_year, i_brand_id, i_brand
order by d_year, ext_price desc, brand_id
limit 100
""",
    # manager monthly sums vs their partitioned average (window over agg)
    53: """
select i_manager_id, sum_sales, avg_monthly_sales
from (select i_manager_id, d_moy, sum(ss_sales_price) as sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_manager_id) as avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
          and ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and d_month_seq between 1176 and 1187
      group by i_manager_id, d_moy) tmp
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
""",
    55: """
select i_brand_id as brand_id, i_brand as brand, sum(ss_ext_sales_price) as ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
    and ss_item_sk = i_item_sk
    and i_manager_id = 28
    and d_moy = 11
    and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
""",
    # items under 10% of their store's average revenue (correlated)
    65: """
select s_store_name, i_item_desc, revenue
from store, item,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) as revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_month_seq between 1176 and 1179
      group by ss_store_sk, ss_item_sk) sc
where revenue <= (select 0.1 * avg(rev2)
                  from (select ss_store_sk as store2, sum(ss_sales_price) as rev2
                        from store_sales, date_dim
                        where ss_sold_date_sk = d_date_sk
                            and d_month_seq between 1176 and 1179
                        group by ss_store_sk, ss_item_sk) sb
                  where store2 = ss_store_sk)
    and s_store_sk = ss_store_sk
    and i_item_sk = ss_item_sk
""",
    # bought-city vs home-city ticket roll-up
    68: """
select c_last_name, c_first_name, ca_city, bought_city, extended_price
from (select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
             sum(ss_ext_sales_price) as extended_price
      from store_sales, date_dim, store, household_demographics, customer_address
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_hdemo_sk = hd_demo_sk
          and ss_addr_sk = ca_address_sk
          and d_year = 1999
          and (hd_dep_count = 4 or hd_vehicle_count = 3)
      group by ss_ticket_number, ss_customer_sk, ca_city) dn,
     customer, customer_address
where ss_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and ca_city <> bought_city
""",
    # time-of-day traffic counts, cross join of single-row aggregates
    88: """
select h8, h9, h10, h11
from (select count(*) as h8 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 8
          and hd_dep_count = 2 and s_store_name = 'ese') s1,
     (select count(*) as h9 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 9
          and hd_dep_count = 2 and s_store_name = 'ese') s2,
     (select count(*) as h10 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 10
          and hd_dep_count = 2 and s_store_name = 'ese') s3,
     (select count(*) as h11 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
          and ss_store_sk = s_store_sk and t_hour = 11
          and hd_dep_count = 2 and s_store_name = 'ese') s4
""",
    # NOT EXISTS anti-join on returns
    94: """
select count(*) as order_count, sum(ws_ext_ship_cost) as total_shipping_cost
from web_sales, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-30'
    and ws_ship_date_sk = d_date_sk
    and ws_ship_addr_sk = ca_address_sk
    and ca_state = 'CA'
    and ws_web_site_sk = web_site_sk
    and web_name = 'site_1'
    and not exists (select * from web_returns
                    where ws_order_number = wr_order_number)
""",
    96: """
select count(*) as cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = t_time_sk
    and ss_hdemo_sk = hd_demo_sk
    and ss_store_sk = s_store_sk
    and t_hour = 20
    and t_minute >= 30
    and hd_dep_count = 7
    and s_store_name = 'ese'
""",
    # store/catalog buyer overlap via FULL OUTER over grouped facts
    97: """
select sum(case when customer_sk is not null and customer_sk2 is null then 1 else 0 end) as store_only,
       sum(case when customer_sk is null and customer_sk2 is not null then 1 else 0 end) as catalog_only,
       sum(case when customer_sk is not null and customer_sk2 is not null then 1 else 0 end) as store_and_catalog
from (select ss_customer_sk as customer_sk, ss_item_sk as item_sk
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_month_seq between 1176 and 1181
      group by ss_customer_sk, ss_item_sk) ssci
full outer join
     (select cs_bill_customer_sk as customer_sk2, cs_item_sk as item_sk2
      from catalog_sales, date_dim
      where cs_sold_date_sk = d_date_sk and d_month_seq between 1176 and 1181
      group by cs_bill_customer_sk, cs_item_sk) csci
on customer_sk = customer_sk2 and item_sk = item_sk2
""",
}

# round-3 breadth: the official shapes of the remaining corpus, adapted
# to the generated schema's column subset and value distributions
QUERIES.update({
    # quantity-bucket report: CASE over scalar-subquery count/avg pairs
    9: """
select case when (select count(*) from store_sales
                  where ss_quantity between 1 and 20) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 1 and 20)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 1 and 20) end as bucket1,
       case when (select count(*) from store_sales
                  where ss_quantity between 21 and 40) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 21 and 40)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 21 and 40) end as bucket2,
       case when (select count(*) from store_sales
                  where ss_quantity between 41 and 60) > 1000
            then (select avg(ss_ext_discount_amt) from store_sales
                  where ss_quantity between 41 and 60)
            else (select avg(ss_net_paid) from store_sales
                  where ss_quantity between 41 and 60) end as bucket3
from reason
where r_reason_sk = 1
""",
    # demographic counts for customers active in store AND (web OR catalog)
    10: """
select cd_gender, cd_marital_status, cd_education_status, count(*) as cnt1,
       cd_purchase_estimate, count(*) as cnt2, cd_credit_rating, count(*) as cnt3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
    and ca_county in ('Williamson County', 'Walker County', 'Barrow County')
    and cd_demo_sk = c.c_current_cdemo_sk
    and exists (select * from store_sales, date_dim
                where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_moy between 1 and 4)
    and (exists (select * from web_sales, date_dim
                 where c.c_customer_sk = ws_bill_customer_sk
                     and ws_sold_date_sk = d_date_sk
                     and d_year = 2002 and d_moy between 1 and 4)
      or exists (select * from catalog_sales, date_dim
                 where c.c_customer_sk = cs_ship_customer_sk
                     and cs_sold_date_sk = d_date_sk
                     and d_year = 2002 and d_moy between 1 and 4))
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100
""",
    # OR'd demographic bands with household-demographics conjuncts
    13: """
select avg(ss_quantity) as avg_qty,
       avg(ss_ext_sales_price) as avg_esp,
       avg(ss_ext_wholesale_cost) as avg_ewc,
       sum(ss_ext_wholesale_cost) as sum_ewc
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk
    and ss_sold_date_sk = d_date_sk and d_year = 2001
    and ((ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
          and cd_marital_status = 'M' and cd_education_status = 'Advanced Degree'
          and ss_sales_price between 100.00 and 150.00 and hd_dep_count = 3)
      or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
          and cd_marital_status = 'S' and cd_education_status = 'College'
          and ss_sales_price between 50.00 and 100.00 and hd_dep_count = 1)
      or (ss_hdemo_sk = hd_demo_sk and cd_demo_sk = ss_cdemo_sk
          and cd_marital_status = 'W' and cd_education_status = '2 yr Degree'
          and ss_sales_price between 150.00 and 200.00 and hd_dep_count = 1))
    and ((ss_addr_sk = ca_address_sk and ca_country = 'UNITED STATES'
          and ca_state in ('TX', 'OH', 'TX')
          and ss_net_profit between 100 and 200)
      or (ss_addr_sk = ca_address_sk and ca_country = 'UNITED STATES'
          and ca_state in ('OR', 'NM', 'KY')
          and ss_net_profit between 150 and 300)
      or (ss_addr_sk = ca_address_sk and ca_country = 'UNITED STATES'
          and ca_state in ('VA', 'TX', 'MS')
          and ss_net_profit between 50 and 250))
""",
    # catalog orders: multi-warehouse EXISTS + no-returns NOT EXISTS +
    # mixed DISTINCT/plain aggregation
    16: """
select count(distinct cs_order_number) as order_count,
       sum(cs_ext_ship_cost) as total_shipping_cost,
       sum(cs_net_profit) as total_net_profit
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between date '2002-02-01' and date '2002-04-02'
    and cs1.cs_ship_date_sk = d_date_sk
    and cs1.cs_ship_addr_sk = ca_address_sk
    and ca_state = 'GA'
    and cs1.cs_call_center_sk = cc_call_center_sk
    and cc_county in ('Williamson County', 'Ziebach County', 'Walker County')
    and exists (select * from catalog_sales cs2
                where cs1.cs_order_number = cs2.cs_order_number
                    and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
    and not exists (select * from catalog_returns cr1
                    where cs1.cs_order_number = cr1.cr_order_number)
""",
    # catalog category revenue share (q12's catalog sibling)
    20: """
select i_item_id, i_category, sum(cs_ext_sales_price) as itemrevenue,
       sum(cs_ext_sales_price) * 100.0
         / sum(sum(cs_ext_sales_price)) over (partition by i_class) as revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
    and i_category in ('Sports', 'Books', 'Home')
    and cs_sold_date_sk = d_date_sk
    and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_class, i_category
order by i_category, i_item_id, itemrevenue
limit 100
""",
    # inventory level before/after a date, bounded ratio
    21: """
select w_warehouse_name, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) as inv_before,
       sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) as inv_after
from inventory, warehouse, item, date_dim
where inv_item_sk = i_item_sk
    and inv_warehouse_sk = w_warehouse_sk
    and inv_date_sk = d_date_sk
    and i_current_price between 10.00 and 90.00
    and d_date between date '2000-02-10' and date '2000-04-10'
group by w_warehouse_name, i_item_id
having sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) > 0
   and sum(case when d_date >= date '2000-03-11'
                then inv_quantity_on_hand else 0 end) * 1.0
     / sum(case when d_date < date '2000-03-11'
                then inv_quantity_on_hand else 0 end) between 0.666667 and 1.5
order by w_warehouse_name, i_item_id
limit 100
""",
    # six independent price-band profiles cross-joined (single-row each)
    28: """
select b1.lp_avg as b1_lp, b1.cnt as b1_cnt, b1.cntd as b1_cntd,
       b2.lp_avg as b2_lp, b2.cnt as b2_cnt, b2.cntd as b2_cntd,
       b3.lp_avg as b3_lp, b3.cnt as b3_cnt, b3.cntd as b3_cntd
from (select sum(ss_list_price) * 1.0 / count(ss_list_price) lp_avg,
             count(ss_list_price) cnt,
             count(distinct ss_list_price) cntd
      from store_sales
      where ss_quantity between 0 and 5
          and (ss_list_price between 8 and 18
            or ss_coupon_amt between 459 and 1459
            or ss_wholesale_cost between 57 and 77)) b1,
     (select sum(ss_list_price) * 1.0 / count(ss_list_price) lp_avg,
             count(ss_list_price) cnt,
             count(distinct ss_list_price) cntd
      from store_sales
      where ss_quantity between 6 and 10
          and (ss_list_price between 90 and 100
            or ss_coupon_amt between 2323 and 3323
            or ss_wholesale_cost between 31 and 51)) b2,
     (select sum(ss_list_price) * 1.0 / count(ss_list_price) lp_avg,
             count(ss_list_price) cnt,
             count(distinct ss_list_price) cntd
      from store_sales
      where ss_quantity between 11 and 15
          and (ss_list_price between 142 and 152
            or ss_coupon_amt between 12214 and 13214
            or ss_wholesale_cost between 79 and 99)) b3
""",
    # quantity flow: store sale -> store return -> catalog re-purchase
    29: """
select i_item_id, i_item_desc, s_store_id, s_store_name,
       sum(ss_quantity) as store_sales_quantity,
       sum(sr_return_quantity) as store_returns_quantity,
       sum(cs_quantity) as catalog_sales_quantity
from store_sales, store_returns, catalog_sales,
     date_dim d1, date_dim d2, date_dim d3, store, item
where d1.d_moy = 9 and d1.d_year = 1999
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and ss_customer_sk = sr_customer_sk
    and ss_item_sk = sr_item_sk
    and ss_ticket_number = sr_ticket_number
    and sr_returned_date_sk = d2.d_date_sk
    and d2.d_moy between 9 and 12 and d2.d_year = 1999
    and sr_customer_sk = cs_bill_customer_sk
    and cs_item_sk = sr_item_sk
    and cs_sold_date_sk = d3.d_date_sk
    and d3.d_year in (1999, 2000, 2001)
group by i_item_id, i_item_desc, s_store_id, s_store_name
order by i_item_id, i_item_desc, s_store_id, s_store_name
limit 100
""",
    # excess catalog discount: correlated 1.3x-average threshold
    32: """
select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 66
    and i_item_sk = cs_item_sk
    and d_date between date '2000-01-27' and date '2000-04-26'
    and d_date_sk = cs_sold_date_sk
    and cs_ext_discount_amt > (
        select 1.3 * avg(cs_ext_discount_amt)
        from catalog_sales, date_dim
        where cs_item_sk = i_item_sk
            and d_date between date '2000-01-27' and date '2000-04-26'
            and d_date_sk = cs_sold_date_sk)
""",
    # ROLLUP over store-sales demographics by state
    27: """
select i_item_id, s_state,
       avg(ss_quantity) as agg1,
       avg(ss_list_price) as agg2,
       avg(ss_coupon_amt) as agg3,
       avg(ss_sales_price) as agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and ss_cdemo_sk = cd_demo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and d_year = 2002
    and s_state in ('TN', 'CA', 'TX')
group by rollup(i_item_id, s_state)
""",
    # manufacturer revenue for one category across all three channels
    33: """
with ss as (
    select i_manufact_id, sum(ss_ext_sales_price) as total_sales
    from store_sales, date_dim, customer_address, item
    where i_item_sk = ss_item_sk
        and ss_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 5
        and ss_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
        and i_category = 'Electronics'
    group by i_manufact_id
),
cs as (
    select i_manufact_id, sum(cs_ext_sales_price) as total_sales
    from catalog_sales, date_dim, customer_address, item
    where i_item_sk = cs_item_sk
        and cs_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 5
        and cs_bill_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
        and i_category = 'Electronics'
    group by i_manufact_id
),
ws as (
    select i_manufact_id, sum(ws_ext_sales_price) as total_sales
    from web_sales, date_dim, customer_address, item
    where i_item_sk = ws_item_sk
        and ws_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 5
        and ws_bill_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
        and i_category = 'Electronics'
    group by i_manufact_id
)
select i_manufact_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs union all select * from ws) t
group by i_manufact_id
order by total_sales, i_manufact_id
limit 100
""",
    # warehouse sales value before/after, returns netted out via
    # LEFT JOIN catalog_returns
    40: """
select w_state, i_item_id,
       sum(case when d_date < date '2000-03-11'
                then cs_sales_price - coalesce(cr_return_amount, 0)
                else 0 end) as sales_before,
       sum(case when d_date >= date '2000-03-11'
                then cs_sales_price - coalesce(cr_return_amount, 0)
                else 0 end) as sales_after
from catalog_sales
     left outer join catalog_returns
        on (cs_order_number = cr_order_number and cs_item_sk = cr_item_sk),
     warehouse, item, date_dim
where i_current_price between 10.00 and 30.00
    and i_item_sk = cs_item_sk
    and cs_warehouse_sk = w_warehouse_sk
    and cs_sold_date_sk = d_date_sk
    and d_date between date '2000-02-10' and date '2000-04-10'
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
""",
    # distinct manufacturers whose items match OR'd category/color bands
    # (correlated count subquery over the item dimension)
    41: """
select distinct i_manufact
from item i1
where i_manufact_id between 700 and 740
    and (select count(*) as item_cnt
         from item
         where (i_manufact = i1.i_manufact
                and i_category = 'Women'
                and i_color in ('red', 'green', 'blue', 'yellow')
                and i_size in ('small', 'medium'))
            or (i_manufact = i1.i_manufact
                and i_category = 'Men'
                and i_color in ('black', 'white', 'pink', 'purple')
                and i_size in ('large', 'extra large'))) > 0
order by i_manufact
limit 100
""",
    # web customers by zip prefix or item list
    45: """
select ca_zip, ca_city, sum(ws_sales_price) as total
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
    and c_current_addr_sk = ca_address_sk
    and ws_item_sk = i_item_sk
    and (substr(ca_zip, 1, 5) in ('10144', '10298', '10113', '10558', '10495')
      or i_item_id in (select i_item_id from item
                       where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)))
    and ws_sold_date_sk = d_date_sk
    and d_qoy = 2 and d_year = 2001
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
""",
    # per-ticket city flows: bought in one city, customer lives in another
    46: """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk, ca_city as bought_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics, customer_address
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_hdemo_sk = hd_demo_sk
          and ss_addr_sk = ca_address_sk
          and (hd_dep_count = 4 or hd_vehicle_count = 3)
          and d_dow in (6, 0)
          and d_year in (1999, 2000, 2001)
          and s_city in ('Fairview', 'Midway')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
    and customer.c_current_addr_sk = current_addr.ca_address_sk
    and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number
limit 100
""",
    # return-delay buckets: days between sale and return
    50: """
select s_store_name, s_store_id,
       sum(case when sr_returned_date_sk - ss_sold_date_sk <= 30
                then 1 else 0 end) as d_30,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 30
                 and sr_returned_date_sk - ss_sold_date_sk <= 60
                then 1 else 0 end) as d_31_60,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 60
                 and sr_returned_date_sk - ss_sold_date_sk <= 90
                then 1 else 0 end) as d_61_90,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 90
                 and sr_returned_date_sk - ss_sold_date_sk <= 120
                then 1 else 0 end) as d_91_120,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 120
                then 1 else 0 end) as d_over_120
from store_sales, store_returns, store, date_dim d1, date_dim d2
where d2.d_year = 2001 and d2.d_moy = 8
    and ss_ticket_number = sr_ticket_number
    and ss_item_sk = sr_item_sk
    and ss_sold_date_sk = d1.d_date_sk
    and sr_returned_date_sk = d2.d_date_sk
    and ss_customer_sk = sr_customer_sk
    and ss_store_sk = s_store_sk
group by s_store_name, s_store_id
order by s_store_name, s_store_id
limit 100
""",
    # item revenue for selected colors across the three channels
    56: """
with ss as (
    select i_item_id, sum(ss_ext_sales_price) as total_sales
    from store_sales, date_dim, customer_address, item
    where i_item_id in (select i_item_id from item
                        where i_color in ('red', 'green', 'blue'))
        and ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 2
        and ss_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
    group by i_item_id
),
cs as (
    select i_item_id, sum(cs_ext_sales_price) as total_sales
    from catalog_sales, date_dim, customer_address, item
    where i_item_id in (select i_item_id from item
                        where i_color in ('red', 'green', 'blue'))
        and cs_item_sk = i_item_sk
        and cs_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 2
        and cs_bill_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
    group by i_item_id
),
ws as (
    select i_item_id, sum(ws_ext_sales_price) as total_sales
    from web_sales, date_dim, customer_address, item
    where i_item_id in (select i_item_id from item
                        where i_color in ('red', 'green', 'blue'))
        and ws_item_sk = i_item_sk
        and ws_sold_date_sk = d_date_sk
        and d_year = 2001 and d_moy = 2
        and ws_bill_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
    group by i_item_id
)
select i_item_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs union all select * from ws) t
group by i_item_id
order by total_sales, i_item_id
limit 100
""",
    # weekly store revenue, this year vs same week last year
    59: """
with wss as (
    select d_week_seq, ss_store_sk,
           sum(case when d_day_name = 'Sunday' then ss_sales_price end) as sun_sales,
           sum(case when d_day_name = 'Monday' then ss_sales_price end) as mon_sales,
           sum(case when d_day_name = 'Tuesday' then ss_sales_price end) as tue_sales,
           sum(case when d_day_name = 'Wednesday' then ss_sales_price end) as wed_sales
    from store_sales, date_dim
    where d_date_sk = ss_sold_date_sk
    group by d_week_seq, ss_store_sk
)
select s_store_name1, s_store_id1, d_week_seq1,
       sun_sales1 / sun_sales2 as sun_ratio,
       mon_sales1 / mon_sales2 as mon_ratio,
       tue_sales1 / tue_sales2 as tue_ratio,
       wed_sales1 / wed_sales2 as wed_ratio
from (select s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             s_store_id s_store_id1, sun_sales sun_sales1,
             mon_sales mon_sales1, tue_sales tue_sales1, wed_sales wed_sales1
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq
          and ss_store_sk = s_store_sk
          and d_month_seq between 1185 and 1185 + 11
      group by s_store_name, wss.d_week_seq, s_store_id, sun_sales,
               mon_sales, tue_sales, wed_sales) y,
     (select s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             s_store_id s_store_id2, sun_sales sun_sales2,
             mon_sales mon_sales2, tue_sales tue_sales2, wed_sales wed_sales2
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq
          and ss_store_sk = s_store_sk
          and d_month_seq between 1185 + 12 and 1185 + 23
      group by s_store_name, wss.d_week_seq, s_store_id, sun_sales,
               mon_sales, tue_sales, wed_sales) x
where s_store_id1 = s_store_id2
    and d_week_seq1 = d_week_seq2 - 52
order by s_store_name1, s_store_id1, d_week_seq1
limit 100
""",
    # item revenue for one category across the three channels (q33/q56
    # family, category variant)
    60: """
with ss as (
    select i_item_id, sum(ss_ext_sales_price) as total_sales
    from store_sales, date_dim, customer_address, item
    where i_item_id in (select i_item_id from item where i_category = 'Music')
        and ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and ss_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
    group by i_item_id
),
cs as (
    select i_item_id, sum(cs_ext_sales_price) as total_sales
    from catalog_sales, date_dim, customer_address, item
    where i_item_id in (select i_item_id from item where i_category = 'Music')
        and cs_item_sk = i_item_sk
        and cs_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and cs_bill_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
    group by i_item_id
),
ws as (
    select i_item_id, sum(ws_ext_sales_price) as total_sales
    from web_sales, date_dim, customer_address, item
    where i_item_id in (select i_item_id from item where i_category = 'Music')
        and ws_item_sk = i_item_sk
        and ws_sold_date_sk = d_date_sk
        and d_year = 1998 and d_moy = 9
        and ws_bill_addr_sk = ca_address_sk
        and ca_gmt_offset = -5
    group by i_item_id
)
select i_item_id, sum(total_sales) as total_sales
from (select * from ss union all select * from cs union all select * from ws) t
group by i_item_id
order by i_item_id, total_sales
limit 100
""",
    # promotional vs all store sales ratio (two single-row subqueries)
    61: """
select promotions, total, promotions * 100.0 / total as promo_pct
from (select sum(ss_ext_sales_price) as promotions
      from store_sales, store, promotion, date_dim, customer,
           customer_address, item
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_promo_sk = p_promo_sk
          and ss_customer_sk = c_customer_sk
          and ca_address_sk = c_current_addr_sk
          and ss_item_sk = i_item_sk
          and ca_gmt_offset = -5
          and i_category = 'Jewelry'
          and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
               or p_channel_tv = 'Y')
          and s_gmt_offset = -5
          and d_year = 1998 and d_moy = 11) promotional_sales,
     (select sum(ss_ext_sales_price) as total
      from store_sales, store, date_dim, customer, customer_address, item
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_customer_sk = c_customer_sk
          and ca_address_sk = c_current_addr_sk
          and ss_item_sk = i_item_sk
          and ca_gmt_offset = -5
          and i_category = 'Jewelry'
          and s_gmt_offset = -5
          and d_year = 1998 and d_moy = 11) all_sales
""",
    # web shipping-delay buckets by warehouse / ship mode / site
    62: """
select w_warehouse_name, sm_type, web_name,
       sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30
                then 1 else 0 end) as d_30,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                 and ws_ship_date_sk - ws_sold_date_sk <= 60
                then 1 else 0 end) as d_31_60,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 60
                 and ws_ship_date_sk - ws_sold_date_sk <= 90
                then 1 else 0 end) as d_61_90,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 90
                then 1 else 0 end) as d_over_90
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_month_seq between 1185 and 1196
    and ws_ship_date_sk = d_date_sk
    and ws_warehouse_sk = w_warehouse_sk
    and ws_ship_mode_sk = sm_ship_mode_sk
    and ws_web_site_sk = web_site_sk
group by w_warehouse_name, sm_type, web_name
order by w_warehouse_name, sm_type, web_name
limit 100
""",
    # manager monthly sales vs their average (window over agg output)
    63: """
select *
from (select i_manager_id, sum(ss_sales_price) as sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_manager_id)
                 as avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
          and ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and d_year = 2000
          and i_category in ('Books', 'Children', 'Electronics')
          and i_class in ('class#1', 'class#2', 'class#3')
      group by i_manager_id, d_moy) tmp1
where case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else 0 end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
""",
    # store-active customers absent from web AND catalog
    69: """
select cd_gender, cd_marital_status, cd_education_status, count(*) as cnt1,
       cd_purchase_estimate, count(*) as cnt2, cd_credit_rating, count(*) as cnt3
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
    and ca_state in ('TN', 'GA', 'NY')
    and cd_demo_sk = c.c_current_cdemo_sk
    and exists (select * from store_sales, date_dim
                where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy between 4 and 6)
    and not exists (select * from web_sales, date_dim
                    where c.c_customer_sk = ws_bill_customer_sk
                        and ws_sold_date_sk = d_date_sk
                        and d_year = 2001 and d_moy between 4 and 6)
    and not exists (select * from catalog_sales, date_dim
                    where c.c_customer_sk = cs_ship_customer_sk
                        and cs_sold_date_sk = d_date_sk
                        and d_year = 2001 and d_moy between 4 and 6)
group by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
order by cd_gender, cd_marital_status, cd_education_status,
         cd_purchase_estimate, cd_credit_rating
limit 100
""",
    # brand revenue by hour for one month, all channels, AM/PM
    71: """
select i_brand_id brand_id, i_brand brand, t_hour, t_minute,
       sum(ext_price) as ext_price
from item,
     (select ws_ext_sales_price as ext_price,
             ws_sold_date_sk as sold_date_sk,
             ws_item_sk as sold_item_sk,
             ws_sold_time_sk as time_sk
      from web_sales, date_dim
      where d_date_sk = ws_sold_date_sk and d_moy = 11 and d_year = 1999
      union all
      select cs_ext_sales_price, cs_sold_date_sk, cs_item_sk, cs_sold_time_sk
      from catalog_sales, date_dim
      where d_date_sk = cs_sold_date_sk and d_moy = 11 and d_year = 1999
      union all
      select ss_ext_sales_price, ss_sold_date_sk, ss_item_sk, ss_sold_time_sk
      from store_sales, date_dim
      where d_date_sk = ss_sold_date_sk and d_moy = 11 and d_year = 1999) tmp,
     time_dim
where sold_item_sk = i_item_sk
    and i_manager_id = 1
    and time_sk = t_time_sk
    and (t_am_pm = 'AM' or t_hour between 19 and 21)
group by i_brand_id, i_brand, t_hour, t_minute
order by ext_price desc, i_brand_id, t_hour, t_minute
limit 100
""",
    # tickets of 1-5 items for targeted demographics (q34 sibling)
    73: """
select c_last_name, c_first_name, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) as cnt
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and ss_hdemo_sk = hd_demo_sk
          and d_dom between 1 and 2
          and (hd_buy_potential = '>10000' or hd_buy_potential = '0-500')
          and hd_vehicle_count > 0
          and case when hd_vehicle_count > 0
                   then hd_dep_count * 1.0 / hd_vehicle_count
                   else null end > 1
          and d_year in (1999, 2000, 2001)
          and s_county in ('Williamson County', 'Ziebach County')
      group by ss_ticket_number, ss_customer_sk) dj, customer
where ss_customer_sk = c_customer_sk
    and cnt between 1 and 5
order by cnt desc, c_last_name asc
limit 100
""",
    # channel union with NULL foreign keys (unsold/unbilled analysis)
    76: """
select channel, col_name, d_year, d_qoy, i_category,
       count(*) as sales_cnt, sum(ext_sales_price) as sales_amt
from (select 'store' as channel, 'ss_promo_sk' as col_name,
             d_year, d_qoy, i_category, ss_ext_sales_price as ext_sales_price
      from store_sales, item, date_dim
      where ss_promo_sk is null
          and ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk
      union all
      select 'web' as channel, 'ws_promo_sk' as col_name,
             d_year, d_qoy, i_category, ws_ext_sales_price as ext_sales_price
      from web_sales, item, date_dim
      where ws_promo_sk is null
          and ws_sold_date_sk = d_date_sk and ws_item_sk = i_item_sk
      union all
      select 'catalog' as channel, 'cs_promo_sk' as col_name,
             d_year, d_qoy, i_category, cs_ext_sales_price as ext_sales_price
      from catalog_sales, item, date_dim
      where cs_promo_sk is null
          and cs_sold_date_sk = d_date_sk and cs_item_sk = i_item_sk) foo
group by channel, col_name, d_year, d_qoy, i_category
order by channel, col_name, d_year, d_qoy, i_category
limit 100
""",
    # store-city customer profit per ticket
    79: """
select c_last_name, c_first_name,
       substr(s_city, 1, 30) as city30, ss_ticket_number, amt, profit
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) as amt, sum(ss_net_profit) as profit
      from store_sales, date_dim, store, household_demographics
      where ss_sold_date_sk = d_date_sk
          and ss_store_sk = store.s_store_sk
          and ss_hdemo_sk = hd_demo_sk
          and (hd_dep_count = 6 or hd_vehicle_count > 2)
          and d_dow = 1
          and d_year in (1998, 1999, 2000)
          and store.s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk,
               store.s_city) ms, customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, city30, profit
limit 100
""",
    # q37's store sibling: price-band items in inventory, sold in store
    82: """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 30.00 and 60.00
    and inv_item_sk = i_item_sk
    and d_date_sk = inv_date_sk
    and d_date between date '2000-05-25' and date '2000-07-24'
    and i_manufact_id in (9, 10, 11, 12, 13, 14, 15, 16)
    and inv_quantity_on_hand between 100 and 500
    and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",
    # returning customers by income band and city
    84: """
select c_customer_id as customer_id,
       c_last_name as customername
from customer, customer_address, customer_demographics,
     household_demographics, income_band, store_returns
where ca_city = 'Fairview'
    and c_current_addr_sk = ca_address_sk
    and ib_lower_bound >= 10000
    and ib_upper_bound <= 50000
    and ib_income_band_sk = hd_income_band_sk
    and cd_demo_sk = sr_cdemo_sk
    and hd_demo_sk = c_current_hdemo_sk
    and cd_demo_sk = c_current_cdemo_sk
order by c_customer_id
limit 100
""",
    # web returns by reason with demographic/address disjunct bands
    85: """
select substr(r_reason_desc, 1, 20) as reason,
       avg(ws_quantity) as avg_qty,
       avg(wr_return_amt) as avg_amt
from web_sales, web_returns, web_page, customer, customer_demographics cd1,
     customer_address, date_dim, reason
where ws_web_page_sk = wp_web_page_sk
    and ws_item_sk = wr_item_sk
    and ws_order_number = wr_order_number
    and ws_sold_date_sk = d_date_sk
    and d_year = 2000
    and wr_returning_customer_sk = c_customer_sk
    and cd1.cd_demo_sk = c_current_cdemo_sk
    and ca_address_sk = c_current_addr_sk
    and r_reason_sk = wr_reason_sk
    and ((cd1.cd_marital_status = 'M'
          and cd1.cd_education_status = 'Advanced Degree'
          and ws_sales_price between 100.00 and 150.00)
      or (cd1.cd_marital_status = 'S'
          and cd1.cd_education_status = 'College'
          and ws_sales_price between 50.00 and 100.00))
    and ((ca_country = 'UNITED STATES' and ca_state in ('IN', 'OH', 'NJ')
          and ws_net_profit between 100 and 200)
      or (ca_country = 'UNITED STATES' and ca_state in ('WI', 'CT', 'KY')
          and ws_net_profit between 150 and 300))
group by r_reason_desc
order by reason, avg_qty, avg_amt
limit 100
""",
    # monthly class sales vs their average (q63's class sibling)
    89: """
select *
from (select i_category, i_class, i_brand, s_store_name, s_county,
             d_moy, sum(ss_sales_price) as sum_sales,
             avg(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                            s_store_name, s_county)
                 as avg_monthly_sales
      from item, store_sales, date_dim, store
      where ss_item_sk = i_item_sk
          and ss_sold_date_sk = d_date_sk
          and ss_store_sk = s_store_sk
          and d_year = 1999
          and ((i_category in ('Books', 'Electronics', 'Sports')
                and i_class in ('class#1', 'class#2', 'class#3'))
            or (i_category in ('Men', 'Jewelry', 'Women')
                and i_class in ('class#4', 'class#5', 'class#6')))
      group by i_category, i_class, i_brand, s_store_name, s_county,
               d_moy) tmp1
where case when avg_monthly_sales <> 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by sum_sales - avg_monthly_sales, s_store_name, i_category, i_class,
         i_brand, d_moy
limit 100
""",
    # morning-to-evening web order ratio for high-dependency households
    90: """
select am_count * 1.0 / pm_count as am_pm_ratio
from (select count(*) as am_count
      from web_sales, customer, household_demographics, time_dim, web_page
      where ws_sold_time_sk = t_time_sk
          and ws_bill_customer_sk = c_customer_sk
          and c_current_hdemo_sk = hd_demo_sk
          and ws_web_page_sk = wp_web_page_sk
          and t_hour between 8 and 9
          and hd_dep_count = 6
          and wp_char_count between 5000 and 5200) at1,
     (select count(*) as pm_count
      from web_sales, customer, household_demographics, time_dim, web_page
      where ws_sold_time_sk = t_time_sk
          and ws_bill_customer_sk = c_customer_sk
          and c_current_hdemo_sk = hd_demo_sk
          and ws_web_page_sk = wp_web_page_sk
          and t_hour between 19 and 20
          and hd_dep_count = 6
          and wp_char_count between 5000 and 5200) pt
where pm_count > 0
""",
    # call-center returns by month for targeted demographics
    91: """
select cc_call_center_id as call_center, cc_name, cc_manager,
       sum(cr_net_loss) as returns_loss
from call_center, catalog_returns, date_dim, customer,
     customer_address, customer_demographics, household_demographics
where cr_call_center_sk = cc_call_center_sk
    and cr_returned_date_sk = d_date_sk
    and cr_returning_customer_sk = c_customer_sk
    and cd_demo_sk = c_current_cdemo_sk
    and hd_demo_sk = c_current_hdemo_sk
    and ca_address_sk = c_current_addr_sk
    and d_year = 1998 and d_moy = 11
    and ((cd_marital_status = 'M' and cd_education_status = 'Unknown')
      or (cd_marital_status = 'W' and cd_education_status = 'Advanced Degree'))
    and hd_buy_potential = '>10000'
    and ca_gmt_offset = -7
group by cc_call_center_id, cc_name, cc_manager, cd_marital_status,
         cd_education_status
order by returns_loss desc, call_center
""",
    # excess web discount (q32's web sibling)
    92: """
select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 350
    and i_item_sk = ws_item_sk
    and d_date between date '2000-01-27' and date '2000-04-26'
    and d_date_sk = ws_sold_date_sk
    and ws_ext_discount_amt > (
        select 1.3 * avg(ws_ext_discount_amt)
        from web_sales, date_dim
        where ws_item_sk = i_item_sk
            and d_date between date '2000-01-27' and date '2000-04-26'
            and d_date_sk = ws_sold_date_sk)
""",
    # web orders shipped from two warehouses with a return on file
    95: """
with ws_wh as (
    select ws1.ws_order_number, ws1.ws_warehouse_sk wh1,
           ws2.ws_warehouse_sk wh2
    from web_sales ws1, web_sales ws2
    where ws1.ws_order_number = ws2.ws_order_number
        and ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk
)
select count(distinct ws1.ws_order_number) as order_count,
       sum(ws1.ws_ext_ship_cost) as total_shipping_cost,
       sum(ws1.ws_net_profit) as total_net_profit
from web_sales ws1, date_dim, customer_address, web_site
where d_date between date '1999-02-01' and date '1999-04-30'
    and ws1.ws_ship_date_sk = d_date_sk
    and ws1.ws_ship_addr_sk = ca_address_sk
    and ca_state = 'CA'
    and ws1.ws_web_site_sk = web_site_sk
    and web_name = 'site_1'
    and ws1.ws_order_number in (select ws_order_number from ws_wh)
    and ws1.ws_order_number in (select wr_order_number
                                from web_returns, ws_wh
                                where wr_order_number = ws_wh.ws_order_number)
""",
    # store category revenue share (q12/q20's store sibling)
    98: """
select i_item_id, i_category, sum(ss_ext_sales_price) as itemrevenue,
       sum(ss_ext_sales_price) * 100.0
         / sum(sum(ss_ext_sales_price)) over (partition by i_class) as revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
    and i_category in ('Sports', 'Books', 'Home')
    and ss_sold_date_sk = d_date_sk
    and d_date between date '1999-02-22' and date '1999-03-24'
group by i_item_id, i_class, i_category
order by i_category, i_item_id, itemrevenue
""",
    # catalog shipping-delay buckets by call center / ship mode
    99: """
select substr(w_warehouse_name, 1, 20) as wh20, sm_type, cc_name,
       sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30
                then 1 else 0 end) as d_30,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
                 and cs_ship_date_sk - cs_sold_date_sk <= 60
                then 1 else 0 end) as d_31_60,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 60
                 and cs_ship_date_sk - cs_sold_date_sk <= 90
                then 1 else 0 end) as d_61_90,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 90
                then 1 else 0 end) as d_over_90
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_month_seq between 1185 and 1196
    and cs_ship_date_sk = d_date_sk
    and cs_warehouse_sk = w_warehouse_sk
    and cs_ship_mode_sk = sm_ship_mode_sk
    and cs_call_center_sk = cc_call_center_sk
group by substr(w_warehouse_name, 1, 20), sm_type, cc_name
order by wh20, sm_type, cc_name
limit 100
""",
    # q10's quarterly sibling: store AND (web OR catalog) activity
    35: """
select ca_state, cd_gender, cd_marital_status, cd_dep_count,
       count(*) as cnt1, min(cd_dep_count) as mn, max(cd_dep_count) as mx,
       avg(cd_dep_count) as av
from customer c, customer_address ca, customer_demographics
where c.c_current_addr_sk = ca.ca_address_sk
    and cd_demo_sk = c.c_current_cdemo_sk
    and exists (select * from store_sales, date_dim
                where c.c_customer_sk = ss_customer_sk
                    and ss_sold_date_sk = d_date_sk
                    and d_year = 2002 and d_qoy < 4)
    and (exists (select * from web_sales, date_dim
                 where c.c_customer_sk = ws_bill_customer_sk
                     and ws_sold_date_sk = d_date_sk
                     and d_year = 2002 and d_qoy < 4)
      or exists (select * from catalog_sales, date_dim
                 where c.c_customer_sk = cs_ship_customer_sk
                     and cs_sold_date_sk = d_date_sk
                     and d_year = 2002 and d_qoy < 4))
group by ca_state, cd_gender, cd_marital_status, cd_dep_count
order by ca_state, cd_gender, cd_marital_status, cd_dep_count
limit 100
""",
    # gross-margin ROLLUP with rank-within-parent (grouping() windows)
    36: """
select sum(ss_net_profit) * 1.0 / sum(ss_ext_sales_price) as gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ss_net_profit) * 1.0
                             / sum(ss_ext_sales_price) asc)
           as rank_within_parent
from store_sales, date_dim d1, item, store
where d1.d_year = 2001
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and s_state in ('TN', 'CA', 'TX', 'OH')
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
""",
    # monthly deviation with prior/next month via rank self-joins
    47: """
with v1 as (
    select i_category, i_brand, s_store_name, s_county, d_year, d_moy,
           sum(ss_sales_price) as sum_sales,
           avg(sum(ss_sales_price)) over (partition by i_category, i_brand,
                                          s_store_name, s_county, d_year)
               as avg_monthly_sales,
           rank() over (partition by i_category, i_brand, s_store_name,
                        s_county order by d_year, d_moy) as rn
    from item, store_sales, date_dim, store
    where ss_item_sk = i_item_sk
        and ss_sold_date_sk = d_date_sk
        and ss_store_sk = s_store_sk
        and (d_year = 1999
          or (d_year = 1998 and d_moy = 12)
          or (d_year = 2000 and d_moy = 1))
    group by i_category, i_brand, s_store_name, s_county, d_year, d_moy
)
select v1.i_category, v1.i_brand, v1.s_store_name, v1.d_year, v1.d_moy,
       v1.avg_monthly_sales, v1.sum_sales,
       v1_lag.sum_sales as psum, v1_lead.sum_sales as nsum
from v1, v1 as v1_lag, v1 as v1_lead
where v1.i_category = v1_lag.i_category
    and v1.i_brand = v1_lag.i_brand
    and v1.s_store_name = v1_lag.s_store_name
    and v1.s_county = v1_lag.s_county
    and v1.rn = v1_lag.rn + 1
    and v1.i_category = v1_lead.i_category
    and v1.i_brand = v1_lead.i_brand
    and v1.s_store_name = v1_lead.s_store_name
    and v1.s_county = v1_lead.s_county
    and v1.rn = v1_lead.rn - 1
    and v1.avg_monthly_sales > 0
    and case when v1.avg_monthly_sales > 0
             then abs(v1.sum_sales - v1.avg_monthly_sales)
                  / v1.avg_monthly_sales
             else null end > 0.1
order by v1.i_category, v1.i_brand, v1.s_store_name, v1.d_year, v1.d_moy
limit 100
""",
    # q47's catalog sibling (call centers)
    57: """
with v1 as (
    select i_category, i_brand, cc_name, d_year, d_moy,
           sum(cs_sales_price) as sum_sales,
           avg(sum(cs_sales_price)) over (partition by i_category, i_brand,
                                          cc_name, d_year)
               as avg_monthly_sales,
           rank() over (partition by i_category, i_brand, cc_name
                        order by d_year, d_moy) as rn
    from item, catalog_sales, date_dim, call_center
    where cs_item_sk = i_item_sk
        and cs_sold_date_sk = d_date_sk
        and cc_call_center_sk = cs_call_center_sk
        and (d_year = 1999
          or (d_year = 1998 and d_moy = 12)
          or (d_year = 2000 and d_moy = 1))
    group by i_category, i_brand, cc_name, d_year, d_moy
)
select v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy,
       v1.avg_monthly_sales, v1.sum_sales,
       v1_lag.sum_sales as psum, v1_lead.sum_sales as nsum
from v1, v1 as v1_lag, v1 as v1_lead
where v1.i_category = v1_lag.i_category
    and v1.i_brand = v1_lag.i_brand
    and v1.cc_name = v1_lag.cc_name
    and v1.rn = v1_lag.rn + 1
    and v1.i_category = v1_lead.i_category
    and v1.i_brand = v1_lead.i_brand
    and v1.cc_name = v1_lead.cc_name
    and v1.rn = v1_lead.rn - 1
    and v1.avg_monthly_sales > 0
    and case when v1.avg_monthly_sales > 0
             then abs(v1.sum_sales - v1.avg_monthly_sales)
                  / v1.avg_monthly_sales
             else null end > 0.1
order by v1.i_category, v1.i_brand, v1.cc_name, v1.d_year, v1.d_moy
limit 100
""",
    # state/county profit ROLLUP gated on a ranked-states subquery
    70: """
select sum(ss_net_profit) as total_sum, s_state, s_county,
       grouping(s_state) + grouping(s_county) as lochierarchy,
       rank() over (partition by grouping(s_state) + grouping(s_county),
                    case when grouping(s_county) = 0 then s_state end
                    order by sum(ss_net_profit) desc) as rank_within_parent
from store_sales, date_dim d1, store
where d1.d_month_seq between 1185 and 1196
    and d1.d_date_sk = ss_sold_date_sk
    and s_store_sk = ss_store_sk
    and s_state in (select s_state
                    from (select s_state as s_state,
                                 rank() over (partition by s_state
                                              order by sum(ss_net_profit) desc)
                                     as ranking
                          from store_sales, store, date_dim
                          where d_year = 2001
                              and d_date_sk = ss_sold_date_sk
                              and s_store_sk = ss_store_sk
                          group by s_state) tmp1
                    where ranking <= 5)
group by rollup(s_state, s_county)
order by lochierarchy desc,
         case when lochierarchy = 0 then s_state end,
         rank_within_parent
limit 100
""",
    # q30's catalog/state sibling: correlated 1.2x state average
    81: """
with customer_total_return as (
    select cr_returning_customer_sk as ctr_customer_sk,
           ca_state as ctr_state,
           sum(cr_return_amount) as ctr_total_return
    from catalog_returns, date_dim, customer_address, customer
    where cr_returned_date_sk = d_date_sk and d_year = 2000
        and cr_returning_customer_sk = c_customer_sk
        and c_current_addr_sk = ca_address_sk
    group by cr_returning_customer_sk, ca_state
)
select c_customer_id, c_first_name, c_last_name, ctr_total_return
from customer_total_return ctr1, customer_address, customer
where ctr1.ctr_total_return > (select avg(ctr_total_return) * 1.2
                               from customer_total_return ctr2
                               where ctr1.ctr_state = ctr2.ctr_state)
    and ca_address_sk = c_current_addr_sk
    and ca_state = 'GA'
    and ctr1.ctr_customer_sk = c_customer_sk
order by c_customer_id, c_first_name, c_last_name, ctr_total_return
limit 100
""",
    # per-channel return quantity shares over a common item set
    83: """
with sr_items as (
    select i_item_id as item_id, sum(sr_return_quantity) as sr_item_qty
    from store_returns, item, date_dim
    where sr_item_sk = i_item_sk
        and d_date between date '2000-06-01' and date '2000-08-31'
        and sr_returned_date_sk = d_date_sk
    group by i_item_id
),
cr_items as (
    select i_item_id as item_id, sum(cr_return_quantity) as cr_item_qty
    from catalog_returns, item, date_dim
    where cr_item_sk = i_item_sk
        and d_date between date '2000-06-01' and date '2000-08-31'
        and cr_returned_date_sk = d_date_sk
    group by i_item_id
),
wr_items as (
    select i_item_id as item_id, sum(wr_return_quantity) as wr_item_qty
    from web_returns, item, date_dim
    where wr_item_sk = i_item_sk
        and d_date between date '2000-06-01' and date '2000-08-31'
        and wr_returned_date_sk = d_date_sk
    group by i_item_id
)
select sr_items.item_id, sr_item_qty,
       sr_item_qty * 1.0 / (sr_item_qty + cr_item_qty + wr_item_qty)
           / 3.0 * 100 as sr_dev,
       cr_item_qty,
       cr_item_qty * 1.0 / (sr_item_qty + cr_item_qty + wr_item_qty)
           / 3.0 * 100 as cr_dev,
       wr_item_qty,
       wr_item_qty * 1.0 / (sr_item_qty + cr_item_qty + wr_item_qty)
           / 3.0 * 100 as wr_dev,
       (sr_item_qty + cr_item_qty + wr_item_qty) / 3.0 as average
from sr_items, cr_items, wr_items
where sr_items.item_id = cr_items.item_id
    and sr_items.item_id = wr_items.item_id
order by sr_items.item_id, sr_item_qty
limit 100
""",
    # profit ROLLUP with rank-within-parent (web, grouping() windows)
    86: """
select sum(ws_net_paid) as total_sum, i_category, i_class,
       grouping(i_category) + grouping(i_class) as lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ws_net_paid) desc) as rank_within_parent
from web_sales, date_dim d1, item
where d1.d_month_seq between 1185 and 1196
    and d1.d_date_sk = ws_sold_date_sk
    and i_item_sk = ws_item_sk
group by rollup(i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
""",
    # returned-for-reason tickets: net sales after returns
    93: """
select ss_customer_sk, sum(act_sales) as sumsales
from (select ss_item_sk, ss_ticket_number, ss_customer_sk,
             case when sr_return_quantity is not null
                  then (ss_quantity - sr_return_quantity) * ss_sales_price
                  else ss_quantity * ss_sales_price end as act_sales
      from store_sales
      left outer join store_returns
          on (sr_item_sk = ss_item_sk and sr_ticket_number = ss_ticket_number),
          reason
      where sr_reason_sk = r_reason_sk
          and r_reason_desc = 'Stopped working') t
group by ss_customer_sk
order by sumsales, ss_customer_sk
limit 100
""",
    # year-over-year growth comparison: one CTE self-joined four ways
    74: """
with year_total as (
    select c_customer_id as customer_id, c_first_name, c_last_name,
           d_year as year_, sum(ss_net_paid) as year_total,
           's' as sale_type
    from customer, store_sales, date_dim
    where c_customer_sk = ss_customer_sk
        and ss_sold_date_sk = d_date_sk
        and d_year in (1999, 2000)
    group by c_customer_id, c_first_name, c_last_name, d_year
    union all
    select c_customer_id, c_first_name, c_last_name,
           d_year, sum(ws_net_paid), 'w'
    from customer, web_sales, date_dim
    where c_customer_sk = ws_bill_customer_sk
        and ws_sold_date_sk = d_date_sk
        and d_year in (1999, 2000)
    group by c_customer_id, c_first_name, c_last_name, d_year
)
select t_s_secyear.customer_id, t_s_secyear.c_first_name, t_s_secyear.c_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
    and t_s_firstyear.customer_id = t_w_secyear.customer_id
    and t_s_firstyear.customer_id = t_w_firstyear.customer_id
    and t_s_firstyear.sale_type = 's' and t_w_firstyear.sale_type = 'w'
    and t_s_secyear.sale_type = 's' and t_w_secyear.sale_type = 'w'
    and t_s_firstyear.year_ = 1999 and t_s_secyear.year_ = 2000
    and t_w_firstyear.year_ = 1999 and t_w_secyear.year_ = 2000
    and t_s_firstyear.year_total > 0 and t_w_firstyear.year_total > 0
    and case when t_w_firstyear.year_total > 0
             then t_w_secyear.year_total * 1.0 / t_w_firstyear.year_total
             else null end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total * 1.0 / t_s_firstyear.year_total
             else null end
order by 1, 2, 3
limit 100
""",
    # worst return ratios per channel, double-ranked, unioned
    49: """
select channel, item, return_ratio, return_rank, currency_rank
from (
    select 'web' as channel, web.item, web.return_ratio,
           web.return_rank, web.currency_rank
    from (select item, return_ratio, currency_ratio,
                 rank() over (order by return_ratio) as return_rank,
                 rank() over (order by currency_ratio) as currency_rank
          from (select ws.ws_item_sk as item,
                       sum(coalesce(wr.wr_return_quantity, 0)) * 1.0
                         / sum(coalesce(ws.ws_quantity, 0)) as return_ratio,
                       sum(coalesce(wr.wr_return_amt, 0)) * 1.0
                         / sum(coalesce(ws.ws_net_paid, 0)) as currency_ratio
                from web_sales ws
                     left outer join web_returns wr
                         on (ws.ws_order_number = wr.wr_order_number
                             and ws.ws_item_sk = wr.wr_item_sk),
                     date_dim
                where wr.wr_return_amt > 100
                    and ws.ws_net_profit > 1
                    and ws.ws_net_paid > 0
                    and ws.ws_quantity > 0
                    and ws_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy = 12
                group by ws.ws_item_sk) in_web) web
    where web.return_rank <= 10 or web.currency_rank <= 10
    union
    select 'catalog' as channel, c.item, c.return_ratio,
           c.return_rank, c.currency_rank
    from (select item, return_ratio, currency_ratio,
                 rank() over (order by return_ratio) as return_rank,
                 rank() over (order by currency_ratio) as currency_rank
          from (select cs.cs_item_sk as item,
                       sum(coalesce(cr.cr_return_quantity, 0)) * 1.0
                         / sum(coalesce(cs.cs_quantity, 0)) as return_ratio,
                       sum(coalesce(cr.cr_return_amount, 0)) * 1.0
                         / sum(coalesce(cs.cs_net_paid, 0)) as currency_ratio
                from catalog_sales cs
                     left outer join catalog_returns cr
                         on (cs.cs_order_number = cr.cr_order_number
                             and cs.cs_item_sk = cr.cr_item_sk),
                     date_dim
                where cr.cr_return_amount > 100
                    and cs.cs_net_profit > 1
                    and cs.cs_net_paid > 0
                    and cs.cs_quantity > 0
                    and cs_sold_date_sk = d_date_sk
                    and d_year = 2001 and d_moy = 12
                group by cs.cs_item_sk) in_cat) c
    where c.return_rank <= 10 or c.currency_rank <= 10
) tmp
order by 1, 4, 5, 2
limit 100
""",
    # flagship year-over-year: three channels, one CTE self-joined 6 ways
    4: """
with year_total as (
    select c_customer_id as customer_id, c_first_name, c_last_name,
           d_year as dyear,
           sum(((ss_ext_list_price - ss_ext_wholesale_cost
                 - ss_ext_discount_amt) + ss_ext_sales_price) / 2) as year_total,
           's' as sale_type
    from customer, store_sales, date_dim
    where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
    group by c_customer_id, c_first_name, c_last_name, d_year
    union all
    select c_customer_id, c_first_name, c_last_name, d_year,
           sum(((cs_ext_list_price - cs_ext_wholesale_cost
                 - cs_ext_discount_amt) + cs_ext_sales_price) / 2), 'c'
    from customer, catalog_sales, date_dim
    where c_customer_sk = cs_bill_customer_sk and cs_sold_date_sk = d_date_sk
    group by c_customer_id, c_first_name, c_last_name, d_year
    union all
    select c_customer_id, c_first_name, c_last_name, d_year,
           sum(((ws_ext_list_price - ws_ext_wholesale_cost
                 - ws_ext_discount_amt) + ws_ext_sales_price) / 2), 'w'
    from customer, web_sales, date_dim
    where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
    group by c_customer_id, c_first_name, c_last_name, d_year
)
select t_s_secyear.customer_id, t_s_secyear.c_first_name, t_s_secyear.c_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_c_firstyear, year_total t_c_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
    and t_s_firstyear.customer_id = t_c_secyear.customer_id
    and t_s_firstyear.customer_id = t_c_firstyear.customer_id
    and t_s_firstyear.customer_id = t_w_firstyear.customer_id
    and t_s_firstyear.customer_id = t_w_secyear.customer_id
    and t_s_firstyear.sale_type = 's' and t_c_firstyear.sale_type = 'c'
    and t_w_firstyear.sale_type = 'w' and t_s_secyear.sale_type = 's'
    and t_c_secyear.sale_type = 'c' and t_w_secyear.sale_type = 'w'
    and t_s_firstyear.dyear = 2001 and t_s_secyear.dyear = 2002
    and t_c_firstyear.dyear = 2001 and t_c_secyear.dyear = 2002
    and t_w_firstyear.dyear = 2001 and t_w_secyear.dyear = 2002
    and t_s_firstyear.year_total > 0 and t_c_firstyear.year_total > 0
    and t_w_firstyear.year_total > 0
    and case when t_c_firstyear.year_total > 0
             then t_c_secyear.year_total * 1.0 / t_c_firstyear.year_total
             else null end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total * 1.0 / t_s_firstyear.year_total
             else null end
    and case when t_c_firstyear.year_total > 0
             then t_c_secyear.year_total * 1.0 / t_c_firstyear.year_total
             else null end
      > case when t_w_firstyear.year_total > 0
             then t_w_secyear.year_total * 1.0 / t_w_firstyear.year_total
             else null end
order by 1, 2, 3
limit 100
""",
    # q4's store/web sibling on list-minus-discount totals
    11: """
with year_total as (
    select c_customer_id as customer_id, c_first_name, c_last_name,
           d_year as dyear,
           sum(ss_ext_list_price - ss_ext_discount_amt) as year_total,
           's' as sale_type
    from customer, store_sales, date_dim
    where c_customer_sk = ss_customer_sk and ss_sold_date_sk = d_date_sk
    group by c_customer_id, c_first_name, c_last_name, d_year
    union all
    select c_customer_id, c_first_name, c_last_name, d_year,
           sum(ws_ext_list_price - ws_ext_discount_amt), 'w'
    from customer, web_sales, date_dim
    where c_customer_sk = ws_bill_customer_sk and ws_sold_date_sk = d_date_sk
    group by c_customer_id, c_first_name, c_last_name, d_year
)
select t_s_secyear.customer_id, t_s_secyear.c_first_name, t_s_secyear.c_last_name
from year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
where t_s_secyear.customer_id = t_s_firstyear.customer_id
    and t_s_firstyear.customer_id = t_w_secyear.customer_id
    and t_s_firstyear.customer_id = t_w_firstyear.customer_id
    and t_s_firstyear.sale_type = 's' and t_w_firstyear.sale_type = 'w'
    and t_s_secyear.sale_type = 's' and t_w_secyear.sale_type = 'w'
    and t_s_firstyear.dyear = 2001 and t_s_secyear.dyear = 2002
    and t_w_firstyear.dyear = 2001 and t_w_secyear.dyear = 2002
    and t_s_firstyear.year_total > 0 and t_w_firstyear.year_total > 0
    and case when t_w_firstyear.year_total > 0
             then t_w_secyear.year_total * 1.0 / t_w_firstyear.year_total
             else null end
      > case when t_s_firstyear.year_total > 0
             then t_s_secyear.year_total * 1.0 / t_s_firstyear.year_total
             else null end
order by 1, 2, 3
limit 100
""",
    # items in a price band currently in inventory and sold by catalog
    37: """
select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 20.00 and 50.00
    and inv_item_sk = i_item_sk
    and d_date_sk = inv_date_sk
    and d_date between date '2000-02-01' and date '2000-04-01'
    and i_manufact_id in (1, 2, 3, 4, 5, 6, 7, 8)
    and inv_quantity_on_hand between 100 and 500
    and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
""",
})


def _rollup_union(select_cols, aggs, from_where, groups):
    """Expand GROUP BY ROLLUP into sqlite UNION ALL (oracle side)."""
    parts = []
    for level in range(len(groups), -1, -1):
        live = groups[:level]
        cols = ", ".join(c if c in live else f"null as {c}" for c in select_cols)
        gb = f" group by {', '.join(live)}" if live else ""
        parts.append(f"select {cols}, {aggs} {from_where}{gb}")
    return " union all ".join(parts)


_Q18_FW = """
from catalog_sales, customer_demographics, customer, customer_address, date_dim, item
where cs_sold_date_sk = d_date_sk
    and cs_item_sk = i_item_sk
    and cs_bill_cdemo_sk = cd_demo_sk
    and cs_bill_customer_sk = c_customer_sk
    and cd_gender = 'F'
    and cd_education_status = 'Unknown'
    and c_current_addr_sk = ca_address_sk
    and d_year = 1998
"""

_Q22_FW = """
from inventory, date_dim, item
where inv_date_sk = d_date_sk
    and inv_item_sk = i_item_sk
    and d_month_seq between 1176 and 1187
"""

_Q27_FW = """
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and ss_store_sk = s_store_sk
    and ss_cdemo_sk = cd_demo_sk
    and cd_gender = 'M'
    and cd_marital_status = 'S'
    and cd_education_status = 'College'
    and d_year = 2002
    and s_state in ('TN', 'CA', 'TX')
"""

_Q77_CTES = """
with ss as (
    select s_store_sk, sum(ss_ext_sales_price) as sales,
           sum(ss_net_profit) as profit
    from store_sales, date_dim, store
    where ss_sold_date_sk = d_date_sk
        and d_date between date '2000-08-03' and date '2000-09-02'
        and ss_store_sk = s_store_sk
    group by s_store_sk
),
sr as (
    select s_store_sk, sum(sr_return_amt) as returns_,
           sum(sr_net_loss) as profit_loss
    from store_returns, date_dim, store
    where sr_returned_date_sk = d_date_sk
        and d_date between date '2000-08-03' and date '2000-09-02'
        and sr_store_sk = s_store_sk
    group by s_store_sk
),
cs as (
    select cs_call_center_sk, sum(cs_ext_sales_price) as sales,
           sum(cs_net_profit) as profit
    from catalog_sales, date_dim
    where cs_sold_date_sk = d_date_sk
        and d_date between date '2000-08-03' and date '2000-09-02'
    group by cs_call_center_sk
),
cr as (
    select cr_call_center_sk, sum(cr_return_amount) as returns_,
           sum(cr_net_loss) as profit_loss
    from catalog_returns, date_dim
    where cr_returned_date_sk = d_date_sk
        and d_date between date '2000-08-03' and date '2000-09-02'
    group by cr_call_center_sk
),
ws as (
    select wp_web_page_sk, sum(ws_ext_sales_price) as sales,
           sum(ws_net_profit) as profit
    from web_sales, date_dim, web_page
    where ws_sold_date_sk = d_date_sk
        and d_date between date '2000-08-03' and date '2000-09-02'
        and ws_web_page_sk = wp_web_page_sk
    group by wp_web_page_sk
),
wr as (
    select wp_web_page_sk, sum(wr_return_amt) as returns_,
           sum(wr_net_loss) as profit_loss
    from web_returns, date_dim, web_page, web_sales
    where wr_returned_date_sk = d_date_sk
        and d_date between date '2000-08-03' and date '2000-09-02'
        and wr_order_number = ws_order_number and wr_item_sk = ws_item_sk
        and ws_web_page_sk = wp_web_page_sk
    group by wp_web_page_sk
),
x as (
    select 'store channel' as channel, ss.s_store_sk as id, sales,
           coalesce(returns_, 0) as returns_,
           (profit - coalesce(profit_loss, 0)) as profit
    from ss left join sr on ss.s_store_sk = sr.s_store_sk
    union all
    select 'catalog channel', cs.cs_call_center_sk, sales,
           coalesce(returns_, 0),
           (profit - coalesce(profit_loss, 0))
    from cs left join cr on cs.cs_call_center_sk = cr.cr_call_center_sk
    union all
    select 'web channel', ws.wp_web_page_sk, sales,
           coalesce(returns_, 0),
           (profit - coalesce(profit_loss, 0))
    from ws left join wr on ws.wp_web_page_sk = wr.wp_web_page_sk
)
"""

# per-channel sales/returns/profit report with channel ROLLUP — built
# from the same CTE fragment the sqlite override uses, so the two sides
# cannot drift
QUERIES[77] = _Q77_CTES + """
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from x
group by rollup(channel, id)
order by channel, id, sales
limit 100
"""

_Q36_FW = """
from store_sales, date_dim d1, item, store
where d1.d_year = 2001
    and d1.d_date_sk = ss_sold_date_sk
    and i_item_sk = ss_item_sk
    and s_store_sk = ss_store_sk
    and s_state in ('TN', 'CA', 'TX', 'OH')
"""

_Q70_FW = """
from store_sales, date_dim d1, store
where d1.d_month_seq between 1185 and 1196
    and d1.d_date_sk = ss_sold_date_sk
    and s_store_sk = ss_store_sk
    and s_state in (select s_state from ranked)
"""

_Q86_FW = """
from web_sales, date_dim d1, item
where d1.d_month_seq between 1185 and 1196
    and d1.d_date_sk = ws_sold_date_sk
    and i_item_sk = ws_item_sk
"""

ORACLE_OVERRIDES = {
    77: _Q77_CTES + """,
sel as (select channel, id, sum(sales) as sales,
        sum(returns_) as returns_, sum(profit) as profit
        from x group by channel, id)
select channel, id, sales, returns_, profit from sel
union all
select channel, null, sum(sales), sum(returns_), sum(profit)
from sel group by channel
union all
select null, null, sum(sales), sum(returns_), sum(profit) from sel
order by channel, id, sales
limit 100
""",
    18: _rollup_union(
        ["i_item_id", "ca_country", "ca_state", "ca_county"],
        "avg(cs_quantity) as agg1, avg(cs_list_price) as agg2, avg(cs_coupon_amt) as agg3",
        _Q18_FW,
        ["i_item_id", "ca_country", "ca_state", "ca_county"],
    ),
    22: _rollup_union(
        ["i_category", "i_class", "i_brand"],
        "avg(inv_quantity_on_hand) as qoh",
        _Q22_FW,
        ["i_category", "i_class", "i_brand"],
    ),
    27: _rollup_union(
        ["i_item_id", "s_state"],
        "avg(ss_quantity) as agg1, avg(ss_list_price) as agg2, "
        "avg(ss_coupon_amt) as agg3, avg(ss_sales_price) as agg4",
        _Q27_FW,
        ["i_item_id", "s_state"],
    ),
    # grouping()-rollup queries with rank-within-parent: sqlite lacks
    # ROLLUP/grouping(), so the levels expand to UNION ALL with literal
    # lochierarchy values and the window runs over the union
    36: """
with agg as (
    select sum(ss_net_profit) * 1.0 / sum(ss_ext_sales_price) as gm,
           i_category, i_class, 0 as lochierarchy """ + _Q36_FW + """
    group by i_category, i_class
    union all
    select sum(ss_net_profit) * 1.0 / sum(ss_ext_sales_price),
           i_category, null, 1 """ + _Q36_FW + """
    group by i_category
    union all
    select sum(ss_net_profit) * 1.0 / sum(ss_ext_sales_price),
           null, null, 2 """ + _Q36_FW + """
)
select gm as gross_margin, i_category, i_class, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then i_category end
                    order by gm asc) as rank_within_parent
from agg
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
""",
    70: """
with ranked as (
    select s_state
    from (select s_state as s_state,
                 rank() over (partition by s_state
                              order by sum(ss_net_profit) desc) as ranking
          from store_sales, store, date_dim
          where d_year = 2001 and d_date_sk = ss_sold_date_sk
              and s_store_sk = ss_store_sk
          group by s_state) tmp1
    where ranking <= 5
),
agg as (
    select sum(ss_net_profit) as ts, s_state, s_county, 0 as lochierarchy
    """ + _Q70_FW + """ group by s_state, s_county
    union all
    select sum(ss_net_profit), s_state, null, 1 """ + _Q70_FW + """
    group by s_state
    union all
    select sum(ss_net_profit), null, null, 2 """ + _Q70_FW + """
)
select ts as total_sum, s_state, s_county, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then s_state end
                    order by ts desc) as rank_within_parent
from agg
order by lochierarchy desc,
         case when lochierarchy = 0 then s_state end,
         rank_within_parent
limit 100
""",
    86: """
with agg as (
    select sum(ws_net_paid) as ts, i_category, i_class, 0 as lochierarchy
    """ + _Q86_FW + """ group by i_category, i_class
    union all
    select sum(ws_net_paid), i_category, null, 1 """ + _Q86_FW + """
    group by i_category
    union all
    select sum(ws_net_paid), null, null, 2 """ + _Q86_FW + """
)
select ts as total_sum, i_category, i_class, lochierarchy,
       rank() over (partition by lochierarchy,
                    case when lochierarchy = 0 then i_category end
                    order by ts desc) as rank_within_parent
from agg
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent
limit 100
""",
}


# ---- round-4 additions: the 18 remaining TPC-DS queries (99/99) ----
# Adapted to this generator's data (constants tuned for nonzero
# results at sf0.01; q5's catalog channel pivots on call centers
# since catalog_returns carries no catalog_page key).
QUERIES.update({
    5: "\nwith ssr as (\nselect s_store_id,\n       sum(sales_price) as sales, sum(profit) as profit,\n       sum(return_amt) as returns_, sum(net_loss) as profit_loss\nfrom (select ss_store_sk as store_sk, ss_sold_date_sk as date_sk,\n             ss_ext_sales_price as sales_price, ss_net_profit as profit,\n             cast(0 as decimal(12,2)) as return_amt,\n             cast(0 as decimal(12,2)) as net_loss\n      from store_sales\n      union all\n      select sr_store_sk, sr_returned_date_sk,\n             cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),\n             sr_return_amt, sr_net_loss\n      from store_returns) salesreturns, date_dim, store\nwhere date_sk = d_date_sk\n  and d_date between date '2000-08-23' and date '2000-09-06'\n  and store_sk = s_store_sk\ngroup by s_store_id\n), csr as (\nselect cc_call_center_id,\n       sum(sales_price) as sales, sum(profit) as profit,\n       sum(return_amt) as returns_, sum(net_loss) as profit_loss\nfrom (select cs_call_center_sk as center_sk, cs_sold_date_sk as date_sk,\n             cs_ext_sales_price as sales_price, cs_net_profit as profit,\n             cast(0 as decimal(12,2)) as return_amt,\n             cast(0 as decimal(12,2)) as net_loss\n      from catalog_sales\n      union all\n      select cr_call_center_sk, cr_returned_date_sk,\n             cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),\n             cr_return_amount, cr_net_loss\n      from catalog_returns) salesreturns, date_dim, call_center\nwhere date_sk = d_date_sk\n  and d_date between date '2000-08-23' and date '2000-09-06'\n  and center_sk = cc_call_center_sk\ngroup by cc_call_center_id\n), wsr as (\nselect web_site_id,\n       sum(sales_price) as sales, sum(profit) as profit,\n       sum(return_amt) as returns_, sum(net_loss) as profit_loss\nfrom (select ws_web_site_sk as wsr_web_site_sk, ws_sold_date_sk as date_sk,\n             ws_ext_sales_price as sales_price, ws_net_profit as profit,\n             cast(0 as decimal(12,2)) as return_amt,\n             cast(0 as decimal(12,2)) as net_loss\n      from web_sales\n      union all\n      select ws_web_site_sk, wr_returned_date_sk,\n             cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),\n             wr_return_amt, wr_net_loss\n      from web_returns\n      left outer join web_sales on (wr_item_sk = ws_item_sk\n                                    and wr_order_number = ws_order_number)\n     ) salesreturns, date_dim, web_site\nwhere date_sk = d_date_sk\n  and d_date between date '2000-08-23' and date '2000-09-06'\n  and wsr_web_site_sk = web_site_sk\ngroup by web_site_id\n)\nselect channel, id, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit\nfrom (select 'store channel' as channel, s_store_id as id, sales, returns_,\n             profit - profit_loss as profit\n      from ssr\n      union all\n      select 'catalog channel', cc_call_center_id, sales, returns_,\n             profit - profit_loss\n      from csr\n      union all\n      select 'web channel', web_site_id, sales, returns_,\n             profit - profit_loss\n      from wsr) x\ngroup by rollup (channel, id)\norder by channel nulls first, id nulls first\nlimit 100\n",
    8: "\nselect s_store_name, sum(ss_net_profit)\nfrom store_sales, date_dim, store,\n     (select ca_zip from (\n        select substr(ca_zip, 1, 5) ca_zip from customer_address\n        where substr(ca_zip, 1, 1) = '1'\n        intersect\n        select ca_zip from (\n          select substr(ca_zip, 1, 5) ca_zip, count(*) cnt\n          from customer_address, customer\n          where ca_address_sk = c_current_addr_sk\n          group by substr(ca_zip, 1, 5) having count(*) > 10) a1) a2) v1\nwhere ss_store_sk = s_store_sk and ss_sold_date_sk = d_date_sk\n  and d_qoy = 2 and d_year = 1998\n  and substr(s_zip, 1, 2) = substr(v1.ca_zip, 1, 2)\ngroup by s_store_name\norder by s_store_name\n",
    14: "\nwith cross_items as (\n  select i_item_sk ss_item_sk\n  from item,\n   (select iss.i_brand_id brand_id, iss.i_class_id class_id,\n           iss.i_category_id category_id\n    from store_sales, item iss, date_dim d1\n    where ss_item_sk = iss.i_item_sk and ss_sold_date_sk = d1.d_date_sk\n      and d1.d_year between 1999 and 2001\n    intersect\n    select ics.i_brand_id, ics.i_class_id, ics.i_category_id\n    from catalog_sales, item ics, date_dim d2\n    where cs_item_sk = ics.i_item_sk and cs_sold_date_sk = d2.d_date_sk\n      and d2.d_year between 1999 and 2001\n    intersect\n    select iws.i_brand_id, iws.i_class_id, iws.i_category_id\n    from web_sales, item iws, date_dim d3\n    where ws_item_sk = iws.i_item_sk and ws_sold_date_sk = d3.d_date_sk\n      and d3.d_year between 1999 and 2001) x\n  where i_brand_id = brand_id and i_class_id = class_id\n    and i_category_id = category_id),\n avg_sales as (\n  select avg(quantity * list_price) average_sales\n  from (select ss_quantity quantity, ss_list_price list_price\n        from store_sales, date_dim\n        where ss_sold_date_sk = d_date_sk and d_year between 1999 and 2001\n        union all\n        select cs_quantity, cs_list_price\n        from catalog_sales, date_dim\n        where cs_sold_date_sk = d_date_sk and d_year between 1999 and 2001\n        union all\n        select ws_quantity, ws_list_price\n        from web_sales, date_dim\n        where ws_sold_date_sk = d_date_sk and d_year between 1999 and 2001) x)\nselect channel, i_brand_id, i_class_id, i_category_id, sum(sales),\n       sum(number_sales)\nfrom (\nselect 'store' channel, i_brand_id, i_class_id, i_category_id,\n       sum(ss_quantity * ss_list_price) sales, count(*) number_sales\nfrom store_sales, item, date_dim\nwhere ss_item_sk in (select ss_item_sk from cross_items)\n  and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk\n  and d_year = 2001 and d_moy = 11\ngroup by i_brand_id, i_class_id, i_category_id\nhaving sum(ss_quantity * ss_list_price) > (select average_sales from avg_sales)\n\n      union all\n      \nselect 'catalog' channel, i_brand_id, i_class_id, i_category_id,\n       sum(cs_quantity * cs_list_price) sales, count(*) number_sales\nfrom catalog_sales, item, date_dim\nwhere cs_item_sk in (select ss_item_sk from cross_items)\n  and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk\n  and d_year = 2001 and d_moy = 11\ngroup by i_brand_id, i_class_id, i_category_id\nhaving sum(cs_quantity * cs_list_price) > (select average_sales from avg_sales)\n\n      union all\n      \nselect 'web' channel, i_brand_id, i_class_id, i_category_id,\n       sum(ws_quantity * ws_list_price) sales, count(*) number_sales\nfrom web_sales, item, date_dim\nwhere ws_item_sk in (select ss_item_sk from cross_items)\n  and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk\n  and d_year = 2001 and d_moy = 11\ngroup by i_brand_id, i_class_id, i_category_id\nhaving sum(ws_quantity * ws_list_price) > (select average_sales from avg_sales)\n\n     ) y\ngroup by rollup (channel, i_brand_id, i_class_id, i_category_id)\norder by channel nulls first, i_brand_id nulls first,\n         i_class_id nulls first, i_category_id nulls first\nlimit 100\n",
    17: '\nselect i_item_id, i_item_desc, s_state,\n       count(ss_quantity) store_sales_quantitycount,\n       avg(ss_quantity) store_sales_quantityave,\n       stddev_samp(ss_quantity) store_sales_quantitystdev,\n       stddev_samp(ss_quantity) / avg(ss_quantity) store_sales_quantitycov,\n       count(sr_return_quantity) store_returns_quantitycount,\n       avg(sr_return_quantity) store_returns_quantityave,\n       stddev_samp(sr_return_quantity) store_returns_quantitystdev,\n       stddev_samp(sr_return_quantity) / avg(sr_return_quantity) store_returns_quantitycov,\n       count(cs_quantity) catalog_sales_quantitycount,\n       avg(cs_quantity) catalog_sales_quantityave,\n       stddev_samp(cs_quantity) catalog_sales_quantitystdev,\n       stddev_samp(cs_quantity) / avg(cs_quantity) catalog_sales_quantitycov\nfrom store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,\n     date_dim d3, store, item\nwhere d1.d_year = 2000 and d1.d_qoy = 1\n  and d1.d_date_sk = ss_sold_date_sk\n  and i_item_sk = ss_item_sk\n  and s_store_sk = ss_store_sk\n  and ss_customer_sk = sr_customer_sk\n  and ss_item_sk = sr_item_sk\n  and ss_ticket_number = sr_ticket_number\n  and sr_returned_date_sk = d2.d_date_sk\n  and d2.d_year = 2000 and d2.d_qoy between 1 and 3\n  and sr_item_sk = cs_item_sk\n  and cs_sold_date_sk = d3.d_date_sk\n  and d3.d_year = 2000 and d3.d_qoy between 1 and 3\ngroup by i_item_id, i_item_desc, s_state\norder by i_item_id, i_item_desc, s_state\nlimit 100\n',
    23: '\nwith frequent_ss_items as (\n  select substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk,\n         d_month_seq seq, count(*) cnt\n  from store_sales, date_dim, item\n  where ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n    and d_year in (2000, 2001, 2002, 2003)\n  group by substr(i_item_desc, 1, 30), i_item_sk, d_month_seq\n  having count(*) > 2),\n max_store_sales as (\n  select max(csales) tpcds_cmax\n  from (select c_customer_sk, sum(ss_quantity * ss_sales_price) csales\n        from store_sales, customer, date_dim\n        where ss_customer_sk = c_customer_sk and ss_sold_date_sk = d_date_sk\n          and d_year in (2000, 2001, 2002, 2003)\n        group by c_customer_sk) t),\n best_ss_customer as (\n  select c_customer_sk, sum(ss_quantity * ss_sales_price) ssales\n  from store_sales, customer\n  where ss_customer_sk = c_customer_sk\n  group by c_customer_sk\n  having sum(ss_quantity * ss_sales_price) >\n         0.5 * (select tpcds_cmax from max_store_sales))\nselect sum(sales)\nfrom (select cs_quantity * cs_list_price sales\n      from catalog_sales, date_dim\n      where d_year = 2000 and d_moy = 2 and cs_sold_date_sk = d_date_sk\n        and cs_item_sk in (select item_sk from frequent_ss_items)\n        and cs_bill_customer_sk in (select c_customer_sk from best_ss_customer)\n      union all\n      select ws_quantity * ws_list_price sales\n      from web_sales, date_dim\n      where d_year = 2000 and d_moy = 2 and ws_sold_date_sk = d_date_sk\n        and ws_item_sk in (select item_sk from frequent_ss_items)\n        and ws_bill_customer_sk in (select c_customer_sk from best_ss_customer)\n     ) x\nlimit 100\n',
    24: "\nwith ssales as (\n  select c_last_name, c_first_name, s_store_name, ca_state, s_state,\n         i_color, i_current_price, i_manager_id, i_size,\n         sum(ss_net_paid) netpaid\n  from store_sales, store_returns, store, item, customer, customer_address\n  where ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk\n    and ss_customer_sk = c_customer_sk and ss_item_sk = i_item_sk\n    and ss_store_sk = s_store_sk and c_current_addr_sk = ca_address_sk\n    and s_zip = ca_zip\n  group by c_last_name, c_first_name, s_store_name, ca_state, s_state,\n           i_color, i_current_price, i_manager_id, i_size)\nselect c_last_name, c_first_name, s_store_name, sum(netpaid) paid\nfrom ssales\nwhere i_color = 'white'\ngroup by c_last_name, c_first_name, s_store_name\nhaving sum(netpaid) > (select 0.05 * avg(netpaid) from ssales)\norder by c_last_name, c_first_name, s_store_name\n",
    31: '\nwith ss as (\n  select ca_county, d_qoy, d_year, sum(ss_ext_sales_price) store_sales\n  from store_sales, date_dim, customer_address\n  where ss_sold_date_sk = d_date_sk and ss_addr_sk = ca_address_sk\n  group by ca_county, d_qoy, d_year),\n ws as (\n  select ca_county, d_qoy, d_year, sum(ws_ext_sales_price) web_sales\n  from web_sales, date_dim, customer_address\n  where ws_sold_date_sk = d_date_sk and ws_bill_addr_sk = ca_address_sk\n  group by ca_county, d_qoy, d_year)\nselect ss1.ca_county, ss1.d_year,\n       cast(ws2.web_sales as double) / ws1.web_sales web_q1_q2_increase,\n       cast(ss2.store_sales as double) / ss1.store_sales store_q1_q2_increase,\n       cast(ws3.web_sales as double) / ws2.web_sales web_q2_q3_increase,\n       cast(ss3.store_sales as double) / ss2.store_sales store_q2_q3_increase\nfrom ss ss1, ss ss2, ss ss3, ws ws1, ws ws2, ws ws3\nwhere ss1.d_qoy = 1 and ss1.d_year = 2000\n  and ss1.ca_county = ss2.ca_county and ss2.d_qoy = 2 and ss2.d_year = 2000\n  and ss2.ca_county = ss3.ca_county and ss3.d_qoy = 3 and ss3.d_year = 2000\n  and ss1.ca_county = ws1.ca_county and ws1.d_qoy = 1 and ws1.d_year = 2000\n  and ws1.ca_county = ws2.ca_county and ws2.d_qoy = 2 and ws2.d_year = 2000\n  and ws1.ca_county = ws3.ca_county and ws3.d_qoy = 3 and ws3.d_year = 2000\n  and case when ws1.web_sales > 0 then cast(ws2.web_sales as double) / ws1.web_sales else null end\n      > case when ss1.store_sales > 0 then cast(ss2.store_sales as double) / ss1.store_sales else null end\norder by ss1.ca_county\n',
    39: '\nwith inv as (\n  select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,\n         case when mean = 0 then null else stdev / mean end cov\n  from (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,\n               stddev_samp(inv_quantity_on_hand) stdev,\n               avg(inv_quantity_on_hand) mean\n        from inventory, item, warehouse, date_dim\n        where inv_item_sk = i_item_sk\n          and inv_warehouse_sk = w_warehouse_sk\n          and inv_date_sk = d_date_sk\n          and d_year = 1998\n        group by w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo\n  where case when mean = 0 then 0 else stdev / mean end > 0.6)\nselect inv1.w_warehouse_sk wsk1, inv1.i_item_sk isk1, inv1.d_moy moy1,\n       inv1.mean mean1, inv1.cov cov1,\n       inv2.w_warehouse_sk wsk2, inv2.i_item_sk isk2, inv2.d_moy moy2,\n       inv2.mean mean2, inv2.cov cov2\nfrom inv inv1, inv inv2\nwhere inv1.i_item_sk = inv2.i_item_sk\n  and inv1.w_warehouse_sk = inv2.w_warehouse_sk\n  and inv1.d_moy = 1 and inv2.d_moy = 2\norder by inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov,\n         inv2.d_moy, inv2.mean, inv2.cov\n',
    44: '\nselect asceding.rnk, i1.i_item_id best_performing, i2.i_item_id worst_performing\nfrom\n (select item_sk, rnk from (\n    select item_sk, rank() over (order by rank_col asc) rnk from (\n      select ss_item_sk item_sk, avg(ss_net_profit) rank_col\n      from store_sales where ss_store_sk = 4\n      group by ss_item_sk\n      having avg(ss_net_profit) > 0.9 * (\n        select avg(ss_net_profit) rank_col from store_sales\n        where ss_store_sk = 4 and ss_quantity > 90\n        group by ss_store_sk)) v1) v11\n  where rnk < 11) asceding,\n (select item_sk, rnk from (\n    select item_sk, rank() over (order by rank_col desc) rnk from (\n      select ss_item_sk item_sk, avg(ss_net_profit) rank_col\n      from store_sales where ss_store_sk = 4\n      group by ss_item_sk\n      having avg(ss_net_profit) > 0.9 * (\n        select avg(ss_net_profit) rank_col from store_sales\n        where ss_store_sk = 4 and ss_quantity > 90\n        group by ss_store_sk)) v2) v21\n  where rnk < 11) descending,\n item i1, item i2\nwhere asceding.rnk = descending.rnk\n  and i1.i_item_sk = asceding.item_sk\n  and i2.i_item_sk = descending.item_sk\norder by asceding.rnk\n',
    54: "\nwith my_customers as (\n  select distinct c_customer_sk, c_current_addr_sk\n  from (select cs_sold_date_sk sold_date_sk,\n               cs_bill_customer_sk customer_sk, cs_item_sk item_sk\n        from catalog_sales\n        union all\n        select ws_sold_date_sk, ws_bill_customer_sk, ws_item_sk\n        from web_sales) cs_or_ws_sales, item, date_dim, customer\n  where sold_date_sk = d_date_sk and item_sk = i_item_sk\n    and i_category = 'Sports'\n    and c_customer_sk = customer_sk\n    and d_moy = 12 and d_year = 1998),\n my_revenue as (\n  select c_customer_sk, sum(ss_ext_sales_price) revenue\n  from my_customers, store_sales, customer_address, store, date_dim\n  where c_current_addr_sk = ca_address_sk\n    and ca_state = s_state\n    and ss_customer_sk = c_customer_sk\n    and ss_sold_date_sk = d_date_sk\n    and d_month_seq >= (select distinct d_month_seq + 1 from date_dim\n                        where d_year = 1998 and d_moy = 12)\n    and d_month_seq <= (select distinct d_month_seq + 12 from date_dim\n                        where d_year = 1998 and d_moy = 12)\n  group by c_customer_sk),\n segments as (select floor(revenue / 50) segment from my_revenue)\nselect segment, count(*) num_customers, segment * 50 segment_base\nfrom segments\ngroup by segment\norder by segment, num_customers\nlimit 100\n",
    58: "\nwith ss_items as (\n  select i_item_id item_id, sum(ss_ext_sales_price) ss_item_rev\n  from store_sales, item, date_dim\n  where ss_item_sk = i_item_sk\n    and d_year = (select d_year from date_dim\n                  where d_date = date '2000-02-02')\n    and ss_sold_date_sk = d_date_sk\n  group by i_item_id),\n cs_items as (\n  select i_item_id item_id, sum(cs_ext_sales_price) cs_item_rev\n  from catalog_sales, item, date_dim\n  where cs_item_sk = i_item_sk\n    and d_year = (select d_year from date_dim\n                  where d_date = date '2000-02-02')\n    and cs_sold_date_sk = d_date_sk\n  group by i_item_id),\n ws_items as (\n  select i_item_id item_id, sum(ws_ext_sales_price) ws_item_rev\n  from web_sales, item, date_dim\n  where ws_item_sk = i_item_sk\n    and d_year = (select d_year from date_dim\n                  where d_date = date '2000-02-02')\n    and ws_sold_date_sk = d_date_sk\n  group by i_item_id)\nselect ss_items.item_id,\n       ss_item_rev,\n       cast(ss_item_rev as double) / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 ss_dev,\n       cs_item_rev,\n       cast(cs_item_rev as double) / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 cs_dev,\n       ws_item_rev,\n       cast(ws_item_rev as double) / ((ss_item_rev + cs_item_rev + ws_item_rev) / 3) * 100 ws_dev,\n       (ss_item_rev + cs_item_rev + ws_item_rev) / 3 average\nfrom ss_items, cs_items, ws_items\nwhere ss_items.item_id = cs_items.item_id\n  and ss_items.item_id = ws_items.item_id\n  and ss_item_rev >= 0.1 * cs_item_rev and ss_item_rev <= 1.9 * cs_item_rev\n  and ss_item_rev >= 0.1 * ws_item_rev and ss_item_rev <= 1.9 * ws_item_rev\n  and cs_item_rev >= 0.1 * ss_item_rev and cs_item_rev <= 1.9 * ss_item_rev\n  and cs_item_rev >= 0.1 * ws_item_rev and cs_item_rev <= 1.9 * ws_item_rev\n  and ws_item_rev >= 0.1 * ss_item_rev and ws_item_rev <= 1.9 * ss_item_rev\n  and ws_item_rev >= 0.1 * cs_item_rev and ws_item_rev <= 1.9 * cs_item_rev\norder by ss_items.item_id, ss_item_rev\nlimit 100\n",
    64: '\nwith cs_ui as (\n  select cs_item_sk,\n         sum(cs_ext_list_price) as sale,\n         sum(cr_return_amount) as refund\n  from catalog_sales, catalog_returns\n  where cs_item_sk = cr_item_sk and cs_order_number = cr_order_number\n  group by cs_item_sk\n  having sum(cs_ext_list_price) > 2 * sum(cr_return_amount)),\n cross_sales as (\n  select i_item_id item_id, i_item_sk item_sk, s_store_name store_name,\n         s_zip store_zip, d1.d_year syear,\n         count(*) cnt,\n         sum(ss_wholesale_cost) s1, sum(ss_list_price) s2,\n         sum(ss_coupon_amt) s3\n  from store_sales, store_returns, cs_ui, date_dim d1, store, item,\n       customer, customer_address ad2, date_dim d2\n  where ss_store_sk = s_store_sk\n    and ss_sold_date_sk = d1.d_date_sk\n    and ss_customer_sk = c_customer_sk\n    and ss_item_sk = i_item_sk\n    and ss_item_sk = sr_item_sk\n    and ss_ticket_number = sr_ticket_number\n    and ss_item_sk = cs_ui.cs_item_sk\n    and c_current_addr_sk = ad2.ca_address_sk\n    and c_first_sales_date_sk = d2.d_date_sk\n  group by i_item_id, i_item_sk, s_store_name, s_zip, d1.d_year)\nselect cs1.item_id, cs1.store_name, cs1.store_zip, cs1.syear, cs1.cnt,\n       cs1.s1 as s11, cs1.s2 as s21, cs1.s3 as s31,\n       cs2.s1 as s12, cs2.s2 as s22, cs2.s3 as s32, cs2.syear as syear2,\n       cs2.cnt as cnt2\nfrom cross_sales cs1, cross_sales cs2\nwhere cs1.item_sk = cs2.item_sk\n  and cs1.syear + 1 = cs2.syear\n  and cs2.cnt <= cs1.cnt + 5\norder by cs1.item_id, cs1.store_name, cs1.store_zip, cs1.syear, cs1.cnt,\n         s11, s21, s31, s12, s22, s32, syear2, cnt2\nlimit 100\n',
    66: "\nselect w_warehouse_name, w_warehouse_sq_ft, w_state, ship_carriers, year_,\n       sum(jan_sales) jan_sales, sum(feb_sales) feb_sales,\n       sum(mar_sales) mar_sales, sum(apr_sales) apr_sales,\n       sum(may_sales) may_sales, sum(jun_sales) jun_sales,\n       sum(jul_sales) jul_sales, sum(aug_sales) aug_sales,\n       sum(sep_sales) sep_sales, sum(oct_sales) oct_sales,\n       sum(nov_sales) nov_sales, sum(dec_sales) dec_sales\nfrom (\n  select w_warehouse_name, w_warehouse_sq_ft, w_state,\n         'DHL,BARIAN' as ship_carriers, d_year as year_,\n         sum(case when d_moy = 1 then ws_ext_sales_price * ws_quantity else 0 end) as jan_sales,\n         sum(case when d_moy = 2 then ws_ext_sales_price * ws_quantity else 0 end) as feb_sales,\n         sum(case when d_moy = 3 then ws_ext_sales_price * ws_quantity else 0 end) as mar_sales,\n         sum(case when d_moy = 4 then ws_ext_sales_price * ws_quantity else 0 end) as apr_sales,\n         sum(case when d_moy = 5 then ws_ext_sales_price * ws_quantity else 0 end) as may_sales,\n         sum(case when d_moy = 6 then ws_ext_sales_price * ws_quantity else 0 end) as jun_sales,\n         sum(case when d_moy = 7 then ws_ext_sales_price * ws_quantity else 0 end) as jul_sales,\n         sum(case when d_moy = 8 then ws_ext_sales_price * ws_quantity else 0 end) as aug_sales,\n         sum(case when d_moy = 9 then ws_ext_sales_price * ws_quantity else 0 end) as sep_sales,\n         sum(case when d_moy = 10 then ws_ext_sales_price * ws_quantity else 0 end) as oct_sales,\n         sum(case when d_moy = 11 then ws_ext_sales_price * ws_quantity else 0 end) as nov_sales,\n         sum(case when d_moy = 12 then ws_ext_sales_price * ws_quantity else 0 end) as dec_sales\n  from web_sales, warehouse, date_dim, time_dim, ship_mode\n  where ws_warehouse_sk = w_warehouse_sk\n    and ws_sold_date_sk = d_date_sk and d_year = 2000\n    and ws_sold_time_sk = t_time_sk\n    and ws_ship_mode_sk = sm_ship_mode_sk\n    and t_time between 30838 and 30838 + 28800\n    and sm_carrier in ('DHL', 'BARIAN')\n  group by w_warehouse_name, w_warehouse_sq_ft, w_state, d_year\n  union all\n  select w_warehouse_name, w_warehouse_sq_ft, w_state,\n         'DHL,BARIAN' as ship_carriers, d_year as year_,\n         sum(case when d_moy = 1 then cs_sales_price * cs_quantity else 0 end) as jan_sales,\n         sum(case when d_moy = 2 then cs_sales_price * cs_quantity else 0 end) as feb_sales,\n         sum(case when d_moy = 3 then cs_sales_price * cs_quantity else 0 end) as mar_sales,\n         sum(case when d_moy = 4 then cs_sales_price * cs_quantity else 0 end) as apr_sales,\n         sum(case when d_moy = 5 then cs_sales_price * cs_quantity else 0 end) as may_sales,\n         sum(case when d_moy = 6 then cs_sales_price * cs_quantity else 0 end) as jun_sales,\n         sum(case when d_moy = 7 then cs_sales_price * cs_quantity else 0 end) as jul_sales,\n         sum(case when d_moy = 8 then cs_sales_price * cs_quantity else 0 end) as aug_sales,\n         sum(case when d_moy = 9 then cs_sales_price * cs_quantity else 0 end) as sep_sales,\n         sum(case when d_moy = 10 then cs_sales_price * cs_quantity else 0 end) as oct_sales,\n         sum(case when d_moy = 11 then cs_sales_price * cs_quantity else 0 end) as nov_sales,\n         sum(case when d_moy = 12 then cs_sales_price * cs_quantity else 0 end) as dec_sales\n  from catalog_sales, warehouse, date_dim, time_dim, ship_mode\n  where cs_warehouse_sk = w_warehouse_sk\n    and cs_sold_date_sk = d_date_sk and d_year = 2000\n    and cs_sold_time_sk = t_time_sk\n    and cs_ship_mode_sk = sm_ship_mode_sk\n    and t_time between 30838 and 30838 + 28800\n    and sm_carrier in ('DHL', 'BARIAN')\n  group by w_warehouse_name, w_warehouse_sq_ft, w_state, d_year\n ) x\ngroup by w_warehouse_name, w_warehouse_sq_ft, w_state, ship_carriers, year_\norder by w_warehouse_name\nlimit 100\n",
    67: '\nselect * from (\n  select i_category, i_class, i_brand, i_item_id, d_year, d_qoy, d_moy,\n         s_store_id, sumsales,\n         rank() over (partition by i_category order by sumsales desc) rk\n  from (\nselect i_category, i_class, i_brand, i_item_id, d_year, d_qoy, d_moy,\n       s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by rollup(i_category, i_class, i_brand, i_item_id, d_year, d_qoy,\n                d_moy, s_store_id)\n) dw1) dw2\nwhere rk <= 10\norder by i_category nulls first, i_class nulls first,\n         i_brand nulls first, i_item_id nulls first, d_year nulls first,\n         d_qoy nulls first, d_moy nulls first, s_store_id nulls first,\n         sumsales nulls first, rk\nlimit 100\n',
    72: '\nselect i_item_desc, w_warehouse_name, d1.d_week_seq,\n       sum(case when p_promo_sk is null then 1 else 0 end) no_promo,\n       sum(case when p_promo_sk is not null then 1 else 0 end) promo,\n       count(*) total_cnt\nfrom catalog_sales\njoin inventory on (cs_item_sk = inv_item_sk)\njoin warehouse on (w_warehouse_sk = inv_warehouse_sk)\njoin item on (i_item_sk = cs_item_sk)\njoin customer_demographics on (cs_bill_cdemo_sk = cd_demo_sk)\njoin household_demographics on (cs_bill_hdemo_sk = hd_demo_sk)\njoin date_dim d1 on (cs_sold_date_sk = d1.d_date_sk)\njoin date_dim d2 on (inv_date_sk = d2.d_date_sk)\njoin date_dim d3 on (cs_ship_date_sk = d3.d_date_sk)\nleft outer join promotion on (cs_promo_sk = p_promo_sk)\nleft outer join catalog_returns on (cr_item_sk = cs_item_sk and cr_order_number = cs_order_number)\nwhere d1.d_week_seq = d2.d_week_seq\n  and inv_quantity_on_hand < cs_quantity + 500\n  and d3.d_date > d1.d_date + 2\n  and d1.d_year between 1998 and 2002\ngroup by i_item_desc, w_warehouse_name, d1.d_week_seq\norder by total_cnt desc, i_item_desc, w_warehouse_name, d1.d_week_seq\nlimit 100\n',
    75: "\nwith all_sales as (\n  select d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,\n         sum(sales_cnt) sales_cnt, sum(sales_amt) sales_amt\n  from (\nselect d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,\n       cs_quantity - coalesce(cr_return_quantity, 0) sales_cnt,\n       cs_ext_sales_price - coalesce(cr_return_amount, 0.0) sales_amt\nfrom catalog_sales join item on i_item_sk = cs_item_sk\n             join date_dim on d_date_sk = cs_sold_date_sk\n             left join catalog_returns on (cs_order_number = cr_order_number and cs_item_sk = cr_item_sk)\nwhere i_category = 'Sports'\n\n        union all\n        \nselect d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,\n       ss_quantity - coalesce(sr_return_quantity, 0) sales_cnt,\n       ss_ext_sales_price - coalesce(sr_return_amt, 0.0) sales_amt\nfrom store_sales join item on i_item_sk = ss_item_sk\n             join date_dim on d_date_sk = ss_sold_date_sk\n             left join store_returns on (ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk)\nwhere i_category = 'Sports'\n\n        union all\n        \nselect d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id,\n       ws_quantity - coalesce(wr_return_quantity, 0) sales_cnt,\n       ws_ext_sales_price - coalesce(wr_return_amt, 0.0) sales_amt\nfrom web_sales join item on i_item_sk = ws_item_sk\n             join date_dim on d_date_sk = ws_sold_date_sk\n             left join web_returns on (ws_order_number = wr_order_number and ws_item_sk = wr_item_sk)\nwhere i_category = 'Sports'\n) sales_detail\n  group by d_year, i_brand_id, i_class_id, i_category_id, i_manufact_id)\nselect prev_yr.d_year prev_year, curr_yr.d_year year_,\n       curr_yr.i_brand_id, curr_yr.i_class_id, curr_yr.i_category_id,\n       curr_yr.i_manufact_id,\n       prev_yr.sales_cnt prev_yr_cnt, curr_yr.sales_cnt curr_yr_cnt,\n       curr_yr.sales_cnt - prev_yr.sales_cnt sales_cnt_diff,\n       curr_yr.sales_amt - prev_yr.sales_amt sales_amt_diff\nfrom all_sales curr_yr, all_sales prev_yr\nwhere curr_yr.i_brand_id = prev_yr.i_brand_id\n  and curr_yr.i_class_id = prev_yr.i_class_id\n  and curr_yr.i_category_id = prev_yr.i_category_id\n  and curr_yr.i_manufact_id = prev_yr.i_manufact_id\n  and curr_yr.d_year = 2001 and prev_yr.d_year = 2000\n  and cast(curr_yr.sales_cnt as double) / prev_yr.sales_cnt < 0.9\norder by sales_cnt_diff, sales_amt_diff\nlimit 100\n",
    78: '\nwith ws as (\n  \nselect d_year ws_sold_year, ws_item_sk ws_item_sk, ws_bill_customer_sk ws_customer_sk,\n       sum(ws_quantity) ws_qty, sum(ws_wholesale_cost) ws_wc, sum(ws_sales_price) ws_sp\nfrom web_sales\nleft join web_returns on wr_order_number = ws_order_number and ws_item_sk = wr_item_sk\njoin date_dim on ws_sold_date_sk = d_date_sk\nwhere wr_order_number is null\ngroup by d_year, ws_item_sk, ws_bill_customer_sk\n),\n cs as (\n  \nselect d_year cs_sold_year, cs_item_sk cs_item_sk, cs_bill_customer_sk cs_customer_sk,\n       sum(cs_quantity) cs_qty, sum(cs_wholesale_cost) cs_wc, sum(cs_sales_price) cs_sp\nfrom catalog_sales\nleft join catalog_returns on cr_order_number = cs_order_number and cs_item_sk = cr_item_sk\njoin date_dim on cs_sold_date_sk = d_date_sk\nwhere cr_order_number is null\ngroup by d_year, cs_item_sk, cs_bill_customer_sk\n),\n ss as (\n  \nselect d_year ss_sold_year, ss_item_sk ss_item_sk, ss_customer_sk ss_customer_sk,\n       sum(ss_quantity) ss_qty, sum(ss_wholesale_cost) ss_wc, sum(ss_sales_price) ss_sp\nfrom store_sales\nleft join store_returns on sr_ticket_number = ss_ticket_number and ss_item_sk = sr_item_sk\njoin date_dim on ss_sold_date_sk = d_date_sk\nwhere sr_ticket_number is null\ngroup by d_year, ss_item_sk, ss_customer_sk\n)\nselect ss_sold_year, ss_item_sk, ss_customer_sk,\n       round(cast(ss_qty as double) / (coalesce(ws_qty, 0) + coalesce(cs_qty, 0)), 2) ratio,\n       ss_qty store_qty, ss_wc store_wholesale_cost, ss_sp store_sales_price,\n       coalesce(ws_qty, 0) + coalesce(cs_qty, 0) other_chan_qty,\n       coalesce(ws_wc, 0) + coalesce(cs_wc, 0) other_chan_wholesale_cost,\n       coalesce(ws_sp, 0) + coalesce(cs_sp, 0) other_chan_sales_price\nfrom ss\nleft join ws on (ws_sold_year = ss_sold_year and ws_item_sk = ss_item_sk\n                 and ws_customer_sk = ss_customer_sk)\nleft join cs on (cs_sold_year = ss_sold_year and cs_item_sk = ss_item_sk\n                 and cs_customer_sk = ss_customer_sk)\nwhere (coalesce(ws_qty, 0) > 0 or coalesce(cs_qty, 0) > 0)\n  and ss_sold_year = 2000\norder by ss_sold_year, ss_item_sk, ss_customer_sk, ss_qty desc, ss_wc desc,\n         ss_sp desc, other_chan_qty, other_chan_wholesale_cost,\n         other_chan_sales_price, ratio\nlimit 100\n',
    80: "\nwith ssr as (\n  select s_store_id as store_id,\n         sum(ss_ext_sales_price) as sales,\n         sum(coalesce(sr_return_amt, 0)) as returns_,\n         sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit\n  from store_sales\n  left outer join store_returns\n    on (ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number)\n  join date_dim on ss_sold_date_sk = d_date_sk\n  join store on ss_store_sk = s_store_sk\n  join item on ss_item_sk = i_item_sk\n  join promotion on ss_promo_sk = p_promo_sk\n  where d_date between date '2000-08-23' and date '2000-09-22'\n    and i_current_price > 50\n    and p_channel_tv = 'N'\n  group by s_store_id),\n csr as (\n  select cp_catalog_page_id as catalog_page_id,\n         sum(cs_ext_sales_price) as sales,\n         sum(coalesce(cr_return_amount, 0)) as returns_,\n         sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit\n  from catalog_sales\n  left outer join catalog_returns\n    on (cs_item_sk = cr_item_sk and cs_order_number = cr_order_number)\n  join date_dim on cs_sold_date_sk = d_date_sk\n  join catalog_page on cs_catalog_page_sk = cp_catalog_page_sk\n  join item on cs_item_sk = i_item_sk\n  join promotion on cs_promo_sk = p_promo_sk\n  where d_date between date '2000-08-23' and date '2000-09-22'\n    and i_current_price > 50\n    and p_channel_tv = 'N'\n  group by cp_catalog_page_id),\n wsr as (\n  select web_site_id,\n         sum(ws_ext_sales_price) as sales,\n         sum(coalesce(wr_return_amt, 0)) as returns_,\n         sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit\n  from web_sales\n  left outer join web_returns\n    on (ws_item_sk = wr_item_sk and ws_order_number = wr_order_number)\n  join date_dim on ws_sold_date_sk = d_date_sk\n  join web_site on ws_web_site_sk = web_site_sk\n  join item on ws_item_sk = i_item_sk\n  join promotion on ws_promo_sk = p_promo_sk\n  where d_date between date '2000-08-23' and date '2000-09-22'\n    and i_current_price > 50\n    and p_channel_tv = 'N'\n  group by web_site_id)\nselect channel, id, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit\nfrom (select 'store channel' as channel, store_id as id, sales, returns_,\n             profit\n      from ssr\n      union all\n      select 'catalog channel', catalog_page_id, sales, returns_, profit\n      from csr\n      union all\n      select 'web channel', web_site_id, sales, returns_, profit\n      from wsr) x\ngroup by rollup (channel, id)\norder by channel nulls first, id nulls first\nlimit 100\n",
})

# sqlite lacks stddev_samp (q17/q39: closed form over sums) and
# ROLLUP (q5/q14/q67/q80: grouping-set union expansion)
ORACLE_OVERRIDES.update({
    5: "\nwith ssr as (\nselect s_store_id,\n       sum(sales_price) as sales, sum(profit) as profit,\n       sum(return_amt) as returns_, sum(net_loss) as profit_loss\nfrom (select ss_store_sk as store_sk, ss_sold_date_sk as date_sk,\n             ss_ext_sales_price as sales_price, ss_net_profit as profit,\n             cast(0 as decimal(12,2)) as return_amt,\n             cast(0 as decimal(12,2)) as net_loss\n      from store_sales\n      union all\n      select sr_store_sk, sr_returned_date_sk,\n             cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),\n             sr_return_amt, sr_net_loss\n      from store_returns) salesreturns, date_dim, store\nwhere date_sk = d_date_sk\n  and d_date between date '2000-08-23' and date '2000-09-06'\n  and store_sk = s_store_sk\ngroup by s_store_id\n), csr as (\nselect cc_call_center_id,\n       sum(sales_price) as sales, sum(profit) as profit,\n       sum(return_amt) as returns_, sum(net_loss) as profit_loss\nfrom (select cs_call_center_sk as center_sk, cs_sold_date_sk as date_sk,\n             cs_ext_sales_price as sales_price, cs_net_profit as profit,\n             cast(0 as decimal(12,2)) as return_amt,\n             cast(0 as decimal(12,2)) as net_loss\n      from catalog_sales\n      union all\n      select cr_call_center_sk, cr_returned_date_sk,\n             cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),\n             cr_return_amount, cr_net_loss\n      from catalog_returns) salesreturns, date_dim, call_center\nwhere date_sk = d_date_sk\n  and d_date between date '2000-08-23' and date '2000-09-06'\n  and center_sk = cc_call_center_sk\ngroup by cc_call_center_id\n), wsr as (\nselect web_site_id,\n       sum(sales_price) as sales, sum(profit) as profit,\n       sum(return_amt) as returns_, sum(net_loss) as profit_loss\nfrom (select ws_web_site_sk as wsr_web_site_sk, ws_sold_date_sk as date_sk,\n             ws_ext_sales_price as sales_price, ws_net_profit as profit,\n             cast(0 as decimal(12,2)) as return_amt,\n             cast(0 as decimal(12,2)) as net_loss\n      from web_sales\n      union all\n      select ws_web_site_sk, wr_returned_date_sk,\n             cast(0 as decimal(12,2)), cast(0 as decimal(12,2)),\n             wr_return_amt, wr_net_loss\n      from web_returns\n      left outer join web_sales on (wr_item_sk = ws_item_sk\n                                    and wr_order_number = ws_order_number)\n     ) salesreturns, date_dim, web_site\nwhere date_sk = d_date_sk\n  and d_date between date '2000-08-23' and date '2000-09-06'\n  and wsr_web_site_sk = web_site_sk\ngroup by web_site_id\n), xsrc as (select 'store channel' as channel, s_store_id as id, sales, returns_,\n             profit - profit_loss as profit\n      from ssr\n      union all\n      select 'catalog channel', cc_call_center_id, sales, returns_,\n             profit - profit_loss\n      from csr\n      union all\n      select 'web channel', web_site_id, sales, returns_,\n             profit - profit_loss\n      from wsr)\nselect channel, id, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit from xsrc group by channel, id\nunion all\nselect channel, null, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit from xsrc group by channel\nunion all\nselect null, null, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit from xsrc\norder by channel nulls first, id nulls first\nlimit 100\n",
    14: "\nwith cross_items as (\n  select i_item_sk ss_item_sk\n  from item,\n   (select iss.i_brand_id brand_id, iss.i_class_id class_id,\n           iss.i_category_id category_id\n    from store_sales, item iss, date_dim d1\n    where ss_item_sk = iss.i_item_sk and ss_sold_date_sk = d1.d_date_sk\n      and d1.d_year between 1999 and 2001\n    intersect\n    select ics.i_brand_id, ics.i_class_id, ics.i_category_id\n    from catalog_sales, item ics, date_dim d2\n    where cs_item_sk = ics.i_item_sk and cs_sold_date_sk = d2.d_date_sk\n      and d2.d_year between 1999 and 2001\n    intersect\n    select iws.i_brand_id, iws.i_class_id, iws.i_category_id\n    from web_sales, item iws, date_dim d3\n    where ws_item_sk = iws.i_item_sk and ws_sold_date_sk = d3.d_date_sk\n      and d3.d_year between 1999 and 2001) x\n  where i_brand_id = brand_id and i_class_id = class_id\n    and i_category_id = category_id),\n avg_sales as (\n  select avg(quantity * list_price) average_sales\n  from (select ss_quantity quantity, ss_list_price list_price\n        from store_sales, date_dim\n        where ss_sold_date_sk = d_date_sk and d_year between 1999 and 2001\n        union all\n        select cs_quantity, cs_list_price\n        from catalog_sales, date_dim\n        where cs_sold_date_sk = d_date_sk and d_year between 1999 and 2001\n        union all\n        select ws_quantity, ws_list_price\n        from web_sales, date_dim\n        where ws_sold_date_sk = d_date_sk and d_year between 1999 and 2001) x), ysrc as (\nselect 'store' channel, i_brand_id, i_class_id, i_category_id,\n       sum(ss_quantity * ss_list_price) sales, count(*) number_sales\nfrom store_sales, item, date_dim\nwhere ss_item_sk in (select ss_item_sk from cross_items)\n  and ss_item_sk = i_item_sk and ss_sold_date_sk = d_date_sk\n  and d_year = 2001 and d_moy = 11\ngroup by i_brand_id, i_class_id, i_category_id\nhaving sum(ss_quantity * ss_list_price) > (select average_sales from avg_sales)\n\n      union all\n      \nselect 'catalog' channel, i_brand_id, i_class_id, i_category_id,\n       sum(cs_quantity * cs_list_price) sales, count(*) number_sales\nfrom catalog_sales, item, date_dim\nwhere cs_item_sk in (select ss_item_sk from cross_items)\n  and cs_item_sk = i_item_sk and cs_sold_date_sk = d_date_sk\n  and d_year = 2001 and d_moy = 11\ngroup by i_brand_id, i_class_id, i_category_id\nhaving sum(cs_quantity * cs_list_price) > (select average_sales from avg_sales)\n\n      union all\n      \nselect 'web' channel, i_brand_id, i_class_id, i_category_id,\n       sum(ws_quantity * ws_list_price) sales, count(*) number_sales\nfrom web_sales, item, date_dim\nwhere ws_item_sk in (select ss_item_sk from cross_items)\n  and ws_item_sk = i_item_sk and ws_sold_date_sk = d_date_sk\n  and d_year = 2001 and d_moy = 11\ngroup by i_brand_id, i_class_id, i_category_id\nhaving sum(ws_quantity * ws_list_price) > (select average_sales from avg_sales)\n\n     )\nselect channel, i_brand_id, i_class_id, i_category_id, sum(sales) s1, sum(number_sales) s2 from ysrc group by channel, i_brand_id, i_class_id, i_category_id union all select channel, i_brand_id, i_class_id, null as i_category_id, sum(sales) s1, sum(number_sales) s2 from ysrc group by channel, i_brand_id, i_class_id union all select channel, i_brand_id, null as i_class_id, null as i_category_id, sum(sales) s1, sum(number_sales) s2 from ysrc group by channel, i_brand_id union all select channel, null as i_brand_id, null as i_class_id, null as i_category_id, sum(sales) s1, sum(number_sales) s2 from ysrc group by channel union all select null as channel, null as i_brand_id, null as i_class_id, null as i_category_id, sum(sales) s1, sum(number_sales) s2 from ysrc \norder by channel nulls first, i_brand_id nulls first,\n         i_class_id nulls first, i_category_id nulls first\nlimit 100\n",
    17: '\nselect i_item_id, i_item_desc, s_state,\n       count(ss_quantity) store_sales_quantitycount,\n       avg(ss_quantity) store_sales_quantityave,\n       sqrt((count(ss_quantity)*sum(ss_quantity*ss_quantity) - sum(ss_quantity)*sum(ss_quantity)) * 1.0 / (count(ss_quantity)*(count(ss_quantity)-1.0))) store_sales_quantitystdev,\n       sqrt((count(ss_quantity)*sum(ss_quantity*ss_quantity) - sum(ss_quantity)*sum(ss_quantity)) * 1.0 / (count(ss_quantity)*(count(ss_quantity)-1.0))) / avg(ss_quantity) store_sales_quantitycov,\n       count(sr_return_quantity) store_returns_quantitycount,\n       avg(sr_return_quantity) store_returns_quantityave,\n       sqrt((count(sr_return_quantity)*sum(sr_return_quantity*sr_return_quantity) - sum(sr_return_quantity)*sum(sr_return_quantity)) * 1.0 / (count(sr_return_quantity)*(count(sr_return_quantity)-1.0))) store_returns_quantitystdev,\n       sqrt((count(sr_return_quantity)*sum(sr_return_quantity*sr_return_quantity) - sum(sr_return_quantity)*sum(sr_return_quantity)) * 1.0 / (count(sr_return_quantity)*(count(sr_return_quantity)-1.0))) / avg(sr_return_quantity) store_returns_quantitycov,\n       count(cs_quantity) catalog_sales_quantitycount,\n       avg(cs_quantity) catalog_sales_quantityave,\n       sqrt((count(cs_quantity)*sum(cs_quantity*cs_quantity) - sum(cs_quantity)*sum(cs_quantity)) * 1.0 / (count(cs_quantity)*(count(cs_quantity)-1.0))) catalog_sales_quantitystdev,\n       sqrt((count(cs_quantity)*sum(cs_quantity*cs_quantity) - sum(cs_quantity)*sum(cs_quantity)) * 1.0 / (count(cs_quantity)*(count(cs_quantity)-1.0))) / avg(cs_quantity) catalog_sales_quantitycov\nfrom store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,\n     date_dim d3, store, item\nwhere d1.d_year = 2000 and d1.d_qoy = 1\n  and d1.d_date_sk = ss_sold_date_sk\n  and i_item_sk = ss_item_sk\n  and s_store_sk = ss_store_sk\n  and ss_customer_sk = sr_customer_sk\n  and ss_item_sk = sr_item_sk\n  and ss_ticket_number = sr_ticket_number\n  and sr_returned_date_sk = d2.d_date_sk\n  and d2.d_year = 2000 and d2.d_qoy between 1 and 3\n  and sr_item_sk = cs_item_sk\n  and cs_sold_date_sk = d3.d_date_sk\n  and d3.d_year = 2000 and d3.d_qoy between 1 and 3\ngroup by i_item_id, i_item_desc, s_state\norder by i_item_id, i_item_desc, s_state\nlimit 100\n',
    39: '\nwith inv as (\n  select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy, stdev, mean,\n         case when mean = 0 then null else stdev / mean end cov\n  from (select w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy,\n               sqrt((count(inv_quantity_on_hand)*sum(inv_quantity_on_hand*inv_quantity_on_hand) - sum(inv_quantity_on_hand)*sum(inv_quantity_on_hand)) * 1.0 / (count(inv_quantity_on_hand)*(count(inv_quantity_on_hand)-1.0))) stdev,\n               avg(inv_quantity_on_hand) mean\n        from inventory, item, warehouse, date_dim\n        where inv_item_sk = i_item_sk\n          and inv_warehouse_sk = w_warehouse_sk\n          and inv_date_sk = d_date_sk\n          and d_year = 1998\n        group by w_warehouse_name, w_warehouse_sk, i_item_sk, d_moy) foo\n  where case when mean = 0 then 0 else stdev / mean end > 0.6)\nselect inv1.w_warehouse_sk wsk1, inv1.i_item_sk isk1, inv1.d_moy moy1,\n       inv1.mean mean1, inv1.cov cov1,\n       inv2.w_warehouse_sk wsk2, inv2.i_item_sk isk2, inv2.d_moy moy2,\n       inv2.mean mean2, inv2.cov cov2\nfrom inv inv1, inv inv2\nwhere inv1.i_item_sk = inv2.i_item_sk\n  and inv1.w_warehouse_sk = inv2.w_warehouse_sk\n  and inv1.d_moy = 1 and inv2.d_moy = 2\norder by inv1.w_warehouse_sk, inv1.i_item_sk, inv1.d_moy, inv1.mean, inv1.cov,\n         inv2.d_moy, inv2.mean, inv2.cov\n',
    67: '\nselect * from (\n  select i_category, i_class, i_brand, i_item_id, d_year, d_qoy, d_moy,\n         s_store_id, sumsales,\n         rank() over (partition by i_category order by sumsales desc) rk\n  from (\nselect i_category, i_class, i_brand, i_item_id, d_year, d_qoy, d_moy, s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category, i_class, i_brand, i_item_id, d_year, d_qoy, d_moy, s_store_id union all \nselect i_category, i_class, i_brand, i_item_id, d_year, d_qoy, d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category, i_class, i_brand, i_item_id, d_year, d_qoy, d_moy union all \nselect i_category, i_class, i_brand, i_item_id, d_year, d_qoy, null as d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category, i_class, i_brand, i_item_id, d_year, d_qoy union all \nselect i_category, i_class, i_brand, i_item_id, d_year, null as d_qoy, null as d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category, i_class, i_brand, i_item_id, d_year union all \nselect i_category, i_class, i_brand, i_item_id, null as d_year, null as d_qoy, null as d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category, i_class, i_brand, i_item_id union all \nselect i_category, i_class, i_brand, null as i_item_id, null as d_year, null as d_qoy, null as d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category, i_class, i_brand union all \nselect i_category, i_class, null as i_brand, null as i_item_id, null as d_year, null as d_qoy, null as d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category, i_class union all \nselect i_category, null as i_class, null as i_brand, null as i_item_id, null as d_year, null as d_qoy, null as d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\ngroup by i_category union all \nselect null as i_category, null as i_class, null as i_brand, null as i_item_id, null as d_year, null as d_qoy, null as d_moy, null as s_store_id, sum(coalesce(ss_sales_price * ss_quantity, 0)) sumsales\nfrom store_sales, date_dim, store, item\nwhere ss_sold_date_sk = d_date_sk and ss_item_sk = i_item_sk\n  and ss_store_sk = s_store_sk\n  and d_month_seq between 1200 and 1211\n) dw1) dw2\nwhere rk <= 10\norder by i_category nulls first, i_class nulls first,\n         i_brand nulls first, i_item_id nulls first, d_year nulls first,\n         d_qoy nulls first, d_moy nulls first, s_store_id nulls first,\n         sumsales nulls first, rk\nlimit 100\n',
    80: "\nwith ssr as (\n  select s_store_id as store_id,\n         sum(ss_ext_sales_price) as sales,\n         sum(coalesce(sr_return_amt, 0)) as returns_,\n         sum(ss_net_profit - coalesce(sr_net_loss, 0)) as profit\n  from store_sales\n  left outer join store_returns\n    on (ss_item_sk = sr_item_sk and ss_ticket_number = sr_ticket_number)\n  join date_dim on ss_sold_date_sk = d_date_sk\n  join store on ss_store_sk = s_store_sk\n  join item on ss_item_sk = i_item_sk\n  join promotion on ss_promo_sk = p_promo_sk\n  where d_date between date '2000-08-23' and date '2000-09-22'\n    and i_current_price > 50\n    and p_channel_tv = 'N'\n  group by s_store_id),\n csr as (\n  select cp_catalog_page_id as catalog_page_id,\n         sum(cs_ext_sales_price) as sales,\n         sum(coalesce(cr_return_amount, 0)) as returns_,\n         sum(cs_net_profit - coalesce(cr_net_loss, 0)) as profit\n  from catalog_sales\n  left outer join catalog_returns\n    on (cs_item_sk = cr_item_sk and cs_order_number = cr_order_number)\n  join date_dim on cs_sold_date_sk = d_date_sk\n  join catalog_page on cs_catalog_page_sk = cp_catalog_page_sk\n  join item on cs_item_sk = i_item_sk\n  join promotion on cs_promo_sk = p_promo_sk\n  where d_date between date '2000-08-23' and date '2000-09-22'\n    and i_current_price > 50\n    and p_channel_tv = 'N'\n  group by cp_catalog_page_id),\n wsr as (\n  select web_site_id,\n         sum(ws_ext_sales_price) as sales,\n         sum(coalesce(wr_return_amt, 0)) as returns_,\n         sum(ws_net_profit - coalesce(wr_net_loss, 0)) as profit\n  from web_sales\n  left outer join web_returns\n    on (ws_item_sk = wr_item_sk and ws_order_number = wr_order_number)\n  join date_dim on ws_sold_date_sk = d_date_sk\n  join web_site on ws_web_site_sk = web_site_sk\n  join item on ws_item_sk = i_item_sk\n  join promotion on ws_promo_sk = p_promo_sk\n  where d_date between date '2000-08-23' and date '2000-09-22'\n    and i_current_price > 50\n    and p_channel_tv = 'N'\n  group by web_site_id), xsrc as (select 'store channel' as channel, store_id as id, sales, returns_,\n             profit\n      from ssr\n      union all\n      select 'catalog channel', catalog_page_id, sales, returns_, profit\n      from csr\n      union all\n      select 'web channel', web_site_id, sales, returns_, profit\n      from wsr)\nselect channel, id, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit from xsrc group by channel, id\nunion all\nselect channel, null, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit from xsrc group by channel\nunion all\nselect null, null, sum(sales) as sales, sum(returns_) as returns_,\n       sum(profit) as profit from xsrc\norder by channel nulls first, id nulls first\nlimit 100\n",
})
