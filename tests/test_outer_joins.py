"""FULL OUTER JOIN vs the sqlite oracle (sqlite >= 3.39 supports FULL).

Reference analog: operator/LookupJoinOperators.java:37 (fullOuterJoin)
+ LookupOuterOperator.java (unvisited build positions streamed after all
probes); TestHashJoinOperator full-outer cases.
"""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


CASES = [
    # unmatched probe rows (nations 5..24 have no region with that key)
    "select n_nationkey, n_name, r_name from nation"
    " full outer join region on n_nationkey = r_regionkey",
    # unmatched build rows (suppliers' nations only cover part of nation)
    "select n_name, s_name from supplier"
    " full outer join (select * from nation where n_nationkey < 10) nn"
    " on s_nationkey = n_nationkey",
    # full outer over subquery relations, unmatched on both sides
    "select a.k, b.k from"
    " (select n_nationkey as k from nation where n_nationkey < 15) a"
    " full outer join"
    " (select n_nationkey + 10 as k from nation) b"
    " on a.k = b.k",
    # aggregation over a full join (null keys group together)
    "select r_name, count(*) from nation"
    " full outer join region on n_nationkey = r_regionkey"
    " group by r_name",
    # many-to-many: duplicate keys on both sides
    "select a.m, b.m from"
    " (select mod(n_nationkey, 4) as m from nation) a"
    " full outer join"
    " (select mod(s_suppkey, 6) as m from supplier) b"
    " on a.m = b.m",
]


@pytest.mark.parametrize("i", range(len(CASES)))
def test_full_outer(env, i):
    runner, oracle = env
    sql = CASES[i]
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_right_outer(env):
    runner, oracle = env
    sql = ("select n_name, s_name from supplier"
           " right outer join nation on s_nationkey = n_nationkey")
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)
