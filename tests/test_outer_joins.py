"""FULL OUTER JOIN vs the sqlite oracle (sqlite >= 3.39 supports FULL).

Reference analog: operator/LookupJoinOperators.java:37 (fullOuterJoin)
+ LookupOuterOperator.java (unvisited build positions streamed after all
probes); TestHashJoinOperator full-outer cases.
"""

import sqlite3

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle

# the ORACLE needs sqlite >= 3.39 for RIGHT/FULL OUTER JOIN; older
# builds cannot produce the expected rows at all (the engine side is
# exercised regardless by tests/test_feature_interactions and the
# join-operator unit tests)
needs_full_join_oracle = pytest.mark.skipif(
    sqlite3.sqlite_version_info < (3, 39),
    reason=f"sqlite {sqlite3.sqlite_version} lacks RIGHT/FULL OUTER "
           "JOIN (needs >= 3.39); oracle cannot compute expected rows")


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


CASES = [
    # unmatched probe rows (nations 5..24 have no region with that key)
    "select n_nationkey, n_name, r_name from nation"
    " full outer join region on n_nationkey = r_regionkey",
    # unmatched build rows (suppliers' nations only cover part of nation)
    "select n_name, s_name from supplier"
    " full outer join (select * from nation where n_nationkey < 10) nn"
    " on s_nationkey = n_nationkey",
    # full outer over subquery relations, unmatched on both sides
    "select a.k, b.k from"
    " (select n_nationkey as k from nation where n_nationkey < 15) a"
    " full outer join"
    " (select n_nationkey + 10 as k from nation) b"
    " on a.k = b.k",
    # aggregation over a full join (null keys group together)
    "select r_name, count(*) from nation"
    " full outer join region on n_nationkey = r_regionkey"
    " group by r_name",
    # many-to-many: duplicate keys on both sides
    "select a.m, b.m from"
    " (select mod(n_nationkey, 4) as m from nation) a"
    " full outer join"
    " (select mod(s_suppkey, 6) as m from supplier) b"
    " on a.m = b.m",
]


@needs_full_join_oracle
@pytest.mark.parametrize("i", range(len(CASES)))
def test_full_outer(env, i):
    runner, oracle = env
    sql = CASES[i]
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


@needs_full_join_oracle
def test_right_outer(env):
    runner, oracle = env
    sql = ("select n_name, s_name from supplier"
           " right outer join nation on s_nationkey = n_nationkey")
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_where_above_outer_join_over_reordered_cluster():
    """Join reordering permutes the inner-join cluster's channel layout;
    a WHERE above an enclosing LEFT JOIN must still bind to the right
    columns (r4 fix: _plan_join_rel dropped the reorder mapping, so
    predicates above the outer join read arbitrary channels — silent
    wrong results when the types happened to align)."""
    import jax  # noqa: F401
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner

    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.01, split_rows=4096))
    r = QueryRunner(cat)
    # 4-relation inner cluster (reorderable) under a LEFT JOIN, with a
    # WHERE that references columns from several cluster relations
    sql = """
    SELECT count(*) AS n,
           sum(CASE WHEN s_suppkey IS NULL THEN 1 ELSE 0 END) AS no_supp
    FROM lineitem
    JOIN orders ON l_orderkey = o_orderkey
    JOIN customer ON o_custkey = c_custkey
    JOIN nation ON c_nationkey = n_nationkey
    LEFT OUTER JOIN (SELECT s_suppkey FROM supplier WHERE s_suppkey < 50) s
      ON l_suppkey = s_suppkey
    WHERE n_name = 'FRANCE' AND o_orderpriority = '1-URGENT'
      AND l_quantity < 10
    """
    got = r.execute(sql).rows
    # oracle: same aggregation with the cluster unreordered (comma FROM
    # binds the WHERE through the top-level g2c path, which was always
    # correct); the left-join miss set is exactly l_suppkey >= 50
    flat = r.execute("""
    SELECT count(*) AS n,
           sum(CASE WHEN l_suppkey >= 50 THEN 1 ELSE 0 END) AS no_supp
    FROM lineitem, orders, customer, nation
    WHERE l_orderkey = o_orderkey AND o_custkey = c_custkey
      AND c_nationkey = n_nationkey
      AND n_name = 'FRANCE' AND o_orderpriority = '1-URGENT'
      AND l_quantity < 10
    """).rows
    assert got[0][0] == flat[0][0] and got[0][0] > 0
    assert got[0][1] == flat[0][1]
