"""REST protocol round-trip tests.

Reference analog: the in-process DistributedQueryRunner pattern
(presto-tests/.../DistributedQueryRunner.java:69 — real HTTP servers on
random localhost ports inside the test JVM) exercising
StatementResource's paging protocol end to end."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.client import StatementClient
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner
from presto_tpu.server import CoordinatorServer
from presto_tpu.cli import format_table


@pytest.fixture(scope="module")
def server():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    srv = CoordinatorServer(QueryRunner(catalog))
    srv.start()
    yield srv
    srv.stop()


def test_statement_roundtrip(server):
    client = StatementClient(server.uri)
    columns, rows = client.execute("select count(*) as n from orders")
    assert columns[0]["name"] == "n"
    assert rows == [(1500,)]


def test_result_paging(server):
    client = StatementClient(server.uri)
    _, rows = client.execute("select o_orderkey from orders")
    assert len(rows) == 1500  # spans multiple 1000-row pages


def test_error_propagation(server):
    client = StatementClient(server.uri)
    with pytest.raises(RuntimeError):
        client.execute("select bogus_column from orders")


def test_info_and_query_list(server):
    client = StatementClient(server.uri)
    info = client.server_info()
    assert info["coordinator"] is True
    client.execute("select 1 as x")
    qs = client.queries()
    assert any(q["state"] == "FINISHED" for q in qs)


def test_non_query_statements_over_rest(server):
    client = StatementClient(server.uri)
    _, rows = client.execute("show tables")
    assert ("lineitem",) in rows
    cols, rows = client.execute("explain select count(*) from orders")
    assert "Aggregation" in rows[0][0]


def test_cli_format():
    out = format_table(["a", "bb"], [(1, "x"), (22, None)])
    lines = out.splitlines()
    assert lines[0].startswith("a ") and "bb" in lines[0]
    assert "NULL" in lines[3]
