"""Table writes (CTAS/INSERT/DROP) + access control.

Reference analogs: TableWriterOperator/TableFinishOperator (the write
path), presto-memory writes, security/AccessControlManager +
FileBasedSystemAccessControl."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner
from presto_tpu.security import AccessDeniedError, RuleBasedAccessControl
from presto_tpu.session import Session


@pytest.fixture()
def runner():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    catalog.register("mem", MemoryConnector(), writable=True)
    return QueryRunner(catalog)


def test_ctas_and_query(runner):
    res = runner.execute(
        "create table big_orders as select o_orderkey, o_totalprice from orders where o_totalprice > 150000"
    )
    n = res.rows[0][0]
    assert n > 0
    res2 = runner.execute("select count(*) from big_orders")
    assert res2.rows == [(n,)]


def test_insert_appends(runner):
    runner.execute("create table t1 as select o_orderkey from orders limit 10")
    runner.execute("insert into t1 select o_orderkey from orders limit 5")
    assert runner.execute("select count(*) from t1").rows == [(15,)]


def test_insert_schema_mismatch(runner):
    runner.execute("create table t2 as select o_orderkey from orders limit 1")
    with pytest.raises(ValueError):
        runner.execute("insert into t2 select o_orderdate from orders limit 1")


def test_drop_table(runner):
    runner.execute("create table t3 as select 1 as x")
    runner.execute("drop table t3")
    # typed SPI error, not a raw KeyError (the binder's statement
    # boundary wraps internal exceptions — engine_lint spi-exception)
    from presto_tpu.sql.binder import BindError

    with pytest.raises(BindError, match="not found"):
        runner.execute("select * from t3")


def test_ctas_preserves_strings(runner):
    runner.execute("create table n2 as select n_name, n_regionkey from nation")
    rows = runner.execute("select n_name from n2 where n_regionkey = 3").rows
    assert ("FRANCE",) in rows


def test_access_control():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    catalog.register("mem", MemoryConnector(), writable=True)
    ac = RuleBasedAccessControl([
        ("admin", "*", True, True),
        ("analyst", "orders", True, False),
        ("analyst", "nation", True, False),
    ])
    analyst = QueryRunner(catalog, session=Session(user="analyst"), access_control=ac)
    assert analyst.execute("select count(*) from orders").rows == [(1500,)]
    with pytest.raises(AccessDeniedError):
        analyst.execute("select count(*) from customer")
    with pytest.raises(AccessDeniedError):
        analyst.execute("create table x as select * from nation")

    admin = QueryRunner(catalog, session=Session(user="admin"), access_control=ac)
    admin.execute("create table x as select n_nationkey from nation")
    assert admin.execute("select count(*) from x").rows == [(25,)]
