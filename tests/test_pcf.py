"""PCF single-file columnar format (presto-orc analog): stripes,
per-stripe stats, adaptive dictionary encoding, real codecs, lazy
column reads, and end-to-end SQL over the PcfConnector."""

import os

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.page import Page
from presto_tpu.runner import QueryRunner
from presto_tpu.storage.pcf import PcfConnector, PcfFile, write_pcf
from presto_tpu.types import (
    BIGINT, DOUBLE, DATE, VARCHAR, DecimalType, VarcharType,
)


def _pages():
    rng = np.random.default_rng(7)
    pages = []
    for k in range(3):
        n = 1000
        pages.append(Page.from_arrays(
            [np.arange(k * n, (k + 1) * n, dtype=np.int64),
             rng.normal(size=n),
             rng.integers(0, 3, n).astype(np.int32),
             rng.integers(100, 999, n).astype(np.int64)],
            [BIGINT, DOUBLE, VARCHAR, DecimalType(10, 2)],
            valids=[None, np.asarray(np.arange(n) % 7 != 0), None, None],
            dictionaries=[None,
                          None,
                          __import__("presto_tpu.page", fromlist=["Dictionary"])
                          .Dictionary(["red", "green", "blue"]),
                          None],
        ))
    return pages


SCHEMA = [("k", BIGINT), ("x", DOUBLE), ("color", VARCHAR),
          ("amt", DecimalType(10, 2))]


@pytest.fixture()
def pcf_path(tmp_path):
    path = str(tmp_path / "t.pcf")
    write_pcf(path, SCHEMA, _pages())
    return path


def test_roundtrip_and_stats(pcf_path):
    f = PcfFile(pcf_path)
    assert f.num_stripes == 3
    assert f.stripe_rows(0) == 1000
    st = f.stripe_stats(1)
    assert st["k"] == (1000, 1999)  # per-stripe min/max
    page = f.read_stripe(0)
    rows = page.compact_host().to_pylist()
    assert len(rows) == 1000
    assert rows[1][0] == 1 and rows[2][2] in ("red", "green", "blue")
    # NULLs survive
    assert rows[0][1] is None


def test_lazy_column_reads(pcf_path):
    f = PcfFile(pcf_path)
    f.read_stripe(0, columns=["k"])
    one_col = f.bytes_read
    f2 = PcfFile(pcf_path)
    f2.read_stripe(0)
    assert one_col < f2.bytes_read / 2  # projection reads far less


def test_adaptive_dictionary_encoding(tmp_path):
    # low-cardinality raw varchar: dict encoding must engage and shrink
    t = VarcharType(16, raw=True)
    vals = np.zeros((5000, 16), dtype=np.uint8)
    for i in range(5000):
        s = b"ab" if i % 2 else b"cd"
        vals[i, :len(s)] = np.frombuffer(s, dtype=np.uint8)
    page = Page.from_arrays([vals], [t])
    p_dict = str(tmp_path / "d.pcf")
    write_pcf(p_dict, [("s", t)], [page], compression="raw")
    f = PcfFile(p_dict)
    meta = f.stripes[0]["columns"]["s"]
    assert meta["enc"] == "dict" and meta["dict_rows"] == 2
    assert meta["len"] < 5000 * 16 / 2
    data, valid = f.read_column(0, "s")
    assert bytes(data[0][:2]) == b"cd" and bytes(data[1][:2]) == b"ab"


def test_codecs(tmp_path):
    for codec in ("raw", "zlib", "lzma"):
        path = str(tmp_path / f"c_{codec}.pcf")
        write_pcf(path, SCHEMA, _pages(), compression=codec)
        f = PcfFile(path)
        assert f.read_stripe(2).compact_host().to_pylist()[0][0] == 2000
    # compressible data actually shrinks under zlib
    raw = os.path.getsize(str(tmp_path / "c_raw.pcf"))
    z = os.path.getsize(str(tmp_path / "c_zlib.pcf"))
    assert z < raw


def test_sql_over_pcf_connector(tmp_path):
    write_pcf(str(tmp_path / "t.pcf"), SCHEMA, _pages())
    cat = Catalog()
    cat.register("pcf", PcfConnector(str(tmp_path)))
    r = QueryRunner(cat)
    assert r.execute("select count(*) from t").rows == [(3000,)]
    rows = r.execute(
        "select color, count(*), sum(amt) from t group by color order by 1").rows
    assert [x[0] for x in rows] == ["blue", "green", "red"]
    # stripe pruning: k >= 2000 only lives in stripe 2
    got = r.execute("select count(*) from t where k >= 2000").rows
    assert got == [(1000,)]


def test_stripe_pruning_skips_io(tmp_path):
    write_pcf(str(tmp_path / "t.pcf"), SCHEMA, _pages())
    conn = PcfConnector(str(tmp_path))
    cat = Catalog()
    cat.register("pcf", conn)
    r = QueryRunner(cat)
    r.execute("select count(*) from t where k < 500")  # stripe 0 only
    f = conn._files["t"]
    before = f.bytes_read
    r.execute("select count(*) from t where k < 500")
    # plan caching may rescan; the point: pruned stripes read nothing
    assert f.bytes_read <= before * 2
