"""GROUPING SETS / ROLLUP / CUBE vs hand-expanded UNION ALL oracles.

Reference analog: operator/GroupIdOperator.java + the analyzer's
grouping-set expansion (StatementAnalyzer.analyzeGroupBy); sqlite has no
grouping sets, so the oracle side is the UNION ALL expansion of each
set, which is the defining semantics."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


CASES = [
    (
        "select n_regionkey, n_nationkey, count(*) from nation"
        " group by rollup(n_regionkey, n_nationkey)",
        "select n_regionkey, n_nationkey, count(*) from nation group by n_regionkey, n_nationkey"
        " union all select n_regionkey, null, count(*) from nation group by n_regionkey"
        " union all select null, null, count(*) from nation",
    ),
    (
        "select n_regionkey, count(*), sum(n_nationkey) from nation"
        " group by cube(n_regionkey)",
        "select n_regionkey, count(*), sum(n_nationkey) from nation group by n_regionkey"
        " union all select null, count(*), sum(n_nationkey) from nation",
    ),
    (
        "select s_nationkey, s_suppkey, max(s_acctbal) from supplier"
        " group by grouping sets ((s_nationkey), (s_suppkey), ())",
        "select s_nationkey, null, max(s_acctbal) from supplier group by s_nationkey"
        " union all select null, s_suppkey, max(s_acctbal) from supplier group by s_suppkey"
        " union all select null, null, max(s_acctbal) from supplier",
    ),
    (
        # mixed plain + rollup: cartesian concatenation
        "select n_regionkey, n_nationkey, count(*) from nation"
        " group by n_regionkey, rollup(n_nationkey)",
        "select n_regionkey, n_nationkey, count(*) from nation group by n_regionkey, n_nationkey"
        " union all select n_regionkey, null, count(*) from nation group by n_regionkey",
    ),
    (
        # string keys through grouping sets (dictionary channels)
        "select r_name, count(*) from region group by rollup(r_name)",
        "select r_name, count(*) from region group by r_name"
        " union all select null, count(*) from region",
    ),
    (
        # aggregation over a join with rollup
        "select r_name, n_name, count(*) from nation, region"
        " where n_regionkey = r_regionkey group by rollup(r_name, n_name)",
        "select r_name, n_name, count(*) from nation, region"
        " where n_regionkey = r_regionkey group by r_name, n_name"
        " union all select r_name, null, count(*) from nation, region"
        " where n_regionkey = r_regionkey group by r_name"
        " union all select null, null, count(*) from nation, region"
        " where n_regionkey = r_regionkey",
    ),
]


@pytest.mark.parametrize("i", range(len(CASES)))
def test_grouping_sets(env, i):
    runner, oracle = env
    sql, oracle_sql = CASES[i]
    expected = run_oracle(oracle, oracle_sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_rollup_empty_input(env):
    """Empty input still yields one row per empty grouping set —
    the () set behaves like a global aggregate."""
    runner, _ = env
    rows = runner.execute(
        "select n_regionkey, count(*), sum(n_nationkey) from nation"
        " where n_nationkey < 0 group by rollup(n_regionkey)"
    ).rows
    assert rows == [(None, 0, None)]
    rows = runner.execute(
        "select n_regionkey, n_nationkey, count(*) from nation"
        " where n_nationkey < 0 group by cube(n_regionkey, n_nationkey)"
    ).rows
    assert rows == [(None, None, 0)]


def test_rollup_cube_parse_shapes(env):
    runner, _ = env
    # cube over two keys = 4 grouping sets
    rows = runner.execute(
        "select n_regionkey, count(*) from nation group by cube(n_regionkey, n_nationkey)"
    ).rows
    # 5 regions x nations(25) + 5 regions + 25 nations + 1 global
    assert len(rows) == 25 + 5 + 25 + 1


def test_grouping_function_rollup():
    """grouping(a, b) bitmask over ROLLUP levels
    (sql/tree/GroupingOperation.java). Also a regression pin for a
    once-observed row drop through the ORDER BY merge path."""
    import numpy as np

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.page import Page
    from presto_tpu.runner import QueryRunner
    from presto_tpu.types import BIGINT

    mem = MemoryConnector()
    mem.create_table(
        "gt", [("a", BIGINT), ("b", BIGINT), ("v", BIGINT)],
        [Page.from_arrays([np.array([1, 1, 2]), np.array([10, 20, 10]),
                           np.array([5, 6, 7])], [BIGINT] * 3)])
    cat = Catalog()
    cat.register("mem", mem)
    r = QueryRunner(cat)
    for _ in range(3):
        rows = r.execute(
            "SELECT a, b, grouping(a, b), sum(v) FROM gt "
            "GROUP BY ROLLUP(a, b) ORDER BY 3, 1, 2").rows
        assert rows == [
            (1, 10, 0, 5), (1, 20, 0, 6), (2, 10, 0, 7),
            (1, None, 1, 11), (2, None, 1, 7), (None, None, 3, 18)]
    # grouping() without grouping sets is a bind error
    import pytest

    from presto_tpu.sql.binder import BindError

    with pytest.raises(BindError):
        r.execute("SELECT a, grouping(a) FROM gt GROUP BY a")
