"""Plugin loading + authenticating proxy.

Reference analogs: server/PluginManager.java + spi/Plugin.java and
presto-proxy (ProxyResource.java).
"""

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.runner import QueryRunner


PLUGIN_SRC = '''
import numpy as np

from presto_tpu.page import Page
from presto_tpu.types import BIGINT


class FortyTwoConnector:
    """One table, one column, rows of 42."""

    def __init__(self, rows):
        self.rows = rows

    def table_names(self):
        return ["answers"]

    def schema(self, table):
        return [("v", BIGINT)]

    def num_splits(self, table):
        return 1

    def row_count(self, table):
        return self.rows

    def page_for_split(self, table, split, capacity=None):
        return Page.from_arrays([np.full(self.rows, 42)], [BIGINT])


PLUGIN = {
    "name": "fortytwo",
    "connector_factories": {
        "fortytwo": lambda props: FortyTwoConnector(int(props.get("rows", "3"))),
    },
}
'''


def test_plugin_loading_and_catalog_build(tmp_path):
    from presto_tpu.config import EngineConfig
    from presto_tpu.plugin import PluginManager

    pdir = tmp_path / "plugin"
    pdir.mkdir()
    (pdir / "fortytwo.py").write_text(PLUGIN_SRC)

    pm = PluginManager()
    assert pm.load_directory(str(pdir)) == ["fortytwo"]

    etc = tmp_path / "etc"
    (etc / "catalog").mkdir(parents=True)
    (etc / "config.properties").write_text(f"plugin.dir={pdir}\n")
    (etc / "catalog" / "ans.properties").write_text(
        "connector.name=fortytwo\nrows=4\n")
    cfg = EngineConfig.from_etc(str(etc))
    catalog = cfg.build_catalog()
    r = QueryRunner(catalog)
    assert r.execute("SELECT count(*), sum(v) FROM answers").rows == [(4, 168)]


def test_plugin_requires_declaration(tmp_path):
    from presto_tpu.plugin import PluginManager

    bad = tmp_path / "bad.py"
    bad.write_text("x = 1\n")
    with pytest.raises(ValueError):
        PluginManager().load_file(str(bad))


def test_proxy_forwards_and_rewrites(tmp_path):
    import json
    import urllib.request

    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.server.proxy import ProxyServer

    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001))
    coord = CoordinatorServer(QueryRunner(cat))
    coord.start()
    proxy = ProxyServer(coord.uri, token="sekrit")
    proxy.start()
    try:
        # unauthorized
        req = urllib.request.Request(proxy.uri + "/v1/statement",
                                     data=b"SELECT 1", method="POST")
        try:
            urllib.request.urlopen(req, timeout=10)
            assert False, "expected 401"
        except urllib.error.HTTPError as e:
            assert e.code == 401
        # authorized end-to-end through the proxy, nextUri stays proxied
        req = urllib.request.Request(
            proxy.uri + "/v1/statement",
            data=b"SELECT count(*) FROM region", method="POST",
            headers={"Authorization": "Bearer sekrit"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            out = json.loads(resp.read())
        rows = list(out.get("data") or [])
        uri = out.get("nextUri")
        while uri:
            assert uri.startswith(proxy.uri), uri
            req = urllib.request.Request(
                uri, headers={"Authorization": "Bearer sekrit"})
            with urllib.request.urlopen(req, timeout=30) as resp:
                out = json.loads(resp.read())
            rows += list(out.get("data") or [])
            uri = out.get("nextUri")
        assert rows == [[5]]
    finally:
        proxy.stop()
        coord.stop()
