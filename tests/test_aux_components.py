"""Blackhole connector, system tables, resource groups.

Reference analogs: presto-blackhole, connector/system (runtime
tables), execution/resourceGroups/InternalResourceGroup."""

import threading
import time

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.blackhole import BlackholeConnector
from presto_tpu.connectors.system import QueryHistory, SystemConnector
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.resource_groups import QueryQueueFullError, ResourceGroup, ResourceGroupManager
from presto_tpu.runner import QueryRunner
from presto_tpu.types import BIGINT


def test_blackhole():
    bh = BlackholeConnector()
    bh.create_table("sink", [("x", BIGINT)], splits=3, rows_per_split=10)
    catalog = Catalog()
    catalog.register("blackhole", bh)
    runner = QueryRunner(catalog)
    res = runner.execute("select count(*) from sink")
    assert res.rows == [(30,)]


def test_blackhole_latency():
    bh = BlackholeConnector()
    bh.create_table("slow", [("x", BIGINT)], splits=2, rows_per_split=1,
                    page_latency_s=0.05)
    catalog = Catalog()
    catalog.register("blackhole", bh)
    runner = QueryRunner(catalog)
    t0 = time.time()
    runner.execute("select count(*) from slow")
    assert time.time() - t0 >= 0.1


def test_system_runtime_queries():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    history = QueryHistory()
    catalog.register("system", SystemConnector(history))
    runner = QueryRunner(catalog)
    runner.events.add(history)

    runner.execute("select count(*) from nation")
    res = runner.execute("select state, rows from system_runtime_queries")
    assert ("FINISHED", 1) in [(r[0], r[1]) for r in res.rows]
    nodes = runner.execute("select node_id, state from system_runtime_nodes")
    assert nodes.rows == [("local", "ACTIVE")]
    # distributed-tier fallback accounting (VERDICT weak #8): local
    # runs report NULL stages/fallback; the count-of-fallbacks query
    # the issue asks for executes
    fb = runner.execute(
        "select count(*) from system_runtime_queries"
        " where dist_fallback is not null")
    assert fb.rows[0][0] == 0
    cols = runner.execute(
        "select dist_stages, dist_fallback from system_runtime_queries")
    assert all(r == (None, None) for r in cols.rows)


def test_system_runtime_queries_records_fallback_reason():
    from presto_tpu.events import QueryCompletedEvent

    history = QueryHistory()
    history.query_completed(QueryCompletedEvent(
        "q1", "select 1", "presto", "FINISHED", 0.0, 0.1,
        rows=1, dist_stages=0, dist_fallback="plan has no scan leaf"))
    history.query_completed(QueryCompletedEvent(
        "q2", "select 2", "presto", "FINISHED", 0.0, 0.1,
        rows=1, dist_stages=3))
    catalog = Catalog()
    catalog.register("system", SystemConnector(history))
    runner = QueryRunner(catalog)
    res = runner.execute(
        "select query_id, dist_stages, dist_fallback"
        " from system_runtime_queries order by query_id")
    assert res.rows == [("q1", 0, "plan has no scan leaf"), ("q2", 3, None)]
    count = runner.execute(
        "select count(*) from system_runtime_queries"
        " where dist_fallback is not null")
    assert count.rows[0][0] == 1


def test_resource_group_concurrency():
    g = ResourceGroup("test", hard_concurrency=2, max_queued=10)
    running = []
    peak = []

    def job(i):
        def body():
            running.append(i)
            peak.append(len(running))
            time.sleep(0.05)
            running.remove(i)
        g.run(body)

    threads = [threading.Thread(target=job, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert max(peak) <= 2  # hard_concurrency respected


def test_resource_group_queue_full():
    g = ResourceGroup("tiny", hard_concurrency=1, max_queued=1)
    release = threading.Event()

    def hold():
        g.acquire()
        release.wait()
        g.release()

    t = threading.Thread(target=hold)
    t.start()
    time.sleep(0.05)

    # one more can queue...
    waiter = threading.Thread(target=lambda: g.run(lambda: None))
    waiter.start()
    time.sleep(0.05)
    # ...but the queue is now full
    with pytest.raises(QueryQueueFullError):
        g.acquire()
    release.set()
    t.join()
    waiter.join()


def test_hierarchical_groups():
    mgr = ResourceGroupManager(ResourceGroup("global", hard_concurrency=2))
    adhoc = mgr.root.subgroup("adhoc", hard_concurrency=2)
    etl = mgr.root.subgroup("etl", hard_concurrency=2)
    mgr.add_selector(lambda user: adhoc if user.startswith("a_") else etl)
    assert mgr.group_for("a_alice") is adhoc
    assert mgr.group_for("bob") is etl
    # parent cap binds across children
    adhoc.acquire()
    etl.acquire()
    with pytest.raises(TimeoutError):
        adhoc.acquire(timeout=0.05)
    adhoc.release()
    etl.release()
