"""Morsel-driven split scheduler (exec/tasks.py): reorder-buffer
ordering, backpressure, exception propagation, and the end-to-end
determinism contract — concurrency 1 vs 4 produce identical query
results across the TPC-H corpus (the existing oracle harness validates
the serial leg; the concurrent leg must match it row for row).

Reference analogs: execution/executor/TaskExecutor.java (bounded split
concurrency), morsel-driven parallelism (Leis et al. SIGMOD 2014).
"""

import threading
import time

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.exec.tasks import (
    SchedulerStats,
    SplitScheduler,
    prefetch_iter,
    set_task_concurrency,
    task_concurrency_default,
)
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle
from tests.tpch_queries import QUERIES


# ---------------------------------------------------------------------------
# scheduler unit behavior
# ---------------------------------------------------------------------------

def test_serial_concurrency_is_plain_generator():
    s = SplitScheduler(concurrency=1)
    seen = []

    def items():
        for i in range(5):
            seen.append(i)
            yield i

    gen = s.map(items(), lambda x: x * 2)
    assert seen == []  # nothing pulled until the consumer asks
    assert next(gen) == 0
    assert seen == [1] or seen == [1, 2] or len(seen) <= 2
    assert list(gen) == [2, 4, 6, 8]
    assert s.stats.splits == 5


def test_ordered_delivery_reorders_out_of_order_completions():
    """Split 0 takes far longer than splits 1..7; the reorder buffer
    must still deliver source order."""

    def fn(i):
        if i == 0:
            time.sleep(0.2)
        return i * 10

    s = SplitScheduler(concurrency=4, prefetch=2, ordered=True)
    assert list(s.map(range(8), fn)) == [i * 10 for i in range(8)]


def test_unordered_delivery_is_completion_order():
    """With one slow head split and unordered delivery, faster splits
    arrive first — completion order, not source order."""

    def fn(i):
        if i == 0:
            time.sleep(0.25)
        return i

    s = SplitScheduler(concurrency=4, prefetch=2, ordered=False)
    out = list(s.map(range(6), fn))
    assert sorted(out) == list(range(6))  # nothing lost or duplicated
    assert out[0] != 0  # the slow head split did NOT arrive first


def test_worker_exception_propagates_at_ordered_position():
    def fn(i):
        if i == 3:
            raise ValueError("split 3 blew up")
        return i

    s = SplitScheduler(concurrency=4, prefetch=2, ordered=True)
    gen = s.map(range(8), fn)
    assert [next(gen) for _ in range(3)] == [0, 1, 2]
    with pytest.raises(ValueError, match="split 3 blew up"):
        next(gen)


def test_source_exception_propagates():
    def items():
        yield 1
        yield 2
        raise RuntimeError("source died")

    s = SplitScheduler(concurrency=2, ordered=True)
    gen = s.map(items(), lambda x: x)
    assert next(gen) == 1
    assert next(gen) == 2
    with pytest.raises(RuntimeError, match="source died"):
        next(gen)


def test_early_close_stops_threads():
    """A consumer that stops early (LIMIT) must not leak producer or
    worker threads, and must stop draining the source."""
    produced = []

    def items():
        for i in range(1000):
            produced.append(i)
            yield i

    before = threading.active_count()
    s = SplitScheduler(concurrency=3, prefetch=1)
    gen = s.map(items(), lambda x: x)
    assert next(gen) == 0
    gen.close()
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before
    # window-bounded production: far from the full source
    assert len(produced) <= 3 + 1 + 2


def test_backpressure_bounds_inflight():
    """At most concurrency + prefetch items are outstanding between
    source and consumer."""
    outstanding = [0]
    peak = [0]
    lock = threading.Lock()

    def items():
        for i in range(40):
            with lock:
                outstanding[0] += 1
                peak[0] = max(peak[0], outstanding[0])
            yield i

    def consume(gen):
        for _ in gen:
            with lock:
                outstanding[0] -= 1
            time.sleep(0.002)  # slow consumer

    s = SplitScheduler(concurrency=2, prefetch=1)
    consume(s.map(items(), lambda x: x))
    assert peak[0] <= 2 + 1 + 1  # window, +1 for the one being yielded


def test_headroom_probe_defers_dispatch():
    """With a closed headroom probe, only the guaranteed-progress split
    runs at a time (dispatch defers while the probe is False)."""
    running = [0]
    peak = [0]
    lock = threading.Lock()

    def fn(i):
        with lock:
            running[0] += 1
            peak[0] = max(peak[0], running[0])
        time.sleep(0.01)
        with lock:
            running[0] -= 1
        return i

    s = SplitScheduler(concurrency=4, prefetch=2, headroom=lambda: False)
    out = list(s.map(range(10), fn))
    assert out == list(range(10))
    # headroom=False still guarantees progress but in-flight stays ~1
    # (the one dispatch the progress guarantee admits, plus scheduling
    # slack of one)
    assert peak[0] <= 2


def test_prefetch_iter_preserves_order_and_overlaps():
    done = []

    def items():
        for i in range(6):
            done.append(i)
            yield i

    out = list(prefetch_iter(items(), depth=2))
    assert out == list(range(6))
    assert done == list(range(6))


def test_stats_accumulate():
    stats = SchedulerStats()
    s = SplitScheduler(concurrency=2, prefetch=1, stats=stats)
    list(s.map(range(7), lambda x: x))
    assert stats.splits == 7
    assert stats.concurrency == 2
    s2 = SplitScheduler(concurrency=4, stats=stats)
    list(s2.map(range(3), lambda x: x))
    assert stats.splits == 10
    assert stats.concurrency == 4
    d = stats.as_dict()
    assert d["splits"] == 10 and d["concurrency"] == 4


def test_env_default_resolves_once():
    base = task_concurrency_default()
    try:
        set_task_concurrency(7)
        assert task_concurrency_default() == 7
        set_task_concurrency(0)  # floor clamps to 1
        assert task_concurrency_default() == 1
    finally:
        set_task_concurrency(base)


# ---------------------------------------------------------------------------
# end-to-end: determinism, accounting, observability
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def env():
    # small splits so every scan is genuinely multi-split (lineitem
    # ~60k rows -> ~15 splits) and the worker pool has real work
    tpch = Tpch(sf=0.01, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    runner = QueryRunner(catalog)
    oracle = load_oracle(tpch)
    return runner, oracle


#: corpus slice for the determinism property: scan-heavy (q1/q6),
#: join+agg (q3/q14), semi-join/exists (q4), multi-join (q9), TopN
#: prefix order sensitivity (q2), SYSTEM-sampling-free global shapes
DETERMINISM_QIDS = [1, 2, 3, 4, 6, 9, 14, 18]


@pytest.mark.parametrize("qid", DETERMINISM_QIDS)
def test_concurrency_matches_serial_results(env, qid):
    """concurrency 4 must produce IDENTICAL rows to concurrency 1 (the
    serial A/B leg), which itself is validated against the sqlite
    oracle — the scheduler may change timing, never results."""
    runner, oracle = env
    sql = QUERIES[qid]
    runner.execute("SET SESSION task_concurrency = 1")
    serial = runner.execute(sql).rows
    runner.execute("SET SESSION task_concurrency = 4")
    try:
        concurrent = runner.execute(sql).rows
    finally:
        runner.execute("RESET SESSION task_concurrency")
    assert serial == concurrent  # byte-identical, order included
    assert_rows_match(concurrent, run_oracle(oracle, sql), ordered=False)


def test_agg_over_limit_subquery_deterministic(env):
    """The unordered-delivery grant must never reach a scan chain that
    feeds a LIMIT: the outer (serial, breaker-leaf) chain pops and
    discards the grant, so the limited row set is scheduling-invariant."""
    runner, _ = env
    sql = ("select count(*), sum(l_orderkey) from "
           "(select l_orderkey from lineitem where l_quantity < 30 "
           "limit 1000)")
    runner.execute("SET SESSION task_concurrency = 1")
    serial = runner.execute(sql).rows
    runner.execute("SET SESSION task_concurrency = 4")
    try:
        for _ in range(3):
            assert runner.execute(sql).rows == serial
    finally:
        runner.execute("RESET SESSION task_concurrency")


def test_early_close_drops_unexecuted_items():
    """Items produced but never executed when the consumer closes
    early are handed to the ``drop`` callback (the executor frees their
    scan_page reservations there) — every produced item is either
    executed or dropped, never silently discarded."""
    executed, dropped = [], []

    def fn(i):
        time.sleep(0.02)
        executed.append(i)
        return i

    s = SplitScheduler(concurrency=2, prefetch=3, ordered=True,
                       drop=dropped.append)
    gen = s.map(iter(range(50)), fn)
    assert next(gen) == 0
    gen.close()
    # the prefetch window was full of produced-but-unexecuted items;
    # each must have been dropped exactly once
    assert dropped, "queued items were discarded without drop()"
    assert not (set(dropped) & set(executed))
    leaked = set(range(max(executed + dropped) + 1)) \
        - set(executed) - set(dropped)
    assert not leaked, f"items neither executed nor dropped: {leaked}"


def test_system_sampling_deterministic_under_concurrency(env):
    """TABLESAMPLE SYSTEM keeps whole splits by a split-hash — the
    kept-split set (and row order) must not depend on scheduling."""
    runner, _ = env
    sql = ("select count(*), sum(l_quantity) from lineitem "
           "tablesample system (40)")
    runner.execute("SET SESSION task_concurrency = 1")
    serial = runner.execute(sql).rows
    runner.execute("SET SESSION task_concurrency = 4")
    try:
        concurrent = runner.execute(sql).rows
    finally:
        runner.execute("RESET SESSION task_concurrency")
    assert serial == concurrent


def test_limit_early_exit_under_concurrency(env):
    runner, _ = env
    runner.execute("SET SESSION task_concurrency = 4")
    try:
        before = threading.active_count()
        rows = runner.execute(
            "select l_orderkey from lineitem limit 5").rows
        assert len(rows) == 5
        deadline = time.monotonic() + 5.0
        while threading.active_count() > before \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        assert threading.active_count() <= before
    finally:
        runner.execute("RESET SESSION task_concurrency")


def test_worker_error_fails_query_cleanly(env):
    """An exception raised on a scheduler worker thread surfaces as an
    ordinary query failure on the caller, and the engine keeps serving
    queries afterwards."""
    from presto_tpu.exec.local import LocalRunner

    runner, _ = env
    plan = runner.plan("select sum(l_quantity) from lineitem")
    ex = LocalRunner(runner.catalog, task_concurrency=4)
    boom = RuntimeError("injected split failure")
    original = ex._source_pages

    def poisoned(node):
        for i, p in enumerate(original(node)):
            yield p
            if i == 1:
                raise boom

    ex._source_pages = poisoned
    with pytest.raises(RuntimeError, match="injected split failure"):
        ex.run(plan)
    # the shared runner is unaffected and keeps executing
    assert runner.execute("select count(*) from nation").rows == [(25,)]


def test_memory_pool_limit_held_and_spill_triggers_under_concurrency():
    """Backpressure under a small pool: the concurrency-4 run still
    routes oversized aggregation state through the spill path, holds
    every ENFORCED reservation under the pool limit, and matches the
    unconstrained result."""
    from presto_tpu.exec.local import LocalRunner
    from presto_tpu.memory import MemoryPool
    from presto_tpu.sql.binder import Binder

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.004, split_rows=1 << 12))
    binder = Binder(catalog)
    sql = ("select l_orderkey, count(*), sum(l_quantity) from lineitem "
           "group by l_orderkey")
    plan = binder.plan(sql)
    reference = LocalRunner(catalog, task_concurrency=1).run(plan)

    class AssertingPool(MemoryPool):
        """Every ENFORCED reservation must hold the limit (soft scan
        pages are exempt by contract — they are bounded by the
        scheduler window, not the pool)."""

        def __init__(self, limit):
            super().__init__(limit)
            self.enforced_peak = 0

        def reserve(self, tag, nbytes, enforce=True):
            super().reserve(tag, nbytes, enforce=enforce)
            if enforce:
                self.enforced_peak = max(self.enforced_peak, self.reserved)

    pool = AssertingPool(4 << 20)  # 4MB: far below the agg state
    ex = LocalRunner(catalog, memory_pool=pool, task_concurrency=4)
    out = ex.run(plan)
    assert sorted(out.rows) == sorted(reference.rows)
    assert pool.enforced_peak <= pool.limit  # limit held, not OOM'd


def test_explain_analyze_and_task_row_surface_scheduler(env):
    runner, _ = env
    runner.execute("SET SESSION task_concurrency = 4")
    try:
        text = runner.execute(
            "EXPLAIN ANALYZE select sum(l_quantity) from lineitem"
        ).rows[0][0]
        assert "task scheduler:" in text
        assert "concurrency 4" in text
    finally:
        runner.execute("RESET SESSION task_concurrency")
    from presto_tpu import obs

    entries = [e for e in obs.TASKS.entries()
               if e.concurrency == 4 and e.splits]
    assert entries, "no task entry carries the scheduler footprint"


def test_scheduler_metrics_preregistered():
    from presto_tpu import obs

    names = {n for n, _ in obs.METRICS.snapshot()}
    for metric in ("task.splits_dispatched",
                   "task.scheduler_stall_seconds_total",
                   "task.prefetch_hits", "task.prefetch_misses",
                   "task.splits_queued", "task.splits_running"):
        assert metric in names, metric


def test_system_runtime_tasks_columns(env):
    runner, _ = env
    from presto_tpu.connectors.system import QueryHistory, SystemConnector

    history = QueryHistory()
    runner.events.add(history)
    sys_conn = SystemConnector(history)
    runner.catalog.register("system", sys_conn)
    try:
        runner.execute("SET SESSION task_concurrency = 4")
        runner.execute("select count(*) from lineitem")
        runner.execute("RESET SESSION task_concurrency")
        rows = runner.execute(
            "select task_id, splits, task_concurrency, scheduler_stall_ms,"
            " prefetch_hits from system_runtime_tasks"
            " where task_concurrency = 4").rows
        assert rows, "no scheduler-annotated task rows"
        tid, splits, conc, stall, hits = rows[-1]
        assert splits >= 2 and conc == 4
        assert stall is not None and hits is not None
    finally:
        runner.catalog._connectors.pop("system", None)
        runner._invalidate_plans()
