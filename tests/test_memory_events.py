"""Memory accounting + event listener tests.

Reference analogs: memory limit enforcement (memory/MemoryPool.java,
ExceededMemoryLimitException) and the QueryMonitor -> EventListener
pipeline (event/query/QueryMonitor.java)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.events import EventListener
from presto_tpu.memory import ExceededMemoryLimitError, MemoryPool
from presto_tpu.runner import QueryRunner
from presto_tpu.verifier import Verifier


def make_runner(limit=None):
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    pool = MemoryPool(limit) if limit else None
    return QueryRunner(catalog, memory_pool=pool), pool


def test_memory_tracked_and_released():
    runner, pool = make_runner(limit=1 << 30)
    runner.execute(
        "select c_custkey, o_orderkey from customer, orders where c_custkey = o_custkey"
    )
    assert pool.peak > 0  # join build was charged
    assert pool.reserved == 0  # released at query end


def test_memory_limit_enforced():
    runner, pool = make_runner(limit=1 << 10)  # 1 KiB: any build blows it
    with pytest.raises(ExceededMemoryLimitError):
        runner.execute(
            "select count(*) from customer, orders where c_custkey = o_custkey"
        )
    assert pool.reserved == 0  # released even on failure


def test_event_listener():
    runner, _ = make_runner()
    seen = []

    class L(EventListener):
        def query_created(self, e):
            seen.append(("created", e.query_id))

        def query_completed(self, e):
            seen.append(("completed", e.state, e.rows))

    runner.events.add(L())
    runner.execute("select count(*) from nation")
    assert seen[0][0] == "created"
    assert seen[1] == ("completed", "FINISHED", 1)

    with pytest.raises(Exception):
        runner.execute("select no_such_column from nation")
    assert seen[-1][1] == "FAILED"


def test_verifier_match_and_mismatch():
    runner, _ = make_runner()

    v = Verifier(
        control=lambda sql: runner.execute(sql).rows,
        test=lambda sql: runner.execute(sql).rows,
    )
    res = v.verify({"ok": "select count(*) from nation"})
    assert res[0].status == "MATCH"

    v2 = Verifier(
        control=lambda sql: [(999,)],
        test=lambda sql: runner.execute(sql).rows,
    )
    res = v2.verify({"bad": "select count(*) from nation"})
    assert res[0].status == "MISMATCH"
