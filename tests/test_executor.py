"""Executor tests: hand-built plans vs numpy oracles on TPC-H data.

Reference analog: operator-level tests driving the Operator interface
with hand-built inputs (presto-main test OperatorAssertion pattern) and
LocalQueryRunner end-to-end checks.
"""

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.exec.local import LocalRunner
from presto_tpu.expr.ir import AggCall, call, col, lit
from presto_tpu.planner.plan import (
    AggregationNode,
    FilterNode,
    JoinNode,
    LimitNode,
    OutputNode,
    ProjectNode,
    SortNode,
    TableScanNode,
    TopNNode,
)
from presto_tpu.types import BIGINT, DATE, DOUBLE, DecimalType


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.01, split_rows=8192)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return tpch, catalog, LocalRunner(catalog)


def _scan(catalog, table, cols):
    h = catalog.resolve(table)
    names = [c.name for c in h.columns]
    return TableScanNode(h, [names.index(c) for c in cols]), h


def _full(tpch, table):
    """All splits of a table concatenated host-side."""
    parts = [tpch.generate_split(table, s) for s in range(tpch.num_splits(table))]
    return {k: np.concatenate([p[k] for p in parts]) for k in parts[0]}


DATE_1994 = (np.datetime64("1994-01-01") - np.datetime64("1970-01-01")).astype(int)
DATE_1995 = (np.datetime64("1995-01-01") - np.datetime64("1970-01-01")).astype(int)


def test_q6_shape(env):
    """TPC-H Q6: scan+filter+project+global agg (BASELINE.md config)."""
    tpch, catalog, runner = env
    scan, h = _scan(catalog, "lineitem", ["l_shipdate", "l_discount", "l_quantity", "l_extendedprice"])
    shipdate = col(0, DATE)
    discount = col(1, DecimalType(12, 2))
    quantity = col(2, DecimalType(12, 2))
    extprice = col(3, DecimalType(12, 2))
    pred = call(
        "and",
        call(
            "and",
            call("ge", shipdate, lit(DATE_1994, DATE)),
            call("lt", shipdate, lit(DATE_1995, DATE)),
        ),
        call(
            "and",
            call("between", discount, lit(5, DecimalType(12, 2)), lit(7, DecimalType(12, 2))),
            call("lt", quantity, lit(2400, DecimalType(12, 2))),
        ),
    )
    f = FilterNode(scan, pred)
    proj = ProjectNode(f, [call("mul", extprice, discount)], ["revenue"])
    agg = AggregationNode(
        proj, [], [], [AggCall("sum", col(0, DecimalType(18, 4)), DecimalType(18, 4))], ["revenue"]
    )
    out = OutputNode(agg, ["revenue"])
    res = runner.run(out)

    li = _full(tpch, "lineitem")
    sel = (
        (li["l_shipdate"] >= DATE_1994)
        & (li["l_shipdate"] < DATE_1995)
        & (li["l_discount"] >= 5)
        & (li["l_discount"] <= 7)
        & (li["l_quantity"] < 2400)
    )
    expected = (li["l_extendedprice"][sel] * li["l_discount"][sel]).sum() / 1e4
    assert len(res) == 1
    assert float(res.rows[0][0]) == pytest.approx(expected, rel=1e-12)


def test_q1_shape(env):
    """TPC-H Q1: grouped agg over returnflag/linestatus with the
    packed-direct path (dictionary keys, 6 groups)."""
    tpch, catalog, runner = env
    cols = ["l_returnflag", "l_linestatus", "l_quantity", "l_extendedprice", "l_discount", "l_tax", "l_shipdate"]
    scan, h = _scan(catalog, "lineitem", cols)
    rf, ls = col(0, h.column("l_returnflag").type), col(1, h.column("l_linestatus").type)
    qty = col(2, DecimalType(12, 2))
    price = col(3, DecimalType(12, 2))
    disc = col(4, DecimalType(12, 2))
    tax = col(5, DecimalType(12, 2))
    shipdate = col(6, DATE)
    cutoff = (np.datetime64("1998-09-02") - np.datetime64("1970-01-01")).astype(int) - 90
    f = FilterNode(scan, call("le", shipdate, lit(cutoff, DATE)))
    disc_price = call("mul", price, call("sub", lit(100, DecimalType(12, 2)), disc))
    charge = call("mul", disc_price, call("add", lit(100, DecimalType(12, 2)), tax))
    aggs = [
        AggCall("sum", qty, DecimalType(18, 2)),
        AggCall("sum", price, DecimalType(18, 2)),
        AggCall("sum", disc_price, disc_price.type),
        AggCall("sum", charge, charge.type),
        AggCall("avg", qty, DOUBLE),
        AggCall("count_star", None, BIGINT),
    ]
    agg = AggregationNode(
        f, [rf, ls], ["l_returnflag", "l_linestatus"], aggs,
        ["sum_qty", "sum_base_price", "sum_disc_price", "sum_charge", "avg_qty", "count_order"],
    )
    sort = SortNode(agg, [col(0, rf.type), col(1, ls.type)], [True, True])
    out = OutputNode(sort, agg.output_names)
    res = runner.run(out)

    li = _full(tpch, "lineitem")
    sel = li["l_shipdate"] <= cutoff
    rf_dict = tpch.dictionary_for("lineitem", "l_returnflag")
    ls_dict = tpch.dictionary_for("lineitem", "l_linestatus")
    keys = sorted(
        set(zip(li["l_returnflag"][sel].tolist(), li["l_linestatus"][sel].tolist()))
    )
    assert len(res) == len(keys)
    for row, (kr, kl) in zip(res.rows, keys):
        m = sel & (li["l_returnflag"] == kr) & (li["l_linestatus"] == kl)
        assert row[0] == rf_dict.decode(np.asarray([kr]))[0]
        assert row[1] == ls_dict.decode(np.asarray([kl]))[0]
        assert float(row[2]) == pytest.approx(li["l_quantity"][m].sum() / 100, rel=1e-12)
        assert float(row[3]) == pytest.approx(li["l_extendedprice"][m].sum() / 100, rel=1e-12)
        dp = li["l_extendedprice"][m] * (100 - li["l_discount"][m])
        assert float(row[4]) == pytest.approx(dp.sum() / 1e4, rel=1e-12)
        ch = dp * (100 + li["l_tax"][m])
        assert float(row[5]) == pytest.approx(ch.sum() / 1e6, rel=1e-12)
        # r4: avg(decimal) keeps scale -> compare at the rounded scale
        assert float(row[6]) == pytest.approx(
            round(li["l_quantity"][m].mean() / 100, 2), abs=0.006)
        assert row[7] == int(m.sum())


def test_join_unique_build(env):
    """lineitem ⋈ orders on orderkey (unique build side, streamed probe)."""
    tpch, catalog, runner = env
    li_scan, lh = _scan(catalog, "lineitem", ["l_orderkey", "l_extendedprice"])
    o_scan, oh = _scan(catalog, "orders", ["o_orderkey", "o_orderdate"])
    join = JoinNode(
        left=li_scan,
        right=o_scan,
        left_keys=[col(0, BIGINT)],
        right_keys=[col(0, BIGINT)],
        kind="inner",
        unique_build=True,
    )
    # filter post-join on o_orderdate < 1995-01-01, sum extendedprice
    f = FilterNode(join, call("lt", col(3, DATE), lit(DATE_1995, DATE)))
    agg = AggregationNode(
        f, [], [], [AggCall("sum", col(1, DecimalType(12, 2)), DecimalType(18, 2)),
                    AggCall("count_star", None, BIGINT)], ["s", "n"]
    )
    res = runner.run(OutputNode(agg, ["s", "n"]))

    li = _full(tpch, "lineitem")
    o = _full(tpch, "orders")
    odate = dict(zip(o["o_orderkey"].tolist(), o["o_orderdate"].tolist()))
    sel = np.asarray([odate[k] < DATE_1995 for k in li["l_orderkey"].tolist()])
    assert res.rows[0][1] == int(sel.sum())
    assert float(res.rows[0][0]) == pytest.approx(li["l_extendedprice"][sel].sum() / 100, rel=1e-12)


def test_expanding_join(env):
    """orders ⋈ lineitem on orderkey (non-unique build: ~4 lines/order)."""
    tpch, catalog, runner = env
    o_scan, oh = _scan(catalog, "orders", ["o_orderkey", "o_totalprice"])
    li_scan, lh = _scan(catalog, "lineitem", ["l_orderkey", "l_quantity"])
    join = JoinNode(
        left=o_scan,
        right=li_scan,
        left_keys=[col(0, BIGINT)],
        right_keys=[col(0, BIGINT)],
        kind="inner",
        unique_build=False,
    )
    agg = AggregationNode(
        join, [], [], [AggCall("count_star", None, BIGINT),
                       AggCall("sum", col(3, DecimalType(12, 2)), DecimalType(18, 2))], ["n", "q"]
    )
    res = runner.run(OutputNode(agg, ["n", "q"]))
    li = _full(tpch, "lineitem")
    assert res.rows[0][0] == len(li["l_orderkey"])  # every line matches its order
    assert float(res.rows[0][1]) == pytest.approx(li["l_quantity"].sum() / 100, rel=1e-12)


def test_semi_join(env):
    """customers with at least one order (semi join)."""
    tpch, catalog, runner = env
    c_scan, ch = _scan(catalog, "customer", ["c_custkey"])
    o_scan, oh = _scan(catalog, "orders", ["o_custkey"])
    join = JoinNode(
        left=c_scan, right=o_scan,
        left_keys=[col(0, BIGINT)], right_keys=[col(0, BIGINT)],
        kind="semi",
    )
    agg = AggregationNode(join, [], [], [AggCall("count_star", None, BIGINT)], ["n"])
    res = runner.run(OutputNode(agg, ["n"]))
    o = _full(tpch, "orders")
    assert res.rows[0][0] == len(np.unique(o["o_custkey"]))


def test_topn_and_limit(env):
    tpch, catalog, runner = env
    scan, h = _scan(catalog, "orders", ["o_orderkey", "o_totalprice"])
    topn = TopNNode(scan, [col(1, DecimalType(12, 2))], [False], 10)
    res = runner.run(OutputNode(topn, ["o_orderkey", "o_totalprice"]))
    o = _full(tpch, "orders")
    top10 = np.sort(o["o_totalprice"])[::-1][:10] / 100
    assert [float(r[1]) for r in res.rows] == pytest.approx(top10.tolist())

    lim = LimitNode(scan, 7)
    res2 = runner.run(OutputNode(lim, ["o_orderkey", "o_totalprice"]))
    assert len(res2) == 7


def test_grouped_join_agg(env):
    """Q3-ish: join + grouped agg via hash path (many groups)."""
    tpch, catalog, runner = env
    li_scan, lh = _scan(catalog, "lineitem", ["l_orderkey", "l_extendedprice", "l_discount"])
    o_scan, oh = _scan(catalog, "orders", ["o_orderkey", "o_orderdate", "o_shippriority"])
    join = JoinNode(
        left=li_scan, right=o_scan,
        left_keys=[col(0, BIGINT)], right_keys=[col(0, BIGINT)],
        kind="inner", unique_build=True,
    )
    rev = call("mul", col(1, DecimalType(12, 2)), call("sub", lit(100, DecimalType(12, 2)), col(2, DecimalType(12, 2))))
    proj = ProjectNode(join, [col(0, BIGINT), rev], ["l_orderkey", "rev"])
    agg = AggregationNode(
        proj, [col(0, BIGINT)], ["l_orderkey"],
        [AggCall("sum", col(1, rev.type), rev.type)], ["revenue"],
        max_groups=1 << 15,
    )
    topn = TopNNode(agg, [col(1, rev.type)], [False], 5)
    res = runner.run(OutputNode(topn, ["l_orderkey", "revenue"]))

    li = _full(tpch, "lineitem")
    revs = li["l_extendedprice"] * (100 - li["l_discount"])
    agg_map = {}
    for k, r in zip(li["l_orderkey"].tolist(), revs.tolist()):
        agg_map[k] = agg_map.get(k, 0) + r
    top = sorted(agg_map.values(), reverse=True)[:5]
    assert [float(r[1]) for r in res.rows] == pytest.approx([t / 1e4 for t in top], rel=1e-12)
