import jax
import numpy as np
import pytest

from presto_tpu.expr import compile_expr, compile_filter
from presto_tpu.expr.ir import call, col, lit
from presto_tpu.page import Dictionary, Page
from presto_tpu.types import BIGINT, BOOLEAN, DATE, DOUBLE, VARCHAR, DecimalType


def page_fixture():
    d = Dictionary(["AIR", "MAIL", "SHIP", "TRUCK"])
    return Page.from_arrays(
        [
            np.array([1, 2, 3, 4], dtype=np.int64),
            np.array([100, 250, 375, 500], dtype=np.int64),  # decimal(12,2)
            np.array([0.5, 1.5, 2.5, 3.5]),
            np.array([3, 0, 1, 2], dtype=np.int32),
            np.array([9204, 9215, 9226, 9237], dtype=np.int32),  # dates in 1995
        ],
        [BIGINT, DecimalType(12, 2), DOUBLE, VARCHAR, DATE],
        valids=[None, np.array([True, True, False, True]), None, None, None],
        dictionaries=[None, None, None, d, None],
    )


def run(e, page=None):
    page = page or page_fixture()
    f = compile_expr(e, page)
    d, v = f(page)
    return np.asarray(d), np.asarray(v)


def test_arith_bigint():
    p = page_fixture()
    d, v = run(call("add", col(0, BIGINT), lit(10, BIGINT)), p)
    assert d[:4].tolist() == [11, 12, 13, 14]
    assert v[:4].all()


def test_decimal_add_rescale():
    dec = DecimalType(12, 2)
    e = call("add", col(1, dec), lit(100, dec))  # +1.00
    d, v = run(e)
    assert d[:2].tolist() == [200, 350]
    assert v[:4].tolist() == [True, True, False, True]  # null propagates


def test_decimal_times_bigint_and_double():
    dec = DecimalType(12, 2)
    e = call("mul", col(1, dec), lit(2, BIGINT))
    assert e.type.scale == 2
    d, _ = run(e)
    assert d[0] == 200
    e2 = call("mul", col(1, dec), col(2, DOUBLE))
    assert e2.type is DOUBLE
    d2, _ = run(e2)
    assert d2[1] == pytest.approx(2.5 * 1.5)


def test_cmp_and_3vl_logic():
    dec = DecimalType(12, 2)
    ge = call("ge", col(1, dec), lit(250, dec))
    d, v = run(ge)
    assert d[[0, 1, 3]].tolist() == [False, True, True]
    assert not v[2]
    # null AND false = false (valid), null AND true = null
    false_lit = call("eq", lit(1, BIGINT), lit(2, BIGINT))
    e_and = call("and", ge, false_lit)
    d2, v2 = run(e_and)
    assert v2[2] and not d2[2]
    true_lit = call("eq", lit(1, BIGINT), lit(1, BIGINT))
    e_and2 = call("and", ge, true_lit)
    _, v3 = run(e_and2)
    assert not v3[2]


def test_between_dates():
    e = call("between", col(4, DATE), lit(9210, DATE), lit(9230, DATE))
    d, _ = run(e)
    assert d[:4].tolist() == [False, True, True, False]


def test_string_eq_and_in_and_like():
    p = page_fixture()
    e = call("eq", col(3, VARCHAR), lit("AIR", VARCHAR))
    d, _ = run(e, p)
    assert d[:4].tolist() == [False, True, False, False]
    e_in = call("in", col(3, VARCHAR), lit("AIR", VARCHAR), lit("SHIP", VARCHAR))
    d, _ = run(e_in, p)
    assert d[:4].tolist() == [False, True, False, True]
    e_like = call("like", col(3, VARCHAR), lit("%AI%", VARCHAR))
    d, _ = run(e_like, p)
    assert d[:4].tolist() == [False, True, True, False]  # AIR, MAIL
    e_like2 = call("like", col(3, VARCHAR), lit("A__", VARCHAR))
    d, _ = run(e_like2, p)
    assert d[:4].tolist() == [False, True, False, False]


def test_case_and_if():
    e = call(
        "case",
        call("eq", col(0, BIGINT), lit(1, BIGINT)), lit(10, BIGINT),
        call("eq", col(0, BIGINT), lit(2, BIGINT)), lit(20, BIGINT),
        lit(0, BIGINT),
    )
    d, v = run(e)
    assert d[:4].tolist() == [10, 20, 0, 0]
    assert v[:4].all()


def test_year_extract():
    e = call("year", col(4, DATE))
    d, _ = run(e)
    assert d[:4].tolist() == [1995, 1995, 1995, 1995]
    # check a specific date: 1995-03-15 = 9204 days
    import datetime
    assert (datetime.date(1970, 1, 1) + datetime.timedelta(days=9204)).year == 1995


def test_is_null_coalesce():
    dec = DecimalType(12, 2)
    d, v = run(call("is_null", col(1, dec)))
    assert d[:4].tolist() == [False, False, True, False]
    assert v[:4].all()
    d2, v2 = run(call("coalesce", col(1, dec), lit(-1, dec)))
    assert d2[2] == -1 and v2[:4].all()


def test_filter_masks_nulls():
    p = page_fixture()
    dec = DecimalType(12, 2)
    f = compile_filter(call("ge", col(1, dec), lit(0, dec)), p)
    mask = np.asarray(f(p))
    assert mask[:4].tolist() == [True, True, False, True]  # null row excluded


def test_compiled_expr_jits():
    p = page_fixture()
    e = call("mul", col(1, DecimalType(12, 2)), call("sub", lit(100, DecimalType(12, 2)), col(1, DecimalType(12, 2))))
    f = compile_expr(e, p)
    jf = jax.jit(lambda pg: f(pg))
    d, v = jf(p)
    assert np.asarray(d)[0] == 100 * (100 - 100)
