"""Cost-based join-order enumeration (VERDICT r2 #6).

Reference analog: iterative/rule/ReorderJoins.java +
cost/CostComparator.java + DetermineJoinDistributionType.java:33 — the
binder's DP over <=6-relation join graphs picks the min-cost order and
folds the broadcast-vs-partitioned exchange term into the same
comparison, instead of taking the FROM-clause order as given.
"""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.planner.plan import JoinNode, TableScanNode
from presto_tpu.runner import QueryRunner


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.01, split_rows=16384))
    return QueryRunner(catalog)


def _joins(node, out):
    if isinstance(node, JoinNode):
        out.append(node)
    for s in node.sources:
        _joins(s, out)
    return out


def _scan_table(node):
    n = node
    while n.sources:
        if isinstance(n, TableScanNode):
            break
        n = n.sources[0]
    return n.handle.table if isinstance(n, TableScanNode) else None


def _leaf_tables(node):
    if isinstance(node, TableScanNode):
        return {node.handle.table}
    out = set()
    for s in node.sources:
        out |= _leaf_tables(s)
    return out


def test_star_query_reordered_away_from_from_order(runner):
    """FROM lists the dimensions first; the fact table must still end
    up as the probe (left) spine with the dimensions as build sides."""
    sql = ("select count(*) from nation, region, supplier "
           "where s_nationkey = n_nationkey and n_regionkey = r_regionkey")
    plan = runner.plan(sql)
    joins = _joins(plan, [])
    assert joins, "no joins planned"
    # the top join's probe subtree must contain supplier (the fact);
    # neither dimension may have the fact on its build side
    top = joins[0]
    assert "supplier" in _leaf_tables(top.left)
    for j in joins:
        assert "supplier" not in _leaf_tables(j.right), (
            "fact table chosen as a build side")
    # and the result is right
    got = runner.execute(sql).rows[0][0]
    n = runner.execute("select count(*) from supplier").rows[0][0]
    assert got == n  # every supplier matches exactly one nation/region


def test_unique_build_orientation_preferred(runner):
    """orders (PK build) vs lineitem (fact): whatever the FROM order,
    the planner must probe with lineitem and build on orders so the
    streaming kernel applies."""
    for sql in (
        "select count(*) from lineitem, orders where l_orderkey = o_orderkey",
        "select count(*) from orders, lineitem where l_orderkey = o_orderkey",
    ):
        plan = runner.plan(sql)
        joins = _joins(plan, [])
        assert len(joins) == 1
        j = joins[0]
        assert "lineitem" in _leaf_tables(j.left)
        assert "orders" in _leaf_tables(j.right)
        assert j.unique_build


def test_cross_join_unique_build_needs_proof(runner):
    """A disconnected term whose ESTIMATE is tiny must still run the
    expanding kernel — unique_build only from structural proof
    (regression: a 12-row build estimated at 0 rows was streamed as
    'unique' and dropped matches)."""
    sql = ("select count(*) from nation, region "
           "where n_name <> 'FRANCE' and r_name = 'EUROPE'")
    plan = runner.plan(sql)
    joins = _joins(plan, [])
    assert len(joins) == 1
    assert not joins[0].unique_build  # filtered scan is not single-row
    got = runner.execute(sql).rows[0][0]
    n = runner.execute("select count(*) from nation where n_name <> 'FRANCE'").rows[0][0]
    assert got == n  # x1 region
