"""Cluster memory manager: pool polling + low-memory killer.

Reference analog: memory/ClusterMemoryManager.java +
TestTotalReservationLowMemoryKiller."""

import pytest

from presto_tpu.cluster_memory import (
    ClusterMemoryManager,
    query_reservations,
    total_reservation_low_memory_killer,
)
from presto_tpu.memory import MemoryPool, QueryMemoryContext


def test_killer_picks_biggest():
    assert total_reservation_low_memory_killer({"a": 10, "b": 99, "c": 5}) == "b"
    assert total_reservation_low_memory_killer({}) is None


def test_query_reservations_aggregates_tags():
    pool = MemoryPool(1 << 20)
    qa = QueryMemoryContext(pool, "qa")
    qb = QueryMemoryContext(pool, "qb")
    qa.reserve("join_build", 100)
    qa.reserve("agg", 50)
    qb.reserve("sort", 70)
    by_q = query_reservations(pool)
    assert by_q == {"qa": 150, "qb": 70}


def test_check_once_kills_over_threshold():
    pool = MemoryPool(1000)
    killed = []
    mgr = ClusterMemoryManager(pool, killed.append, threshold=0.5)
    QueryMemoryContext(pool, "small").reserve("x", 100)
    assert mgr.check_once() is None  # 10% < 50%
    QueryMemoryContext(pool, "big").reserve("y", 600)
    assert mgr.check_once() == "big"
    assert killed == ["big"]
    # the kill actually freed the victim's reservations (real relief)
    assert pool.reserved == 100
    assert mgr.check_once() is None  # back under threshold


def test_kill_escalates_and_interrupts():
    from presto_tpu.memory import QueryKilledError

    pool = MemoryPool(1000)
    killed = []
    mgr = ClusterMemoryManager(pool, killed.append, threshold=0.5)
    a = QueryMemoryContext(pool, "a")
    b = QueryMemoryContext(pool, "b")
    a.reserve("x", 500)
    b.reserve("y", 450)
    assert mgr.check_once() == "a"
    b.reserve("more", 400)  # b grows past the threshold next
    assert mgr.check_once() == "b"  # escalation, not re-killing a
    with pytest.raises(QueryKilledError):
        a.reserve("z", 10)  # the killed query dies at its next reserve


def test_concurrent_queries_share_runner():
    """Concurrent queries on one LocalRunner keep independent memory
    contexts and join-build state (thread-local)."""
    import threading

    import jax

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.exec.local import LocalRunner
    from presto_tpu.runner import QueryRunner

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.002, split_rows=4096))
    pool = MemoryPool(1 << 30)
    runner = QueryRunner(catalog)
    runner.executor = LocalRunner(catalog, memory_pool=pool)

    sqls = [
        "select count(*) from lineitem, orders where l_orderkey = o_orderkey",
        "select n_name, count(*) from nation, supplier"
        " where n_nationkey = s_nationkey group by n_name",
        "select sum(l_quantity) from lineitem where l_discount > 0.02",
    ] * 2
    expected = [runner.execute(s).rows for s in sqls]

    results = [None] * len(sqls)
    errors = []

    def go(i):
        try:
            results[i] = runner.execute(sqls[i]).rows
        except Exception as e:
            errors.append(e)

    threads = [threading.Thread(target=go, args=(i,)) for i in range(len(sqls))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(120)
    assert not errors, errors
    for got, want in zip(results, expected):
        assert sorted(got) == sorted(want)
    assert pool.reserved == 0  # every context released its own tags


def test_coordinator_kill_path():
    """End-to-end: an over-threshold pool cancels the reserving query
    through the coordinator's state machine."""
    import jax

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.exec.local import LocalRunner
    from presto_tpu.runner import QueryRunner
    from presto_tpu.server.coordinator import CoordinatorServer

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    pool = MemoryPool(1 << 30)
    runner = QueryRunner(catalog)
    runner.executor = LocalRunner(catalog, memory_pool=pool)
    srv = CoordinatorServer(runner)
    assert srv.memory_manager is not None
    # simulate a query holding nearly the whole pool
    q = srv._submit("select count(*) from nation")
    q.done.wait(timeout=60)
    ctx = QueryMemoryContext(pool, q.id)
    ctx.reserve("huge", int(0.96 * (1 << 30)))
    with srv._lock:
        q.state = "RUNNING"  # pretend it is still executing
        q.done.clear()
    victim = srv.memory_manager.check_once()
    assert victim == q.id
    assert q.state == "CANCELED" and "memory manager" in q.error
    srv.stop()
