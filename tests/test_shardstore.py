"""Shard store — the presto-raptor architectural slot (PCF shards +
sqlite shard metadata + compactor/rebalancer/backup;
``presto-raptor/.../metadata/DatabaseShardManager.java``,
``storage/organization/ShardCompactor.java``, ``backup/BackupStore.java``)."""

import os

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner
from presto_tpu.storage.shardstore import ShardStoreConnector


@pytest.fixture()
def ss_runner(tmp_path):
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.002, split_rows=1024))
    ss = ShardStoreConnector(
        str(tmp_path / "ss"), nodes=("n1", "n2", "n3"),
        max_shard_rows=600, backup_root=str(tmp_path / "backup"))
    catalog.register("ss", ss, writable=True)
    return QueryRunner(catalog), ss


def test_ctas_roundtrip_and_shard_bound(ss_runner):
    r, ss = ss_runner
    r.execute("CREATE TABLE ss.orders_s AS "
              "SELECT o_orderkey, o_custkey, o_totalprice, o_orderpriority "
              "FROM orders")
    want = r.execute(
        "SELECT count(*), sum(o_totalprice), min(o_orderpriority) "
        "FROM orders").rows
    got = r.execute(
        "SELECT count(*), sum(o_totalprice), min(o_orderpriority) "
        "FROM orders_s").rows
    assert got == want
    # shards respect max_shard_rows and spread across nodes
    info = ss.shard_info("orders_s")
    assert all(s["row_count"] <= 600 for s in info)
    assert len({s["node"] for s in info}) > 1


def test_metadata_pruning_skips_files(ss_runner):
    r, ss = ss_runner
    r.execute("CREATE TABLE ss.orders_s AS "
              "SELECT o_orderkey, o_totalprice FROM orders")
    # pruning decision comes from the metadata DB alone; only matching
    # shard files may be opened
    info = ss.shard_info("orders_s")
    lo = min(s["stats"]["o_orderkey"][0] for s in info)
    matching = [s for s in info if s["stats"]["o_orderkey"][0] <= lo
                <= s["stats"]["o_orderkey"][1]]
    opened_before = set(ss._files)
    (cnt,) = r.execute(
        f"SELECT count(*) FROM orders_s WHERE o_orderkey = {lo}").rows[0]
    assert cnt >= 1
    opened = {k.split("/")[1] for k in set(ss._files) - opened_before}
    assert opened <= {s["shard_uuid"] for s in matching}
    assert len(opened) < len(info)


def test_insert_extends_table_dictionary(ss_runner):
    r, ss = ss_runner
    r.execute("CREATE TABLE ss.t AS SELECT o_orderpriority FROM orders "
              "WHERE o_orderkey < 100")
    r.execute("INSERT INTO ss.t SELECT 'brand-new-value'")
    vals = ss.dictionary_for("t", "o_orderpriority").values
    assert "brand-new-value" in vals
    (cnt,) = r.execute("SELECT count(*) FROM t "
                       "WHERE o_orderpriority = 'brand-new-value'").rows[0]
    assert cnt == 1


def test_compaction_preserves_results(ss_runner):
    r, ss = ss_runner
    r.execute("CREATE TABLE ss.small AS "
              "SELECT o_orderkey, o_totalprice, o_orderpriority FROM orders "
              "WHERE o_orderkey < 512")
    for lo in (512, 1024, 1536, 2048):
        r.execute(f"INSERT INTO ss.small SELECT o_orderkey, o_totalprice, "
                  f"o_orderpriority FROM orders "
                  f"WHERE o_orderkey >= {lo} AND o_orderkey < {lo + 512}")
    want = r.execute("SELECT count(*), sum(o_totalprice) FROM small").rows
    before = len(ss.shard_info("small"))
    eliminated = ss.compact("small")
    assert eliminated > 0
    assert len(ss.shard_info("small")) < before
    assert r.execute("SELECT count(*), sum(o_totalprice) FROM small").rows \
        == want
    # dictionary-encoded column survives the merge
    assert r.execute(
        "SELECT o_orderpriority, count(*) FROM small "
        "GROUP BY o_orderpriority ORDER BY o_orderpriority").rows == \
        r.execute(
        "SELECT o_orderpriority, count(*) FROM orders "
        "WHERE o_orderkey < 2560 "
        "GROUP BY o_orderpriority ORDER BY o_orderpriority").rows


def test_sorted_by_keeps_shards_sorted(ss_runner):
    r, ss = ss_runner
    r.execute("CREATE TABLE ss.sorted_t WITH (sorted_by = 'o_totalprice') AS "
              "SELECT o_orderkey, o_totalprice FROM orders")
    assert ss.sort_order("sorted_t") == ["o_totalprice"]
    import numpy as np
    for i in range(ss.num_splits("sorted_t")):
        p = ss.page_for_split("sorted_t", i)
        n = int(np.asarray(p.row_mask).sum())
        prices = np.asarray(p.blocks[1].data)[:n]
        assert (np.diff(prices) >= 0).all()


def test_rebalance_evens_nodes(tmp_path):
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.002, split_rows=1024))
    ss = ShardStoreConnector(str(tmp_path / "ss"), nodes=("a",),
                             max_shard_rows=500)
    catalog.register("ss", ss, writable=True)
    r = QueryRunner(catalog)
    r.execute("CREATE TABLE ss.t AS SELECT o_orderkey, o_totalprice "
              "FROM orders")
    want = r.execute("SELECT sum(o_totalprice) FROM t").rows
    # a new node joins empty; rebalance must move shards onto it
    ss.nodes.append("b")
    os.makedirs(os.path.join(ss.root, "b"), exist_ok=True)
    moved = ss.rebalance()
    assert moved > 0
    nodes = {s["node"] for s in ss.shard_info("t")}
    assert nodes == {"a", "b"}
    assert r.execute("SELECT sum(o_totalprice) FROM t").rows == want


def test_backup_restore_lost_shard(ss_runner):
    r, ss = ss_runner
    r.execute("CREATE TABLE ss.t AS SELECT o_orderkey, o_totalprice "
              "FROM orders")
    want = r.execute("SELECT count(*), sum(o_totalprice) FROM t").rows
    # lose one shard file from its node
    victim = ss.shard_info("t")[0]
    os.unlink(ss._shard_path(victim["node"], victim["shard_uuid"]))
    ss._files.clear()
    assert ss.restore_missing() == 1
    assert r.execute("SELECT count(*), sum(o_totalprice) FROM t").rows == want


def test_delete_rewrite_and_drop(ss_runner):
    r, ss = ss_runner
    r.execute("CREATE TABLE ss.t AS SELECT o_orderkey, o_totalprice "
              "FROM orders")
    (total,) = r.execute("SELECT count(*) FROM t").rows[0]
    r.execute("DELETE FROM t WHERE o_orderkey % 2 = 0")
    (odd,) = r.execute("SELECT count(*) FROM t").rows[0]
    assert 0 < odd < total
    r.execute("DROP TABLE ss.t")
    assert "t" not in ss.table_names()
    assert not any(f.endswith(".pcf")
                   for n in ss.nodes
                   for f in os.listdir(os.path.join(ss.root, n)))
