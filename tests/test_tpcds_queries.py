"""TPC-DS end-to-end vs the sqlite oracle (same pattern as the TPC-H
suite; reference analog: TestTpcdsDistributedStats-class coverage)."""

import numpy as np
import pytest
import sqlite3

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpcds import SCHEMAS, Tpcds
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, register_scalar_udfs, translate
from tests.tpcds_queries import ORACLE_OVERRIDES, QUERIES


def load_tpcds_oracle(ds: Tpcds) -> sqlite3.Connection:
    conn = sqlite3.connect(":memory:")
    # scalar builtins this sqlite build lacks (floor/sqrt/mod...) —
    # without them q17/q39/q51/q54/q97 failed at the ORACLE, not the
    # engine (the r6 standing-failure set)
    register_scalar_udfs(conn)
    for table in ds.table_names():
        schema = SCHEMAS[table]
        cols = ", ".join(n for n, _ in schema)
        conn.execute(f"create table {table} ({cols})")
        for split in range(ds.num_splits(table)):
            data = ds.generate_split(table, split)
            out_cols = []
            for name, t in schema:
                arr = data[name]
                if t.is_string:
                    d = ds.dictionary_for(table, name)
                    out_cols.append(d.decode(arr).tolist())
                elif t.is_decimal:
                    out_cols.append((arr / (10.0 ** t.scale)).tolist())
                else:
                    out_cols.append(arr.tolist())
            ph = ", ".join("?" for _ in schema)
            conn.executemany(
                f"insert into {table} values ({ph})", list(zip(*out_cols))
            )
    conn.commit()
    return conn


@pytest.fixture(scope="module")
def env():
    # cd/inventory truncated: both are sf-independent cross products
    ds = Tpcds(sf=0.01, split_rows=16384, cd_rows=2 * 5 * 7 * 20, inv_rows=60000)
    catalog = Catalog()
    catalog.register("tpcds", ds)
    runner = QueryRunner(catalog)
    oracle = load_tpcds_oracle(ds)
    return runner, oracle


_since_clear = [0]

# queries whose ORACLE text (or override) uses RIGHT/FULL OUTER JOIN —
# sqlite < 3.39 cannot compute the expected rows (the engine side still
# runs FULL joins under tests/test_outer_joins + feature interactions)
_NEEDS_FULL_JOIN_ORACLE = {17, 51, 97}


@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpcds_query(env, qid):
    if qid in _NEEDS_FULL_JOIN_ORACLE \
            and sqlite3.sqlite_version_info < (3, 39):
        pytest.skip(f"sqlite {sqlite3.sqlite_version} lacks RIGHT/FULL "
                    "OUTER JOIN (needs >= 3.39); oracle cannot compute "
                    "expected rows")
    runner, oracle = env
    # bound live compiled executables: the 99-query corpus in ONE
    # process accumulates thousands of XLA:CPU programs across the
    # runner's chain/fold caches plus jax's own jit caches, and past
    # ~30 queries the next compile segfaults (r5, deterministic).
    # Dropping every cache each ~10 queries trades recompiles for a
    # bounded executable arena.
    _since_clear[0] += 1
    if _since_clear[0] >= 10:
        _since_clear[0] = 0
        runner.executor._chain_cache.clear()
        runner.executor._fold_cache.clear()
        runner.executor._builds.clear()
        runner._plans.clear()
        import jax

        jax.clear_caches()
    sql = QUERIES[qid]
    oracle_sql = ORACLE_OVERRIDES.get(qid, sql)
    expected = [tuple(r) for r in oracle.execute(translate(oracle_sql)).fetchall()]
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_date_dim_calendar(env):
    runner, _ = env
    res = runner.execute(
        "select d_year, d_moy, d_dom from date_dim where d_date = date '2000-02-29'"
    )
    assert res.rows == [(2000, 2, 29)]
