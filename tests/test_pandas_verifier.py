"""Cross-engine verification: the engine's TPC-H answers vs a second
independent engine (pandas dataframe programs sharing no code with the
SQL path).  Together with the sqlite oracle this gives presto-verifier
style two-independent-engines agreement (VERDICT r2 #7;
presto-verifier/.../Validator.java + H2QueryRunner analog)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match
from tests.pandas_oracle import PANDAS_QUERIES, load_frames
from tests.tpch_queries import QUERIES


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.01, split_rows=16384)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    runner = QueryRunner(catalog)
    frames = load_frames(tpch)
    return runner, frames


@pytest.mark.parametrize("qid", sorted(PANDAS_QUERIES))
def test_tpch_vs_pandas(env, qid):
    runner, frames = env
    actual = runner.execute(QUERIES[qid]).rows
    expected = PANDAS_QUERIES[qid](frames)
    assert_rows_match(actual, expected, ordered=False)
