"""Stage scheduling policies + node selection (scheduler.py —
PhasedExecutionSchedule / AllAtOnceExecutionSchedule /
NodeScheduler+TopologyAwareNodeSelector analogs)."""

import pytest

from presto_tpu.parallel.scheduler import (
    AllAtOnceExecutionSchedule,
    NodeSelector,
    PhasedExecutionSchedule,
)


class _Frag:
    def __init__(self, name, children=()):
        self.name = name
        self.children = list(children)

    def __repr__(self):
        return self.name


def test_phased_schedule_orders_builds_before_probes():
    build = _Frag("build")
    leaf = _Frag("leaf", [build])
    merge = _Frag("merge", [leaf])
    root = _Frag("root", [merge])
    phases = PhasedExecutionSchedule([root]).phases()
    names = [[f.name for f in p] for p in phases]
    assert names == [["build"], ["leaf"], ["merge"], ["root"]]


def test_phased_schedule_parallel_siblings_share_a_phase():
    b1, b2 = _Frag("b1"), _Frag("b2")
    leaf = _Frag("leaf", [b1, b2])
    phases = PhasedExecutionSchedule([leaf]).phases()
    assert [sorted(f.name for f in p) for p in phases] == [
        ["b1", "b2"], ["leaf"]]


def test_all_at_once_single_phase():
    a, b = _Frag("a"), _Frag("b")
    assert AllAtOnceExecutionSchedule([a, b]).phases() == [[a, b]]


def test_phased_over_real_fragment_tree():
    """The simulated fragment tree from a join+agg plan phases its
    build fragment before the leaf that probes it."""
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.parallel.fragment import fragment_plan
    from presto_tpu.runner import QueryRunner

    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001, split_rows=1024))
    r = QueryRunner(cat)
    plan = r.plan(
        "SELECT o_orderpriority, count(*) FROM orders, customer "
        "WHERE o_custkey = c_custkey GROUP BY o_orderpriority")
    root = fragment_plan(plan, catalog=cat)
    phases = PhasedExecutionSchedule([root]).phases()
    # the customer build fragment must appear in an earlier phase than
    # the orders leaf fragment that consumes it
    def phase_of(pred):
        for i, p in enumerate(phases):
            for f in p:
                if pred(f):
                    return i
        return None

    build_i = phase_of(lambda f: str(f.output).startswith(("BROADCAST",
                                                           "FIXED_HASH"))
                       and not f.children)
    leaf_i = phase_of(lambda f: f.children)
    assert build_i is not None and leaf_i is not None
    assert build_i < leaf_i


class _W:
    def __init__(self, uri):
        self.uri = uri

    def __repr__(self):
        return self.uri


def test_node_selector_balances_load():
    ws = [_W("a"), _W("b"), _W("c")]
    out = NodeSelector(ws).assign(range(9))
    assert all(len(v) == 3 for v in out.values())


def test_node_selector_prefers_local_workers():
    ws = [_W("a"), _W("b"), _W("c")]
    locs = {id(ws[0]): "rack1", id(ws[1]): "rack2", id(ws[2]): "rack2"}
    sel = NodeSelector(ws, locations=locs)
    preferred = {s: ("rack1" if s % 2 == 0 else "rack2") for s in range(8)}
    out = sel.assign(range(8), preferred)
    assert sorted(out[ws[0]]) == [0, 2, 4, 6]  # rack1 splits on a
    assert sorted(out[ws[1]] + out[ws[2]]) == [1, 3, 5, 7]


def test_node_selector_backpressure_spills_to_remote():
    ws = [_W("a"), _W("b")]
    locs = {id(ws[0]): "rack1", id(ws[1]): "rack2"}
    sel = NodeSelector(ws, max_splits_per_node=2, locations=locs)
    preferred = {s: "rack1" for s in range(4)}
    out = sel.assign(range(4), preferred)
    # locality wants everything on a, the cap pushes half to b
    assert len(out[ws[0]]) == 2 and len(out[ws[1]]) == 2


def test_node_selector_stretches_when_all_at_cap():
    ws = [_W("a"), _W("b")]
    sel = NodeSelector(ws, max_splits_per_node=1)
    out = sel.assign(range(6))
    assert len(out[ws[0]]) == 3 and len(out[ws[1]]) == 3  # stretched


def test_multihost_honors_locality(tmp_path):
    """End-to-end: a connector reporting split locations sees its
    splits land on the matching workers."""
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.parallel.multihost import MultiHostRunner
    from presto_tpu.runner import QueryRunner
    from presto_tpu.server.worker import WorkerServer

    class LocTpch(Tpch):
        def split_location(self, table, split):
            return "east" if split % 2 == 0 else "west"

    def make_cat():
        c = Catalog()
        c.register("tpch", LocTpch(sf=0.002, split_rows=512))
        return c

    workers = [WorkerServer(make_cat()) for _ in range(2)]
    for w in workers:
        w.start()
    try:
        cat = make_cat()
        local = QueryRunner(cat)
        multi = MultiHostRunner(
            cat, [w.uri for w in workers],
            worker_locations={workers[0].uri: "east",
                              workers[1].uri: "west"})
        sql = "SELECT count(*), sum(o_totalprice) FROM orders"
        got = multi.run(local.binder.plan(sql)).rows
        want = local.executor.run(local.plan(sql)).rows
        assert len(got) == len(want)
        for (a1, a2), (e1, e2) in zip(got, want):
            assert a1 == e1 and float(a2) == pytest.approx(float(e2))
        # placement actually honored locality: east worker got the even
        # splits, west the odd ones
        east = multi.last_assignments[workers[0].uri.rstrip("/")]
        west = multi.last_assignments[workers[1].uri.rstrip("/")]
        assert east and all(s % 2 == 0 for s in east)
        assert west and all(s % 2 == 1 for s in west)
    finally:
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass
