"""Geospatial functions + spatial join.

Reference analogs: presto-geospatial GeoFunctions.java (ST_* scalars),
operator/SpatialJoinOperator.java:38 + PagesRTreeIndex.java (the
point-in-polygon join, realized here as vectorized PIP kernels over a
cross join with bbox prefiltering).
"""

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.page import Dictionary, Page
from presto_tpu.runner import QueryRunner
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR

SQUARE = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0))"
HOLED = "POLYGON ((0 0, 10 0, 10 10, 0 10, 0 0), (4 4, 6 4, 6 6, 4 6, 4 4))"
FAR = "POLYGON ((100 100, 110 100, 110 110, 100 110, 100 100))"


@pytest.fixture(scope="module")
def runner():
    mem = MemoryConnector()
    xs = np.asarray([1.0, 5.0, 50.0, 105.0])
    ys = np.asarray([1.0, 5.0, 5.0, 105.0])
    mem.create_table(
        "points", [("pid", BIGINT), ("x", DOUBLE), ("y", DOUBLE)],
        [Page.from_arrays([np.arange(1, 5), xs, ys], [BIGINT, DOUBLE, DOUBLE])])
    regions = [SQUARE, FAR]
    d = Dictionary(regions)
    mem.create_table(
        "regions", [("rid", BIGINT), ("geom", VARCHAR)],
        [Page.from_arrays(
            [np.arange(1, 3), np.arange(2, dtype=np.int32)],
            [BIGINT, VARCHAR], dictionaries=[None, d])])
    cat = Catalog()
    cat.register("mem", mem)
    return QueryRunner(cat)


def test_wkt_parsing_and_area():
    from presto_tpu.geo import parse_wkt, st_area

    g = parse_wkt(SQUARE)
    assert g.kind == "POLYGON" and g.bbox == (0.0, 0.0, 10.0, 10.0)
    assert st_area(SQUARE) == 100.0
    assert st_area(HOLED) == 96.0
    mp = parse_wkt("MULTIPOLYGON (((0 0, 1 0, 1 1, 0 1, 0 0)), "
                   "((5 5, 6 5, 6 6, 5 6, 5 5)))")
    assert len(mp.rings) == 2


def test_st_scalars(runner):
    assert runner.execute(
        f"SELECT ST_Area(ST_GeometryFromText('{SQUARE}'))").rows == [(100.0,)]
    assert runner.execute(
        "SELECT ST_X(ST_GeometryFromText('POINT (3 4)')), "
        "ST_Y(ST_GeometryFromText('POINT (3 4)'))").rows == [(3.0, 4.0)]
    assert runner.execute(
        "SELECT ST_Distance(ST_Point(0, 0), ST_Point(3, 4))").rows == [(5.0,)]


def test_st_contains_literal(runner):
    rows = runner.execute(
        f"SELECT pid FROM points WHERE ST_Contains("
        f"ST_GeometryFromText('{SQUARE}'), ST_Point(x, y)) ORDER BY pid").rows
    assert rows == [(1,), (2,)]


def test_st_contains_with_hole(runner):
    rows = runner.execute(
        f"SELECT pid FROM points WHERE ST_Contains("
        f"ST_GeometryFromText('{HOLED}'), ST_Point(x, y)) ORDER BY pid").rows
    # (5,5) falls in the hole
    assert rows == [(1,)]


def test_spatial_join(runner):
    rows = runner.execute(
        "SELECT r.rid, p.pid FROM regions r, points p "
        "WHERE ST_Contains(r.geom, ST_Point(p.x, p.y)) "
        "ORDER BY r.rid, p.pid").rows
    assert rows == [(1, 1), (1, 2), (2, 4)]


def test_st_distance_point_columns(runner):
    rows = runner.execute(
        "SELECT pid, ST_Distance(ST_Point(x, y), ST_Point(0, 0)) AS d "
        "FROM points ORDER BY pid LIMIT 2").rows
    assert rows[0][1] == pytest.approx(np.hypot(1, 1))
    assert rows[1][1] == pytest.approx(np.hypot(5, 5))


def test_geo_area_over_column(runner):
    rows = runner.execute(
        "SELECT rid, ST_Area(geom) FROM regions ORDER BY rid").rows
    assert rows == [(1, 100.0), (2, 100.0)]
