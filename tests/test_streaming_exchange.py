"""Streaming page exchange: token/ack protocol, backpressure, abort,
kill-path cleanup, and mid-stream producer-death replay.

Reference analogs: ``TestArbitraryOutputBuffer``/``TestClientBuffer``
(token re-GET + ack semantics), OutputBufferMemoryManager blocking
(backpressure), and the RFC-era fault-tolerance property that a retried
fragment must not duplicate rows the consumer already took."""

import threading
import time

import numpy as np
import pytest

from presto_tpu.obs import METRICS
from presto_tpu.parallel import streams
from presto_tpu.server.buffers import BufferAborted, TaskOutputBuffer


# ---------------------------------------------------------------------------
# token/ack protocol units
# ---------------------------------------------------------------------------

def test_token_reget_is_idempotent():
    buf = TaskOutputBuffer()
    buf.enqueue(b"page0")
    buf.enqueue(b"page1")
    pages1, nxt1, done1, _ = buf.get(0, timeout=0.1)
    pages2, nxt2, done2, _ = buf.get(0, timeout=0.1)
    assert pages1 == pages2 == [b"page0", b"page1"]
    assert nxt1 == nxt2 == 2
    assert not done1 and not done2  # producer not complete yet


def test_acknowledge_frees_bytes_and_forbids_replay():
    buf = TaskOutputBuffer()
    buf.enqueue(b"x" * 100)
    buf.enqueue(b"y" * 50)
    assert buf.unacked_bytes == 150
    buf.acknowledge(1)
    assert buf.unacked_bytes == 50
    assert buf.acked_token == 1
    with pytest.raises(KeyError):
        buf.get(0, timeout=0.1)  # below the acked watermark
    pages, nxt, _, _ = buf.get(1, timeout=0.1)
    assert pages == [b"y" * 50] and nxt == 2


def test_payload_agnostic_sizes():
    """In-process streams store live objects with explicit nbytes; the
    byte accounting must follow the declared size, not len()."""
    buf = TaskOutputBuffer(max_bytes=1 << 20)
    buf.enqueue(("not", "bytes"), nbytes=4096)
    assert buf.unacked_bytes == 4096
    pages, nxt, _, _ = buf.get(0, timeout=0.1)
    assert pages == [("not", "bytes")]
    buf.acknowledge(nxt)
    assert buf.unacked_bytes == 0


def test_backpressure_blocks_then_unblocks():
    buf = TaskOutputBuffer(max_bytes=10)
    buf.enqueue(b"0123456789")  # cap reached
    state = {"entered": False, "done": False}

    def producer():
        state["entered"] = True
        buf.enqueue(b"next")  # must block until the consumer acks
        state["done"] = True

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    deadline = time.monotonic() + 2.0
    while not state["entered"] and time.monotonic() < deadline:
        time.sleep(0.01)
    time.sleep(0.15)
    assert state["entered"] and not state["done"]  # blocked on the cap
    _, nxt, _, _ = buf.get(0, timeout=0.1)
    buf.acknowledge(nxt)  # frees bytes -> producer proceeds
    t.join(2.0)
    assert state["done"]
    assert buf.stall_seconds > 0  # backpressure time accounted


def test_abort_unblocks_producer_and_consumer():
    buf = TaskOutputBuffer(max_bytes=5)
    buf.enqueue(b"12345")
    raised = []

    def producer():
        try:
            buf.enqueue(b"67890")
        except BufferAborted:
            raised.append("producer")

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.1)
    buf.abort()
    t.join(2.0)
    assert raised == ["producer"]
    with pytest.raises(BufferAborted):
        buf.get(1, timeout=0.1)


def test_multi_producer_completion_countdown():
    buf = TaskOutputBuffer(producers=2)
    buf.enqueue(b"a")
    buf.set_complete()  # first producer done; stream still open
    _, _, done, _ = buf.get(0, timeout=0.1)
    assert not done
    buf.enqueue(b"b")
    buf.set_complete()  # second producer done -> complete
    pages, nxt, done, _ = buf.get(0, timeout=0.1)
    assert pages == [b"a", b"b"] and done


# ---------------------------------------------------------------------------
# PageStream / StreamingExchange
# ---------------------------------------------------------------------------

def test_pagestream_drain_counts_metrics():
    p0 = METRICS.counter("exchange.stream_pages_total").value
    b0 = METRICS.counter("exchange.stream_bytes_total").value
    ex = streams.StreamingExchange("gather", "t")
    s = ex.stream(producers=2)

    def produce(st):
        for i in range(4):
            st.put(("page", i), nbytes=100)

    ex.run(s, produce)
    ex.run(s, produce)
    got = list(s.drain())
    ex.join()
    assert len(got) == 8
    assert METRICS.counter("exchange.stream_pages_total").value - p0 == 8
    assert METRICS.counter("exchange.stream_bytes_total").value - b0 == 800
    assert s.peak_bytes > 0
    assert s.first_page_at is not None
    assert s.completed_at is not None


def test_producer_error_reaches_consumer_with_original_type():
    class Boom(RuntimeError):
        pass

    ex = streams.StreamingExchange("gather", "t")
    s = ex.stream()

    def produce(st):
        st.put(("ok",), nbytes=1)
        raise Boom("producer died")

    ex.run(s, produce)
    with pytest.raises(Boom):
        list(s.drain())
    ex.join()


def test_materialized_mode_runs_inline():
    """streaming=False is the A/B leg: the producer completes before
    the consumer sees anything (no thread)."""
    ex = streams.StreamingExchange("gather", "t", streaming=False)
    s = ex.stream()
    order = []
    ex.run(s, lambda st: (order.append("produced"), st.put((1,), nbytes=1))[0])
    order.append("consumed")
    assert list(s.drain()) == [(1,)]
    assert order == ["produced", "consumed"]


def test_kill_query_aborts_registered_streams():
    """pool.kill_query must abort the query's exchange buffers so a
    producer blocked in enqueue exits instead of leaking (deadline and
    low-memory kills)."""
    from presto_tpu.memory import MemoryPool

    a0 = METRICS.counter("exchange.streams_aborted").value
    pool = MemoryPool(limit_bytes=1 << 30)
    outcome = []
    with streams.query_scope("q-killed"):
        s = streams.PageStream(max_bytes=8)

        def producer():
            try:
                s.put(b"12345678")
                s.put(b"12345678")  # blocks on the cap
                outcome.append("no-block?")
            except BufferAborted:
                outcome.append("aborted")

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        time.sleep(0.15)
        pool.kill_query("q-killed")
        t.join(2.0)
    assert outcome == ["aborted"]
    assert METRICS.counter("exchange.streams_aborted").value - a0 >= 1
    assert METRICS.counter(
        "exchange.producer_stall_seconds_total").value > 0


def test_abort_query_idempotent_and_drain_safe():
    """The abort-after-final-ack race (protocol invariant
    exchange.abort-after-drain-noop): a deadline/kill abort that loses
    the race with a successful drain must be a no-op — never raise,
    never retroactively fail the drained stream, never count an abort
    that didn't happen."""
    a0 = METRICS.counter("exchange.streams_aborted").value
    with streams.query_scope("q-drained"):
        s = streams.PageStream()
        s.put((1,), nbytes=8)
        s.buffer.set_complete()
        _, nxt, done, _ = s.buffer.get(0, timeout=1.0)
        assert done
        s.buffer.acknowledge(nxt)       # consumer took everything
        assert streams.abort_query("q-drained") == 0   # lost the race
        assert not s.buffer.aborted     # the drained result stands
    # double abort on a LIVE stream: first wins, second is a no-op
    with streams.query_scope("q-live"):
        live = streams.PageStream()
        live.put((1,), nbytes=8)
        assert streams.abort_query("q-live") == 1
        assert live.abort() is False    # already aborted: idempotent
    assert streams.abort_query("q-live") == 0      # registry drained
    assert streams.abort_query("q-never-existed") == 0
    assert METRICS.counter("exchange.streams_aborted").value - a0 == 1


# ---------------------------------------------------------------------------
# mid-stream producer death: replay from the last acked token
# ---------------------------------------------------------------------------

@pytest.fixture()
def dqr3():
    from presto_tpu.testing import DistributedQueryRunner
    from presto_tpu.testing_faults import FAULTS

    FAULTS.disarm_all()
    rig = DistributedQueryRunner(n_workers=3, sf=0.01, split_rows=2048)
    rig.multihost.min_stage_rows = 0
    try:
        yield rig
    finally:
        FAULTS.disarm_all()
        rig.close()


def test_die_after_n_pages_replays_from_acked_token(dqr3):
    """A producer killed mid-stream after the consumer took k pages:
    the fragment re-runs on a survivor and the consumer's stream
    resumes at its delivered watermark — oracle-correct, no duplicate
    and no missing rows, with the replay counted."""
    import collections

    mh = dqr3.multihost
    local = dqr3.runner
    sql = "SELECT l_orderkey, l_extendedprice FROM lineitem"
    expected = local.executor.run(local.plan(sql)).rows

    dqr3.arm_fault("worker.die_after_n_pages", worker=0, pages=3)
    r0 = METRICS.counter("exchange.stream_replays_total").value
    leg = local.plan(sql).source
    page = mh._stage_chain(leg)
    got = page.compact_host().to_pylist()
    assert collections.Counter(map(tuple, got)) == collections.Counter(
        map(tuple, expected))
    assert METRICS.counter("exchange.stream_replays_total").value > r0


def test_die_mid_stream_distributed_sort_oracle(dqr3):
    """End-to-end: mid-stream worker death under a distributed ORDER BY
    still returns the exact ordered oracle result."""
    mh = dqr3.multihost
    local = dqr3.runner
    sql = ("SELECT l_orderkey, l_extendedprice FROM lineitem "
           "ORDER BY l_extendedprice, l_orderkey")
    expected = local.executor.run(local.plan(sql)).rows
    dqr3.arm_fault("worker.die_after_n_pages", worker=1, pages=2)
    out = mh.run(local.plan(sql))
    assert out.rows == expected


def test_streamed_vs_materialized_same_rows(dqr3):
    """The A/B toggle changes timing, never results."""
    mh = dqr3.multihost
    local = dqr3.runner
    sql = ("SELECT o_orderkey FROM orders UNION ALL "
           "SELECT l_orderkey FROM lineitem")
    plan = local.plan(sql)
    mh.exchange_streaming = True
    a = sorted(mh.run(local.plan(sql)).rows)
    mh.exchange_streaming = False
    b = sorted(mh.run(local.plan(sql)).rows)
    mh.exchange_streaming = True
    assert a == b
    assert len(a) == len(local.executor.run(plan).rows)


@pytest.mark.slow  # heavy 3-worker chaos runs; exercised by the ci.sh protocol leg
@pytest.mark.parametrize("qid", [3, 6])
def test_replay_byte_equality_under_net_faults(dqr3, qid):
    """Replay-from-watermark property over real TPC-H plans: with a
    worker dying mid-stream (fragment failover + watermark replay), a
    duplicated results response (net.duplicate_page — client dedupe
    must swallow it), AND dropped acks (net.drop_ack — unacked pages
    re-serve at the same token), q3/q6 still return the EXACT oracle
    rows.  This is invariant exchange.replay-prefix-equality made
    end-to-end: at-least-once on the wire, exactly-once delivered."""
    from tests.tpch_queries import QUERIES

    mh = dqr3.multihost
    local = dqr3.runner
    sql = QUERIES[qid]
    expected = local.executor.run(local.plan(sql)).rows

    dqr3.arm_fault("worker.die_after_n_pages", worker=0, pages=2)
    # the net faults go on SURVIVORS — worker 0's pulls die with it
    dqr3.arm_fault("net.duplicate_page", worker=1, after=1, count=3)
    dqr3.arm_fault("net.drop_ack", worker=2, count=3)
    out = mh.run(local.plan(sql))
    assert out.rows == expected
    """With in-process HTTP workers the consumer's first page must land
    before the last producer completes (stage overlap), and the
    exchange's in-flight memory stays bounded by the byte cap."""
    mh = dqr3.multihost
    local = dqr3.runner
    leg = local.plan("SELECT l_orderkey, l_extendedprice FROM lineitem").source
    mh._stage_chain(leg)
    st = mh.last_exchange_stats
    assert st["pages"] >= 2
    assert 0 < st["first_page_at"] <= st["producers_done_at"]
    assert st["peak_buffered_bytes"] <= mh.exchange_buffer_bytes
