"""Unit tests for the interval × null × nan abstract domain
(presto_tpu/analysis/ranges.py): interval arithmetic with ±inf
sentinels, per-type bounds, and one transfer-function test per IR op
family.  These are the soundness bricks the kernel-soundness checker
and the runtime range sanitizer are built from — each case states the
concrete kernel behavior the abstract rule must over-approximate.
"""

import math

import pytest

from presto_tpu.analysis import ranges
from presto_tpu.analysis.ranges import (
    I8,
    I16,
    I32,
    I64,
    INF,
    AbstractValue,
    device_int_bounds,
    eval_expr,
    from_literal,
    iv_abs,
    iv_add,
    iv_div,
    iv_mod,
    iv_mul,
    iv_neg,
    iv_sub,
    null_effect,
    top,
    transfer,
    type_bounds,
)
from presto_tpu.expr.ir import Call, ColumnRef, Literal
from presto_tpu.types import (
    BIGINT,
    BOOLEAN,
    DATE,
    DOUBLE,
    INTEGER,
    REAL,
    SMALLINT,
    TINYINT,
    VARCHAR,
    DecimalType,
)


def av(lo, hi, **kw):
    kw.setdefault("may_null", False)
    kw.setdefault("known", True)
    return AbstractValue(lo, hi, **kw)


# ---------------------------------------------------------------------------
# lattice + bounds
# ---------------------------------------------------------------------------

def test_join_is_lub():
    a = av(0, 10)
    b = AbstractValue(-5, 3, may_null=True, may_nan=True, known=False)
    j = a.join(b)
    assert (j.lo, j.hi) == (-5, 10)
    assert j.may_null and j.may_nan
    # evidence survives only if BOTH sides carry it
    assert j.known is False
    assert a.join(av(20, 30)).known is True


def test_contains():
    assert av(-INF, 5).contains(-(10 ** 30))
    assert not av(0, 5).contains(6)


def test_type_bounds_per_type():
    assert type_bounds(TINYINT) == I8
    assert type_bounds(SMALLINT) == I16
    assert type_bounds(INTEGER) == I32
    assert type_bounds(DATE) == I32
    assert type_bounds(BIGINT) == I64
    assert type_bounds(BOOLEAN) == (0, 1)
    assert type_bounds(DOUBLE) == (-INF, INF)
    # dictionary codes are non-negative
    assert type_bounds(VARCHAR) == (0, INF)
    # short decimal: the declared bound (fits the int64 lane at p<=18)
    assert type_bounds(DecimalType(3, 1)) == (-999, 999)
    assert type_bounds(DecimalType(18, 0)) == (-(10 ** 18 - 1), 10 ** 18 - 1)
    # long decimal: limbs cover the full declared precision
    assert type_bounds(DecimalType(30, 0)) == (-(10 ** 30 - 1), 10 ** 30 - 1)


def test_device_int_bounds_wrap_points():
    # DECIMAL(12,2) is stored in int64 lanes: it physically wraps at
    # I64, not at 10^12 — the distinction the overflow checker rests on
    assert device_int_bounds(DecimalType(12, 2)) == I64
    assert device_int_bounds(BIGINT) == I64
    assert device_int_bounds(INTEGER) == I32
    assert device_int_bounds(DATE) == I32
    assert device_int_bounds(SMALLINT) == I16
    assert device_int_bounds(TINYINT) == I8
    # floats and limb vectors have no wrap point
    assert device_int_bounds(DOUBLE) is None
    assert device_int_bounds(DecimalType(30, 2)) is None


def test_from_literal():
    assert from_literal(Literal(type=BIGINT, value=7)) == av(7, 7)
    n = from_literal(Literal(type=BIGINT, value=None))
    assert n.may_null and n.known
    t = from_literal(Literal(type=BOOLEAN, value=True))
    assert (t.lo, t.hi) == (1, 1)
    nan = from_literal(Literal(type=DOUBLE, value=float("nan")))
    assert nan.may_nan and nan.known and nan.lo == -INF
    # strings resolve to dictionary codes at compile time: unknown here
    s = from_literal(Literal(type=VARCHAR, value="x"))
    assert not s.known and not s.may_null


def test_top_is_assumed():
    t = top(DOUBLE)
    assert not t.known and t.may_nan and t.may_null
    assert not top(BIGINT, may_null=False).may_null


# ---------------------------------------------------------------------------
# interval arithmetic (±inf sentinels, exact ints when finite)
# ---------------------------------------------------------------------------

def test_iv_add_sub():
    assert iv_add(av(1, 2), av(10, 20)) == (11, 22)
    assert iv_sub(av(1, 2), av(10, 20)) == (-19, -8)
    assert iv_add(av(-INF, 5), av(1, 1)) == (-INF, 6)


def test_iv_mul_corners_and_zero_times_inf():
    assert iv_mul(av(-2, 3), av(-5, 7)) == (-15, 21)
    # standard interval convention: 0 × ±inf = 0
    assert iv_mul(av(0, 0), av(-INF, INF)) == (0, 0)
    assert iv_mul(av(0, 2), av(-INF, INF)) == (-INF, INF)


def test_iv_neg_abs():
    assert iv_neg(av(-3, 7)) == (-7, 3)
    assert iv_abs(av(-3, 7)) == (0, 7)
    assert iv_abs(av(2, 7)) == (2, 7)
    assert iv_abs(av(-7, -2)) == (2, 7)
    assert iv_abs(av(-INF, -2)) == (2, INF)


def test_iv_div():
    # positive divisor interval
    assert iv_div(av(10, 10), av(2, 5), trunc=True) == (2, 5)
    # straddling zero: the excluded-zero worst cases are at ±1
    assert iv_div(av(7, 7), av(-3, 3), trunc=True) == (-7, 7)
    # all-zero divisor: every lane nulls, quotient interval collapses
    assert iv_div(av(7, 7), av(0, 0), trunc=True) == (0, 0)
    # unbounded dividend keeps the unbounded direction
    lo, hi = iv_div(av(-INF, INF), av(1, 1), trunc=True)
    assert (lo, hi) == (-INF, INF)
    # unbounded divisor magnitude drives quotients toward zero
    assert 0 in range(*map(int, iv_div(av(5, 5), av(1, INF), trunc=True))) \
        or iv_div(av(5, 5), av(1, INF), trunc=True)[0] == 0


def test_iv_mod_dividend_sign():
    # SQL mod takes the dividend's sign, |r| < |b|
    assert iv_mod(av(-10, 20), av(3, 7)) == (-6, 6)
    assert iv_mod(av(5, 20), av(3, 7)) == (0, 6)
    # |r| also bounded by |a|
    assert iv_mod(av(2, 2), av(100, 100)) == (0, 2)
    assert iv_mod(av(-4, -1), av(-INF, INF)) == (-4, 0)


def test_rescale_iv():
    # up-scale multiplies, preserving inf sentinels
    assert ranges._rescale_iv(-2, 3, 0, 2) == (-200, 300)
    assert ranges._rescale_iv(-INF, 3, 0, 2) == (-INF, 300)
    # down-scale truncates toward zero (the kernel's integer divide)
    assert ranges._rescale_iv(-25, 25, 1, 0) == (-2, 2)


# ---------------------------------------------------------------------------
# transfer catalog, one case per op family
# ---------------------------------------------------------------------------

def test_transfer_bool_fns_three_valued():
    r = transfer("lt", BOOLEAN, [av(0, 9), av(0, 9, may_null=True)],
                 [BIGINT, BIGINT])
    assert (r.lo, r.hi) == (0, 1)
    assert r.may_null and r.known
    # is_null / not_null never return NULL, whatever the input
    r = transfer("is_null", BOOLEAN, [top(BIGINT)], [BIGINT])
    assert not r.may_null


def test_transfer_add_rescales_to_output_scale():
    # DECIMAL(4,1) + DECIMAL(4,2) -> scale-2 output: the scale-1 arg's
    # raw ints are ×10 before the add, exactly like the kernel
    a = av(-50, 50)      # 5.0 at scale 1
    b = av(-25, 25)      # 0.25 at scale 2
    r = transfer("add", DecimalType(6, 2), [a, b],
                 [DecimalType(4, 1), DecimalType(4, 2)])
    assert (r.lo, r.hi) == (-525, 525)
    assert r.known and not r.may_null


def test_transfer_mul_scales_add():
    # mul: no rescale — output scale is sa+sb, raw products are exact
    r = transfer("mul", DecimalType(8, 3), [av(0, 100), av(-30, 30)],
                 [DecimalType(4, 1), DecimalType(4, 2)])
    assert (r.lo, r.hi) == (-3000, 3000)


def test_transfer_div():
    # double division: TOP with nan (inf/0-adjacent lanes)
    r = transfer("div", DOUBLE, [av(1, 1), av(1, 1)], [DOUBLE, DOUBLE])
    assert (r.lo, r.hi) == (-INF, INF) and r.may_nan
    # integer division: iv_div, and may_null (zero-divisor guard)
    r = transfer("div", BIGINT, [av(100, 100), av(3, 5)], [BIGINT, BIGINT])
    assert (r.lo, r.hi) == (20, 33)
    assert r.may_null


def test_transfer_cast_bigint_half_up_slack():
    # short-decimal -> bigint rounds HALF_UP: ±1 slack on the truncated
    # interval keeps the rule sound for the round-away-from-zero lane
    r = transfer("cast_bigint", BIGINT, [av(-25, 25)], [DecimalType(10, 1)])
    assert (r.lo, r.hi) == (-3, 3)
    assert r.known
    # parse casts (string source) are bounded by the target width only
    # and may NULL on unparseable input (documented deviation)
    r = transfer("cast_bigint", BIGINT, [av(0, 5)], [VARCHAR])
    assert (r.lo, r.hi) == I64 and r.may_null and not r.known


def test_transfer_cast_decimal_rescale():
    r = transfer("cast_decimal", DecimalType(10, 3), [av(-7, 7)],
                 [DecimalType(10, 1)])
    assert (r.lo, r.hi) == (-700, 700) and r.known


def test_transfer_cast_double_unscales():
    r = transfer("cast_double", DOUBLE, [av(-250, 250)], [DecimalType(10, 2)])
    assert (r.lo, r.hi) == (-2.5, 2.5)
    r = transfer("cast_real", REAL, [av(1, 1)], [BIGINT])
    assert r.may_nan and (r.lo, r.hi) == (-INF, INF)


def test_transfer_dateparts_exact_and_known():
    # calendar-field ranges are exact by construction of the kernels —
    # the one family where the contract itself is evidence
    r = transfer("month", BIGINT, [top(DATE)], [DATE])
    assert (r.lo, r.hi) == (1, 12) and r.known
    assert transfer("day_of_week", BIGINT, [top(DATE)], [DATE]).hi == 7
    # calendar MOVES are data-dependent: contract only
    r = transfer("date_add_days", DATE, [av(0, 10), top(DATE)],
                 [BIGINT, DATE])
    assert not r.known and (r.lo, r.hi) == I32


def test_transfer_sign_round_family():
    assert (lambda r: (r.lo, r.hi, r.known))(
        transfer("sign", BIGINT, [av(-9, 9)], [BIGINT])) == (-1, 1, True)
    # decimal round family rescales with ±1 rounding slack
    r = transfer("round", BIGINT, [av(-149, 149)], [DecimalType(5, 2)])
    assert (r.lo, r.hi) == (-2, 2)


def test_transfer_greatest_least_strict():
    g = transfer("greatest", BIGINT, [av(0, 5), av(3, 9, may_null=True)],
                 [BIGINT, BIGINT])
    assert (g.lo, g.hi) == (3, 9)
    assert g.may_null  # NULL if ANY argument is NULL (kernel parity)
    l = transfer("least", BIGINT, [av(0, 5), av(3, 9)], [BIGINT, BIGINT])
    assert (l.lo, l.hi) == (0, 5)


def test_transfer_coalesce_if_nullif():
    c = transfer("coalesce", BIGINT,
                 [av(0, 5, may_null=True), av(10, 20)], [BIGINT, BIGINT])
    assert (c.lo, c.hi) == (0, 20)
    assert not c.may_null  # a non-null fallback resolves the chain
    # IF without ELSE can yield NULL even over non-null branches
    i = transfer("if", BIGINT, [av(0, 1), av(5, 5)], [BOOLEAN, BIGINT])
    assert i.may_null and (i.lo, i.hi) == (5, 5)
    n = transfer("nullif", BIGINT, [av(5, 5), av(5, 5)], [BIGINT, BIGINT])
    assert n.may_null and (n.lo, n.hi) == (5, 5)


def test_transfer_length_family_and_bitwise():
    r = transfer("bit_count", BIGINT, [top(BIGINT)], [BIGINT])
    assert (r.lo, r.hi) == (0, 64)
    r = transfer("from_base", BIGINT, [top(VARCHAR), av(16, 16)],
                 [VARCHAR, BIGINT])
    assert (r.lo, r.hi) == I64 and r.may_null  # parse failures NULL
    r = transfer("bitwise_xor", BIGINT, [av(0, 1), av(0, 1)],
                 [BIGINT, BIGINT])
    assert (r.lo, r.hi) == I64  # bit ops roam the whole lane


def test_transfer_default_is_type_contract():
    # any unmodeled scalar kernel falls back to the output contract
    r = transfer("upper", VARCHAR, [top(VARCHAR)], [VARCHAR])
    assert (r.lo, r.hi) == (0, INF) and not r.known and r.may_null


def test_null_effect_classes():
    assert null_effect("add") == "generating"       # overflow -> NULL
    assert null_effect("div") == "generating"       # zero divisor
    assert null_effect("cast_tinyint") == "generating"
    assert null_effect("coalesce") == "preserving"
    assert null_effect("between") == "preserving"   # and(ge, le) 3VL
    assert null_effect("eq") == "strict"
    assert null_effect("upper") == "strict"


# ---------------------------------------------------------------------------
# eval_expr: clamping + hazard reporting
# ---------------------------------------------------------------------------

def _hazards_of(e, env=()):
    got = []

    def on_hazard(kind, expr, raw, bounds, known):
        got.append((kind, expr.fn, raw, bounds, known))

    v = eval_expr(e, list(env), on_hazard)
    return v, got


def test_eval_literal_add_overflow_hazard_and_clamp():
    e = Call(type=BIGINT, fn="add",
             args=(Literal(type=BIGINT, value=I64[1]),
                   Literal(type=BIGINT, value=1)))
    v, hazards = _hazards_of(e)
    assert hazards and hazards[0][0] == "overflow"
    assert hazards[0][4] is True  # literal evidence: error-grade
    # the returned value is clamped to the lane (escaped lanes NULL)
    assert v.hi == I64[1] and v.may_null


def test_eval_contract_overflow_not_known():
    # type-contract-only escape: hazard fires with known=False (the
    # checker downgrades / ignores it — every int64 add "may" overflow)
    e = Call(type=BIGINT, fn="add",
             args=(ColumnRef(type=BIGINT, index=0),
                   ColumnRef(type=BIGINT, index=1)))
    _, hazards = _hazards_of(e, env=[top(BIGINT), top(BIGINT)])
    assert hazards and hazards[0][0] == "overflow" and hazards[0][4] is False


def test_eval_division_hazard_point_zero_vs_straddle():
    zero = Call(type=BIGINT, fn="div",
                args=(Literal(type=BIGINT, value=10),
                      Literal(type=BIGINT, value=0)))
    _, hazards = _hazards_of(zero)
    assert hazards == [("division", "div", (0, 0), (0, 0), True)]
    # a divisor that merely CAN be zero is a possibility, not evidence
    straddle = Call(type=BIGINT, fn="div",
                    args=(Literal(type=BIGINT, value=10),
                          ColumnRef(type=BIGINT, index=0)))
    _, hazards = _hazards_of(
        straddle, env=[av(-5, 5, may_null=True)])
    assert hazards and hazards[0][0] == "division" and hazards[0][4] is False


def test_eval_lossy_cast_hazard():
    e = Call(type=SMALLINT, fn="cast_smallint",
             args=(Literal(type=BIGINT, value=40_000),))
    v, hazards = _hazards_of(e)
    assert hazards and hazards[0][0] == "lossy-cast"
    assert hazards[0][3] == I16 and hazards[0][4] is True
    assert v.may_null  # out-of-range lanes NULL at runtime


def test_eval_in_range_expressions_are_silent():
    e = Call(type=BIGINT, fn="add",
             args=(Literal(type=BIGINT, value=3),
                   Literal(type=BIGINT, value=4)))
    v, hazards = _hazards_of(e)
    assert hazards == []
    assert (v.lo, v.hi) == (7, 7) and not v.may_null and v.known


def test_eval_columnref_out_of_bounds_is_top():
    v = eval_expr(ColumnRef(type=BIGINT, index=99), [])
    assert not v.known and (v.lo, v.hi) == I64


def test_channel_value_of_channel_domain_is_evidence():
    from types import SimpleNamespace

    ch = SimpleNamespace(type=BIGINT, domain=(0, 100))
    v = ranges.channel_value_of_channel(ch)
    assert v.known and (v.lo, v.hi) == (0, 100)
    bare = SimpleNamespace(type=BIGINT, domain=None)
    assert not ranges.channel_value_of_channel(bare).known
