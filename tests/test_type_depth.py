"""Round-3 type-system depth (VERDICT r2 #8): REAL / SMALLINT /
TINYINT / TIME / VARBINARY / CHAR, typeof(), and the generic
signature binder (metadata/FunctionRegistry.java:349 + SignatureBinder
analog)."""

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.page import Page
from presto_tpu.runner import QueryRunner
from presto_tpu.types import (
    BIGINT, DOUBLE, INTEGER, REAL, SMALLINT, TIME, TINYINT,
    CharType, VarbinaryType, common_super_type, parse_type,
)


@pytest.fixture(scope="module")
def runner():
    mem = MemoryConnector()
    mem.create_table(
        "t", [("s", SMALLINT), ("b", TINYINT), ("r", REAL), ("x", BIGINT)],
        [Page.from_arrays(
            [np.array([1, 2, 3, 30000], dtype=np.int16),
             np.array([1, 2, 3, 100], dtype=np.int8),
             np.array([0.5, 1.5, 2.5, 3.5], dtype=np.float32),
             np.array([10, 20, 30, 40], dtype=np.int64)],
            [SMALLINT, TINYINT, REAL, BIGINT])])
    cat = Catalog()
    cat.register("mem", mem)
    return QueryRunner(cat)


def test_parse_and_repr():
    assert repr(parse_type("real")) == "real"
    assert repr(parse_type("smallint")) == "smallint"
    assert repr(parse_type("tinyint")) == "tinyint"
    assert repr(parse_type("time")) == "time"
    assert repr(parse_type("varbinary(16)")) == "varbinary(16)"
    assert repr(parse_type("char(10)")) == "char(10)"
    assert parse_type("varbinary(16)").np_dtype == np.dtype(np.uint8)
    assert CharType(10).dictionary and VarbinaryType(4).value_shape == (4,)


def test_coercion_ladder():
    assert common_super_type(TINYINT, SMALLINT) is SMALLINT
    assert common_super_type(SMALLINT, INTEGER) is INTEGER
    assert common_super_type(INTEGER, BIGINT) is BIGINT
    assert common_super_type(BIGINT, REAL) is REAL
    assert common_super_type(REAL, DOUBLE) is DOUBLE
    assert common_super_type(parse_type("decimal(10,2)"), REAL) is REAL
    assert common_super_type(CharType(5), parse_type("varchar")).name == "varchar"


def test_narrow_types_execute(runner):
    rows = runner.execute(
        "select sum(s), sum(b), sum(r), max(s), min(b) from t").rows
    assert rows[0][0] == 30006 and rows[0][1] == 106
    assert rows[0][2] == pytest.approx(8.0)
    assert rows[0][3] == 30000 and rows[0][4] == 1
    # arithmetic promotes: smallint + bigint -> bigint, real * 2 real-ish
    rows = runner.execute("select s + x, r * 2.0 from t order by x limit 1").rows
    assert rows[0][0] == 11 and rows[0][1] == pytest.approx(1.0)


def test_casts(runner):
    rows = runner.execute(
        "select cast(x as real), cast(x as smallint), cast(x as tinyint) "
        "from t order by x limit 1").rows
    assert rows[0] == (10.0, 10, 10)
    rows = runner.execute("select cast(r as bigint) from t order by x").rows
    assert [r[0] for r in rows] == [0, 1, 2, 3]


def test_typeof(runner):
    rows = runner.execute(
        "select typeof(s), typeof(b), typeof(r), typeof(x), "
        "typeof(r + 1.0), typeof(s + x), typeof(time '10:30:00') from t limit 1").rows
    # r + 1.0: the literal 1.0 is decimal(18,1); DECIMAL op REAL -> REAL
    assert rows[0] == ("smallint", "tinyint", "real", "bigint",
                      "real", "bigint", "time")


def test_time_literals(runner):
    rows = runner.execute(
        "select time '10:30:00' < time '11:00:00', "
        "       time '23:59:59' > time '00:00:00' from t limit 1").rows
    assert rows[0] == (True, True)


def test_signature_binder_generics():
    from presto_tpu.signature import REGISTRY
    from presto_tpu.types import ArrayType, MapType, VARCHAR, BOOLEAN

    arr = ArrayType(DOUBLE, 4)
    assert REGISTRY.resolve("array_max", [arr]) is DOUBLE
    assert REGISTRY.resolve("array_sort", [arr]) == arr
    m = MapType(VARCHAR, BIGINT, 4)
    assert REGISTRY.resolve("map_keys", [m]) == ArrayType(VARCHAR, 4)
    assert REGISTRY.resolve("element_at", [m, VARCHAR]) is BIGINT
    # coercion pass: INTEGER index coerces to the declared bigint
    assert REGISTRY.resolve("subscript", [arr, INTEGER]) is DOUBLE
    # T-unification with coercion: contains(array(bigint), integer)
    assert REGISTRY.resolve("contains", [ArrayType(BIGINT, 4), INTEGER]) is BOOLEAN
    # unknown names fall through to the structural arms
    assert REGISTRY.resolve("no_such_fn", [BIGINT]) is None
    with pytest.raises(TypeError):
        REGISTRY.resolve("array_max", [BIGINT])  # known name, no match


def test_signature_binder_through_sql(runner):
    rows = runner.execute(
        "select greatest(s, x), least(r, 1.0), "
        "       array_max(array[x, x + 5]) from t order by x limit 1").rows
    assert rows[0][0] == 10 and rows[0][1] == pytest.approx(0.5)
    assert rows[0][2] == 15
