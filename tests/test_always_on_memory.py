"""Always-on memory accounting (VERDICT r2 #10).

Reference analog: memory/MemoryPool.java:43 — every operator's memory
is tracked unconditionally; an untracked path that works at toy scale
OOMs silently at SF100.  The runner therefore defaults to the
process-wide pool sized from detected HBM/RAM, and QueryStats-level
peak bytes are nonzero without any opt-in.
"""

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.memory import MemoryPool, default_memory_pool
from presto_tpu.runner import QueryRunner


def _runner(**kw):
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=2048))
    return QueryRunner(catalog, **kw)


def test_default_pool_engaged_without_opt_in():
    r = _runner()
    assert r.memory_pool is default_memory_pool()
    assert r.memory_pool.limit > 0
    r.execute("select o_orderpriority, count(*) from orders, customer "
              "where o_custkey = c_custkey group by o_orderpriority")
    # scan pages + join build + agg accumulator were all charged
    assert r.executor.last_peak_bytes > 0
    # and released at query end: no reservations may remain
    assert r.memory_pool.reserved == 0, list(r.memory_pool.tags())


def test_peak_shows_in_explain_analyze():
    r = _runner()
    res = r.execute("explain analyze select count(*) from lineitem")
    assert "peak reserved memory" in res.rows[0][0]


def test_explicit_pool_still_respected():
    pool = MemoryPool(1 << 30)
    r = _runner(memory_pool=pool)
    assert r.memory_pool is pool
    r.execute("select count(*) from lineitem")
    assert pool.peak > 0
    assert pool.reserved == 0  # released


def test_opt_out_with_false():
    r = _runner(memory_pool=False)
    assert r.memory_pool is None
    r.execute("select count(*) from lineitem")
