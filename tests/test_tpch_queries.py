"""End-to-end TPC-H: SQL -> parse -> bind -> plan -> execute, checked
against the sqlite oracle (the reference's AbstractTestQueries +
H2QueryRunner pattern, presto-tests)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle
from tests.tpch_queries import QUERIES

SUPPORTED = list(range(1, 23))
NOT_YET = []


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.01, split_rows=16384)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    runner = QueryRunner(catalog)
    oracle = load_oracle(tpch)
    return runner, oracle


@pytest.mark.parametrize("qid", SUPPORTED)
def test_tpch_query(env, qid):
    runner, oracle = env
    sql = QUERIES[qid]
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


@pytest.mark.parametrize("qid", NOT_YET)
def test_tpch_query_not_yet(env, qid):
    runner, oracle = env
    sql = QUERIES[qid]
    expected = run_oracle(oracle, sql)
    try:
        actual = runner.execute(sql).rows
    except Exception:
        pytest.xfail(f"Q{qid} not yet supported")
    assert_rows_match(actual, expected, ordered=False)
