"""The repo-wide concurrency-lint pin (tier-1), mirroring
test_engine_lint.py::test_repo_lint_clean: the concurrency sanitizer's
static detectors run over the whole engine + tools with the shared
suppression file applied, and HEAD stays at zero findings.  A
regression here names its file:line — fix it, or add a JUSTIFIED entry
to tools/lint_suppressions.txt."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import engine_lint  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_repo_concurrency_lint_clean():
    findings, _report = engine_lint.lint_concurrency(
        [os.path.join(REPO, "presto_tpu"), os.path.join(REPO, "tools")])
    entries, problems = engine_lint.load_suppressions(
        engine_lint.DEFAULT_SUPPRESSIONS)
    assert problems == [], "\n".join(str(p) for p in problems)
    findings = engine_lint.apply_suppressions(findings, entries)
    assert findings == [], "\n".join(str(f) for f in findings)


def test_no_statically_possible_deadlock_cycles():
    """The whole-repo lock graph is acyclic: the strongest static
    guarantee the sanitizer offers.  If this fails, run
    ``python tools/lock_sanitizer.py`` to see whether the runtime
    confirms or refutes the new cycle — then break it either way."""
    sys.path.insert(0, REPO)
    from presto_tpu.analysis import concurrency

    _findings, report = concurrency.analyze(
        [os.path.join(REPO, "presto_tpu")])
    assert report["cycles"] == [], report["cycles"]


def test_suppression_file_entries_all_still_match():
    """Every suppression entry must still cover a live finding or at
    least name an existing file — dead entries rot the contract.  (We
    check file existence, not finding liveness: inline fixes may
    legitimately leave file-level entries for near-identical lines.)"""
    entries, _ = engine_lint.load_suppressions(
        engine_lint.DEFAULT_SUPPRESSIONS)
    assert entries, "suppression file missing or empty"
    for e in entries:
        assert os.path.exists(os.path.join(REPO, e.path)), \
            f"suppression names a missing file: {e.path}"
        assert e.reason.strip(), f"empty justification: {e}"
