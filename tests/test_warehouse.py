"""Warehouse connector (directory-of-PCF + file metastore) — the
presto-hive architectural slot (HiveMetadata.java partitioned tables,
BackgroundHiveSplitLoader.java splits, TupleDomain partition pruning)."""

import os

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner
from presto_tpu.storage.warehouse import WarehouseConnector


@pytest.fixture()
def wh_runner(tmp_path):
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.002, split_rows=1024))
    wh = WarehouseConnector(str(tmp_path / "wh"))
    catalog.register("wh", wh, writable=True)
    return QueryRunner(catalog), wh


def test_partitioned_ctas_roundtrip(wh_runner):
    r, wh = wh_runner
    r.execute(
        "CREATE TABLE wh.orders_p WITH (partitioned_by = 'o_orderpriority') "
        "AS SELECT o_orderkey, o_custkey, o_totalprice, o_orderpriority "
        "FROM orders")
    # one partition directory per priority value on disk
    assert len(wh.partition_columns("orders_p")) == 1
    n_parts = len(wh._meta("orders_p")["partitions"])
    assert n_parts == 5  # TPC-H priorities

    want = r.execute("SELECT count(*), sum(o_totalprice) FROM orders").rows
    got = r.execute("SELECT count(*), sum(o_totalprice) FROM orders_p").rows
    assert got == want

    # per-partition contents match
    for prio, cnt in r.execute(
        "SELECT o_orderpriority, count(*) FROM orders "
        "GROUP BY o_orderpriority").rows:
        (got_cnt,) = r.execute(
            f"SELECT count(*) FROM orders_p "
            f"WHERE o_orderpriority = '{prio}'").rows[0]
        assert got_cnt == cnt


def test_partition_pruning_reads_less(wh_runner):
    r, wh = wh_runner
    r.execute(
        "CREATE TABLE wh.orders_p WITH (partitioned_by = 'o_orderpriority') "
        "AS SELECT o_orderkey, o_totalprice, o_orderpriority FROM orders")
    # pruned scan: only splits of the matching partition may be read
    files = {p["file"]: p for p in wh._meta("orders_p")["partitions"]}
    reads_before = {rel: wh._pcf("orders_p", rel).bytes_read
                    for rel in files}
    r.execute("SELECT count(*) FROM orders_p "
              "WHERE o_orderpriority = '1-URGENT'")
    touched = [rel for rel in files
               if wh._pcf("orders_p", rel).bytes_read > reads_before[rel]]
    urgent = [p["file"] for p in wh._meta("orders_p")["partitions"]
              if p["values"]["o_orderpriority"] == "1-URGENT"]
    assert touched == urgent  # non-matching partitions untouched


def test_insert_appends_new_partition_files(wh_runner):
    r, wh = wh_runner
    r.execute(
        "CREATE TABLE wh.t WITH (partitioned_by = 'o_orderpriority') "
        "AS SELECT o_orderkey, o_orderpriority FROM orders "
        "WHERE o_orderkey < 100")
    before = len(wh._meta("t")["partitions"])
    r.execute("INSERT INTO wh.t SELECT o_orderkey, o_orderpriority "
              "FROM orders WHERE o_orderkey >= 100 AND o_orderkey < 200")
    after = len(wh._meta("t")["partitions"])
    assert after > before  # INSERT wrote new partition files
    want = r.execute("SELECT count(*) FROM orders WHERE o_orderkey < 200").rows
    got = r.execute("SELECT count(*) FROM t").rows
    assert got == want


def test_unpartitioned_table_and_drop(wh_runner):
    r, wh = wh_runner
    r.execute("CREATE TABLE wh.flat AS SELECT o_orderkey FROM orders "
              "WHERE o_orderkey < 50")
    got = r.execute("SELECT count(*) FROM flat").rows[0][0]
    want = r.execute(
        "SELECT count(*) FROM orders WHERE o_orderkey < 50").rows[0][0]
    assert got == want
    r.execute("DROP TABLE wh.flat")
    assert "flat" not in wh.table_names()


def test_bigint_partition_values(wh_runner):
    r, wh = wh_runner
    r.execute(
        "CREATE TABLE wh.bykey WITH (partitioned_by = 'k') "
        "AS SELECT o_orderkey % 3 AS k, o_totalprice FROM orders")
    assert len(wh._meta("bykey")["partitions"]) == 3
    want = sorted(r.execute(
        "SELECT o_orderkey % 3 AS k, sum(o_totalprice) FROM orders "
        "GROUP BY 1").rows)
    got = sorted(r.execute(
        "SELECT k, sum(o_totalprice) FROM bykey GROUP BY k").rows)
    assert got == want


def test_dynamic_partition_insert_new_value(wh_runner):
    """INSERT with a partition value unseen at CTAS time creates a new
    partition (dynamic partitioning) instead of a dictionary error."""
    r, wh = wh_runner
    r.execute(
        "CREATE TABLE wh.dyn WITH (partitioned_by = 'o_orderpriority') "
        "AS SELECT o_orderkey, o_orderpriority FROM orders "
        "WHERE o_orderpriority = '1-URGENT'")
    assert len(wh._meta("dyn")["partitions"]) == 1
    r.execute("INSERT INTO wh.dyn SELECT o_orderkey, o_orderpriority "
              "FROM orders WHERE o_orderpriority = '2-HIGH'")
    vals = {p["values"]["o_orderpriority"]
            for p in wh._meta("dyn")["partitions"]}
    assert vals == {"1-URGENT", "2-HIGH"}
    want = r.execute("SELECT count(*) FROM orders WHERE o_orderpriority "
                     "IN ('1-URGENT', '2-HIGH')").rows
    assert r.execute("SELECT count(*) FROM dyn").rows == want


def test_delete_from_warehouse_table(wh_runner):
    r, wh = wh_runner
    r.execute(
        "CREATE TABLE wh.d WITH (partitioned_by = 'o_orderpriority') "
        "AS SELECT o_orderkey, o_orderpriority FROM orders")
    before = r.execute("SELECT count(*) FROM d").rows[0][0]
    res = r.execute("DELETE FROM d WHERE o_orderpriority = '1-URGENT'")
    assert res.rows[0][0] > 0
    after = r.execute("SELECT count(*) FROM d").rows[0][0]
    assert after == before - res.rows[0][0]
    # partitioning survives the delete-by-rewrite
    assert wh.partition_columns("d") == ["o_orderpriority"]


def test_warehouse_transaction_staging(wh_runner):
    r, wh = wh_runner
    r.execute("START TRANSACTION")
    r.execute("CREATE TABLE wh.txt AS SELECT o_orderkey FROM orders "
              "WHERE o_orderkey < 20")
    assert "txt" not in wh.table_names()  # staged, not applied
    r.execute("COMMIT")
    assert "txt" in wh.table_names()
    got = r.execute("SELECT count(*) FROM txt").rows[0][0]
    want = r.execute(
        "SELECT count(*) FROM orders WHERE o_orderkey < 20").rows[0][0]
    assert got == want


def test_double_partition_key_rejected(wh_runner):
    r, wh = wh_runner
    with pytest.raises(Exception, match="unsupported type"):
        r.execute("CREATE TABLE wh.bad WITH (partitioned_by = 'd') "
                  "AS SELECT cast(o_totalprice as double) AS d, o_orderkey "
                  "FROM orders")


def test_properties_rejected_by_plain_connectors(tmp_path):
    from presto_tpu.connectors.memory import MemoryConnector

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=1024))
    catalog.register("mem", MemoryConnector(), writable=True)
    r = QueryRunner(catalog)
    with pytest.raises(Exception, match="does not support CREATE TABLE"):
        r.execute("CREATE TABLE mem.t WITH (partitioned_by = 'x') "
                  "AS SELECT o_orderkey AS x FROM orders")


def test_show_partitions_statement(tmp_path):
    """SHOW PARTITIONS FROM t (SqlBase.g4:89) lists the metastore's
    partition values."""
    from presto_tpu.catalog import Catalog
    from presto_tpu.runner import QueryRunner

    wh = WarehouseConnector(str(tmp_path))
    cat = Catalog()
    cat.register("wh", wh, writable=True)
    r = QueryRunner(cat)
    r.execute("create table pt with (partitioned_by = 'g') as "
              "select * from (values (1, 'a'), (2, 'b'), (3, 'a')) t(x, g)")
    assert sorted(r.execute("show partitions from pt").rows) == [
        ("a",), ("b",)]
