"""Cluster metrics plane: OpenMetrics exposition, HBM/memory
accounting surfaces, and live query progress (ISSUE 4).

Covers: spec-valid ``GET /v1/metrics`` on coordinator AND worker
(names, label escaping, cumulative bucket monotonicity via a line
grammar), the ``system_metrics`` node column + cluster rollup,
``system_memory_pools`` nonzero reservations, the low-memory-kill
counter + query-log event line, EXPLAIN ANALYZE per-operator peak
memory, and statement-protocol progress monotonicity for TPC-H Q3.
"""

import json
import re
import sys
import os
import urllib.error
import urllib.request

import pytest

from presto_tpu import obs
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.system import QueryHistory, SystemConnector
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.memory import MemoryPool, QueryMemoryContext
from presto_tpu.runner import QueryRunner

from tests.tpch_queries import QUERIES

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def make_runner(sf=0.001, split_rows=4096):
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=sf, split_rows=split_rows))
    history = QueryHistory()
    runner = QueryRunner(catalog)
    catalog.register("system", SystemConnector(history))
    runner.events.add(history)
    return runner, history


# ---------------------------------------------------------------------------
# OpenMetrics grammar validation
# ---------------------------------------------------------------------------

_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABELS = r"\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\"" \
          r"(?:,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\\\|\\\"|\\n)*\")*\}"
_VALUE = r"(?:[+-]?(?:\d+(?:\.\d+)?(?:[eE][+-]?\d+)?|Inf)|NaN)"
_SAMPLE_RE = re.compile(rf"^({_NAME})({_LABELS})? {_VALUE}$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME}) (counter|gauge|histogram)$")
_LE_RE = re.compile(r'le="([^"]*)"')


def validate_openmetrics(text: str) -> dict:
    """Line-grammar check for an OpenMetrics body; returns
    {family: type}.  Asserts on: name charset, sample/label shape,
    samples belonging to a declared family, counter ``_total`` suffix,
    cumulative bucket monotonicity and ``+Inf == _count``."""
    assert text.endswith("# EOF\n"), "body must end with # EOF"
    families = {}
    buckets = {}  # family -> [(le, value)]
    counts = {}
    for line in text.splitlines():
        if line == "# EOF":
            continue
        if line.startswith("#"):
            m = _TYPE_RE.match(line)
            assert m, f"bad metadata line: {line!r}"
            families[m.group(1)] = m.group(2)
            continue
        m = _SAMPLE_RE.match(line)
        assert m, f"bad sample line: {line!r}"
        sample, labels = m.group(1), m.group(2)
        fam = next((f for f in families
                    if sample == f or (sample.startswith(f)
                                       and sample[len(f):] in
                                       ("_total", "_sum", "_count",
                                        "_bucket"))), None)
        assert fam is not None, f"sample {sample!r} has no # TYPE family"
        kind = families[fam]
        value = float(line.rsplit(" ", 1)[1])
        if kind == "counter":
            assert sample == f"{fam}_total", \
                f"counter sample {sample!r} must end _total"
            assert value >= 0
        if kind == "histogram" and sample == f"{fam}_bucket":
            le = _LE_RE.search(labels or "")
            assert le, f"bucket sample without le label: {line!r}"
            buckets.setdefault(fam, []).append((le.group(1), value))
        if kind == "histogram" and sample == f"{fam}_count":
            counts[fam] = value
    for fam, series in buckets.items():
        values = [v for _, v in series]
        assert values == sorted(values), \
            f"{fam} buckets not cumulative-monotone: {series}"
        assert series[-1][0] == "+Inf", f"{fam} missing +Inf bucket"
        assert series[-1][1] == counts.get(fam), \
            f"{fam} +Inf bucket != _count"
    return families


def test_render_grammar_and_types():
    reg = obs.MetricsRegistry()
    reg.counter("query.started").inc(3)
    reg.counter("dist.stages_total").inc(2)
    reg.gauge("memory.pool_reserved_bytes").set(123.0)
    h = reg.histogram("query.execution_ms")
    for v in (0.5, 3.0, 3.0, 3000.0):
        h.observe(v)
    text = obs.openmetrics.render(reg)
    families = validate_openmetrics(text)
    assert families["query_started"] == "counter"
    # catalog names already ending _total don't double the suffix
    assert families["dist_stages"] == "counter"
    assert "dist_stages_total 2" in text
    assert families["memory_pool_reserved_bytes"] == "gauge"
    assert families["query_execution_ms"] == "histogram"
    # cumulative: le=1 has the 0.5 sample, le=4 adds both 3.0s
    assert 'query_execution_ms_bucket{le="1"} 1' in text
    assert 'query_execution_ms_bucket{le="4"} 3' in text
    assert 'query_execution_ms_bucket{le="+Inf"} 4' in text
    assert "query_execution_ms_count 4" in text


def test_label_escaping():
    assert obs.openmetrics.escape_label_value('a"b\\c\nd') \
        == 'a\\"b\\\\c\\nd'
    assert obs.openmetrics.metric_name("query.exec-ms/9") == "query_exec_ms_9"
    assert obs.openmetrics.metric_name("9lives") == "_9lives"


def test_live_coordinator_and_worker_expose_openmetrics():
    from presto_tpu.server.coordinator import CoordinatorServer
    from presto_tpu.server.worker import WorkerServer

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    wrk = WorkerServer(catalog, memory_pool=MemoryPool(1 << 30))
    wrk.start()
    runner, _ = make_runner()
    srv = CoordinatorServer(runner, worker_uris=[wrk.uri])
    srv.start()
    try:
        # move some counters + the execution histogram
        with urllib.request.urlopen(urllib.request.Request(
                f"{srv.uri}/v1/statement",
                data=b"select count(*) from nation", method="POST"),
                timeout=60) as r:
            assert json.load(r)["stats"]["state"] == "FINISHED"
        for uri in (srv.uri, wrk.uri):
            req = urllib.request.Request(f"{uri}/v1/metrics")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.headers["Content-Type"].startswith(
                    "application/openmetrics-text")
                text = r.read().decode()
            families = validate_openmetrics(text)
            assert families["query_execution_ms"] == "histogram"
            assert "query_started_total" in text
            # JSON twin for machine polling
            with urllib.request.urlopen(
                    f"{uri}/v1/metrics?format=json", timeout=10) as r:
                doc = json.load(r)
            assert doc["node"]
            names = {n for n, _ in doc["metrics"]}
            assert "query.started" in names
        # the query moved the coordinator-side histogram
        cm = dict(obs.METRICS.snapshot())
        assert cm["query.execution_ms.count"] >= 1
        # the coordinator auto-wired its worker polls into the runner's
        # SystemConnector: SQL sees the worker node + cluster rollup
        res = runner.execute(
            "select node from system_metrics"
            " where name = 'query.started'")
        nodes = {r[0] for r in res.rows}
        assert "cluster" in nodes and "local" in nodes
        assert any(n.startswith("worker-") for n in nodes), nodes
        # ...and system_memory_pools covers the worker's pool too
        res = runner.execute(
            "select node, limit_bytes from system_memory_pools")
        assert any(limit == (1 << 30) for _, limit in res.rows), res.rows
    finally:
        srv.stop()
        wrk.stop()


# ---------------------------------------------------------------------------
# memory accounting surfaces
# ---------------------------------------------------------------------------

def test_worker_info_reports_per_query_breakdown():
    from presto_tpu.server.worker import WorkerServer

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    pool = MemoryPool(1 << 30)
    wrk = WorkerServer(catalog, memory_pool=pool)
    wrk.start()
    try:
        ctx = QueryMemoryContext(pool, "q_breakdown")
        ctx.reserve("join_build", 4096)
        with urllib.request.urlopen(f"{wrk.uri}/v1/info", timeout=10) as r:
            info = json.load(r)
        mem = info["memory"]
        assert mem["reserved"] >= 4096
        assert mem["limit"] == 1 << 30
        assert mem["peak"] >= 4096
        # killer decisions reproducible from scraped data alone
        assert mem["query_reservations"]["q_breakdown"] == 4096
    finally:
        wrk.stop()


def test_system_metrics_node_column_and_cluster_rollup():
    runner, history = make_runner()
    remote = {"worker-9": [("query.started", 2.0), ("spill.bytes", 5.0)]}
    runner.catalog.register(
        "sys2", SystemConnector(history, remote_metrics=lambda: remote))
    runner._invalidate_plans()
    res = runner.execute(
        "select node, value from sys2.system_metrics "
        "where name = 'query.started' order by node")
    by_node = dict(res.rows)
    assert set(by_node) == {"cluster", "local", "worker-9"}
    assert by_node["worker-9"] == 2.0
    assert by_node["cluster"] == by_node["local"] + 2.0
    # without remote nodes there is no rollup row (it would duplicate)
    res = runner.execute(
        "select node from system_metrics where name = 'spill.bytes'")
    # both connectors registered; the plain system one has local only
    nodes = {r[0] for r in res.rows}
    assert "local" in nodes


def test_system_memory_pools_shows_live_reservations():
    runner, _ = make_runner()
    pool = runner.executor.memory_pool
    assert pool is not None
    ctx = QueryMemoryContext(pool, "q_pools_test")
    ctx.reserve("join_build", 1 << 20)
    try:
        res = runner.execute(
            "select node, reserved_bytes, peak_bytes, limit_bytes, queries"
            " from system_memory_pools")
        assert len(res.rows) >= 1
        node, reserved, peak, limit, queries = res.rows[0]
        assert reserved >= (1 << 20)
        assert peak >= reserved
        assert limit > 0
        assert queries >= 1
    finally:
        ctx.release_all()


def test_memory_pool_gauges_wired():
    runner, _ = make_runner()
    pool = runner.executor.memory_pool
    from presto_tpu.memory import wire_pool_gauges

    wire_pool_gauges(pool)
    ctx = QueryMemoryContext(pool, "q_gauge")
    ctx.reserve("agg", 2048)
    try:
        snap = dict(obs.METRICS.snapshot())
        assert snap["memory.pool_reserved_bytes"] >= 2048
        assert snap["memory.pool_limit_bytes"] == pool.limit
        assert snap["memory.pool_queries"] >= 1
    finally:
        ctx.release_all()


def test_low_memory_kill_emits_counter_and_log_event(tmp_path):
    from presto_tpu.cluster_memory import ClusterMemoryManager
    from presto_tpu.events import EventListenerManager

    log = tmp_path / "query.log"
    events = EventListenerManager()
    events.add(obs.QueryLogListener(str(log)))
    pool = MemoryPool(1000)
    killed = []
    mgr = ClusterMemoryManager(pool, killed.append, threshold=0.5,
                               events=events)
    before = obs.METRICS.counter("memory.query_killed").value
    QueryMemoryContext(pool, "victim").reserve("huge", 900)
    assert mgr.check_once() == "victim"
    assert obs.METRICS.counter("memory.query_killed").value == before + 1
    lines = [json.loads(l) for l in log.read_text().splitlines()]
    kills = [l for l in lines if l.get("event") == "memory_kill"]
    assert len(kills) == 1
    assert kills[0]["query_id"] == "victim"
    assert kills[0]["freed_bytes"] == 900
    assert kills[0]["limit_bytes"] == 1000


def test_explain_analyze_reports_per_operator_peak_memory():
    runner, _ = make_runner(sf=0.002, split_rows=2048)
    res = runner.execute(
        "explain analyze select n_name, count(*)"
        " from nation, supplier where n_nationkey = s_nationkey"
        " group by n_name")
    text = res.rows[0][0]
    assert "peak reserved memory:" in text
    # the join build and/or aggregation accumulator attribute to their
    # own plan lines from the tagged reservations
    assert "peak_mem=" in text, text


# ---------------------------------------------------------------------------
# live progress
# ---------------------------------------------------------------------------

def test_query_progress_monotone_under_stage_resets():
    p = obs.QueryProgress("q_prog")
    st = p.stage("scan:a", splits_total=4)
    assert p.percentage() == 0.0
    p.split_done("scan:a")
    p.split_done("scan:a")
    mid = p.percentage()
    assert mid == 50.0
    # a new stage appears: the raw ratio dips, the figure must not
    p.stage("scan:b", splits_total=4)
    assert p.percentage() >= mid
    # a retry resets stage a — still monotone
    p.stage("scan:a", splits_total=4)
    assert p.percentage() >= mid
    p.mark_done()
    assert p.percentage() == 100.0
    snap = p.snapshot()
    assert snap["progressPercentage"] == 100.0
    assert all(s["state"] == "FINISHED" for s in snap["stages"])
    del st


def test_runner_publishes_scan_progress():
    runner, _ = make_runner(sf=0.002, split_rows=1024)
    res = runner.execute("select count(*) from lineitem",
                         query_id="q_scan_prog")
    assert res.rows
    prog = obs.progress_for("q_scan_prog")
    assert prog is not None
    snap = prog.snapshot()
    assert snap["done"] and snap["progressPercentage"] == 100.0
    scans = [s for s in snap["stages"] if s["stage"].startswith("scan:")]
    assert scans, snap["stages"]
    assert any(s["splitsTotal"] and s["splitsDone"] == s["splitsTotal"]
               and s["bytes"] > 0 for s in scans)


def test_statement_protocol_progress_monotone_q3():
    from presto_tpu.client import StatementClient
    from presto_tpu.server.coordinator import CoordinatorServer

    runner, _ = make_runner(sf=0.01, split_rows=2048)
    srv = CoordinatorServer(runner)
    srv.start()
    try:
        client = StatementClient(srv.uri)
        seen = []

        def on_progress(stats):
            if "progressPercentage" in stats:
                seen.append(stats["progressPercentage"])

        columns, rows = client.execute(QUERIES[3], on_progress=on_progress)
        assert rows, "Q3 returned no rows"
        assert columns[0]["name"]
        assert seen, "no page carried progressPercentage"
        assert seen == sorted(seen), f"progress regressed: {seen}"
        assert seen[-1] == 100.0
    finally:
        srv.stop()


def test_progress_endpoint_and_ui_detail():
    from presto_tpu.server.coordinator import CoordinatorServer

    runner, _ = make_runner()
    srv = CoordinatorServer(runner)
    srv.start()
    try:
        with urllib.request.urlopen(urllib.request.Request(
                f"{srv.uri}/v1/statement",
                data=b"select count(*) from orders", method="POST"),
                timeout=60) as r:
            doc = json.load(r)
        qid = doc["id"]
        assert doc["stats"]["progressPercentage"] == 100.0
        with urllib.request.urlopen(
                f"{srv.uri}/v1/query/{qid}/progress", timeout=10) as r:
            snap = json.load(r)
        assert snap["queryId"] == qid
        assert snap["progressPercentage"] == 100.0
        assert isinstance(snap["stages"], list)
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"{srv.uri}/v1/query/nope/progress", timeout=10)
        with urllib.request.urlopen(f"{srv.uri}/ui", timeout=10) as r:
            html = r.read().decode()
        assert "/progress" in html and "span timeline" in html
        # the query list carries the progress column
        with urllib.request.urlopen(f"{srv.uri}/v1/query", timeout=10) as r:
            qs = json.load(r)
        assert any(q.get("progress") == 100.0 for q in qs)
    finally:
        srv.stop()


def test_cli_progress_text():
    from presto_tpu.cli import _progress_text

    text = _progress_text({
        "progressPercentage": 42.5,
        "stages": [{"stage": "scan:lineitem#0", "state": "RUNNING",
                    "splitsDone": 3, "splitsTotal": 8,
                    "rows": 100, "bytes": 10}],
    })
    assert "42.5%" in text and "scan:lineitem#0 3/8" in text


# ---------------------------------------------------------------------------
# bench trajectory diff (tools/bench_compare.py)
# ---------------------------------------------------------------------------

def test_bench_compare_flags_regressions(tmp_path):
    import bench_compare

    old = {"parsed": {"rates": {"q1": 100.0, "q3": 50.0},
                      "raw_times": {"q1": [1.0, 1.1, 1.05]}}}
    new = {"parsed": {"rates": {"q1": 70.0, "q3": 55.0},
                      "raw_times": {"q1": [1.4, 1.5, 1.45]}}}
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(old))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(new))
    result = bench_compare.compare(old["parsed"], new["parsed"])
    assert result["regressions"] == ["q1"]
    q1 = next(e for e in result["queries"] if e["query"] == "q1")
    assert q1["regression"] and q1["new_median_s"] == 1.45
    # report mode exits 0 even with regressions; strict exits 1
    assert bench_compare.main(["--dir", str(tmp_path)]) == 0
    assert bench_compare.main(["--dir", str(tmp_path), "--strict"]) == 1
    # fewer than two rounds: clean no-op
    assert bench_compare.main(["--dir", str(tmp_path / "nope")]) == 0
