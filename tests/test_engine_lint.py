"""engine_lint: per-rule fixture snippets + the repo-wide lint-clean
pin (tier-1).  The pin is the CI contract ISSUE 2 establishes: a PR
reintroducing a recompile/crash hazard (raw capacity, hot-path env
read, traced branch, device sync, SPI exception leak) fails here with
the exact file:line."""

import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import engine_lint  # noqa: E402

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _lint_snippet(tmp_path, code, name="snippet.py", subdir=""):
    d = tmp_path / "presto_tpu" / subdir if subdir else tmp_path
    d.mkdir(parents=True, exist_ok=True)
    p = d / name
    p.write_text(textwrap.dedent(code))
    return engine_lint.lint_file(str(p))


# ---------------------------------------------------------------------------
# per-rule fixtures
# ---------------------------------------------------------------------------

def test_env_read_in_function_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import os

        def per_page_hot_path(page):
            return os.environ.get("PRESTO_TPU_X", "1")
    """)
    assert [f.rule for f in findings] == ["env-read"]


def test_env_read_resolve_once_allowed(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import os

        _X = os.environ.get("AT_IMPORT", "1")  # module scope: fine

        def resolve_x():
            return os.environ.get("PRESTO_TPU_X")

        def x_enabled():
            return os.environ.get("PRESTO_TPU_X", "1") != "0"

        class C:
            def __init__(self):
                self.x = os.environ.get("PRESTO_TPU_X")
    """)
    assert findings == []


def test_env_read_suppression_comment(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import os

        def hot(page):
            return os.environ.get("X")  # lint: allow(env-read)
    """)
    assert findings == []


def test_traced_branch_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def f(mask):
            if jnp.any(mask):
                return 1
            while jnp.sum(mask) > 0:
                pass
    """)
    assert [f.rule for f in findings] == ["traced-branch", "traced-branch"]


def test_dtype_predicates_not_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def f(data):
            if jnp.issubdtype(data.dtype, jnp.floating):
                return 1
    """)
    assert findings == []


def test_device_sync_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def f(lane):
            lo = int(jnp.min(lane))
            hi = float(jnp.max(lane))
            v = lane.sum().item()
            return lo, hi, v
    """)
    assert [f.rule for f in findings] == ["device-sync"] * 3


def test_device_sync_metadata_exempt(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def f():
            return float(jnp.iinfo(jnp.int64).min)
    """)
    assert findings == []


def test_block_until_ready_in_ops_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax

        def kernel(page):
            jax.block_until_ready(page)
    """, subdir="ops")
    assert [f.rule for f in findings] == ["block-until-ready"]


def test_bare_except_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def f():
            try:
                return 1
            except:
                return 2
    """)
    assert [f.rule for f in findings] == ["bare-except"]


def test_spi_exception_leak_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def bind(name, scope):
            if name not in scope:
                raise KeyError(name)
            raise AssertionError("unreachable")
    """, subdir="sql")
    assert [f.rule for f in findings] == ["spi-exception", "spi-exception"]


def test_spi_rule_scoped_to_frontend(tmp_path):
    # the same raise outside sql// expr/ir.py is internal dispatch
    findings = _lint_snippet(tmp_path, """
        def dispatch(kind):
            raise KeyError(kind)
    """, subdir="ops")
    assert findings == []


def test_raw_capacity_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def store(page, rows, Page):
            return Page.from_arrays(rows, [], capacity=len(rows))
    """)
    assert [f.rule for f in findings] == ["raw-capacity"]


def test_ladder_routed_capacity_allowed(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def store(page, rows, Page, bucket_capacity):
            return Page.from_arrays(
                rows, [], capacity=bucket_capacity(len(rows)))
    """)
    assert findings == []


def test_wallclock_duration_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import time

        def measure(run):
            t0 = time.time()
            run()
            return time.time() - t0

        def deadline(timeout):
            return time.time() + timeout
    """)
    assert [f.rule for f in findings] == ["wallclock", "wallclock"]


def test_wallclock_monotonic_and_timestamps_allowed(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import time

        def measure(run):
            t0 = time.perf_counter()
            run()
            return time.perf_counter() - t0

        def deadline(timeout):
            return time.monotonic() + timeout

        def stamp():
            return time.time()  # plain epoch timestamp: fine
    """)
    assert findings == []


def test_wallclock_non_module_time_methods_allowed(tmp_path):
    # .time() methods that are not the time module are not clocks
    findings = _lint_snippet(tmp_path, """
        def schedule(sched, delay):
            return sched.time() + delay

        def diff(self, t0):
            return self.time() - t0
    """)
    assert findings == []


def test_wallclock_aliased_time_module_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import time as _time

        def measure(run):
            t0 = _time.time()
            run()
            return _time.time() - t0
    """)
    assert [f.rule for f in findings] == ["wallclock"]


def test_wallclock_from_import_flagged_once_per_expression(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import time
        from time import time as now

        def measure(run):
            t0 = now()
            run()
            return now() - t0

        def chained(a, b):
            return time.time() + a + b
    """)
    assert [f.rule for f in findings] == ["wallclock", "wallclock"]


def test_wallclock_suppression_comment(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import time

        def jwt_exp(ttl):
            return int(time.time()) + ttl  # lint: allow(wallclock)
    """)
    assert findings == []


def test_rule_filter_and_check_exit():
    rc = engine_lint.main(["--rule", "bare-except", "--check",
                           os.path.join(REPO, "presto_tpu")])
    assert rc == 0


def _lint_with_catalog(tmp_path, code, catalog):
    import textwrap

    p = tmp_path / "snippet_metrics.py"
    p.write_text(textwrap.dedent(code))
    return engine_lint.lint_file(str(p), metric_catalog=frozenset(catalog))


def test_metric_catalog_uncatalogued_name_flagged(tmp_path):
    findings = _lint_with_catalog(tmp_path, """
        from presto_tpu.obs import METRICS

        def record():
            METRICS.counter("query.started").inc()
            METRICS.counter("my.adhoc_counter").inc()
            METRICS.gauge("my.adhoc_gauge").set(1)
            METRICS.histogram("query.execution_ms").observe(3)
    """, {"query.started", "query.execution_ms"})
    assert [f.rule for f in findings] == ["metric-catalog"] * 2
    assert "my.adhoc_counter" in findings[0].message


def test_metric_catalog_allow_comment_and_dynamic_names(tmp_path):
    findings = _lint_with_catalog(tmp_path, """
        from presto_tpu.obs import METRICS

        def record(name):
            METRICS.counter("test.fixture").inc()  # metrics: allow
            METRICS.counter(name).inc()  # dynamic: not checkable
    """, {"query.started"})
    assert findings == []


def test_thread_pool_unbounded_executor_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor

        def fan_out(tasks):
            with ThreadPoolExecutor() as ex:
                return list(ex.map(str, tasks))
    """)
    assert [f.rule for f in findings] == ["thread-pool"]


def test_thread_pool_hardcoded_width_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor
        import threading

        def fan_out(tasks):
            ex = ThreadPoolExecutor(max_workers=8)
            workers = [threading.Thread(target=str) for _ in range(4)]
            return ex, workers
    """)
    assert [f.rule for f in findings] == ["thread-pool", "thread-pool"]


def test_thread_pool_config_derived_allowed(tmp_path):
    findings = _lint_snippet(tmp_path, """
        from concurrent.futures import ThreadPoolExecutor
        import threading

        def fan_out(tasks, workers, concurrency):
            ex = ThreadPoolExecutor(max_workers=concurrency)
            # per-target threads bounded by the (config-sized) worker
            # list, and a pool sized by a parameter: both legal
            ts = [threading.Thread(target=str, args=(w,)) for w in workers]
            for i in range(concurrency):
                threading.Thread(target=str, args=(i,))
            # literal START is fine — only the stop argument sizes the
            # pool (range(0, n) must not be misread as hard-coded)
            for i in range(0, concurrency):
                threading.Thread(target=str, args=(i,))
            return ex, ts
    """)
    assert findings == []


def test_thread_pool_suppression_comment(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import threading

        def two_phase():
            for _ in range(2):  # lint: allow(thread-pool)
                threading.Thread(target=str)
    """)
    # the allow comment sits on the loop line; the Thread call inside
    # still needs its own line-level suppression to pass
    assert [f.rule for f in findings] == ["thread-pool"]


def test_naked_urlopen_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import urllib.request

        def fetch(uri):
            with urllib.request.urlopen(uri) as r:
                return r.read()
    """)
    assert [f.rule for f in findings] == ["naked-urlopen"]


def test_naked_urlopen_with_timeout_allowed(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import urllib.request

        def fetch(uri, req):
            with urllib.request.urlopen(uri, timeout=5.0) as r:
                body = r.read()
            # third positional IS the timeout
            urllib.request.urlopen(uri, None, 10.0).close()
            return body
    """)
    assert findings == []


def test_naked_urlopen_suppression_comment(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import urllib.request

        def fetch(uri):
            return urllib.request.urlopen(uri)  # lint: allow(naked-urlopen)
    """)
    assert findings == []


def test_metric_catalog_discovered_from_repo():
    """Auto-discovery walks up to presto_tpu/obs/metrics.py: the real
    catalog governs files linted inside the repo."""
    catalog = engine_lint._metric_catalog_for(
        os.path.join(REPO, "presto_tpu", "runner.py"))
    assert catalog is not None
    assert "query.started" in catalog
    assert "memory.query_killed" in catalog
    assert "memory.pool_reserved_bytes" in catalog


# ---------------------------------------------------------------------------
# rule-purity: Rule.apply must not mutate its input or read the env
# ---------------------------------------------------------------------------

def test_rule_purity_attribute_assignment_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        class ShrinkLimit(Rule):
            def apply(self, node):
                node.count = 1  # in-place edit of the matched node
                return node
    """)
    assert [f.rule for f in findings] == ["rule-purity"]
    assert "node.count" in findings[0].message


def test_rule_purity_mutation_through_alias_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        class RecordThings(Rule):
            def apply(self, node):
                scan = node.source
                scan.constraints.extend([("a", "eq", 1)])
                for arm in node.source.inputs:
                    arm.names[0] = "renamed"
                return node
    """)
    assert [f.rule for f in findings] == ["rule-purity", "rule-purity"]
    assert ".extend() on scan.constraints" in findings[0].message


def test_rule_purity_env_read_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import os

        class EnvGated(Rule):
            def apply(self, node):
                if os.environ.get("FAST_MODE"):
                    return node.source
                return None
    """)
    assert [f.rule for f in findings
            if f.rule == "rule-purity"] == ["rule-purity"]


def test_rule_purity_fresh_construction_allowed(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import dataclasses

        class PureRewrite(Rule):
            def apply(self, node):
                projs = list(node.projections)  # fresh list: mutable
                projs.append(None)
                out = dataclasses.replace(node, projections=projs)
                out.cached = True  # fresh node: attribute set is fine
                return out

        class NotARule:
            def apply(self, node):
                node.count = 1  # not a Rule subclass: out of scope
                return node
    """)
    assert findings == []


def test_rule_purity_suppression_entry(tmp_path):
    code = """
        class Recorder(Rule):
            def apply(self, node):
                node.source.constraints.extend([1])
                return node
    """
    findings = _lint_snippet(tmp_path, code)
    assert [f.rule for f in findings] == ["rule-purity"]
    sup = tmp_path / "sup.txt"
    sup.write_text("snippet.py | rule-purity | constraints.extend | "
                   "reviewed: metadata-only recording\n")
    entries, problems = engine_lint.load_suppressions(str(sup))
    assert problems == []
    assert engine_lint.apply_suppressions(findings, entries) == []


def test_narrow_cast_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def kernel(d):
            a = d.astype(jnp.int32)
            b = jnp.asarray(d, dtype=jnp.int16)
            c = d.astype("int8")
            return a, b, c
    """, subdir="ops")
    assert [f.rule for f in findings] == ["narrow-cast"] * 3


def test_narrow_cast_type_map_and_fresh_construction_exempt(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def kernel(d, t):
            a = d.astype(t.np_dtype)              # declared type map
            idx = jnp.arange(8, dtype=jnp.int32)  # fresh construction
            z = jnp.zeros(8, dtype=jnp.int32)     # fresh construction
            wide = d.astype(jnp.int64)            # widening
            return a, idx, z, wide
    """, subdir="ops")
    assert findings == []


def test_narrow_cast_scoped_to_kernel_code(tmp_path):
    # non-kernel tiers (exec/, parallel/, obs/...) narrow host-side
    # bookkeeping values freely
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def helper(d):
            return d.astype(jnp.int32)
    """)
    assert findings == []


def test_narrow_cast_allow_comment(tmp_path):
    findings = _lint_snippet(tmp_path, """
        import jax.numpy as jnp

        def kernel(codes):
            # codes bounded by dictionary size
            return codes.astype(jnp.int32)  # lint: allow(narrow-cast)
    """, subdir="ops")
    assert findings == []


def test_protocol_state_write_outside_owner_flagged(tmp_path):
    # a transition bypass: WorkerHealth.state assigned outside
    # __init__/_transition defeats the model-checked detector machine
    findings = _lint_snippet(tmp_path, """
        class WorkerHealth:
            def __init__(self):
                self.state = "ALIVE"

            def _transition(self, new):
                self.state = new

            def force_dead(self):
                self.state = "DEAD"
    """, name="failure.py", subdir="parallel")
    assert [f.rule for f in findings] == ["protocol-state"]
    assert "force_dead" in findings[0].message


def test_protocol_state_ticket_flags_flagged(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def helper(ticket):
            ticket.released = True
            ticket.canceled = True
    """, name="admission.py", subdir="serving")
    assert sorted(f.rule for f in findings) == ["protocol-state"] * 2


def test_protocol_state_owner_methods_allowed(tmp_path):
    findings = _lint_snippet(tmp_path, """
        class TaskOutputBuffer:
            def __init__(self):
                self._acked = 0
                self._aborted = False
                self._complete = False

            def acknowledge(self, token):
                self._acked = max(self._acked, token)

            def abort(self):
                self._aborted = True

            def set_complete(self):
                self._complete = True

            def fail(self, message):
                self._complete = True
    """, name="buffers.py", subdir="server")
    assert findings == []


def test_protocol_state_scoped_to_owning_files(tmp_path):
    # `.state` names unrelated machines elsewhere (coordinator query
    # lifecycle, progress tracker) — the rule must not fire there
    findings = _lint_snippet(tmp_path, """
        def helper(q):
            q.state = "FINISHED"
            q.released = True
    """, name="coordinator.py", subdir="server")
    assert findings == []


def test_protocol_state_allow_comment(tmp_path):
    findings = _lint_snippet(tmp_path, """
        def test_fixture(h):
            h.state = "DEAD"  # lint: allow(protocol-state)
    """, name="failure.py", subdir="parallel")
    assert findings == []


# ---------------------------------------------------------------------------
# the repo-wide pin
# ---------------------------------------------------------------------------

def test_repo_lint_clean():
    """``tools/engine_lint.py --check presto_tpu tools`` exits 0 on
    HEAD — the ISSUE 2 acceptance pin (ISSUE 4 widened it to the tools
    themselves; ISSUE 8 moved reviewed exceptions into the shared
    suppression file).  A finding here names its file:line; fix it, or
    add a justified entry to tools/lint_suppressions.txt (inline
    ``# lint: allow(rule)`` stays available for line-local cases)."""
    findings = engine_lint.lint_paths([os.path.join(REPO, "presto_tpu"),
                                       os.path.join(REPO, "tools")])
    entries, problems = engine_lint.load_suppressions(
        engine_lint.DEFAULT_SUPPRESSIONS)
    assert problems == [], "\n".join(str(p) for p in problems)
    findings = engine_lint.apply_suppressions(findings, entries)
    assert findings == [], "\n".join(str(f) for f in findings)
