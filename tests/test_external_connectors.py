"""External-data connectors: DB-API (sqlite), local files, metrics.

Reference analogs: presto-base-jdbc, presto-local-file +
presto-record-decoder, presto-jmx.
"""

import os
import sqlite3

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.runner import QueryRunner


@pytest.fixture()
def sqlite_db(tmp_path):
    path = str(tmp_path / "ext.db")
    db = sqlite3.connect(path)
    db.execute("CREATE TABLE emp (id INTEGER PRIMARY KEY, name TEXT, "
               "salary REAL, hired DATE, active BOOLEAN)")
    db.executemany(
        "INSERT INTO emp VALUES (?, ?, ?, ?, ?)",
        [
            (1, "alice", 100.0, "2020-01-02", 1),
            (2, "bob", 85.5, "2021-06-30", 1),
            (3, "carol", None, "2019-11-11", 0),
            (4, None, 70.0, "2022-03-03", 1),
        ],
    )
    db.commit()
    db.close()
    return path


def test_jdbc_sqlite_scan_and_aggregate(sqlite_db):
    from presto_tpu.connectors.jdbc import JdbcConnector

    cat = Catalog()
    cat.register("ext", JdbcConnector.sqlite(sqlite_db))
    r = QueryRunner(cat)
    assert r.execute("SELECT count(*) FROM emp").rows == [(4,)]
    assert r.execute("SELECT name FROM emp WHERE id = 2").rows == [("bob",)]
    # NULLs survive the boundary
    assert r.execute("SELECT count(salary) FROM emp").rows == [(3,)]
    assert r.execute("SELECT sum(salary) FROM emp WHERE active").rows == [(255.5,)]
    assert r.execute("SELECT sum(salary) FROM emp WHERE not active").rows == [(None,)]
    # dates decode to engine DATE
    assert r.execute("SELECT count(*) FROM emp WHERE hired >= DATE '2021-01-01'").rows == [(2,)]


def test_jdbc_joins_engine_tables(sqlite_db):
    import numpy as np

    from presto_tpu.connectors.jdbc import JdbcConnector
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.page import Page
    from presto_tpu.types import BIGINT

    mem = MemoryConnector()
    mem.create_table("bonus", [("emp_id", BIGINT), ("amount", BIGINT)],
                     [Page.from_arrays([np.asarray([1, 2]), np.asarray([10, 20])],
                                       [BIGINT, BIGINT])])
    cat = Catalog()
    cat.register("ext", JdbcConnector.sqlite(sqlite_db))
    cat.register("mem", mem)
    r = QueryRunner(cat)
    rows = r.execute("SELECT e.name, b.amount FROM emp e JOIN bonus b "
                     "ON e.id = b.emp_id ORDER BY b.amount").rows
    assert rows == [("alice", 10), ("bob", 20)]


def test_jdbc_pushdown_escape_hatch(sqlite_db):
    from presto_tpu.connectors.jdbc import JdbcConnector

    conn = JdbcConnector.sqlite(sqlite_db)
    rows = conn.scan_remote("emp", ["id", "name"], "salary > ?", (80,))
    assert rows == [(1, "alice"), (2, "bob")]


def test_index_join_point_lookup(tmp_path):
    """Index join: the big remote table is fetched by probe keys only
    (IndexLoader analog)."""
    import numpy as np

    from presto_tpu.connectors.jdbc import JdbcConnector
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.page import Page
    from presto_tpu.planner.plan import JoinNode
    from presto_tpu.types import BIGINT

    path = str(tmp_path / "big.db")
    db = sqlite3.connect(path)
    db.execute("CREATE TABLE big (id INTEGER PRIMARY KEY, payload INTEGER)")
    db.executemany("INSERT INTO big VALUES (?, ?)",
                   [(i, i * 100) for i in range(5000)])
    db.commit()
    db.close()

    mem = MemoryConnector()
    mem.create_table("probe", [("k", BIGINT)],
                     [Page.from_arrays([np.asarray([3, 4999, 7, 3])], [BIGINT])])
    jdbc = JdbcConnector.sqlite(path)
    lookups = []
    orig = jdbc.index_lookup
    jdbc.index_lookup = lambda *a: (lookups.append(a), orig(*a))[1]
    cat = Catalog()
    cat.register("mem", mem)
    cat.register("ext", jdbc)
    r = QueryRunner(cat)

    sql = ("SELECT k, payload FROM probe JOIN big ON k = id ORDER BY k, payload")
    plan = r.plan(sql)

    def walk(n):
        yield n
        for s in n.sources:
            yield from walk(s)

    joins = [n for n in walk(plan) if isinstance(n, JoinNode)]
    assert joins and any(j.use_index for j in joins)
    rows = r.execute(sql).rows
    assert rows == [(3, 300), (3, 300), (7, 700), (4999, 499900)]
    # the lookup ran with only the distinct probe keys
    assert lookups and sorted(lookups[0][2]) == [(3,), (7,), (4999,)]


def test_localfile_csv_and_json(tmp_path):
    from presto_tpu.connectors.localfile import LocalFileConnector

    csv_path = tmp_path / "sales.csv"
    csv_path.write_text("region,amount\neast,10\nwest,20\neast,5\n")
    jsonl = tmp_path / "events.jsonl"
    jsonl.write_text('{"user": "u1", "n": 3}\n{"user": "u2"}\n')

    lf = LocalFileConnector()
    lf.add_table("sales", str(csv_path), "csv",
                 [("region", "varchar"), ("amount", "bigint")], header=True)
    lf.add_table("events", str(jsonl), "json",
                 [("user", "varchar"), ("n", "bigint")])
    cat = Catalog()
    cat.register("files", lf)
    r = QueryRunner(cat)
    assert r.execute("SELECT region, sum(amount) FROM sales "
                     "GROUP BY region ORDER BY region").rows == [
        ("east", 15), ("west", 20)]
    # missing json key -> NULL
    assert r.execute("SELECT count(*), count(n) FROM events").rows == [(2, 1)]


def test_localfile_directory_splits(tmp_path):
    from presto_tpu.connectors.localfile import LocalFileConnector

    d = tmp_path / "logs"
    d.mkdir()
    (d / "a.csv").write_text("1\n2\n")
    (d / "b.csv").write_text("3\n")
    lf = LocalFileConnector()
    lf.add_table("logs", str(d), "csv", [("x", "bigint")])
    assert lf.num_splits("logs") == 2
    cat = Catalog()
    cat.register("files", lf)
    r = QueryRunner(cat)
    assert r.execute("SELECT sum(x) FROM logs").rows == [(6,)]


def test_metrics_connector():
    from presto_tpu.connectors.metrics import MetricsConnector

    cat = Catalog()
    cat.register("metrics", MetricsConnector())
    r = QueryRunner(cat)
    rows = r.execute("SELECT name, value FROM runtime ORDER BY name").rows
    names = [n for n, _ in rows]
    assert "process.rss_kb" in names and "process.threads" in names
    assert all(v >= 0 for _, v in rows)
    devs = r.execute("SELECT count(*) FROM devices").rows
    assert devs[0][0] >= 1
