"""Host-RAM spill tier: aggregation and join state exceeding the memory
pool completes with correct results via partitioned (lifespan-style)
execution.

Reference analog: TestDistributedSpilledQueries /
TestHashJoinOperator.testInnerJoinWithSpill — queries run under a
constrained pool and must produce identical results to the unconstrained
run."""

import numpy as np
import pytest

import presto_tpu.exec.local as local_mod
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.exec.local import LocalRunner
from presto_tpu.memory import ExceededMemoryLimitError, MemoryPool
from presto_tpu.sql.binder import Binder

from tests.oracle import assert_rows_match


@pytest.fixture(scope="module")
def catalog():
    # small sf: every spilled bucket fold compiles at a fresh capacity
    # (uncacheable), so data volume directly buys suite wall-clock
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.004, split_rows=1 << 12))
    return catalog


AGG_SQL = ("select l_orderkey, count(*), sum(l_quantity), max(l_extendedprice)"
           " from lineitem group by l_orderkey")
JOIN_SQL = ("select o_orderkey, o_totalprice, l_quantity from orders, lineitem"
            " where o_orderkey = l_orderkey and l_linenumber = 1")
FULL_SQL = ("select o1.k, o2.k from"
            " (select o_orderkey as k from orders where o_orderkey < 40000) o1"
            " full outer join"
            " (select l_orderkey + 1 as k from lineitem where l_linenumber = 1) o2"
            " on o1.k = o2.k")


def run(catalog, sql, pool=None, **kw):
    runner = LocalRunner(catalog, memory_pool=pool, **kw)
    return runner.run(Binder(catalog).plan(sql))


def _agg_acc_bytes(catalog):
    """Measure the unconstrained aggregation accumulator footprint."""
    pool = MemoryPool(1 << 40)
    run(catalog, AGG_SQL, pool=pool)
    return max(n for t, n in pool_peek(pool).items() if "agg_accumulator" in t)


def pool_peek(pool):
    return getattr(pool, "_peek_tags", {})


class PeekPool(MemoryPool):
    """Pool that remembers every reservation size (test instrumentation)."""

    def __init__(self, limit):
        super().__init__(limit)
        self._peek_tags = {}

    def reserve(self, tag, nbytes, enforce=True):
        self._peek_tags[tag] = nbytes
        super().reserve(tag, nbytes, enforce=enforce)


def test_agg_spill_memory_trigger(catalog):
    expected = run(catalog, AGG_SQL).rows

    probe = PeekPool(1 << 40)
    run(catalog, AGG_SQL, pool=probe)
    acc_bytes = max(n for t, n in probe._peek_tags.items() if "agg_accumulator" in t)

    # pool too small for the in-place accumulator but fine for 1/8 buckets
    pool = MemoryPool(int(acc_bytes * 0.6))
    actual = run(catalog, AGG_SQL, pool=pool).rows
    assert_rows_match(actual, expected, ordered=False)


def test_agg_spill_capacity_trigger(catalog, monkeypatch):
    """Overflow beyond SPILL_GROUP_THRESHOLD switches to partitioned
    execution instead of doubling forever."""
    expected = run(catalog, AGG_SQL).rows

    monkeypatch.setattr(local_mod, "SPILL_GROUP_THRESHOLD", 1 << 12)
    binder = Binder(catalog)
    plan = binder.plan(AGG_SQL)
    # force a tiny initial capacity so the doubling path overflows
    from presto_tpu.planner.plan import AggregationNode

    node = plan
    while not isinstance(node, AggregationNode):
        node = node.source
    node.max_groups = 1 << 10
    runner = LocalRunner(catalog)
    actual = runner.run(plan).rows
    assert_rows_match(actual, expected, ordered=False)


def test_join_spill(catalog):
    expected = run(catalog, JOIN_SQL).rows

    probe = PeekPool(1 << 40)
    run(catalog, JOIN_SQL, pool=probe)
    build_bytes = max(n for t, n in probe._peek_tags.items() if "join_build@" in t)

    pool = MemoryPool(int(build_bytes * 0.6))
    actual = run(catalog, JOIN_SQL, pool=pool).rows
    assert_rows_match(actual, expected, ordered=False)
    # the partitioned path really ran (per-partition builds were tagged)
    peek = PeekPool(int(build_bytes * 0.6))
    run(catalog, JOIN_SQL, pool=peek)
    assert any("join_build_partition" in t for t in peek._peek_tags)


def test_full_outer_join_spill(catalog):
    expected = run(catalog, FULL_SQL).rows

    probe = PeekPool(1 << 40)
    run(catalog, FULL_SQL, pool=probe)
    build_bytes = max(n for t, n in probe._peek_tags.items() if "join_build@" in t)

    pool = MemoryPool(int(build_bytes * 0.6))
    actual = run(catalog, FULL_SQL, pool=pool).rows
    assert_rows_match(actual, expected, ordered=False)


def test_pool_still_enforced_for_oversized_results(catalog):
    """A query whose sort input genuinely exceeds the pool still fails
    cleanly (spill covers agg/join state, not arbitrary materialization)."""
    pool = MemoryPool(1 << 10)
    with pytest.raises(ExceededMemoryLimitError):
        run(catalog, "select * from lineitem order by l_extendedprice", pool=pool)
