"""The tpu-cached bench degradation path (bench.py) must actually work
when the tunnel recovers: a successful on-device run persists
TPU_MEASURED.json, and a later run with a dead tunnel loads it back as
platform "tpu-cached".  Round-3 shipped a watcher whose write path had
never fired; this fakes the recovery so the path is proven without a
tunnel (VERDICT r3 "next round" item 1a)."""

import importlib.util
import json
import os
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(HERE, "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules["bench_under_test"] = mod
    spec.loader.exec_module(mod)
    mod.TPU_FILE = str(tmp_path / "TPU_MEASURED.json")
    mod.BASELINE_FILE = str(tmp_path / "BASELINE_MEASURED.json")
    return mod


def test_save_then_load_roundtrip(tmp_path):
    bench = _load_bench(tmp_path)
    fake = {
        "platform": "tpu", "sf": 1.0,
        "rates": {"q1": 6.4e7, "q6": 1.9e8, "q3": 7.0e6},
        "device": {"q1": {"seconds": 0.09, "rows_per_sec": 6.6e7,
                          "bytes": 336000000, "gbps": 3.7}},
    }
    bench._save_tpu(fake)
    assert os.path.exists(bench.TPU_FILE)

    cached = bench._load_tpu(1.0)
    assert cached is not None
    assert cached["platform"] == "tpu-cached"
    assert cached["rates"] == {k: round(v, 1) for k, v in fake["rates"].items()}
    assert cached["device"]["q1"]["gbps"] == 3.7
    assert cached["measured_at"]
    # per-sf keying: sf10 absent
    assert bench._load_tpu(10.0) is None


def test_partial_runs_merge_per_query(tmp_path):
    bench = _load_bench(tmp_path)
    bench._save_tpu({"platform": "tpu", "sf": 1.0, "rates": {"q1": 1e7}})
    bench._save_tpu({"platform": "tpu", "sf": 1.0, "rates": {"q6": 2e7}})
    bench._save_tpu({"platform": "tpu", "sf": 10.0, "rates": {"q1": 9e6}})
    cached = bench._load_tpu(1.0)
    assert set(cached["rates"]) == {"q1", "q6"}
    assert bench._load_tpu(10.0)["rates"] == {"q1": 9000000.0}


def test_pinned_baseline_survives_multi_sf(tmp_path):
    """BASELINE_MEASURED.json is keyed by scale factor: pinning an SF10
    run must not clobber the pinned SF1 entry (pre-r4 bug:
    single-entry file)."""
    bench = _load_bench(tmp_path)
    sf1 = {"platform": "cpu", "sf": 1.0,
           "rates": {"q1": 1.1e7, "q6": 8.0e7, "q3": 1.7e6}}
    bench._pin_baseline(1.0, sf1, bench._load_baselines())
    sf10 = {"platform": "cpu", "sf": 10.0, "rates": {"q6": 7.5e7}}
    bench._pin_baseline(10.0, sf10, bench._load_baselines())

    loaded = bench._load_baselines()
    assert loaded["sf1"]["rates"]["q6"] == 8.0e7  # not clobbered
    assert loaded["sf10"]["rates"]["q6"] == 7.5e7


def test_legacy_single_entry_baseline_upgrades(tmp_path):
    bench = _load_bench(tmp_path)
    legacy = {"platform": "cpu", "sf": 1.0, "rates": {"q1": 1e7}}
    with open(bench.BASELINE_FILE, "w") as f:
        json.dump(legacy, f)
    loaded = bench._load_baselines()
    assert loaded["sf1"]["rates"]["q1"] == 1e7
    # a new sf pin keeps the upgraded sf1 entry on disk
    bench._pin_baseline(10.0, {"platform": "cpu", "sf": 10.0,
                               "rates": {"q1": 9e6}}, loaded)
    reloaded = bench._load_baselines()
    assert set(reloaded) == {"sf1", "sf10"}


def test_baseline_file_is_committed():
    """The pinned baseline must live in git: the watcher benches from a
    `git archive HEAD` snapshot, and an untracked baseline would be
    re-measured into vs_baseline=1.0 there (r3 failure mode).  Inside
    such an archive export there is no .git to ask — but the file
    having materialized there proves the same property."""
    import os
    import subprocess

    if not os.path.isdir(os.path.join(HERE, ".git")):
        assert os.path.exists(os.path.join(HERE, "BASELINE_MEASURED.json"))
        return
    out = subprocess.run(
        ["git", "ls-files", "BASELINE_MEASURED.json"], cwd=HERE,
        stdout=subprocess.PIPE).stdout.decode().strip()
    assert out == "BASELINE_MEASURED.json"
