"""Corpus plan-diff harness (tools/plan_diff.py).

The full 121-query corpus sweep runs as a ci.sh leg
(``python tools/plan_diff.py --check``); these tests pin the harness
mechanics — fingerprint determinism, golden-file integrity, the diff
report — plus a small live-replan slice against the committed goldens
so a rule change that moves TPC-H plan shapes fails tier-1 too, not
just the CI leg.
"""

import json
import os

import pytest

from tools.plan_diff import GOLDEN_PATH, diff, fingerprint


@pytest.fixture(scope="module")
def golden():
    assert os.path.exists(GOLDEN_PATH), "committed goldens missing"
    with open(GOLDEN_PATH) as f:
        return json.load(f)


def test_fingerprint_deterministic():
    shape = "Project\n  TableScan[nation]"
    assert fingerprint(shape) == fingerprint(shape)
    assert len(fingerprint(shape)) == 16
    assert fingerprint(shape) != fingerprint(shape + " ")


def test_golden_file_integrity(golden):
    # both corpora present, and every stored fingerprint is the hash
    # of its stored shape (a hand-edited golden can't sneak through)
    assert len(golden) == 121
    assert sum(1 for k in golden if k.startswith("tpch/")) == 22
    assert sum(1 for k in golden if k.startswith("tpcds/")) == 99
    for key, entry in golden.items():
        assert entry["fingerprint"] == fingerprint(entry["shape"]), key


def test_diff_reports_changes(capsys):
    base = {"tpch/1": {"fingerprint": "aaaa", "shape": "A"},
            "tpch/2": {"fingerprint": "bbbb", "shape": "B"}}
    assert diff(base, dict(base)) is False

    moved = {"tpch/1": {"fingerprint": "cccc", "shape": "A2"},
             "tpch/3": {"fingerprint": "dddd", "shape": "D"}}
    assert diff(base, moved) is True
    out = capsys.readouterr().out
    assert "CHANGED tpch/1" in out
    assert "REMOVED tpch/2" in out
    assert "NEW     tpch/3" in out


def test_live_replan_matches_goldens(golden):
    """Replan a slice of TPC-H and compare against the committed
    goldens — the same path the CI leg takes, scoped for tier-1."""
    from presto_tpu.analysis.soundness import plan_shape_str
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner
    from tests.tpch_queries import QUERIES

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.01))
    runner = QueryRunner(catalog)
    runner.session.set("validate_rewrites", True)
    for qid in (1, 3, 6, 14):
        shape = plan_shape_str(runner.binder.plan(QUERIES[qid]))
        assert fingerprint(shape) == golden[f"tpch/{qid}"]["fingerprint"], (
            f"tpch/{qid} plan shape moved vs goldens:\n{shape}")
