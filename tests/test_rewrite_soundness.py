"""Rewrite-soundness gate (presto_tpu/analysis/properties.py +
soundness.py + the IterativeOptimizer ``validate`` hook).

Three halves, mirroring tests/test_plan_validator.py's structure:

- the TPC-H corpus optimizes CLEAN with per-rewrite validation forced
  on (the TPC-DS corpus runs in the tools/plan_diff.py CI leg);
- ~8 deliberately unsound rules are each caught by their NAMED checker
  with the rule attributed — the gate's whole contract is "unsound
  rewrite -> rule name + checker + before/after snippet", not "wrong
  answer three stages later";
- the observability satellites: per-rule counters in EXPLAIN (TYPE
  VALIDATE) / EXPLAIN ANALYZE VERBOSE and the pre-registered
  ``optimizer.*`` metrics.
"""

import dataclasses

import pytest

from presto_tpu.analysis import (
    RewriteSoundnessError,
    check_rewrite,
    derive_properties,
    rewrite_validation_enabled,
    set_rewrite_validation,
)
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.expr.ir import AggCall, Call, call, col, lit
from presto_tpu.matching import Pattern
from presto_tpu.planner.iterative import (
    DEFAULT_RULES,
    IterativeOptimizer,
    OptimizerStats,
    Rule,
)
from presto_tpu.planner.plan import (
    AggregationNode,
    FilterNode,
    LimitNode,
    OutputNode,
    ProjectNode,
    SortNode,
    TopNNode,
    UnionNode,
    ValuesNode,
)
from presto_tpu.runner import QueryRunner
from presto_tpu.sql.parser import parse_query
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR
from tests.tpch_queries import QUERIES


def _random():
    # random() is not a registered SQL function in this engine; the
    # determinism checker keys on the _NONDETERMINISTIC name set
    return Call(type=DOUBLE, fn="random", args=())


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.01))
    return QueryRunner(catalog)


def _values(n=3):
    rows = [(i, f"s{i}") for i in range(n)]
    return ValuesNode(["a", "b"], [BIGINT, VARCHAR], rows)


def _optimize(plan, rule):
    """One seeded rule under the gate; DEFAULT_RULES stay out of the
    way so the violation is unambiguously the seed's."""
    return IterativeOptimizer(rules=[rule], validate=True).optimize(plan)


def _catch(plan, rule):
    with pytest.raises(RewriteSoundnessError) as ei:
        _optimize(plan, rule)
    return ei.value


# ---------------------------------------------------------------------------
# clean corpus: every TPC-H query optimizes with zero violations
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("qid", sorted(QUERIES))
def test_tpch_corpus_rewrites_sound(runner, qid):
    plan = runner.binder.plan_ast(parse_query(QUERIES[qid]),
                                  validate_rewrites=True)
    assert plan is not None


def test_env_flag_enables_gate_suite_wide():
    """conftest sets PRESTO_TPU_VALIDATE_REWRITES=1, so every suite
    query already runs under the gate — pin that wiring."""
    assert rewrite_validation_enabled() is True


def test_set_rewrite_validation_override():
    set_rewrite_validation(False)
    try:
        assert rewrite_validation_enabled() is False
    finally:
        set_rewrite_validation(None)
    assert rewrite_validation_enabled() is True


# ---------------------------------------------------------------------------
# seeded unsound rules: each caught by its named checker
# ---------------------------------------------------------------------------

def test_seeded_dropped_column_caught():
    class DropColumn(Rule):
        pattern = Pattern.type_of(ProjectNode)

        def apply(self, node):
            if len(node.projections) < 2:
                return None
            return ProjectNode(node.source, list(node.projections[:-1]),
                               list(node.names[:-1]))

    v = _values()
    plan = OutputNode(
        ProjectNode(v, [col(0, BIGINT), col(1, VARCHAR)], ["a", "b"]),
        ["a", "b"])
    err = _catch(plan, DropColumn())
    assert err.rule == "DropColumn"
    assert "output-schema" in {x.checker for x in err.violations}


def test_seeded_retyped_column_caught():
    class RetypeColumn(Rule):
        pattern = Pattern.type_of(ProjectNode).where(
            lambda n: any(p.type is BIGINT for p in n.projections))

        def apply(self, node):
            projs = [lit(0.0, DOUBLE) if p.type is BIGINT else p
                     for p in node.projections]
            return ProjectNode(node.source, projs, list(node.names))

    plan = OutputNode(ProjectNode(_values(), [col(0, BIGINT)], ["a"]), ["a"])
    err = _catch(plan, RetypeColumn())
    assert err.rule == "RetypeColumn"
    assert "output-schema" in {x.checker for x in err.violations}


def test_seeded_widened_exact_count_caught():
    class WidenLimit(Rule):
        pattern = Pattern.type_of(LimitNode).where(lambda n: n.count == 2)

        def apply(self, node):
            return LimitNode(node.source, 3)

    plan = OutputNode(LimitNode(_values(3), 2), ["a", "b"])
    err = _catch(plan, WidenLimit())
    assert err.rule == "WidenLimit"
    assert "row-count" in {x.checker for x in err.violations}
    # the diagnostic carries before/after plan snippets
    assert "before:" in str(err) and "after:" in str(err)


def test_seeded_lost_ordering_caught():
    class DropSortKeepCount(Rule):
        pattern = Pattern.type_of(TopNNode)

        def apply(self, node):
            return LimitNode(node.source, node.count)  # forgot the sort

    plan = OutputNode(
        TopNNode(_values(), [col(0, BIGINT)], [True], 2, None), ["a", "b"])
    err = _catch(plan, DropSortKeepCount())
    assert err.rule == "DropSortKeepCount"
    assert "ordering" in {x.checker for x in err.violations}


def test_seeded_duplicate_node_caught():
    class SelfUnion(Rule):
        pattern = Pattern.type_of(FilterNode)

        def apply(self, node):
            fresh = FilterNode(node.source, node.predicate)
            return UnionNode([fresh, fresh])  # one node, two positions

    plan = OutputNode(
        FilterNode(_values(), call("gt", col(0, BIGINT), lit(0, BIGINT))),
        ["a", "b"])
    err = _catch(plan, SelfUnion())
    assert err.rule == "SelfUnion"
    assert "duplicate-node" in {x.checker for x in err.violations}


def test_seeded_stale_columnref_caught():
    class StaleRef(Rule):
        pattern = Pattern.type_of(FilterNode)

        def apply(self, node):
            # predicate indexes a channel the source does not have
            return FilterNode(node.source,
                              call("gt", col(7, BIGINT), lit(0, BIGINT)))

    plan = OutputNode(
        FilterNode(_values(), call("gt", col(0, BIGINT), lit(0, BIGINT))),
        ["a", "b"])
    err = _catch(plan, StaleRef())
    assert err.rule == "StaleRef"
    assert "dangling-columnref" in {x.checker for x in err.violations}


def test_seeded_nondeterministic_hoist_caught():
    class DoubleRandom(Rule):
        pattern = Pattern.type_of(ProjectNode).where(
            lambda n: any(getattr(p, "fn", None) == "random"
                          for p in n.projections))

        def apply(self, node):
            projs = [call("add", p, _random())
                     if getattr(p, "fn", None) == "random" else p
                     for p in node.projections]
            return ProjectNode(node.source, projs, list(node.names))

    plan = OutputNode(
        ProjectNode(_values(), [_random()], ["r"]), ["r"])
    err = _catch(plan, DoubleRandom())
    assert err.rule == "DoubleRandom"
    assert "determinism" in {x.checker for x in err.violations}


def test_seeded_lost_uniqueness_caught():
    class DropDistinct(Rule):
        """distinct-projecting aggregation replaced by its source —
        uniqueness of the group key is lost."""

        pattern = Pattern.type_of(AggregationNode).where(
            lambda n: not n.aggs and n.step == "single")

        def apply(self, node):
            return node.source

    v = ValuesNode(["a"], [BIGINT], [(1,), (1,), (2,)])
    plan = OutputNode(
        AggregationNode(v, [col(0, BIGINT)], ["a"], [], [], "single"),
        ["a"])
    err = _catch(plan, DropDistinct())
    assert err.rule == "DropDistinct"
    assert "keys" in {x.checker for x in err.violations}


def test_seeded_sort_dropped_entirely_caught():
    class DropSort(Rule):
        pattern = Pattern.type_of(SortNode)

        def apply(self, node):
            return node.source

    plan = OutputNode(
        SortNode(_values(), [col(0, BIGINT)], [True], None), ["a", "b"])
    err = _catch(plan, DropSort())
    assert err.rule == "DropSort"
    assert "ordering" in {x.checker for x in err.violations}


def test_violations_off_without_validate():
    """The same unsound rule passes silently with validate=False — the
    gate, not luck, is what catches it."""
    class WidenLimit(Rule):
        pattern = Pattern.type_of(LimitNode).where(lambda n: n.count == 2)

        def apply(self, node):
            return LimitNode(node.source, 3)

    plan = OutputNode(LimitNode(_values(3), 2), ["a", "b"])
    out = IterativeOptimizer(rules=[WidenLimit()]).optimize(plan)
    assert out is not None  # silently wrong: exactly the pre-gate world


# ---------------------------------------------------------------------------
# logical-properties unit checks
# ---------------------------------------------------------------------------

def test_properties_values_exact():
    p = derive_properties(_values(4))
    assert (p.lo, p.hi, p.exact) == (4, 4, 4)
    assert p.names == ("a", "b")


def test_properties_limit_tightens():
    p = derive_properties(LimitNode(_values(5), 2))
    assert p.exact == 2


def test_properties_filter_upper_bound_only():
    p = derive_properties(
        FilterNode(_values(5), call("gt", col(0, BIGINT), lit(3, BIGINT))))
    assert (p.lo, p.hi, p.exact) == (0, 5, None)


def test_properties_scan_keys_from_primary_key(runner):
    plan = runner.binder.plan("SELECT n_nationkey, n_name FROM nation")
    p = derive_properties(plan)
    assert frozenset([0]) in p.keys  # pk column survives projection
    assert p.exact == 25


def test_properties_distinct_agg_keys():
    v = ValuesNode(["a"], [BIGINT], [(1,), (1,), (2,)])
    agg = AggregationNode(v, [col(0, BIGINT)], ["a"], [], [], "single")
    p = derive_properties(agg)
    assert frozenset([0]) in p.keys


def test_properties_topn_ordering():
    p = derive_properties(
        TopNNode(_values(), [col(0, BIGINT)], [True], 2, None))
    assert len(p.ordering) == 1 and p.ordering[0][1] is True


def test_properties_global_agg_scalar():
    agg = AggregationNode(
        _values(), [], [],
        [AggCall(fn="count_star", arg=None, type=BIGINT)], ["c"], "single")
    p = derive_properties(agg)
    assert p.exact == 1 and p.scalar


def test_check_rewrite_identical_tree_clean():
    plan = LimitNode(_values(), 2)
    assert check_rewrite("Noop", plan, plan) == []


# ---------------------------------------------------------------------------
# observability satellites
# ---------------------------------------------------------------------------

def test_optimizer_stats_summary_format():
    s = OptimizerStats()
    assert s.summary() == "optimizer: 0 iterations"
    s.record("B")
    s.record("A")
    s.record("A")
    assert s.summary() == "optimizer: 3 iterations, rule hits: A=2, B=1"


def test_explain_validate_reports_rule_hits(runner):
    res = runner.execute(
        "EXPLAIN (TYPE VALIDATE) SELECT n_name FROM nation "
        "ORDER BY n_name LIMIT 3")
    assert res.names == ["Valid", "Optimizer"]
    valid, report = res.rows[0]
    assert valid is True
    assert report.startswith("optimizer:")
    # the ORDER BY + LIMIT collapses via the TopN path; the report
    # names whichever rule fired with its hit count
    assert "PushTopNThroughProject=1" in report


def test_explain_analyze_verbose_reports_optimizer_line(runner):
    res = runner.execute(
        "EXPLAIN ANALYZE VERBOSE SELECT n_name FROM nation "
        "ORDER BY n_name LIMIT 3")
    text = res.rows[0][0]
    assert any(line.startswith("optimizer: ")
               for line in text.splitlines())


def test_optimizer_metrics_preregistered_and_counted(runner):
    from presto_tpu.obs.metrics import METRICS

    before = METRICS.counter("optimizer.rule_applications").value
    runner.binder.plan("SELECT n_name FROM nation ORDER BY n_name LIMIT 3")
    after = METRICS.counter("optimizer.rule_applications").value
    assert after > before


def test_rule_violations_metric_incremented():
    from presto_tpu.obs.metrics import METRICS

    class WidenLimit(Rule):
        pattern = Pattern.type_of(LimitNode).where(lambda n: n.count == 2)

        def apply(self, node):
            return LimitNode(node.source, 3)

    before = METRICS.counter("optimizer.rule_violations").value
    with pytest.raises(RewriteSoundnessError):
        IterativeOptimizer(rules=[WidenLimit()], validate=True).optimize(
            OutputNode(LimitNode(_values(3), 2), ["a", "b"]))
    assert METRICS.counter("optimizer.rule_violations").value == before + 1


def test_session_property_round_trip(runner):
    runner.execute("SET SESSION validate_rewrites = true")
    try:
        res = runner.execute("SELECT count(*) FROM region")
        assert res.rows == [(5,)]
    finally:
        runner.execute("RESET SESSION validate_rewrites")


def test_config_key_sets_session_default():
    from presto_tpu.config import EngineConfig

    cfg = EngineConfig(props={"query.validate-rewrites": "true"})
    assert cfg.build_session().get("validate_rewrites") is True
    assert EngineConfig().build_session().get("validate_rewrites") is False
