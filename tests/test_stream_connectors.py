"""Stream connectors — the presto-kafka / presto-redis slots (topic
logs and key/value stores as tables through the record-decoder layer;
``presto-kafka/.../KafkaRecordSet.java``,
``presto-redis/.../RedisRecordCursor.java``)."""

import json

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.stream import KvConnector, LogBroker, StreamConnector
from presto_tpu.runner import QueryRunner


@pytest.fixture()
def broker(tmp_path):
    return LogBroker(str(tmp_path / "log"), segment_bytes=400)


def _mk_runner(conn):
    catalog = Catalog()
    catalog.register("stream", conn)
    return QueryRunner(catalog)


def test_topic_scan_json(broker):
    broker.append("events", [
        json.dumps({"ts": i, "kind": "click" if i % 3 else "view",
                    "amount": i * 1.5})
        for i in range(100)
    ])
    sc = StreamConnector(broker, {
        "events": {"format": "json",
                   "schema": [["ts", "bigint"], ["kind", "varchar"],
                              ["amount", "double"]]}})
    r = _mk_runner(sc)
    assert r.execute("SELECT count(*) FROM events").rows == [(100,)]
    rows = r.execute(
        "SELECT kind, count(*), sum(amount) FROM events "
        "GROUP BY kind ORDER BY kind").rows
    assert [(k, c) for k, c, _ in rows] == [("click", 66), ("view", 34)]


def test_segments_are_splits_and_internal_columns(broker):
    # small segment_bytes forces segment roll -> multiple splits
    for batch in range(10):
        broker.append("t", [json.dumps({"n": batch * 10 + i})
                            for i in range(10)])
    sc = StreamConnector(broker, {
        "t": {"format": "json", "schema": [["n", "bigint"]]}})
    assert sc.num_splits("t") > 1
    r = _mk_runner(sc)
    # kafka-style internal columns: (_segment, _offset) identify a message
    rows = r.execute(
        "SELECT count(*), count(distinct _segment) FROM t").rows
    assert rows[0][0] == 100
    assert rows[0][1] == sc.num_splits("t")
    (mx,) = r.execute("SELECT max(n) FROM t WHERE _offset = 0").rows[0]
    assert mx % 10 == 0  # offset 0 is always a batch head here


def test_append_visible_to_cached_plan(broker):
    broker.append("live", [json.dumps({"n": 1})])
    sc = StreamConnector(broker, {
        "live": {"format": "json", "schema": [["n", "bigint"]]}})
    r = _mk_runner(sc)
    assert r.execute("SELECT count(*) FROM live").rows == [(1,)]
    # streaming semantics: new messages appear on re-execution of the
    # SAME (plan-cached) query because splits enumerate at run time
    broker.append("live", [json.dumps({"n": k}) for k in range(2, 600)])
    assert r.execute("SELECT count(*) FROM live").rows == [(599,)]


def test_csv_topic(broker):
    broker.append("csvt", [f"{i},name-{i % 5}" for i in range(50)])
    sc = StreamConnector(broker, {
        "csvt": {"format": "csv",
                 "schema": [["id", "bigint"], ["name", "varchar"]]}})
    r = _mk_runner(sc)
    rows = r.execute("SELECT name, count(*) FROM csvt "
                     "GROUP BY name ORDER BY name").rows
    assert len(rows) == 5 and all(c == 10 for _, c in rows)


def test_kv_connector(tmp_path):
    kv = KvConnector(str(tmp_path / "kv.db"), {
        "users": {"format": "json",
                  "schema": [["age", "bigint"], ["city", "varchar"]]}})
    for i in range(20):
        kv.put("users", f"user-{i:02d}", {"age": 20 + i % 4,
                                          "city": "sf" if i % 2 else "nyc"})
    r = _mk_runner(kv)
    assert r.execute("SELECT count(*) FROM users").rows == [(20,)]
    rows = r.execute("SELECT city, count(*) FROM users "
                     "GROUP BY city ORDER BY city").rows
    assert rows == [("nyc", 10), ("sf", 10)]
    # _key column scans and filters
    (k,) = r.execute("SELECT max(_key) FROM users WHERE age = 21").rows[0]
    assert k.startswith("user-")
    # overwrite semantics: a re-put replaces, count is stable
    kv.put("users", "user-00", {"age": 99, "city": "la"})
    assert r.execute("SELECT count(*) FROM users").rows == [(20,)]
    assert r.execute("SELECT count(*) FROM users WHERE age = 99").rows \
        == [(1,)]
