"""Randomized property tests for the round-5 surfaces.

Two oracles: python's arbitrary-precision Decimal for wide-decimal
arithmetic/aggregation, and sqlite for three-valued IN/NOT IN over
randomized NULL-bearing data.  Seeds are fixed — failures reproduce.
"""

import decimal
import random
import sqlite3
from decimal import Decimal

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import QueryRunner

decimal.getcontext().prec = 60


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("mem", MemoryConnector(), writable=True)
    return QueryRunner(catalog)


def test_decimal38_sum_min_max_random(runner):
    rng = random.Random(421)
    # magnitudes past int64 so every literal binds as a wide decimal
    vals = [rng.choice((-1, 1)) * rng.randint(10 ** 19, 10 ** 37)
            for _ in range(200)]
    rows = ", ".join(f"({v})" for v in vals)
    runner.execute(f"create table rnd38 as select * from (values {rows}) t(v)")
    s, mn, mx = runner.execute(
        "select sum(v), min(v), max(v) from rnd38").rows[0]
    assert s == Decimal(sum(vals))
    assert mn == Decimal(min(vals))
    assert mx == Decimal(max(vals))


def test_decimal38_grouped_sums_random(runner):
    rng = random.Random(99)
    data = [(rng.randint(0, 7),
             rng.choice((-1, 1)) * rng.randint(10 ** 19, 10 ** 36))
            for _ in range(300)]
    rows = ", ".join(f"({g}, {v})" for g, v in data)
    runner.execute(
        f"create table rnd38g as select * from (values {rows}) t(g, v)")
    got = dict(runner.execute(
        "select g, sum(v) from rnd38g group by g").rows)
    expect = {}
    for g, v in data:
        expect[g] = expect.get(g, 0) + v
    assert got == {g: Decimal(s) for g, s in expect.items()}


def test_decimal38_add_sub_compare_random(runner):
    rng = random.Random(7)
    for _ in range(25):
        a = rng.randint(-(10 ** 37), 10 ** 37)
        b = rng.randint(-(10 ** 37), 10 ** 37)
        row = runner.execute(
            f"select cast({a} as decimal(38,0)) + cast({b} as decimal(38,0)),"
            f" cast({a} as decimal(38,0)) - cast({b} as decimal(38,0)),"
            f" cast({a} as decimal(38,0)) < cast({b} as decimal(38,0))"
        ).rows[0]
        assert row == (Decimal(a + b), Decimal(a - b), a < b), (a, b)


def test_null_aware_in_random_vs_sqlite(runner):
    rng = random.Random(1234)
    probe = [rng.choice([None] + list(range(12))) for _ in range(60)]
    build = [rng.choice([None] + list(range(12))) for _ in range(20)]

    con = sqlite3.connect(":memory:")
    con.execute("create table p(x)")
    con.executemany("insert into p values (?)", [(v,) for v in probe])
    con.execute("create table b(y)")
    con.executemany("insert into b values (?)", [(v,) for v in build])

    def lit(vs, col):
        return ", ".join("(null)" if v is None else f"({v})" for v in vs)

    runner.execute(f"create table rp as select * from "
                   f"(values {lit(probe, 'x')}) t(x)")
    runner.execute(f"create table rb as select * from "
                   f"(values {lit(build, 'y')}) t(y)")
    try:
        for sql in [
            "select x from {p} where x in (select y from {b})",
            "select x from {p} where x not in (select y from {b})",
            "select x from {p} where not (x in (select y from {b}))",
            "select x from {p} where x in (select y from {b} where y < 5)",
            "select x from {p} where x not in "
            "(select y from {b} where y is not null)",
        ]:
            expected = sorted(
                (r[0] for r in con.execute(
                    sql.format(p="p", b="b")).fetchall()),
                key=lambda v: (v is None, v))
            actual = sorted(
                (r[0] for r in runner.execute(
                    sql.format(p="rp", b="rb")).rows),
                key=lambda v: (v is None, v))
            assert actual == expected, sql
    finally:
        runner.execute("drop table rp")
        runner.execute("drop table rb")


def test_kmv_digest_cardinality_random(runner):
    """KMV estimate within 4 standard errors over random cardinalities
    (K=64 -> stderr ~ 1/sqrt(62) ~ 12.7%)."""
    rng = random.Random(5)
    for n in (40, 500, 3000):
        vals = rng.sample(range(10 ** 9), n)
        rows = ", ".join(f"({v})" for v in vals)
        est = runner.execute(
            f"select cardinality(make_set_digest(v)) from "
            f"(values {rows}) t(v)").rows[0][0]
        if n <= 64:
            assert est == n
        else:
            assert abs(est - n) / n < 0.51, (n, est)
