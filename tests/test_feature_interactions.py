"""Cross-feature stress: CTEs + set ops + unnest + windows + containers
composed in single statements (the shapes real workloads mix)."""

import pytest

from presto_tpu.testing import LocalQueryRunner


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner(sf=0.001)


def test_cte_over_unnest_with_window(runner):
    rows = runner.execute(
        "WITH expanded AS ("
        "  SELECT k, e FROM (VALUES (1, ARRAY[3, 1]), (2, ARRAY[2])) AS t(k, a) "
        "  CROSS JOIN UNNEST(a) AS u(e)) "
        "SELECT k, e, row_number() OVER (PARTITION BY k ORDER BY e) AS rn "
        "FROM expanded ORDER BY k, rn").rows
    assert rows == [(1, 1, 1), (1, 3, 2), (2, 2, 1)]


def test_setop_over_ctes(runner):
    rows = runner.execute(
        "WITH a AS (SELECT n_regionkey AS k FROM nation), "
        "b AS (SELECT r_regionkey AS k FROM region WHERE r_regionkey >= 2) "
        "SELECT k FROM a EXCEPT SELECT k FROM b ORDER BY k").rows
    assert rows == [(0,), (1,)]


def test_array_agg_of_cte_join(runner):
    rows = runner.execute(
        "WITH big AS (SELECT n_regionkey AS rk, n_nationkey AS nk FROM nation "
        "WHERE n_nationkey < 6) "
        "SELECT r_name, array_agg(nk) FROM region JOIN big ON r_regionkey = rk "
        "GROUP BY r_name ORDER BY r_name").rows
    assert all(isinstance(arr, list) and arr for _, arr in rows)
    total = sum(len(arr) for _, arr in rows)
    assert total == 6


def test_lambda_over_aggregated_array(runner):
    rows = runner.execute(
        "SELECT transform(array_agg(n_nationkey), x -> x * 10) FROM nation "
        "WHERE n_nationkey < 3").rows
    assert sorted(rows[0][0]) == [0, 10, 20]


def test_prepared_cte_with_parameter(runner):
    runner.execute(
        "PREPARE fi FROM WITH f AS (SELECT n_regionkey AS k FROM nation "
        "WHERE n_nationkey < ?) SELECT count(*) FROM f")
    assert runner.execute("EXECUTE fi USING 5").rows == [(5,)]
    assert runner.execute("EXECUTE fi USING 10").rows == [(10,)]
    runner.execute("DEALLOCATE PREPARE fi")


def test_grouping_sets_with_having_and_setop(runner):
    rows = runner.execute(
        "SELECT n_regionkey, count(*) AS c FROM nation "
        "GROUP BY ROLLUP(n_regionkey) HAVING count(*) >= 5 "
        "EXCEPT SELECT NULL, 25 ORDER BY 2, 1").rows
    # the rollup total row (NULL, 25) is removed by the EXCEPT
    assert (None, 25) not in rows
    assert all(c == 5 for _, c in rows)
