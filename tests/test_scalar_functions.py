"""Scalar builtin coverage vs the sqlite oracle.

Reference analog: per-function unit tests in
presto-main/src/test/.../operator/scalar/ (58 files)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


CASES = [
    "select s_suppkey, abs(s_acctbal), sign(s_acctbal) from supplier",
    "select s_suppkey, round(s_acctbal), round(s_acctbal, 1) from supplier",
    "select s_suppkey, ceil(s_acctbal), floor(s_acctbal) from supplier",
    "select o_orderkey, sqrt(o_totalprice), ln(o_totalprice), log10(o_totalprice) from orders limit 500",
    "select o_orderkey, power(o_shippriority + 2, 3) from orders limit 100",
    "select s_suppkey, greatest(s_acctbal, 0.0), least(s_acctbal, 0.0) from supplier",
    "select s_suppkey, nullif(s_nationkey, 7) from supplier",
    "select c_custkey, length(c_name), strpos(c_phone, '-') from customer",
    "select n_nationkey, lower(n_name), reverse(n_name) from nation",
    "select o_orderkey, day_of_week(o_orderdate), day_of_year(o_orderdate), quarter(o_orderdate) from orders limit 500",
    "select l_orderkey, l_linenumber, mod(l_quantity, 7) from lineitem limit 500",
    "select coalesce(nullif(n_regionkey, 0), n_nationkey) from nation",
]


@pytest.mark.parametrize("i", range(len(CASES)))
def test_scalar_case(env, i):
    runner, oracle = env
    sql = CASES[i]
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


AGG_CASES = [
    # sqlite lacks stddev; emulate via sum/count identities
    ("select s_nationkey, stddev_pop(s_acctbal) from supplier group by s_nationkey",
     """select s_nationkey,
               case when count(s_acctbal) > 0 then
                 sqrt(max(sum(s_acctbal*s_acctbal)/count(s_acctbal)
                      - (sum(s_acctbal)/count(s_acctbal))*(sum(s_acctbal)/count(s_acctbal)), 0))
               end
        from supplier group by s_nationkey"""),
    ("select var_samp(s_acctbal) from supplier",
     """select (sum(s_acctbal*s_acctbal) - sum(s_acctbal)*sum(s_acctbal)/count(s_acctbal))
               / (count(s_acctbal) - 1) from supplier"""),
    ("select n_regionkey, bool_and(n_nationkey > 2), bool_or(n_nationkey > 20) from nation group by n_regionkey",
     """select n_regionkey, min(n_nationkey > 2), max(n_nationkey > 20)
        from nation group by n_regionkey"""),
]


@pytest.mark.parametrize("i", range(len(AGG_CASES)))
def test_agg_function_case(env, i):
    runner, oracle = env
    sql, oracle_sql = AGG_CASES[i]
    expected = run_oracle(oracle, oracle_sql)
    # sum-of-squares variance is cancellation-prone at ~1e10 magnitudes:
    # blunt both sides below the noise floor before exact compare
    def blunt(rows):
        return [
            tuple(
                round(float(v), 3) if isinstance(v, float)
                else int(v) if isinstance(v, bool) else v
                for v in row
            )
            for row in rows
        ]

    assert_rows_match(blunt(actual := runner.execute(sql).rows), blunt(expected), ordered=False)
