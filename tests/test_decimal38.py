"""DECIMAL precision 37-38: the five-limb base-10^9 wide layout.

Reference analog: spi/type/DecimalType.java (MAX_PRECISION = 38) +
UnscaledDecimal128Arithmetic.java.  The r5 extension: p <= 36 keeps the
two base-10^18 limbs; p in (36, 38] stores five base-10^9 limbs, with
add/sub/compare/min/max/sum/avg/rescale/casts exact.  Wide x short
multiplication is exact for any product that fits 38 digits (the
reference's DecimalType cap; VERDICT weak #6 flagged the old
rejection); only wide x long products stay unsupported.

Expected values come from python's arbitrary-precision Decimal.
"""

import decimal
from decimal import Decimal

import pytest

decimal.getcontext().prec = 60  # expected values must not round at 28

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import QueryRunner

N38 = 99999999999999999999999999999999999999  # 38 nines


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("mem", MemoryConnector(), writable=True)
    r = QueryRunner(catalog)
    r.execute("create table d38 as select cast(x as decimal(38,2)) as v "
              "from (values 1.25, 7.50, 12345678901234567890123456789012345.67) t(x)")
    return r


def test_wide_literal_roundtrip(runner):
    assert runner.execute(
        "select cast(12345678901234567890123456789012345678 as decimal(38,0))"
    ).rows == [(Decimal(12345678901234567890123456789012345678),)]


def test_add_sub_full_range(runner):
    assert runner.execute(
        "select cast(99999999999999999999999999999999999.99 as decimal(38,2))"
        " - cast(0.99 as decimal(38,2))"
    ).rows == [(Decimal("99999999999999999999999999999999999.00"),)]
    assert runner.execute(
        "select cast(1.25 as decimal(38,2)) + cast(2.50 as decimal(38,2))"
    ).rows == [(Decimal("3.75"),)]


def test_compare_and_mixed_width(runner):
    assert runner.execute(
        "select cast(1.25 as decimal(38,2)) < cast(1.30 as decimal(20,2))"
    ).rows == [(True,)]
    assert runner.execute(
        "select cast(123.456 as decimal(38,3)) = cast(123.456 as decimal(20,3))"
    ).rows == [(True,)]


def test_table_sum_avg_min_max(runner):
    s, a, mx, mn = runner.execute(
        "select sum(v), avg(v), max(v), min(v) from d38").rows[0]
    vals = [Decimal("1.25"), Decimal("7.50"),
            Decimal("12345678901234567890123456789012345.67")]
    assert s == sum(vals)
    # avg HALF_UP at scale 2
    expect_avg = (sum(vals) / 3).quantize(Decimal("0.01"))
    assert a == expect_avg
    assert mx == max(vals) and mn == min(vals)


def test_filter_on_wide_values(runner):
    rows = sorted(runner.execute("select v from d38 where v > 2").rows)
    assert rows == [
        (Decimal("7.50"),),
        (Decimal("12345678901234567890123456789012345.67"),)]


def test_cast_to_double_and_back(runner):
    assert runner.execute(
        "select cast(cast(5.75 as decimal(38,2)) as double)").rows == [(5.75,)]


def test_wide_multiplication_by_short(runner):
    """Wide x short products compute exactly whenever they fit 38
    digits (VERDICT weak #6: the old tier rejected them outright)."""
    assert runner.execute(
        "select cast(2.5 as decimal(38,2)) * 3").rows == [(Decimal("7.50"),)]
    assert runner.execute(
        "select cast(12345678901234567890 as decimal(38,0)) * 10"
    ).rows == [(Decimal("123456789012345678900"),)]
    big = 12345678901234567890 * 999999999999999999  # 38 digits exactly
    assert runner.execute(
        "select cast(12345678901234567890 as decimal(38,0))"
        " * 999999999999999999").rows == [(Decimal(big),)]
    assert runner.execute(
        "select cast(-4.5 as decimal(38,1)) * 1000000000000000"
    ).rows == [(Decimal("-4500000000000000.0"),)]


def test_wide_times_long_still_unsupported(runner):
    with pytest.raises(Exception, match="mul unsupported"):
        runner.execute(
            "select cast(2.5 as decimal(38,2)) "
            "* cast(3.5 as decimal(38,2)) from d38 limit 1")


def test_rescale_between_wide_scales(runner):
    assert runner.execute(
        "select cast(cast(1.2 as decimal(38,1)) as decimal(38,4))"
    ).rows == [(Decimal("1.2000"),)]
