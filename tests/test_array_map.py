"""ARRAY / MAP types, container functions, UNNEST, array_agg.

Reference analog: presto-main operator/scalar array/map function tests
(TestArrayOperators, TestMapOperators), TestUnnestOperator, and the
array_agg aggregation tests (TestArrayAggregation).
"""

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.page import Dictionary, Page
from presto_tpu.runner import QueryRunner
from presto_tpu.types import (
    BIGINT, DOUBLE, VARCHAR, ArrayType, MapType, parse_type,
)


@pytest.fixture(scope="module")
def runner():
    mem = MemoryConnector()
    at = ArrayType(BIGINT, 4)
    mt = MapType(BIGINT, BIGINT, 4)
    page = Page.from_arrays(
        [
            np.arange(1, 5),
            [[1, 2], [3], [], [4, 5, None]],
            [{1: 10}, {2: 20, 3: 30}, {}, {9: None}],
            np.array([1, 1, 2, 2]),
        ],
        [BIGINT, at, mt, BIGINT],
    )
    mem.create_table(
        "t", [("id", BIGINT), ("arr", at), ("mp", mt), ("g", BIGINT)], [page]
    )
    cat = Catalog()
    cat.register("mem", mem)
    return QueryRunner(cat)


def q(runner, sql):
    return runner.execute(sql).rows


# ---------------------------------------------------------------------------
# scalar container functions
# ---------------------------------------------------------------------------

def test_array_literal_and_cardinality(runner):
    assert q(runner, "SELECT cardinality(ARRAY[1,2,3])") == [(3,)]


def test_subscript_and_element_at(runner):
    assert q(runner, "SELECT ARRAY[1,2,3][2]") == [(2,)]
    # out-of-range subscript is NULL (element_at semantics; the
    # reference's [] raises — deviation)
    assert q(runner, "SELECT element_at(ARRAY[10,20], 5)") == [(None,)]


def test_contains_position(runner):
    assert q(runner, "SELECT contains(ARRAY[1,2,3], 2)") == [(True,)]
    assert q(runner, "SELECT array_position(ARRAY[5,6], 6)") == [(2,)]
    assert q(runner, "SELECT array_position(ARRAY[5,6], 7)") == [(0,)]


def test_array_reductions(runner):
    assert q(runner, "SELECT array_min(ARRAY[3,1,2]), array_max(ARRAY[3,1,2])") == [(1, 3)]
    assert q(runner, "SELECT array_sum(ARRAY[1,2,3])") == [(6,)]
    assert q(runner, "SELECT array_average(ARRAY[1,2,3,4])") == [(2.5,)]


def test_array_sort_distinct(runner):
    assert q(runner, "SELECT array_sort(ARRAY[3,1,2])") == [([1, 2, 3],)]
    assert q(runner, "SELECT array_distinct(ARRAY[3,1,3,2,1])") == [([1, 2, 3],)]


def test_array_type_coercion(runner):
    # int + decimal literal -> decimal elements
    assert q(runner, "SELECT ARRAY[1, 2.5]") == [([1.0, 2.5],)]


def test_map_functions(runner):
    assert q(runner, "SELECT map(ARRAY[1,2],ARRAY[10,20])[2]") == [(20,)]
    assert q(runner, "SELECT map_keys(map(ARRAY[1,2],ARRAY[10,20]))") == [([1, 2],)]
    assert q(runner, "SELECT map_values(map(ARRAY[1,2],ARRAY[10,20]))") == [([10, 20],)]
    assert q(runner, "SELECT cardinality(map(ARRAY[1,2],ARRAY[10,20]))") == [(2,)]
    # missing key -> NULL
    assert q(runner, "SELECT map(ARRAY[1],ARRAY[10])[7]") == [(None,)]


def test_container_column_roundtrip(runner):
    assert q(runner, "SELECT id, arr FROM t ORDER BY id") == [
        (1, [1, 2]), (2, [3]), (3, []), (4, [4, 5, None]),
    ]
    assert q(runner, "SELECT mp FROM t WHERE id = 2") == [({2: 20, 3: 30},)]


def test_container_in_predicates(runner):
    assert q(runner, "SELECT id FROM t WHERE cardinality(arr) > 1 ORDER BY id") == [
        (1,), (4,),
    ]
    assert q(runner, "SELECT id FROM t WHERE contains(arr, 3)") == [(2,)]


# ---------------------------------------------------------------------------
# UNNEST
# ---------------------------------------------------------------------------

def test_unnest_array(runner):
    assert q(runner, "SELECT id, e FROM t CROSS JOIN UNNEST(arr) AS u(e) ORDER BY id, e") == [
        (1, 1), (1, 2), (2, 3), (4, 4), (4, 5), (4, None),
    ]


def test_unnest_with_ordinality(runner):
    rows = q(runner, "SELECT id, e, o FROM t CROSS JOIN UNNEST(arr) "
                     "WITH ORDINALITY AS u(e, o) ORDER BY id, o")
    assert rows == [(1, 1, 1), (1, 2, 2), (2, 3, 1), (4, 4, 1), (4, 5, 2), (4, None, 3)]


def test_unnest_map(runner):
    rows = q(runner, "SELECT id, k, v FROM t CROSS JOIN UNNEST(mp) AS u(k, v) ORDER BY id, k")
    assert rows == [(1, 1, 10), (2, 2, 20), (2, 3, 30), (4, 9, None)]


def test_unnest_comma_form_with_filter(runner):
    # the filter references the unnest output -> applies post-expansion
    assert q(runner, "SELECT id, e FROM t, UNNEST(arr) AS u(e) WHERE e > 2 ORDER BY e") == [
        (2, 3), (4, 4), (4, 5),
    ]


def test_unnest_aggregate(runner):
    assert q(runner, "SELECT sum(e) FROM t CROSS JOIN UNNEST(arr) AS u(e)") == [(15,)]
    assert q(runner, "SELECT id, count(e) FROM t CROSS JOIN UNNEST(arr) AS u(e) "
                     "GROUP BY id ORDER BY id") == [(1, 2), (2, 1), (4, 2)]


def test_unnest_filter_with_case(runner):
    # identifiers nested inside tuple AST fields (CASE whens) must still
    # defer the conjunct past the expansion
    rows = q(runner, "SELECT id, e FROM t, UNNEST(arr) AS u(e) "
                     "WHERE CASE WHEN e > 2 THEN true ELSE false END ORDER BY e")
    assert rows == [(2, 3), (4, 4), (4, 5)]


def test_unnest_filter_with_subquery(runner):
    # subquery conjuncts over unnest output apply post-expansion
    rows = q(runner, "SELECT e FROM t, UNNEST(arr) AS u(e) "
                     "WHERE e IN (SELECT id FROM t) ORDER BY e")
    assert rows == [(1,), (2,), (3,), (4,)]


def test_array_sort_nulls_last_double(runner):
    # float path: NULLs sort last, not inf-before-null
    rows = q(runner, "SELECT array_sort(arr) FROM t WHERE id = 4")
    assert rows == [([4, 5, None],)]


def test_array_distinct_keeps_extreme_values(runner):
    assert q(runner, "SELECT array_distinct(ARRAY[9223372036854775807, 1, "
                     "9223372036854775807])") == [([1, 9223372036854775807],)]


# ---------------------------------------------------------------------------
# array_agg
# ---------------------------------------------------------------------------

def test_array_agg_grouped(runner):
    assert q(runner, "SELECT g, array_agg(id) FROM t GROUP BY g ORDER BY g") == [
        (1, [1, 2]), (2, [3, 4]),
    ]


def test_array_agg_global(runner):
    assert q(runner, "SELECT array_agg(id) FROM t") == [([1, 2, 3, 4],)]


def test_sequence_slice_repeat_concat(runner):
    assert q(runner, "SELECT sequence(1, 5)") == [([1, 2, 3, 4, 5],)]
    assert q(runner, "SELECT sequence(0, 10, 3)") == [([0, 3, 6, 9],)]
    assert q(runner, "SELECT slice(ARRAY[1,2,3,4,5], 2, 3)") == [([2, 3, 4],)]
    assert q(runner, "SELECT slice(ARRAY[1,2], 2, 9)") == [([2],)]
    assert q(runner, "SELECT repeat(7, 3)") == [([7, 7, 7],)]
    assert q(runner, "SELECT ARRAY[1,2] || ARRAY[3,4]") == [([1, 2, 3, 4],)]
    # negative start counts from the end; element append/prepend
    assert q(runner, "SELECT slice(ARRAY[1,2,3,4], -2, 2)") == [([3, 4],)]
    assert q(runner, "SELECT ARRAY[1,2] || 3") == [([1, 2, 3],)]
    assert q(runner, "SELECT 0 || ARRAY[1]") == [([0, 1],)]
    # mixed-type concat rescales decimals and keeps NULL elements
    assert q(runner, "SELECT ARRAY[1.5] || ARRAY[2.25]") == [([1.5, 2.25],)]
    assert q(runner, "SELECT ARRAY[1, 2] || ARRAY[2.5]") == [([1.0, 2.0, 2.5],)]
    with pytest.raises(Exception, match="indices start at 1"):
        q(runner, "SELECT slice(ARRAY[1,2], 0, 1)")
    with pytest.raises(Exception, match="length"):
        q(runner, "SELECT slice(ARRAY[1,2], 1, -1)")
    assert q(runner, "SELECT transform(sequence(1, 4), x -> x * x)") == [
        ([1, 4, 9, 16],)]


def test_map_agg(runner):
    rows = q(runner, "SELECT g, map_agg(id, id * 10) FROM t GROUP BY g ORDER BY g")
    assert rows == [(1, {1: 10, 2: 20}), (2, {3: 30, 4: 40})]
    assert q(runner, "SELECT cardinality(map_agg(id, g)) FROM t") == [(4,)]
    # subscript over an aggregated map
    assert q(runner, "SELECT map_agg(id, g)[3] FROM t") == [(2,)]


def test_array_agg_roundtrip_unnest(runner):
    # array_agg then unnest recovers the rows
    rows = q(runner, "SELECT e FROM (SELECT array_agg(id) AS a FROM t) "
                     "CROSS JOIN UNNEST(a) AS u(e) ORDER BY e")
    assert rows == [(1,), (2,), (3,), (4,)]


# ---------------------------------------------------------------------------
# lambdas (LambdaBytecodeGenerator + ArrayTransform/Filter analogs)
# ---------------------------------------------------------------------------

def test_transform_lambda(runner):
    assert q(runner, "SELECT transform(ARRAY[1,2,3], x -> x * 2)") == [([2, 4, 6],)]
    # type-changing body
    assert q(runner, "SELECT transform(ARRAY[1,2], x -> x * 0.5)") == [([0.5, 1.0],)]


def test_transform_captures_outer_column(runner):
    rows = q(runner, "SELECT id, transform(arr, x -> x + id) FROM t "
                     "WHERE id <= 2 ORDER BY id")
    assert rows == [(1, [2, 3]), (2, [5])]


def test_filter_lambda(runner):
    assert q(runner, "SELECT filter(ARRAY[1,2,3,4], x -> x % 2 = 0)") == [([2, 4],)]
    rows = q(runner, "SELECT id, filter(arr, x -> x > 1) FROM t ORDER BY id")
    assert rows == [(1, [2]), (2, [3]), (3, []), (4, [4, 5])]


def test_match_lambdas(runner):
    assert q(runner, "SELECT any_match(ARRAY[1,2], x -> x > 1)") == [(True,)]
    assert q(runner, "SELECT all_match(ARRAY[2,4], x -> x % 2 = 0)") == [(True,)]
    assert q(runner, "SELECT none_match(ARRAY[1,3], x -> x > 5)") == [(True,)]
    # empty arrays: any=false, all vacuously true
    assert q(runner, "SELECT any_match(arr, x -> x > 0), "
                     "all_match(arr, x -> x > 0) FROM t WHERE id = 3") == [
        (False, True)]


def test_lambda_in_where(runner):
    assert q(runner, "SELECT id FROM t WHERE any_match(arr, x -> x >= 4) "
                     "ORDER BY id") == [(4,)]


def test_stray_lambda_rejected(runner):
    from presto_tpu.sql.binder import BindError

    with pytest.raises(BindError):
        runner.execute("SELECT x -> x + 1")


# ---------------------------------------------------------------------------
# type plumbing
# ---------------------------------------------------------------------------

def test_parse_type_containers():
    at = parse_type("array(bigint, 16)")
    assert at.is_array and at.element == BIGINT and at.max_elems == 16
    mt = parse_type("map(bigint, double)")
    assert mt.is_map and mt.key_element == BIGINT and mt.element == DOUBLE
    assert parse_type("array(double)").np_dtype == np.dtype(np.float64)


def test_distributed_smoke_with_arrays():
    """Array columns survive the page serde (worker protocol)."""
    from presto_tpu.server.serde import deserialize_page, serialize_page

    at = ArrayType(BIGINT, 3)
    page = Page.from_arrays([np.arange(3), [[1], [2, 2], []]], [BIGINT, at])
    blob = serialize_page(page.compact_host())
    back = deserialize_page(blob)
    assert back.to_pylist() == page.to_pylist()


def test_histogram():
    """histogram(x): two-level rewrite to inner counts + map_agg
    (Histogram.java analog)."""
    from presto_tpu.testing import LocalQueryRunner

    r = LocalQueryRunner(sf=0.001)
    assert r.execute("SELECT histogram(n_regionkey) FROM nation").rows == [
        ({0: 5, 1: 5, 2: 5, 3: 5, 4: 5},)]
    rows = r.execute("SELECT n_regionkey, histogram(n_nationkey % 2) "
                     "FROM nation GROUP BY n_regionkey ORDER BY n_regionkey").rows
    assert len(rows) == 5
    assert all(sum(h.values()) == 5 for _, h in rows)
    assert r.execute("SELECT cardinality(histogram(n_regionkey)) FROM nation"
                     ).rows == [(5,)]


# ---------------------------------------------------------------------------
# round-4b: multi-parameter lambdas + array set algebra
# (MapFilterFunction, MapTransformKey/ValueFunction, ZipWithFunction,
# ReduceFunction, ArrayIntersect/Union/Except/RemoveFunction,
# ArraysOverlapFunction, MapConcatFunction)
# ---------------------------------------------------------------------------

def test_map_filter_and_transforms(runner):
    assert runner.execute(
        "select map_filter(map(array[1,2,3], array[10,20,30]), "
        "(k, v) -> v > 15 and k < 3)").rows == [({2: 20},)]
    assert runner.execute(
        "select transform_values(map(array[1,2], array[10,20]), "
        "(k, v) -> k + v)").rows == [({1: 11, 2: 22},)]
    assert runner.execute(
        "select transform_keys(map(array[1,2], array[10,20]), "
        "(k, v) -> k * 100)").rows == [({100: 10, 200: 20},)]
    # empty result map is a map, not NULL
    assert runner.execute(
        "select map_filter(map(array[1], array[10]), (k, v) -> v < 0)"
    ).rows == [({},)]


def test_map_lambda_over_column(runner):
    rows = runner.execute(
        "select g, map_filter(m, (k, v) -> v >= 2) from (select g, "
        "map_agg(k, v) m from (values (1,1,1),(1,2,2),(2,3,3)) t(g,k,v)"
        " group by g) order by g").rows
    assert rows == [(1, {2: 2}), (2, {3: 3})]


def test_zip_with(runner):
    assert runner.execute(
        "select zip_with(array[1,2,3], array[10,20,30], (x, y) -> x + y)"
    ).rows == [([11, 22, 33],)]
    # shorter side binds NULL for its missing lanes
    assert runner.execute(
        "select zip_with(array[1,2], array[10], "
        "(x, y) -> coalesce(y, 0) + x)").rows == [([11, 2],)]
    assert runner.execute(
        "select zip_with(array[1,2], array[3,4], (x, y) -> x * y)"
    ).rows == [([3, 8],)]


def test_reduce(runner):
    assert runner.execute(
        "select reduce(array[5,20,50], 0, (s, x) -> s + x, s -> s)"
    ).rows == [(75,)]
    assert runner.execute(
        "select reduce(array[5,20,50], cast(1 as double), "
        "(s, x) -> s * x, s -> s / 2)").rows == [(2500.0,)]
    # NULL elements reach the lambda as NULL
    assert runner.execute(
        "select reduce(array[1, null, 3], 0, "
        "(s, x) -> s + coalesce(x, 100), s -> s)").rows == [(104,)]
    # outer-column capture inside the combiner (id is the init state)
    rows = runner.execute(
        "select id, reduce(array[1, 2], id, (s, x) -> s + x, s -> s) "
        "from t order by 1").rows
    assert rows == [(1, 4), (2, 5), (3, 6), (4, 7)]


def test_array_set_algebra(runner):
    assert runner.execute(
        "select array_intersect(array[1,2,2,3], array[2,3,4])"
    ).rows == [([2, 3],)]
    assert runner.execute(
        "select array_union(array[1,2], array[2,3])").rows == [([1, 2, 3],)]
    assert runner.execute(
        "select array_except(array[1,2,3], array[2])").rows == [([1, 3],)]
    assert runner.execute(
        "select array_remove(array[1,2,1,3], 1)").rows == [([2, 3],)]
    assert runner.execute(
        "select arrays_overlap(array[1,2], array[2,3]), "
        "arrays_overlap(array[1], array[3])").rows == [(True, False)]
    # three-valued: no match + a NULL element on either side -> NULL
    assert runner.execute(
        "select arrays_overlap(array[1, null], array[3])"
    ).rows == [(None,)]
    assert runner.execute(
        "select arrays_overlap(array[1, null], array[1])").rows == [(True,)]


def test_map_concat_last_wins(runner):
    assert runner.execute(
        "select map_concat(map(array[1], array[10]), "
        "map(array[1,2], array[99,20]))").rows == [({1: 99, 2: 20},)]
    # variadic left-fold
    assert runner.execute(
        "select map_concat(map(array[1], array[1]), "
        "map(array[2], array[2]), map(array[1], array[7]))"
    ).rows == [({1: 7, 2: 2},)]
    # device lookup agrees with the decode (the dedupe guarantees it)
    assert runner.execute(
        "select map_concat(map(array[1], array[10]), "
        "map(array[1], array[99]))[1]").rows == [(99,)]


def test_parenthesized_single_param_lambda(runner):
    assert runner.execute(
        "select transform(array[1,2], (x) -> x + 1)").rows == [([2, 3],)]


def test_nested_lambdas_scope_correctly(runner):
    """Inner lambda parameters must not capture the outer lambda's
    substitution (code-review regression: slot-unique LambdaVars)."""
    assert runner.execute(
        "select transform(array[1,2], x -> "
        "array_sum(transform(array[10], y -> y + x)))"
    ).rows == [([11, 12],)]


def test_set_algebra_mixed_types_compare_exactly(runner):
    # 2 (bigint) must NOT match 2.5 (double truncation regression)
    assert runner.execute(
        "select array_intersect(a, b), array_except(a, b), "
        "arrays_overlap(a, b) from (select array[1,2] a, "
        "array[2.5, 3.5] b) t").rows == [([], [1, 2], False)]
    assert runner.execute(
        "select array_intersect(a, b) from (select array[1,2] a, "
        "array[2.0, 3.5] b) t").rows == [([2],)]


def test_map_concat_mixed_value_types(runner):
    (m,) = runner.execute(
        "select map_concat(map(array[1], array[10]), "
        "map(array[2], array[2.5]))").rows[0]
    assert m[1] == 10.0 and m[2] == 2.5


def test_transform_keys_dedupes_first_wins(runner):
    got = runner.execute(
        "select transform_keys(map(array[1,2], array[10,20]), "
        "(k, v) -> 0)").rows
    assert got == [({0: 10},)]
    assert runner.execute(
        "select transform_keys(map(array[1,2], array[10,20]), "
        "(k, v) -> 0)[0]").rows == [(10,)]


def test_container_arity_bind_errors(runner):
    import pytest as _pytest

    for sql in ("select map_concat(map(array[1], array[10]))",
                "select array_intersect(array[1])",
                "select arrays_overlap(array[1])"):
        with _pytest.raises(Exception) as ei:
            runner.execute(sql)
        assert "argument" in str(ei.value) or "takes" in str(ei.value), sql


def test_trailing_comma_lambda_rejected(runner):
    import pytest as _pytest

    with _pytest.raises(Exception):
        runner.execute(
            "select zip_with(array[1], array[2], (x, y,) -> x + y)")
