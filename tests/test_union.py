"""UNION [ALL] tests vs the sqlite oracle."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner

from tests.oracle import assert_rows_match, load_oracle, run_oracle


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


CASES = [
    # same-dictionary arms
    "select c_custkey as k from customer union all select s_suppkey from supplier",
    # distinct union deduplicates
    "select n_regionkey as r from nation union select r_regionkey from region",
    # type coercion across arms (bigint + decimal)
    "select s_suppkey as v from supplier union all select s_acctbal from supplier",
    # merged dictionaries across different VARCHAR columns
    "select n_name as name from nation union all select r_name from region",
    # union + order + limit
    """select c_custkey as k, c_acctbal as v from customer
       union all
       select s_suppkey, s_acctbal from supplier
       order by v desc limit 20""",
    # union as a subquery relation feeding aggregation
    """select cnt, count(*) from (
         select n_regionkey as cnt from nation
         union all
         select r_regionkey from region
       ) as t group by cnt""",
]


@pytest.mark.parametrize("i", range(len(CASES)))
def test_union_case(env, i):
    runner, oracle = env
    sql = CASES[i]
    expected = run_oracle(oracle, sql)
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_intersect_except():
    """INTERSECT/EXCEPT lower to null-safe semi/anti joins over a
    distinct left arm (SetOperationNodeTranslator analog)."""
    from presto_tpu.testing import LocalQueryRunner

    r = LocalQueryRunner(sf=0.001)
    assert r.execute(
        "SELECT n_regionkey FROM nation INTERSECT "
        "SELECT r_regionkey FROM region WHERE r_regionkey < 3 ORDER BY 1"
    ).rows == [(0,), (1,), (2,)]
    assert r.execute(
        "SELECT r_regionkey FROM region EXCEPT "
        "SELECT n_regionkey FROM nation WHERE n_regionkey < 4 ORDER BY 1"
    ).rows == [(4,)]
    # NULLs compare equal in set operations (IS NOT DISTINCT FROM)
    assert r.execute(
        "SELECT a FROM (VALUES (1), (NULL), (2)) AS t(a) INTERSECT "
        "SELECT b FROM (VALUES (NULL), (2)) AS s(b) ORDER BY 1").rows == [
        (2,), (None,)]
    assert r.execute(
        "SELECT a FROM (VALUES (1), (NULL)) AS t(a) EXCEPT "
        "SELECT b FROM (VALUES (NULL)) AS s(b)").rows == [(1,)]
    # output deduplicates (set semantics) and precedence binds
    # INTERSECT tighter than UNION
    assert r.execute(
        "SELECT n_regionkey FROM nation INTERSECT "
        "SELECT n_regionkey FROM nation WHERE n_regionkey = 1").rows == [(1,)]
    rows = r.execute(
        "SELECT n_regionkey FROM nation WHERE n_regionkey = 0 UNION "
        "SELECT n_regionkey FROM nation INTERSECT "
        "SELECT n_regionkey FROM nation WHERE n_regionkey IN (2, 3) "
        "ORDER BY 1").rows
    assert rows == [(0,), (2,), (3,)]


def test_setop_order_limit_hoists_to_union():
    """ORDER BY/LIMIT after A UNION B INTERSECT C bind to the whole
    union, not the inner intersect arm."""
    from presto_tpu.testing import LocalQueryRunner

    r = LocalQueryRunner(sf=0.001)
    rows = r.execute(
        "SELECT n_regionkey FROM nation WHERE n_regionkey = 4 UNION "
        "SELECT n_regionkey FROM nation INTERSECT "
        "SELECT n_regionkey FROM nation WHERE n_regionkey IN (1, 2) "
        "ORDER BY 1 DESC LIMIT 2").rows
    assert rows == [(4,), (2,)]
