"""Query doctor + telemetry history: rulebook diagnosis, the metrics
history ring, per-query timelines, and their SQL/REST surfaces.

Every rulebook rule (obs/doctor.py) is pinned twice:

1. deterministically — synthetic evidence that makes the rule the
   TOP-ranked finding, asserting rule name, rank, and the evidence
   numbers it carries, and
2. end-to-end where the engine can produce the evidence cheaply — a
   cold run (compile-bound), a tiny pool (spill-bound), an admission
   burst (queue-bound / memory-blocked), a skewed join key on the
   device mesh (skewed-stage), a slowed worker (straggler-worker).

Plus: ring bounds/eviction for both retention planes, the
``system_metrics_history`` table, and the coordinator's
``/v1/metrics/history`` / ``/v1/query/<id>/timeline`` /
``/v1/query/<id>/doctor`` endpoints."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from presto_tpu import obs
from presto_tpu.catalog import Catalog
from presto_tpu.connectors.system import QueryHistory, SystemConnector
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.obs import doctor
from presto_tpu.obs.timeseries import (
    HISTORY,
    MetricsHistory,
    QueryTimeline,
    ensure_timeline,
    record_point,
    recording,
    timeline_for,
    timelines_enabled,
)
from presto_tpu.runner import QueryRunner


def make_runner(sf=0.001, split_rows=4096):
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=sf, split_rows=split_rows))
    history = QueryHistory()
    catalog.register("system", SystemConnector(history))
    runner = QueryRunner(catalog)
    runner.events.add(history)
    return runner, history


class _StubTracer:
    """diagnose() only consults tracer.summary()."""

    def __init__(self, summary):
        self._summary = summary

    def summary(self):
        return self._summary


def _tl(qid="q_doc", **annotations):
    tl = QueryTimeline(qid)
    for k, v in annotations.items():
        tl.annotate(k, v)
    return tl


# ---------------------------------------------------------------------------
# rulebook: every rule ranks FIRST under deterministic evidence
# ---------------------------------------------------------------------------

def test_compile_bound_ranks_first():
    tracer = _StubTracer({"xla_compile": {"total_ms": 800.0, "count": 3}})
    fs = doctor.diagnose(tracer=tracer, wall_ms=1000.0)
    assert fs and fs[0].rule == "compile-bound"
    ev = fs[0].evidence
    assert ev["compile_ms"] == 800.0
    assert ev["share"] == pytest.approx(0.8)
    assert ev["compiles"] == 3


def test_queue_bound_ranks_first():
    fs = doctor.diagnose(timeline=_tl(queued_ms=900.0), wall_ms=100.0)
    assert fs and fs[0].rule == "queue-bound"
    assert fs[0].evidence["queued_ms"] == 900.0
    assert fs[0].score == pytest.approx(0.9)


def test_memory_blocked_ranks_first():
    fs = doctor.diagnose(timeline=_tl(memory_blocked_ms=450.0),
                         wall_ms=500.0)
    assert fs and fs[0].rule == "memory-blocked"
    assert fs[0].evidence["memory_blocked_ms"] == 450.0


def test_spill_bound_ranks_first():
    fs = doctor.diagnose(
        timeline=_tl(spill_bytes=80e6, input_bytes=100e6), wall_ms=100.0)
    assert fs and fs[0].rule == "spill-bound"
    assert fs[0].evidence["ratio"] == pytest.approx(0.8)


def test_exchange_backpressure_ranks_first():
    fs = doctor.diagnose(
        timeline=_tl(exchange_producer_stall_s=0.9), wall_ms=1000.0)
    assert fs and fs[0].rule == "exchange-backpressure"
    assert fs[0].evidence["producer_stall_ms"] == pytest.approx(900.0)


def test_skewed_stage_ranks_first():
    tl = _tl()
    tl.extend("partition_rows", "dist:join-build", [1000, 10, 10, 10])
    fs = doctor.diagnose(timeline=tl, wall_ms=100.0)
    assert fs and fs[0].rule == "skewed-stage"
    ev = fs[0].evidence
    assert ev["stage"] == "dist:join-build"
    assert ev["max_rows"] == 1000
    assert ev["ratio"] == pytest.approx(100.0)


def test_straggler_worker_ranks_first():
    tl = _tl()
    tl.extend("fragment_ms", "http://w1", 900.0)
    tl.extend("fragment_ms", "http://w2", 10.0)
    tl.extend("fragment_ms", "http://w3", 12.0)
    fs = doctor.diagnose(timeline=tl, wall_ms=1000.0)
    assert fs and fs[0].rule == "straggler-worker"
    ev = fs[0].evidence
    assert ev["worker"] == "http://w1"
    assert ev["max_ms"] == pytest.approx(900.0)
    assert set(ev["per_worker_ms"]) == {"http://w1", "http://w2",
                                        "http://w3"}


def test_straggler_needs_three_workers():
    """With two workers the median IS the midpoint, so the 3x ratio is
    unreachable by construction — the rule must stay silent rather than
    fire on a meaningless 2-sample median."""
    tl = _tl()
    tl.extend("fragment_ms", "http://w1", 900.0)
    tl.extend("fragment_ms", "http://w2", 10.0)
    fs = doctor.diagnose(timeline=tl, wall_ms=1000.0)
    assert not any(f.rule == "straggler-worker" for f in fs)


def test_scan_bound_ranks_first():
    tracer = _StubTracer({"tpch:split": {"total_ms": 900.0, "count": 8}})
    fs = doctor.diagnose(tracer=tracer, wall_ms=1000.0)
    assert fs and fs[0].rule == "scan-bound"
    assert fs[0].evidence["split_ms"] == pytest.approx(900.0)


def test_fallback_taken_ranks_first():
    fs = doctor.diagnose(dist_fallback="unsupported plan shape: limit",
                         wall_ms=50.0)
    assert fs and fs[0].rule == "fallback-taken"
    assert "limit" in fs[0].evidence["reason"]


def test_findings_rank_by_score_across_rules():
    """Mixed evidence sorts by severity: a 90% queue wait must outrank
    a 30% compile share."""
    tracer = _StubTracer({"xla_compile": {"total_ms": 30.0, "count": 1}})
    fs = doctor.diagnose(tracer=tracer, timeline=_tl(queued_ms=900.0),
                         wall_ms=100.0)
    rules = [f.rule for f in fs]
    assert rules.index("queue-bound") < rules.index("compile-bound")
    assert [f.score for f in fs] == sorted(
        (f.score for f in fs), reverse=True)


def test_quiet_query_yields_no_findings():
    fs = doctor.diagnose(timeline=_tl(), wall_ms=100.0)
    assert fs == []
    text = doctor.format_findings([])
    assert text.startswith("diagnosis:") and "no findings" in text


def test_format_findings_renders_rank_and_score():
    fs = doctor.diagnose(dist_fallback="no mesh")
    text = doctor.format_findings([f.as_dict() for f in fs])
    assert "1. fallback-taken" in text and "score 0.95" in text


# ---------------------------------------------------------------------------
# retention planes: both rings bounded, eviction observable
# ---------------------------------------------------------------------------

def test_metrics_history_ring_evicts_oldest():
    h = MetricsHistory(max_ticks=4)
    for _ in range(10):
        h.sample_once()
    assert h.tick_count() == 4
    ts = [t for t, _, _ in h.rows()]
    assert ts == sorted(ts)  # oldest tick first
    h.clear()
    assert h.tick_count() == 0 and h.rows() == []


def test_metrics_history_rates_and_percentiles():
    h = MetricsHistory(max_ticks=8)
    h.sample_once()  # baseline for rate deltas
    obs.METRICS.counter("device.get_calls").inc(5)
    obs.METRICS.histogram("admission.queue_wait_ms").observe(7.0)
    h.sample_once()
    last = {}
    for ts, name, value in h.rows():
        last[name] = value
    assert last["device.get_calls.rate"] > 0
    # log2-bucket percentiles ride the tick for any observed histogram
    assert "admission.queue_wait_ms.p50" in last
    assert "admission.queue_wait_ms.p95" in last
    assert "admission.queue_wait_ms.p99" in last


def test_timeline_ring_bounds_and_dropped_counter():
    tl = QueryTimeline("q_doc_ring", max_points=8)
    for i in range(20):
        tl.record("x", float(i))
    pts = tl.points()
    assert len(pts) == 8
    assert tl.dropped == 12
    assert pts[0][2] == 12.0  # oldest points evicted, newest kept
    snap = tl.snapshot()
    assert snap["dropped"] == 12 and len(snap["points"]) == 8


def test_record_point_is_noop_without_active_timeline():
    assert obs.current_timeline() is None
    record_point("x", 1.0)  # must not raise, must not allocate a timeline
    tl = QueryTimeline("q_doc_active")
    with recording(tl):
        record_point("y", 2.0)
    assert [p[1] for p in tl.points()] == ["y"]
    assert obs.current_timeline() is None


def test_timelines_master_switch_disables_everything():
    timelines_enabled.set(False)
    try:
        assert ensure_timeline("q_doc_disabled") is None
        assert timeline_for("q_doc_disabled") is None
    finally:
        timelines_enabled.set(None)
    tl = ensure_timeline("q_doc_enabled")
    assert tl is not None
    assert ensure_timeline("q_doc_enabled") is tl  # get-or-create


# ---------------------------------------------------------------------------
# SQL surfaces
# ---------------------------------------------------------------------------

def test_system_metrics_history_table():
    runner, _ = make_runner()
    HISTORY.clear()
    try:
        HISTORY.sample_once()
        obs.METRICS.counter("query.started").inc(0)  # registry warm
        HISTORY.sample_once()
        res = runner.execute(
            "select node, ts_ms, name, value from system_metrics_history")
        assert res.rows, "armed ring produced no table rows"
        nodes = {node for node, _, _, _ in res.rows}
        assert nodes == {"local"}
        assert all(isinstance(ts, float) and ts > 0
                   for _, ts, _, _ in res.rows)
        assert any(name.endswith(".rate") for _, _, name, _ in res.rows)
        res = runner.execute(
            "select count(*) from system_metrics_history"
            " where name = 'query.started.rate'")
        assert res.rows[0][0] >= 1
    finally:
        HISTORY.clear()


def test_runtime_queries_queued_columns_null_safe():
    runner, history = make_runner()
    runner.execute("select count(*) from nation")
    qid = history.completed[-1].query_id
    res = runner.execute(
        "select queued_ms, memory_blocked_ms from system_runtime_queries"
        " where query_id = '%s'" % qid)
    assert len(res.rows) == 1
    queued, blocked = res.rows[0]
    # embedded runs skip admission: both columns are NULL, not a crash
    assert queued is None and blocked is None


# ---------------------------------------------------------------------------
# end-to-end evidence: the engine produces what the rulebook consumes
# ---------------------------------------------------------------------------

def test_cold_run_is_compile_bound_end_to_end():
    runner, history = make_runner(sf=0.002)
    runner.session.set("trace", "true")
    res = runner.execute(
        "select l_linestatus, max(l_discount * 0.34), min(l_tax + 0.21)"
        " from lineitem group by l_linestatus")
    findings = res.findings
    assert findings is not None
    by_rule = {f["rule"]: f for f in findings}
    assert "compile-bound" in by_rule, findings
    ev = by_rule["compile-bound"]["evidence"]
    assert ev["compile_ms"] > 0 and ev["compiles"] >= 1
    # the completion event carries the same findings (query-log field)
    assert history.completed[-1].findings == findings


def test_spill_bound_end_to_end():
    from presto_tpu.memory import MemoryPool

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.004, split_rows=1 << 12))
    sql = ("select l_orderkey, count(*), sum(l_quantity)"
           " from lineitem group by l_orderkey")
    probe = QueryRunner(catalog, memory_pool=MemoryPool(1 << 40))
    probe.execute(sql)  # measure the unconstrained accumulator
    peak = probe.executor.last_peak_bytes
    runner = QueryRunner(catalog, memory_pool=MemoryPool(int(peak * 0.5)))
    res = runner.execute(sql)
    by_rule = {f["rule"]: f for f in res.findings}
    assert "spill-bound" in by_rule, res.findings
    assert by_rule["spill-bound"]["evidence"]["spill_bytes"] > 0
    tl = timeline_for(res.query_id)
    assert tl is not None and tl.annotation("spill_bytes") > 0


def _controller(pool=None, **kw):
    from presto_tpu.resource_groups import ResourceGroup, ResourceGroupManager
    from presto_tpu.serving import AdmissionController

    root = ResourceGroup(
        "global", hard_concurrency=kw.pop("hard_concurrency", 4),
        max_queued=kw.pop("max_queued", 100))
    return AdmissionController(ResourceGroupManager(root), pool=pool, **kw)


def test_queue_bound_from_admission_burst():
    """concurrency-1 controller + a held slot: the waiter's real
    queued_ms lands on its timeline and the doctor ranks queue-bound
    first for a short query."""
    ctl = _controller(hard_concurrency=1)
    first = ctl.admit("q_doc_holder", "alice")
    got = []

    def waiter():
        t = ctl.admit("q_doc_queued", "alice", timeout=10.0)
        got.append(t)

    th = threading.Thread(target=waiter, daemon=True, name="doc-admit")
    th.start()
    time.sleep(0.06)  # hold the slot past QUEUE_MIN_MS
    ctl.release(first)
    th.join(timeout=10.0)
    assert got
    ctl.release(got[0])
    tl = timeline_for("q_doc_queued")
    assert tl is not None
    queued = tl.annotation("queued_ms")
    assert queued is not None and queued >= 10.0
    # admission also timelines the queue depth it saw
    assert any(name == "admission.queue_depth" for _, name, _ in tl.points())
    fs = doctor.diagnose("q_doc_queued", wall_ms=5.0)
    assert fs and fs[0].rule == "queue-bound"
    assert fs[0].evidence["queued_ms"] == pytest.approx(queued)


def test_memory_blocked_from_admission_gate():
    from presto_tpu.memory import MemoryPool

    pool = MemoryPool(1000)
    pool.reserve("other/x", 950)
    ctl = _controller(pool=pool, memory_fraction=0.9)
    got = []

    def submit():
        got.append(ctl.admit("q_doc_blocked", "alice", timeout=10.0))

    th = threading.Thread(target=submit, daemon=True, name="doc-admit-mem")
    th.start()
    time.sleep(0.1)
    assert not got  # still blocked on headroom
    pool.free("other/x")
    th.join(timeout=10.0)
    assert got
    ctl.release(got[0])
    tl = timeline_for("q_doc_blocked")
    assert tl is not None
    blocked = tl.annotation("memory_blocked_ms")
    assert blocked is not None and blocked >= 50.0
    fs = doctor.diagnose("q_doc_blocked", wall_ms=20.0)
    assert fs and fs[0].rule == "memory-blocked"
    assert fs[0].evidence["memory_blocked_ms"] == pytest.approx(blocked)


def test_skewed_join_key_end_to_end():
    """A build side whose key is constant hash-routes every row to one
    device partition; the dist tier's fill counts land on the timeline
    and the doctor calls the skew."""
    from presto_tpu.parallel.dist import DistributedRunner, make_mesh

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.002, split_rows=4096))
    local = QueryRunner(catalog)
    dist = DistributedRunner(catalog, make_mesh(8), broadcast_threshold=0)
    sql = ("select count(*) from orders o join"
           " (select (l_orderkey % 1) + 1 as k from lineitem) b"
           " on o.o_orderkey = b.k")
    plan = local.binder.plan(sql)
    tl = ensure_timeline("q_doc_skew")
    with recording(tl):
        out = dist.run(plan)
    assert out.dist_fallback is None, out.dist_fallback
    rows_by_stage = tl.annotation("partition_rows")
    assert rows_by_stage and "dist:join-build" in rows_by_stage
    fs = doctor.diagnose("q_doc_skew", wall_ms=100.0)
    assert fs and fs[0].rule == "skewed-stage"
    ev = fs[0].evidence
    assert ev["stage"] == "dist:join-build"
    assert ev["ratio"] >= doctor.SKEW_RATIO
    assert ev["max_rows"] >= doctor.SKEW_MIN_ROWS


def test_straggler_worker_end_to_end():
    """One of three workers answers every request 150ms late
    (worker.slow_response_ms, node-scoped): its fragment_ms total
    dwarfs the median and the doctor names the worker.  A chain stage
    keeps fragments independent (no worker-to-worker shuffle), so the
    delay attributes cleanly, and a faultless warm-up run first takes
    worker-side compilation out of the timings."""
    from presto_tpu.parallel.multihost import MultiHostRunner
    from presto_tpu.server.worker import WorkerServer
    from presto_tpu.testing_faults import FAULTS

    def make_catalog():
        catalog = Catalog()
        catalog.register("tpch", Tpch(sf=0.002, split_rows=2048))
        return catalog

    workers = [WorkerServer(make_catalog()) for _ in range(3)]
    for w in workers:
        w.start()
    try:
        catalog = make_catalog()
        local = QueryRunner(catalog)
        multi = MultiHostRunner(catalog, [w.uri for w in workers])
        plan = local.binder.plan(
            "select l_orderkey, l_quantity from lineitem"
            " where l_quantity < 10")
        warm = multi.run(plan)  # compile worker-side programs
        assert warm.dist_fallback is None, warm.dist_fallback
        FAULTS.arm("worker.slow_response_ms",
                   node=workers[0].node_id, ms=150)
        tl = ensure_timeline("q_doc_straggler")
        with recording(tl):
            out = multi.run(plan)
        assert out.dist_fallback is None, out.dist_fallback
        assert len(out.rows) == len(warm.rows)
        fragment_ms = tl.annotation("fragment_ms")
        assert fragment_ms and workers[0].uri in fragment_ms, fragment_ms
        fs = doctor.diagnose("q_doc_straggler", wall_ms=600.0)
        straggler = [f for f in fs if f.rule == "straggler-worker"]
        assert straggler, (fs, fragment_ms)
        assert straggler[0].evidence["worker"] == workers[0].uri
    finally:
        FAULTS.disarm_all()
        for w in workers:
            try:
                w.stop()
            except Exception:
                pass


def test_explain_analyze_verbose_carries_diagnosis():
    runner, _ = make_runner()
    res = runner.execute("explain analyze verbose"
                         " select count(*) from nation")
    text = res.rows[0][0]
    assert text.startswith("diagnosis:")


# ---------------------------------------------------------------------------
# REST surfaces (coordinator)
# ---------------------------------------------------------------------------

def test_coordinator_history_timeline_doctor_endpoints():
    from presto_tpu.server.coordinator import CoordinatorServer

    runner, _ = make_runner()
    sampler_was_running = HISTORY.running
    srv = CoordinatorServer(runner)
    srv.start()
    try:
        req = urllib.request.Request(
            f"{srv.uri}/v1/statement",
            data=b"select count(*) from nation", method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            doc = json.load(r)
        assert doc["stats"]["state"] == "FINISHED"
        qid = doc["id"]

        with urllib.request.urlopen(
                f"{srv.uri}/v1/metrics/history", timeout=10) as r:
            hist = json.load(r)
        assert hist["intervalMs"] >= 1  # the server armed the sampler
        assert "local" in hist["nodes"]
        HISTORY.sample_once()  # don't wait out the 1s cadence
        with urllib.request.urlopen(
                f"{srv.uri}/v1/metrics/history", timeout=10) as r:
            hist = json.load(r)
        assert hist["nodes"]["local"], "sampled tick missing from endpoint"
        ts, name, value = hist["nodes"]["local"][0]
        assert isinstance(name, str) and isinstance(value, (int, float))

        with urllib.request.urlopen(
                f"{srv.uri}/v1/query/{qid}/timeline", timeout=10) as r:
            snap = json.load(r)
        assert snap["queryId"] == qid
        assert {"points", "dropped", "annotations"} <= set(snap)
        assert "wall_ms" in snap["annotations"]

        with urllib.request.urlopen(
                f"{srv.uri}/v1/query/{qid}/doctor", timeout=10) as r:
            rep = json.load(r)
        assert rep["queryId"] == qid
        assert isinstance(rep["findings"], list)
        for f in rep["findings"]:
            assert {"rule", "score", "summary", "evidence"} <= set(f)

        for endpoint in ("timeline", "doctor"):
            with pytest.raises(urllib.error.HTTPError):
                urllib.request.urlopen(
                    f"{srv.uri}/v1/query/nope/{endpoint}", timeout=10)
    finally:
        srv.stop()
    # the arming server stopped its sampler: no thread leaks into the
    # rest of the suite
    assert HISTORY.running == sampler_was_running


def test_statement_stats_mirror_queued_columns():
    from presto_tpu.server.coordinator import CoordinatorServer

    runner, _ = make_runner()
    srv = CoordinatorServer(runner)
    srv.start()
    try:
        req = urllib.request.Request(
            f"{srv.uri}/v1/statement",
            data=b"select count(*) from region", method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            doc = json.load(r)
        stats = doc["stats"]
        # embedded coordinator runs don't queue: the keys appear only
        # when admission produced a value (JSON mirrors are omitted-
        # when-NULL like compileMs)
        for key in ("queuedMs", "memoryBlockedMs"):
            if key in stats:
                assert isinstance(stats[key], (int, float))
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# CLI --doctor
# ---------------------------------------------------------------------------

def test_cli_doctor_prints_diagnosis(capsys):
    from presto_tpu import cli

    rc = cli.main(["--sf", "0.001", "-e", "select count(*) from nation",
                   "--doctor"])
    assert rc == 0
    captured = capsys.readouterr()
    assert "diagnosis:" in captured.err
