"""Views, USE, schema DDL, CALL procedures.

Reference analogs: execution/CreateViewTask.java:44 (views stored as
SQL, re-bound at reference time via StatementAnalyzer.java:789),
execution/UseTask.java:33, execution/CreateSchemaTask.java:38,
execution/AddColumnTask.java, spi/procedure/Procedure.java +
execution/CallTask.java:60 (kill_query ships as a procedure).
"""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import QueryRunner


@pytest.fixture()
def runner():
    catalog = Catalog()
    mem = MemoryConnector()
    catalog.register("mem", mem, writable=True)
    r = QueryRunner(catalog)
    r.execute("create table base as select * from "
              "(values (1, 'a'), (2, 'b'), (3, 'a')) t(id, tag)")
    return r


# -- views -------------------------------------------------------------------

def test_create_select_drop_view(runner):
    runner.execute("create view v as select id * 10 as ten, tag from base")
    res = runner.execute("select ten, tag from v where ten > 10 order by ten")
    assert res.rows == [(20, "b"), (30, "a")]
    # SHOW TABLES lists the view
    assert ("v",) in runner.execute("show tables").rows
    runner.execute("drop view v")
    with pytest.raises(Exception):
        runner.execute("select * from v")


def test_view_re_binds_at_reference_time(runner):
    """Views store SQL, not plans: data changes flow through."""
    runner.execute("create view v as select count(*) as n from base")
    assert runner.execute("select n from v").rows == [(3,)]
    runner.execute("insert into base select 4, 'a'")
    assert runner.execute("select n from v").rows == [(4,)]
    runner.execute("drop view v")


def test_create_or_replace_view(runner):
    runner.execute("create view v as select id from base")
    with pytest.raises(Exception):
        runner.execute("create view v as select tag from base")
    runner.execute("create or replace view v as select tag from base")
    assert runner.execute("select * from v limit 1").names == ["tag"]
    runner.execute("drop view v")


def test_drop_view_if_exists(runner):
    runner.execute("drop view if exists nothere")
    with pytest.raises(Exception):
        runner.execute("drop view nothere")


def test_view_over_view_and_cycle_detection(runner):
    runner.execute("create view v1 as select id from base")
    runner.execute("create view v2 as select id + 1 as id from v1")
    assert sorted(runner.execute("select id from v2").rows) == [
        (2,), (3,), (4,)]
    # a replace that makes v1 reference v2 creates a cycle
    runner.execute("create or replace view v1 as select id from v2")
    with pytest.raises(Exception, match="[Rr]ecursi|cycle"):
        runner.execute("select * from v2")
    runner.execute("drop view v2")
    runner.execute("drop view v1")


def test_view_name_cannot_shadow_table(runner):
    with pytest.raises(Exception, match="already exists"):
        runner.execute("create view base as select 1 as x")


def test_broken_view_fails_at_create(runner):
    with pytest.raises(Exception):
        runner.execute("create view v as select no_such_col from base")


def test_cte_shadows_view(runner):
    runner.execute("create view v as select id from base")
    res = runner.execute("with v as (select 99 as id) select id from v")
    assert res.rows == [(99,)]
    runner.execute("drop view v")


# -- USE + schemas -----------------------------------------------------------

def test_use_and_schema_ddl(runner):
    runner.execute("create schema mem.s1")
    assert ("s1",) in runner.execute("show schemas from mem").rows
    runner.execute("use mem.s1")
    # CTAS lands in the schema; unqualified reads resolve there
    runner.execute("create table t as select 7 as x")
    assert runner.execute("select x from t").rows == [(7,)]
    # fully-qualified name reaches it from any session state
    assert runner.execute("select x from mem.s1.t").rows == [(7,)]
    # under USE mem.s1 an unqualified name means THAT schema: a table
    # living elsewhere must be qualified (no silent cross-schema read)
    with pytest.raises(Exception, match="not found"):
        runner.execute("select * from base")
    assert len(runner.execute("select * from mem.base").rows) == 3
    runner.execute("use mem.default")
    with pytest.raises(Exception):
        runner.execute("select x from t")  # t lives in s1, not default


def test_use_validates_names(runner):
    with pytest.raises(Exception, match="catalog"):
        runner.execute("use nope.default")
    with pytest.raises(Exception, match="schema"):
        runner.execute("use mem.nope")


def test_create_schema_if_not_exists(runner):
    runner.execute("create schema mem.s2")
    with pytest.raises(Exception, match="exists"):
        runner.execute("create schema mem.s2")
    runner.execute("create schema if not exists mem.s2")


def test_drop_schema_restrict_and_cascade(runner):
    runner.execute("create schema mem.s3")
    runner.execute("use mem.s3")
    runner.execute("create table t3 as select 1 as a")
    with pytest.raises(Exception, match="not empty"):
        runner.execute("drop schema mem.s3")
    runner.execute("use mem.default")
    runner.execute("drop schema mem.s3 cascade")
    assert ("s3",) not in runner.execute("show schemas from mem").rows
    with pytest.raises(Exception):
        runner.execute("select * from mem.s3.t3")
    runner.execute("drop schema if exists mem.s3")


def test_rename_schema(runner):
    runner.execute("create schema mem.olds")
    runner.execute("use mem.olds")
    runner.execute("create table rt as select 5 as v")
    runner.execute("alter schema mem.olds rename to news")
    # session follows the rename
    assert runner.execute("select v from rt").rows == [(5,)]
    assert runner.execute("select v from mem.news.rt").rows == [(5,)]
    runner.execute("use mem.default")
    runner.execute("drop schema mem.news cascade")


# -- ALTER TABLE ADD/DROP COLUMN --------------------------------------------

def test_add_and_drop_column(runner):
    runner.execute("create table alt as select 1 as a")
    runner.execute("alter table alt add column b bigint")
    res = runner.execute("select a, b from alt")
    assert res.rows == [(1, None)]  # NULL backfill
    runner.execute("insert into alt select 2, 20")
    assert sorted(runner.execute("select a, b from alt").rows) == [
        (1, None), (2, 20)]
    runner.execute("alter table alt drop column b")
    assert runner.execute("select * from alt").names == ["a"]


# -- CALL --------------------------------------------------------------------

def test_call_kill_query(runner):
    res = runner.execute("call system.runtime.kill_query('q_42')")
    assert "q_42" in res.rows[0][0]


def test_call_unknown_procedure(runner):
    with pytest.raises(Exception, match="procedure"):
        runner.execute("call system.runtime.nope()")


def test_registered_procedure_receives_literal_args(runner):
    seen = {}

    def proc(session, a, b=None):
        seen["args"] = (a, b)
        return "ok"

    runner.register_procedure("sys.echo", proc)
    assert runner.execute("call sys.echo(3, 'x')").rows == [("ok",)]
    assert seen["args"] == (3, "x")


# -- bare VALUES -------------------------------------------------------------

def test_bare_values_statement(runner):
    assert runner.execute("values 1, 2, 3").rows == [(1,), (2,), (3,)]
    assert runner.execute("values (1, 'a'), (2, 'b')").rows == [
        (1, "a"), (2, "b")]
    assert runner.execute("values 3, 1, 2 order by 1 limit 2").rows == [
        (1,), (2,)]
    assert runner.execute(
        "select a + 1 from (values 1, 2) t(a) order by 1").rows == [(2,), (3,)]


def test_set_path(runner):
    assert runner.execute("set path mem.default").rows == [("SET PATH",)]
    assert runner.session.path == "mem.default"


def test_show_partitions_unpartitioned_errors(runner):
    with pytest.raises(Exception, match="not partitioned"):
        runner.execute("show partitions from base")
