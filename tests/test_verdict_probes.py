"""Regression tests for the VERDICT r5 probe crashes.

1. ``SELECT k, v FROM UNNEST(MAP(...))`` raised a raw
   ``KeyError: frozenset()`` — a lone UNNEST in FROM left the join
   planner with zero terms.  It now expands against a synthetic
   one-row relation.
2. Array super-type unification rejected two *identical-looking*
   element types ("no common super type for array(bigint) and
   array(bigint)") — the widths (hidden by repr) differed and
   ``common_super_type`` had no container rules.  Containers now
   unify recursively with widened slot capacities.
"""

import pytest

from presto_tpu.types import (
    BIGINT, DOUBLE, ArrayType, MapType, common_super_type,
)


@pytest.fixture(scope="module")
def runner():
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001))
    return QueryRunner(catalog)


def test_unnest_map_literal(runner):
    res = runner.execute(
        "SELECT k, v FROM UNNEST(MAP(ARRAY[1,2], ARRAY['a','b'])) AS t(k, v)")
    assert sorted(res.rows) == [(1, "a"), (2, "b")]


def test_unnest_array_literal(runner):
    res = runner.execute("SELECT x FROM UNNEST(ARRAY[3,1,2]) AS t(x)")
    assert sorted(res.rows) == [(1,), (2,), (3,)]


def test_unnest_array_with_ordinality(runner):
    res = runner.execute(
        "SELECT x, o FROM UNNEST(ARRAY[5,6]) WITH ORDINALITY AS t(x, o)")
    assert sorted(res.rows) == [(5, 1), (6, 2)]


def test_unnest_only_from_with_where(runner):
    res = runner.execute(
        "SELECT x FROM UNNEST(ARRAY[1,2,3,4]) AS t(x) WHERE x > 2")
    assert sorted(res.rows) == [(3,), (4,)]


def test_unnest_star_excludes_dummy(runner):
    res = runner.execute("SELECT * FROM UNNEST(ARRAY[7,8]) AS t(x)")
    assert res.names == ["x"]
    assert sorted(res.rows) == [(7,), (8,)]


def test_array_super_type_identical():
    a = ArrayType(BIGINT, 4)
    assert common_super_type(a, a) == a


def test_array_super_type_widths_unify():
    # the r5 probe: identical element types, different (repr-hidden)
    # slot widths — must unify to the wider, not error
    t = common_super_type(ArrayType(BIGINT, 2), ArrayType(BIGINT, 1))
    assert t.name == "array" and t.element == BIGINT
    assert t.max_elems == 2


def test_array_super_type_element_coercion():
    t = common_super_type(ArrayType(BIGINT, 3), ArrayType(DOUBLE, 5))
    assert t.element == DOUBLE and t.max_elems == 5


def test_map_super_type_unifies():
    t = common_super_type(MapType(BIGINT, BIGINT, 2),
                          MapType(BIGINT, DOUBLE, 4))
    assert t.key_element == BIGINT and t.element == DOUBLE
    assert t.max_elems == 4


def test_row_super_type_unifies():
    from presto_tpu.types import RowType

    a = RowType(BIGINT, BIGINT, names=("x", "y"))
    b = RowType(BIGINT, DOUBLE, names=("x", "y"))
    t = common_super_type(a, b)
    assert t.fields == (BIGINT, DOUBLE)
    assert t.field_names == ("x", "y")
    # eq must see field types (it ignored them, making every pair of
    # row types "equal" and the unification unreachable)
    assert RowType(BIGINT) != RowType(BIGINT, DOUBLE)
    with pytest.raises(TypeError):
        common_super_type(RowType(BIGINT), RowType(BIGINT, BIGINT))


def test_string_array_concat_clean_error():
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner
    from presto_tpu.sql.binder import BindError

    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001))
    r = QueryRunner(catalog)
    # derived per-literal dictionaries have incompatible code spaces;
    # must fail at bind, never emit silent NULLs
    with pytest.raises(BindError, match="string-array concat"):
        r.execute("SELECT ARRAY['a','b'] || 'c'")


def test_nested_array_ctor_reports_bind_error(runner):
    # nested-array VALUES remain unsupported by the flat container
    # storage, but the failure is now a clear BindError naming the
    # limitation, not a self-contradictory super-type error
    from presto_tpu.sql.binder import BindError

    with pytest.raises(BindError, match="nested ARRAY"):
        runner.execute("SELECT ARRAY[ARRAY[1,2], ARRAY[3]]")
