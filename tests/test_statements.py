"""Statement-level tests: EXPLAIN (ANALYZE), SET SESSION, SHOW.

Reference analog: coordinator statement handling
(sql/analyzer/QueryExplainer.java, SystemSessionProperties round trip,
metadata SHOW queries)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    return QueryRunner(catalog)


def test_explain(runner):
    res = runner.execute("explain select count(*) from orders where o_orderdate > date '1995-01-01'")
    text = res.rows[0][0]
    assert "Aggregation" in text and "TableScan" in text and "Filter" in text


def test_explain_analyze(runner):
    res = runner.execute("explain analyze select o_orderpriority, count(*) from orders group by o_orderpriority")
    text = res.rows[0][0]
    assert "rows=" in text and "wall=" in text


def test_explain_analyze_verbose_exclusive(runner):
    """VERBOSE re-runs chain prefixes to attribute EXCLUSIVE time to
    each fused chain member — scan, filter, and each join probe get
    their own [excl=..] line (VERDICT: fusion-breaking attribution)."""
    res = runner.execute(
        "explain analyze verbose "
        "select o_orderpriority, count(*) from orders, customer "
        "where o_custkey = c_custkey and o_totalprice > 1000 "
        "group by o_orderpriority")
    text = res.rows[0][0]
    # inclusive stats still present, plus exclusive attribution on the
    # scan leaf, the filter, and the streaming probe
    assert "wall=" in text
    assert text.count("excl=") >= 3
    for line in text.splitlines():
        if "- TableScan orders" in line or "- Filter" in line or "- Join" in line:
            assert "excl=" in line, line


def test_set_session_and_show(runner):
    res = runner.execute("show session")
    names = [r[0] for r in res.rows]
    assert "jit" in names and "distributed" in names
    runner.execute("set session max_groups = 1024")
    assert runner.session.get("max_groups") == 1024
    with pytest.raises(KeyError):
        runner.execute("set session bogus_prop = 1")


def test_show_tables_and_columns(runner):
    res = runner.execute("show tables")
    tables = [r[0] for r in res.rows]
    assert "lineitem" in tables and "orders" in tables
    res = runner.execute("show columns from lineitem")
    cols = dict(res.rows)
    assert cols["l_orderkey"] == "bigint"
    assert cols["l_quantity"].startswith("decimal")


def test_row_comparisons(runner):
    assert runner.execute(
        "SELECT count(*) FROM nation WHERE (n_regionkey, n_nationkey) "
        "IN ((1, 1), (2, 8))").rows == [(2,)]
    assert runner.execute(
        "SELECT count(*) FROM nation WHERE (n_regionkey, n_nationkey) "
        "NOT IN ((1, 1))").rows == [(24,)]
    assert runner.execute(
        "SELECT count(*) FROM nation WHERE (n_regionkey, 0) <> (1, 0)").rows == [(20,)]


def test_prepare_execute_deallocate(runner):
    runner.execute("PREPARE stq FROM SELECT count(*) FROM nation "
                   "WHERE n_regionkey = ?")
    assert runner.execute("EXECUTE stq USING 1").rows == [(5,)]
    assert runner.execute("EXECUTE stq USING 3").rows == [(5,)]
    runner.execute("DEALLOCATE PREPARE stq")
    import pytest

    with pytest.raises(ValueError):
        runner.execute("EXECUTE stq USING 1")
    # a bare ? outside EXECUTE is a bind error
    from presto_tpu.sql.binder import BindError

    with pytest.raises(BindError):
        runner.execute("SELECT ? + 1")


def test_show_catalogs_functions_describe(runner):
    assert runner.execute("SHOW CATALOGS").rows == [("tpch",)]
    fns = dict(runner.execute("SHOW FUNCTIONS").rows)
    assert fns["sum"] == "aggregate" and fns["sqrt"] == "scalar"
    assert fns["row_number"] == "window"
    cols = dict(runner.execute("DESCRIBE region").rows)
    assert cols["r_regionkey"] == "bigint"


def test_jit_off_still_correct(runner):
    runner.execute("set session jit = false")
    try:
        res = runner.execute("select count(*) from orders")
        assert res.rows[0][0] == 1500
    finally:
        runner.execute("set session jit = true")


# ---------------------------------------------------------------------------
# round-4 parser/DDL surface: TABLESAMPLE, GRANT/REVOKE, ALTER TABLE
# RENAME (SqlBase.g4 statements previously unsupported)
# ---------------------------------------------------------------------------

def _tpch_runner():
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner

    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001, split_rows=256))
    mem = MemoryConnector()
    cat.register("mem", mem, writable=True)
    return QueryRunner(cat)


def test_tablesample_bernoulli_and_system():
    r = _tpch_runner()
    n = r.execute("SELECT count(*) FROM orders").rows[0][0]
    s = r.execute(
        "SELECT count(*) FROM orders TABLESAMPLE BERNOULLI (20)").rows[0][0]
    assert 0.10 * n < s < 0.35 * n  # ~20% with deterministic hash
    # deterministic: same sample every run
    s2 = r.execute(
        "SELECT count(*) FROM orders TABLESAMPLE BERNOULLI (20)").rows[0][0]
    assert s2 == s
    sys_rows = r.execute(
        "SELECT count(*) FROM lineitem TABLESAMPLE SYSTEM (50)").rows[0][0]
    total = r.execute("SELECT count(*) FROM lineitem").rows[0][0]
    assert 0 < sys_rows < total


def test_alter_table_rename():
    r = _tpch_runner()
    r.execute("CREATE TABLE mem.t1 AS SELECT o_orderkey FROM orders "
              "WHERE o_orderkey < 20")
    r.execute("ALTER TABLE mem.t1 RENAME TO t2")
    assert r.execute("SELECT count(*) FROM t2").rows[0][0] > 0
    import pytest

    with pytest.raises(Exception):
        r.execute("SELECT count(*) FROM t1")


def test_grant_revoke_lifecycle():
    import pytest

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner
    from presto_tpu.security import AccessDeniedError, GrantingAccessControl

    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001, split_rows=256))
    r = QueryRunner(cat, access_control=GrantingAccessControl(
        admins=("admin",)))
    r.session.user = "admin"
    r.execute("GRANT SELECT ON orders TO alice")
    r.session.user = "alice"
    assert r.execute("SELECT count(*) FROM orders").rows[0][0] > 0
    with pytest.raises(AccessDeniedError):
        r.execute("SELECT count(*) FROM customer")
    r.session.user = "admin"
    r.execute("REVOKE SELECT ON orders FROM alice")
    r.session.user = "alice"
    with pytest.raises(AccessDeniedError):
        r.execute("SELECT count(*) FROM orders")


def test_grant_requires_admin_and_privileges_are_specific():
    import pytest

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner
    from presto_tpu.security import AccessDeniedError, GrantingAccessControl

    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001, split_rows=256))
    mem = MemoryConnector()
    cat.register("mem", mem, writable=True)
    r = QueryRunner(cat, access_control=GrantingAccessControl(
        admins=("admin",)))
    # no self-escalation: a non-admin cannot grant
    r.session.user = "alice"
    with pytest.raises(AccessDeniedError):
        r.execute("GRANT SELECT ON orders TO alice")
    # insert-only grant does NOT authorize DELETE
    r.session.user = "admin"
    r.execute("CREATE TABLE mem.g AS SELECT o_orderkey FROM orders "
              "WHERE o_orderkey < 10")
    r.execute("GRANT SELECT, INSERT ON g TO bob")
    r.execute("GRANT SELECT ON orders TO bob")
    r.session.user = "bob"
    r.execute("INSERT INTO mem.g SELECT o_orderkey FROM orders "
              "WHERE o_orderkey >= 10 AND o_orderkey < 15")
    with pytest.raises(AccessDeniedError):
        r.execute("DELETE FROM g WHERE o_orderkey < 5")


def test_tablesample_after_alias_reference_order():
    r = _tpch_runner()
    n = r.execute("SELECT count(*) FROM orders").rows[0][0]
    s = r.execute("SELECT count(o.o_orderkey) FROM orders o "
                  "TABLESAMPLE BERNOULLI (20)").rows[0][0]
    assert 0 < s < n


def test_quantified_keeps_subquery_order_limit():
    r = _tpch_runner()
    # > ALL over the BOTTOM-3 prices (ORDER BY asc LIMIT 3) is much
    # weaker than > ALL over all prices — the ordered LIMIT must apply
    got = r.execute(
        "SELECT count(*) FROM orders WHERE o_totalprice > ALL "
        "(SELECT o_totalprice FROM orders ORDER BY o_totalprice LIMIT 3)"
    ).rows[0][0]
    n = r.execute("SELECT count(*) FROM orders").rows[0][0]
    assert got == n - 3


def test_interval_values_and_aggregates(runner):
    """First-class INTERVAL values (IntervalDayTimeType /
    IntervalYearMonthType + Interval*Sum/AverageAggregation): datetime
    differences produce intervals, and sum/avg/min/max fold them."""
    import datetime

    td = datetime.timedelta
    assert runner.execute("select interval '3' day").rows == [(td(days=3),)]
    assert runner.execute(
        "select interval '90' second + interval '30' second").rows == [
        (td(seconds=120),)]
    rows = runner.execute(
        "select sum(d), avg(d) from (select timestamp '2020-01-02 00:00:00'"
        " - timestamp '2020-01-01 12:00:00' as d from nation)").rows
    n = runner.execute("select count(*) from nation").rows[0][0]
    assert rows == [(td(hours=12) * n, td(hours=12))]
    assert runner.execute("select interval '14' month").rows == [(14,)]
    assert runner.execute(
        "select max(dd) from (select date '2020-01-03' - date '2020-01-01'"
        " as dd from nation)").rows == [(td(days=2),)]
