"""Statement-level tests: EXPLAIN (ANALYZE), SET SESSION, SHOW.

Reference analog: coordinator statement handling
(sql/analyzer/QueryExplainer.java, SystemSessionProperties round trip,
metadata SHOW queries)."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.001, split_rows=4096))
    return QueryRunner(catalog)


def test_explain(runner):
    res = runner.execute("explain select count(*) from orders where o_orderdate > date '1995-01-01'")
    text = res.rows[0][0]
    assert "Aggregation" in text and "TableScan" in text and "Filter" in text


def test_explain_analyze(runner):
    res = runner.execute("explain analyze select o_orderpriority, count(*) from orders group by o_orderpriority")
    text = res.rows[0][0]
    assert "rows=" in text and "wall=" in text


def test_explain_analyze_verbose_exclusive(runner):
    """VERBOSE re-runs chain prefixes to attribute EXCLUSIVE time to
    each fused chain member — scan, filter, and each join probe get
    their own [excl=..] line (VERDICT: fusion-breaking attribution)."""
    res = runner.execute(
        "explain analyze verbose "
        "select o_orderpriority, count(*) from orders, customer "
        "where o_custkey = c_custkey and o_totalprice > 1000 "
        "group by o_orderpriority")
    text = res.rows[0][0]
    # inclusive stats still present, plus exclusive attribution on the
    # scan leaf, the filter, and the streaming probe
    assert "wall=" in text
    assert text.count("excl=") >= 3
    for line in text.splitlines():
        if "- TableScan orders" in line or "- Filter" in line or "- Join" in line:
            assert "excl=" in line, line


def test_set_session_and_show(runner):
    res = runner.execute("show session")
    names = [r[0] for r in res.rows]
    assert "jit" in names and "distributed" in names
    runner.execute("set session max_groups = 1024")
    assert runner.session.get("max_groups") == 1024
    with pytest.raises(KeyError):
        runner.execute("set session bogus_prop = 1")


def test_show_tables_and_columns(runner):
    res = runner.execute("show tables")
    tables = [r[0] for r in res.rows]
    assert "lineitem" in tables and "orders" in tables
    res = runner.execute("show columns from lineitem")
    cols = dict(res.rows)
    assert cols["l_orderkey"] == "bigint"
    assert cols["l_quantity"].startswith("decimal")


def test_row_comparisons(runner):
    assert runner.execute(
        "SELECT count(*) FROM nation WHERE (n_regionkey, n_nationkey) "
        "IN ((1, 1), (2, 8))").rows == [(2,)]
    assert runner.execute(
        "SELECT count(*) FROM nation WHERE (n_regionkey, n_nationkey) "
        "NOT IN ((1, 1))").rows == [(24,)]
    assert runner.execute(
        "SELECT count(*) FROM nation WHERE (n_regionkey, 0) <> (1, 0)").rows == [(20,)]


def test_prepare_execute_deallocate(runner):
    runner.execute("PREPARE stq FROM SELECT count(*) FROM nation "
                   "WHERE n_regionkey = ?")
    assert runner.execute("EXECUTE stq USING 1").rows == [(5,)]
    assert runner.execute("EXECUTE stq USING 3").rows == [(5,)]
    runner.execute("DEALLOCATE PREPARE stq")
    import pytest

    with pytest.raises(ValueError):
        runner.execute("EXECUTE stq USING 1")
    # a bare ? outside EXECUTE is a bind error
    from presto_tpu.sql.binder import BindError

    with pytest.raises(BindError):
        runner.execute("SELECT ? + 1")


def test_show_catalogs_functions_describe(runner):
    assert runner.execute("SHOW CATALOGS").rows == [("tpch",)]
    fns = dict(runner.execute("SHOW FUNCTIONS").rows)
    assert fns["sum"] == "aggregate" and fns["sqrt"] == "scalar"
    assert fns["row_number"] == "window"
    cols = dict(runner.execute("DESCRIBE region").rows)
    assert cols["r_regionkey"] == "bigint"


def test_jit_off_still_correct(runner):
    runner.execute("set session jit = false")
    try:
        res = runner.execute("select count(*) from orders")
        assert res.rows[0][0] == 1500
    finally:
        runner.execute("set session jit = true")
