"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's DistributedQueryRunner approach (SURVEY.md §4.5:
one JVM hosting coordinator + N workers) — here one process hosting an
8-device virtual TPU topology via XLA's host-platform device count, so
multi-chip sharding is exercised without hardware.

Note: jax is pre-imported at interpreter startup in this image (axon
platform plugin), so env vars alone are too late — use jax.config,
which takes effect before the backend is first initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

# Static plan/IR validation always-on for the whole suite: every query
# any test plans through a QueryRunner also runs the analysis tier
# (presto_tpu/analysis/), so a type/null-mask/ladder invariant break
# fails the suite with a node-specific diagnostic instead of a kernel
# crash.  setdefault: an explicit =0 in the environment still wins.
os.environ.setdefault("PRESTO_TPU_VALIDATE_PLANS", "1")
# ... and every optimizer rule application runs the rewrite-soundness
# gate (presto_tpu/analysis/soundness.py): an unsound rewrite fails
# the suite naming the rule, not as a wrong answer downstream
os.environ.setdefault("PRESTO_TPU_VALIDATE_REWRITES", "1")
# ... and every bound plan runs the expression-tier abstract
# interpreter (presto_tpu/analysis/kernel_soundness.py): a provable
# overflow, lossy cast, literal zero divisor, wrapping accumulator, or
# null-policy mismatch fails the suite with node-level attribution
os.environ.setdefault("PRESTO_TPU_VALIDATE_KERNELS", "1")

import jax

jax.config.update("jax_platforms", "cpu")
# float64/int64 for DOUBLE/BIGINT columns on the CPU test backend.
jax.config.update("jax_enable_x64", True)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavy multi-worker/chaos tests excluded from the tier-1 "
        "sweep (-m 'not slow'); tools/ci.sh runs them in dedicated legs",
    )

# Persistent compilation cache: the suite's wall-clock is dominated by
# XLA recompilation (every query/capacity pair is a fresh program), so
# compiled executables are cached on disk across runs and processes.
# The directory is keyed by a CPU-feature fingerprint: rounds run on
# heterogeneous driver hosts, and replaying executables AOT-compiled
# for another host's avx512/amx feature set SIGILLs/segfaults (observed
# r5: a 21k-entry cache from a prior host crashed the suite mid-write).


def host_cache_dir(root: str) -> str:
    import hashlib
    import platform

    tag = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    tag = hashlib.sha256(line.encode()).hexdigest()[:12]
                    break
    except OSError:
        pass
    return os.path.join(root, tag)


_cache_dir = host_cache_dir(
    os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
# 0.25s floor: writing EVERY executable (tens of thousands per suite
# run) tripped a cumulative segfault inside jax's cache-write path
# (r5, deterministic at ~650 tests in); only the compiles that are
# expensive enough to matter get persisted
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.25)
# NOTE: deliberately NOT enabling jax_persistent_cache_enable_xla_caches:
# XLA:CPU kernel caches are AOT-compiled for this host's CPU features and
# replaying them on a different machine can SIGILL; the jit cache alone
# is portable (it keys on the platform) and captures most of the win.
