"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's DistributedQueryRunner approach (SURVEY.md §4.5:
one JVM hosting coordinator + N workers) — here one process hosting an
8-device virtual TPU topology via XLA's host-platform device count, so
multi-chip sharding is exercised without hardware.

Note: jax is pre-imported at interpreter startup in this image (axon
platform plugin), so env vars alone are too late — use jax.config,
which takes effect before the backend is first initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
# float64/int64 for DOUBLE/BIGINT columns on the CPU test backend.
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: the suite's wall-clock is dominated by
# XLA recompilation (every query/capacity pair is a fresh program), so
# compiled executables are cached on disk across runs and processes.
_cache_dir = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
# NOTE: deliberately NOT enabling jax_persistent_cache_enable_xla_caches:
# XLA:CPU kernel caches are AOT-compiled for this host's CPU features and
# replaying them on a different machine can SIGILL; the jit cache alone
# is portable (it keys on the platform) and captures most of the win.
