"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's DistributedQueryRunner approach (SURVEY.md §4.5:
one JVM hosting coordinator + N workers) — here one process hosting an
8-device virtual TPU topology via XLA's host-platform device count, so
multi-chip sharding is exercised without hardware.

Note: jax is pre-imported at interpreter startup in this image (axon
platform plugin), so env vars alone are too late — use jax.config,
which takes effect before the backend is first initialized.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
# float64/int64 for DOUBLE/BIGINT columns on the CPU test backend.
jax.config.update("jax_enable_x64", True)
