"""Function-breadth coverage: regex/JSON/URL scalars, min_by/max_by,
approx_percentile, HyperLogLog approx_distinct.

Reference analogs: operator/scalar/{RegexpFunctions,JsonFunctions,
UrlFunctions,StringFunctions}.java, operator/aggregation/minmaxby/,
ApproximateLongPercentileAggregations.java,
ApproximateCountDistinctAggregations.java."""

import math

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.page import Dictionary, Page
from presto_tpu.runner import QueryRunner
from presto_tpu.types import BIGINT, DOUBLE, VARCHAR

from tests.oracle import assert_rows_match, load_oracle, run_oracle


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.001, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    return QueryRunner(catalog), load_oracle(tpch)


@pytest.fixture(scope="module")
def docs_runner():
    """A table with JSON/URL shaped strings."""
    docs = [
        '{"a": 1, "b": [10, 20, 30], "c": {"d": "x"}}',
        '{"a": 2, "b": [], "s": "str"}',
        '{"a": null}',
        "[1, 2, 3]",
        "not json",
        '{"a": 42, "b": [7]}',
    ]
    urls = [
        "https://example.com:8080/path/to/page?q=1",
        "http://presto.io/docs",
        "https://tpu.dev/",
        "ftp://files.org/a/b.txt",
        "not a url",
        "https://example.com/other?x=2",
    ]
    d_docs, d_urls = Dictionary(docs), Dictionary(urls)
    n = len(docs)
    page = Page.from_arrays(
        [np.arange(n, dtype=np.int64), np.arange(n, dtype=np.int32),
         np.arange(n, dtype=np.int32)],
        [BIGINT, VARCHAR, VARCHAR],
        dictionaries=[None, d_docs, d_urls],
    )
    mem = MemoryConnector()
    mem.create_table("docs", [("id", BIGINT), ("doc", VARCHAR), ("url", VARCHAR)], [page])
    catalog = Catalog()
    catalog.register("mem", mem)
    return QueryRunner(catalog), docs, urls


# ---------------------------------------------------------------------------
# regex / string transforms (vs sqlite-computed or python expectations)
# ---------------------------------------------------------------------------

def test_regexp_like(env):
    runner, oracle = env
    sql = "select n_name from nation where regexp_like(n_name, '^[A-C].*A$')"
    import re as _re

    expected = [r for r in run_oracle(oracle, "select n_name from nation")
                if _re.search("^[A-C].*A$", r[0])]
    actual = runner.execute(sql).rows
    assert_rows_match(actual, expected, ordered=False)


def test_regexp_extract_replace(env):
    runner, oracle = env
    rows = runner.execute(
        "select c_phone, regexp_extract(c_phone, '^([0-9]+)-', 1),"
        " regexp_replace(c_phone, '[0-9]', '#') from customer limit 200").rows
    import re as _re

    for phone, cc, masked in rows:
        m = _re.search(r"^([0-9]+)-", phone)
        assert cc == (m.group(1) if m else None)
        assert masked == _re.sub("[0-9]", "#", phone)


def test_replace_split_pad_concat(env):
    runner, _ = env
    rows = runner.execute(
        "select n_name, replace(n_name, 'A', '@'), split_part(n_name, 'A', 1),"
        " lpad(n_name, 12, '*'), rpad(n_name, 12, '*'),"
        " 'x-' || n_name, concat(n_name, '!') from nation").rows
    for name, repl, sp, lp, rp, cc, cc2 in rows:
        assert repl == name.replace("A", "@")
        assert sp == name.split("A")[0]
        assert lp == ("*" * 12)[: 12 - len(name)] + name if len(name) < 12 else name[:12]
        assert rp == (name + "*" * 12)[:12] if len(name) < 12 else name[:12]
        assert cc == "x-" + name
        assert cc2 == name + "!"


def test_starts_ends_with_codepoint(env):
    runner, _ = env
    rows = runner.execute(
        "select n_name, starts_with(n_name, 'A'), ends_with(n_name, 'A'),"
        " codepoint(n_name) from nation").rows
    for name, sw, ew, cp in rows:
        assert sw == name.startswith("A")
        assert ew == name.endswith("A")
        assert cp == ord(name[0])


def test_split_part_out_of_range_null(env):
    runner, _ = env
    rows = runner.execute(
        "select count(*) from nation where split_part(n_name, 'Q', 2) is null").rows
    # names without 'Q' have no part 2
    names = runner.execute("select n_name from nation").rows
    want = sum(1 for (n,) in names if len(n.split("Q")) < 2)
    assert rows == [(want,)]


# ---------------------------------------------------------------------------
# JSON / URL
# ---------------------------------------------------------------------------

def test_json_functions(docs_runner):
    runner, docs, _ = docs_runner
    rows = runner.execute(
        "select id, json_extract_scalar(doc, '$.a'),"
        " json_extract(doc, '$.b'), json_array_length(doc),"
        " json_extract_scalar(doc, '$.c.d'), json_extract_scalar(doc, '$.b[1]'),"
        " is_json_scalar(doc)"
        " from docs order by id").rows
    import json as _json

    for i, a, b, alen, cd, b1, scalar in rows:
        doc = docs[i]
        try:
            parsed = _json.loads(doc)
        except Exception:
            parsed = None
        want_a = None
        if isinstance(parsed, dict) and parsed.get("a") is not None:
            want_a = str(parsed["a"])
        assert a == want_a, (i, a)
        want_b = None
        if isinstance(parsed, dict) and "b" in parsed:
            want_b = _json.dumps(parsed["b"], separators=(",", ":"))
        assert b == want_b
        assert alen == (len(parsed) if isinstance(parsed, list) else None)
        want_cd = None
        if isinstance(parsed, dict) and isinstance(parsed.get("c"), dict):
            want_cd = parsed["c"].get("d")
        assert cd == want_cd
        want_b1 = None
        if isinstance(parsed, dict) and isinstance(parsed.get("b"), list) and len(parsed["b"]) > 1:
            want_b1 = str(parsed["b"][1])
        assert b1 == want_b1
        assert scalar == (parsed is not None and not isinstance(parsed, (dict, list)))


def test_url_functions(docs_runner):
    runner, _, urls = docs_runner
    rows = runner.execute(
        "select id, url_extract_host(url), url_extract_path(url),"
        " url_extract_protocol(url), url_extract_query(url), url_extract_port(url)"
        " from docs order by id").rows
    from urllib.parse import urlparse

    for i, host, path, proto, query, port in rows:
        u = urlparse(urls[i])
        assert host == (u.hostname or None)
        assert path == (u.path if u.path else (None if u.scheme else u.path or None)) or path == u.path
        assert proto == (u.scheme or None)
        assert query == (u.query or None)
        assert port == u.port


# ---------------------------------------------------------------------------
# min_by / max_by / approx_percentile / approx_distinct
# ---------------------------------------------------------------------------

def test_min_by_max_by(env):
    runner, oracle = env
    actual = runner.execute(
        "select s_nationkey, min_by(s_name, s_acctbal), max_by(s_name, s_acctbal)"
        " from supplier group by s_nationkey").rows
    expected = run_oracle(oracle, """
        select s_nationkey,
               (select s2.s_name from supplier s2 where s2.s_nationkey = s1.s_nationkey
                order by s2.s_acctbal asc limit 1),
               (select s3.s_name from supplier s3 where s3.s_nationkey = s1.s_nationkey
                order by s3.s_acctbal desc limit 1)
        from supplier s1 group by s_nationkey""")
    assert_rows_match(actual, expected, ordered=False)


def test_min_by_global(env):
    runner, oracle = env
    actual = runner.execute(
        "select max_by(c_name, c_acctbal) from customer").rows
    expected = run_oracle(
        oracle, "select c_name from customer order by c_acctbal desc limit 1")
    assert actual == expected


def test_approx_percentile(env):
    runner, oracle = env
    for p in (0.0, 0.25, 0.5, 0.9, 1.0):
        actual = runner.execute(
            f"select approx_percentile(o_totalprice, {p}) from orders").rows
        vals = sorted(v for (v,) in run_oracle(oracle, "select o_totalprice from orders"))
        want = vals[int(math.floor(p * (len(vals) - 1)))]
        assert math.isclose(actual[0][0], want, rel_tol=1e-9), (p, actual, want)


def test_approx_percentile_grouped(env):
    runner, oracle = env
    actual = dict(runner.execute(
        "select s_nationkey, approx_percentile(s_acctbal, 0.5)"
        " from supplier group by s_nationkey").rows)
    groups = {}
    for k, v in run_oracle(oracle, "select s_nationkey, s_acctbal from supplier"):
        groups.setdefault(k, []).append(v)
    for k, vals in groups.items():
        vals.sort()
        want = vals[int(math.floor(0.5 * (len(vals) - 1)))]
        assert math.isclose(actual[k], want, rel_tol=1e-9), k


def test_approx_distinct_hll(env):
    runner, oracle = env
    for col, table in (("o_custkey", "orders"), ("l_partkey", "lineitem"),
                       ("s_nationkey", "supplier")):
        actual = runner.execute(f"select approx_distinct({col}) from {table}").rows[0][0]
        exact = run_oracle(oracle, f"select count(distinct {col}) from {table}")[0][0]
        assert abs(actual - exact) <= max(0.05 * exact, 2), (col, actual, exact)


def test_approx_distinct_grouped(env):
    runner, oracle = env
    actual = dict(runner.execute(
        "select o_orderstatus, approx_distinct(o_custkey) from orders"
        " group by o_orderstatus").rows)
    expected = dict(run_oracle(
        oracle, "select o_orderstatus, count(distinct o_custkey) from orders"
        " group by o_orderstatus"))
    assert set(actual) == set(expected)
    for k, exact in expected.items():
        assert abs(actual[k] - exact) <= max(0.05 * exact, 2), k


def test_approx_distinct_empty(env):
    runner, _ = env
    rows = runner.execute(
        "select approx_distinct(o_custkey) from orders where o_orderkey < 0").rows
    assert rows == [(0,)]


def test_varchar_min_max_collation(env):
    """min/max over VARCHAR must order by value, not dictionary code
    (s_name codes are assignment-ordered; p_type's are not lexicographic)."""
    runner, oracle = env
    for col, table in (("p_type", "part"), ("c_mktsegment", "customer"),
                       ("s_name", "supplier")):
        actual = runner.execute(f"select min({col}), max({col}) from {table}").rows
        expected = run_oracle(oracle, f"select min({col}), max({col}) from {table}")
        assert actual == expected, col


def test_min_by_string_key(env):
    """min_by/max_by with a VARCHAR ordering key compares values."""
    runner, oracle = env
    actual = runner.execute(
        "select min_by(p_partkey, p_type), max_by(p_partkey, p_type) from part").rows
    expected = run_oracle(oracle, """
        select (select p_partkey from part order by p_type asc, p_partkey limit 1),
               (select p_partkey from part order by p_type desc, p_partkey limit 1)""")
    # ties on p_type broken arbitrarily: compare the chosen key's type
    types = dict(run_oracle(oracle, "select p_partkey, p_type from part"))
    want_min = run_oracle(oracle, "select min(p_type) from part")[0][0]
    want_max = run_oracle(oracle, "select max(p_type) from part")[0][0]
    assert types[actual[0][0]] == want_min
    assert types[actual[0][1]] == want_max


def test_approx_distinct_over_transform(env):
    """approx_distinct(substr(x, 1, 1)) counts distinct transformed
    values, not distinct source codes."""
    runner, oracle = env
    actual = runner.execute(
        "select approx_distinct(substr(c_name, 1, 10)) from customer").rows[0][0]
    exact = run_oracle(
        oracle, "select count(distinct substr(c_name, 1, 10)) from customer")[0][0]
    assert abs(actual - exact) <= max(0.05 * exact, 2), (actual, exact)


def test_cross_dict_eq_with_derived(env):
    """Equality through a derived dictionary that maps many codes to one
    value (substr) must compare values."""
    runner, oracle = env
    sql = ("select count(*) from supplier, customer"
           " where substr(s_phone, 1, 2) = substr(c_phone, 1, 2)"
           " and s_suppkey < 20 and c_custkey < 50")
    actual = runner.execute(sql).rows
    expected = run_oracle(oracle, sql)
    assert_rows_match(actual, expected, ordered=False)


def test_statistical_aggregates_vs_numpy():
    """covar/corr/regr two-argument moments (AggregationUtils states)."""
    import numpy as np

    from presto_tpu.testing import LocalQueryRunner

    r = LocalQueryRunner(sf=0.001)
    got = r.execute(
        "SELECT covar_pop(l_extendedprice, l_quantity), "
        "covar_samp(l_extendedprice, l_quantity), "
        "corr(l_extendedprice, l_quantity), "
        "regr_slope(l_extendedprice, l_quantity), "
        "regr_intercept(l_extendedprice, l_quantity) FROM lineitem").rows[0]
    raw = r.execute(
        "SELECT l_quantity, l_extendedprice FROM lineitem").rows
    x = np.asarray([float(a) for a, _ in raw])
    y = np.asarray([float(b) for _, b in raw])
    n = len(x)
    cov_pop = ((x - x.mean()) * (y - y.mean())).mean()
    assert float(got[0]) == pytest.approx(cov_pop, rel=1e-9)
    assert float(got[1]) == pytest.approx(cov_pop * n / (n - 1), rel=1e-9)
    assert float(got[2]) == pytest.approx(np.corrcoef(x, y)[0, 1], rel=1e-9)
    slope = cov_pop / x.var()
    assert float(got[3]) == pytest.approx(slope, rel=1e-9)
    assert float(got[4]) == pytest.approx(y.mean() - slope * x.mean(), rel=1e-9)


def test_checksum_arbitrary_count_if_geomean():
    import numpy as np

    from presto_tpu.testing import LocalQueryRunner

    r = LocalQueryRunner(sf=0.001)
    # checksum is order-independent and deterministic
    a = r.execute("SELECT checksum(l_orderkey) FROM lineitem").rows
    b = r.execute("SELECT checksum(l_orderkey) FROM "
                  "(SELECT l_orderkey FROM lineitem ORDER BY l_orderkey DESC)").rows
    assert a == b and isinstance(a[0][0], int)
    # differs when the multiset differs
    c = r.execute("SELECT checksum(l_orderkey) FROM lineitem "
                  "WHERE l_orderkey > 5").rows
    assert c != a
    assert r.execute("SELECT count_if(l_quantity > 25), "
                     "count(CASE WHEN l_quantity > 25 THEN 1 END) "
                     "FROM lineitem").rows[0][0] == r.execute(
        "SELECT count(*) FROM lineitem WHERE l_quantity > 25").rows[0][0]
    flags = {f for (f,) in r.execute(
        "SELECT DISTINCT l_returnflag FROM lineitem").rows}
    assert r.execute("SELECT arbitrary(l_returnflag) FROM lineitem"
                     ).rows[0][0] in flags
    qty = [float(q) for (q,) in r.execute(
        "SELECT l_quantity FROM lineitem").rows]
    expect = float(np.exp(np.mean(np.log(qty))))
    got = r.execute("SELECT geometric_mean(l_quantity) FROM lineitem").rows[0][0]
    assert got == pytest.approx(expect, rel=1e-9)


def test_trig_and_math_sweep():
    import math

    from presto_tpu.testing import LocalQueryRunner

    r = LocalQueryRunner(sf=0.001)
    rows = r.execute(
        "SELECT sin(pi()/2), cos(0), tan(0), atan2(1, 1), log2(8), "
        "degrees(pi()), radians(180e0), truncate(-2.7e0), "
        "width_bucket(3.5, 0, 10, 5), is_nan(sqrt(-1e0)), is_finite(1e0), "
        "sinh(0), cosh(0), tanh(0), e()").rows[0]
    assert rows[0] == pytest.approx(1.0)
    assert rows[3] == pytest.approx(math.pi / 4)
    assert rows[4] == 3.0
    assert rows[5] == pytest.approx(180.0)
    assert rows[6] == pytest.approx(math.pi)
    assert rows[7] == -2.0
    assert rows[8] == 2
    assert rows[9] is True and rows[10] is True
    assert rows[12] == 1.0
    assert rows[14] == pytest.approx(math.e)


# ---------------------------------------------------------------------------
# round-4 aggregate breadth: HLL sketches as values, multimap_agg,
# numeric_histogram, weighted/array approx_percentile, avg(decimal)
# scale (VERDICT r3 next-round item 6)
# ---------------------------------------------------------------------------

def test_approx_set_merge_cardinality(env):
    runner, _ = env
    true = runner.execute(
        "select count(distinct o_custkey) from orders").rows[0][0]
    est = runner.execute(
        "select cardinality(approx_set(o_custkey)) from orders").rows[0][0]
    # m=512 registers: ~4.6% standard error; allow 4 sigma
    assert abs(est - true) <= max(0.2 * true, 10)
    # union of per-group sketches == the global sketch exactly
    merged = runner.execute("""
        select cardinality(merge(s)) from (
          select o_orderpriority, approx_set(o_custkey) as s
          from orders group by o_orderpriority) t
    """).rows[0][0]
    assert merged == est


def test_multimap_agg(env):
    runner, _ = env
    got = runner.execute(
        "select g, multimap_agg(k, v) from (values "
        "(1,1,10),(1,1,11),(1,2,20),(2,3,30)) t(g,k,v) "
        "group by g order by g").rows
    assert got[0][0] == 1 and got[0][1][1] == [10, 11] and got[0][1][2] == [20]
    assert got[1][1] == {3: [30]}


def test_numeric_histogram(env):
    runner, _ = env
    (m,) = runner.execute(
        "select numeric_histogram(4, x) from (values "
        "(1.0),(2.0),(3.0),(4.0),(10.0)) t(x)").rows[0]
    # weights sum to the row count; centroids are per-bin means
    assert sum(m.values()) == 5.0
    assert any(abs(k - 10.0) < 1e-9 for k in m)  # the outlier bin


def test_weighted_approx_percentile(env):
    runner, _ = env
    (v,) = runner.execute(
        "select approx_percentile(x, w, 0.5) from (values "
        "(1.0, 1), (2.0, 1), (100.0, 10)) t(x, w)").rows[0]
    assert v == 100.0  # weight 10 dominates: median lands on 100
    (v2,) = runner.execute(
        "select approx_percentile(x, w, 0.1) from (values "
        "(1.0, 5), (2.0, 1), (100.0, 1)) t(x, w)").rows[0]
    assert v2 == 1.0


def test_array_approx_percentile(env):
    runner, _ = env
    (arr,) = runner.execute(
        "select approx_percentile(o_totalprice, array[0.1, 0.5, 0.9]) "
        "from orders").rows[0]
    singles = [runner.execute(
        f"select approx_percentile(o_totalprice, {p}) from orders").rows[0][0]
        for p in (0.1, 0.5, 0.9)]
    assert [float(a) for a in arr] == [float(s) for s in singles]
    assert float(arr[0]) < float(arr[1]) < float(arr[2])


def test_avg_decimal_keeps_scale(env):
    runner, _ = env
    from decimal import Decimal

    (v,) = runner.execute(
        "select avg(x) from (values (0.01), (0.02)) t(x)").rows[0]
    assert v == Decimal("0.02")  # 0.015 rounds HALF_UP at scale 2
    (v2,) = runner.execute(
        "select avg(x) from (values (-0.01), (-0.02)) t(x)").rows[0]
    assert v2 == Decimal("-0.02")  # away from zero


def test_weighted_percentile_ignores_null_rows(env):
    runner, _ = env
    # NULL-x rows contribute no weight (review finding r4)
    (v,) = runner.execute(
        "select approx_percentile(nullif(x, 9.0), w, 0.5) from (values "
        "(1.0, 1), (2.0, 1), (9.0, 2)) t(x, w)").rows[0]
    assert v == 1.0


# -- r4 batch 2: central moments + bitwise folds -----------------------------

def test_skewness_kurtosis_vs_numpy(env):
    runner, _ = env
    import numpy as np

    rows = runner.execute("select o_totalprice from orders").rows
    x = np.asarray([r[0] for r in rows], dtype=np.float64)
    n = len(x)
    m2 = float(((x - x.mean()) ** 2).sum())
    m3 = float(((x - x.mean()) ** 3).sum())
    m4 = float(((x - x.mean()) ** 4).sum())
    want_skew = np.sqrt(n) * m3 / m2 ** 1.5
    # independent check via scipy-convention kurtosis: the unbiased
    # estimator expressed through the population excess g2
    g2 = n * m4 / (m2 * m2) - 3.0
    want_kurt = ((n - 1) / ((n - 2) * (n - 3))) * ((n + 1) * g2 + 6.0)
    got_s, got_k = runner.execute(
        "select skewness(o_totalprice), kurtosis(o_totalprice) "
        "from orders").rows[0]
    assert abs(got_s - want_skew) < 1e-9 * max(1.0, abs(want_skew))
    assert abs(got_k - want_kurt) < 1e-9 * max(1.0, abs(want_kurt))


def test_moments_grouped_and_split_merged(env):
    runner, _ = env
    import numpy as np

    # per-group result must equal a single-group computation on the
    # filtered subset (exercises the M3/M4 partial-state merge)
    rows = runner.execute(
        "select o_orderpriority, skewness(o_totalprice), "
        "kurtosis(o_totalprice) from orders group by o_orderpriority "
        "order by o_orderpriority").rows
    assert len(rows) == 5
    for prio, skew, kurt in rows[:2]:
        one = runner.execute(
            f"select skewness(o_totalprice), kurtosis(o_totalprice) "
            f"from orders where o_orderpriority = '{prio}'").rows[0]
        assert abs(skew - one[0]) < 1e-9 * max(1.0, abs(one[0]))
        assert abs(kurt - one[1]) < 1e-9 * max(1.0, abs(one[1]))


def test_moment_null_thresholds(env):
    runner, _ = env
    # skewness needs n >= 3, kurtosis n >= 4
    assert runner.execute(
        "select skewness(x) from (values (1.0), (2.0)) t(x)"
    ).rows == [(None,)]
    assert runner.execute(
        "select kurtosis(x) from (values (1.0), (2.0), (3.0)) t(x)"
    ).rows == [(None,)]
    assert runner.execute(
        "select skewness(x) from (values (5.0), (5.0), (5.0)) t(x)"
    ).rows == [(None,)]  # zero variance


def test_bitwise_agg_vs_python(env):
    runner, _ = env
    rows = runner.execute("select o_orderkey from orders").rows
    keys = [r[0] for r in rows]
    import functools

    want_and = functools.reduce(lambda a, b: a & b, keys)
    want_or = functools.reduce(lambda a, b: a | b, keys)
    got = runner.execute(
        "select bitwise_and_agg(o_orderkey), bitwise_or_agg(o_orderkey) "
        "from orders").rows[0]
    assert got == (want_and, want_or)


def test_bitwise_agg_grouped_nulls(env):
    runner, _ = env
    rows = runner.execute(
        "select g, bitwise_and_agg(v), bitwise_or_agg(v) from (values "
        "(1, 12), (1, 10), (1, NULL), (2, 5), (3, NULL)) t(g, v) "
        "group by g order by g").rows
    assert rows == [(1, 12 & 10, 12 | 10), (2, 5, 5), (3, None, None)]


# round-4b aggregate breadth: map_union, max/min(x, n), max_by/min_by
# (x, y, n).  Reference: operator/aggregation/MapUnionAggregation.java,
# MaxNAggregationFunction.java (TypedHeap), MinByNAggregationFunction
# (TypedKeyValueHeap) — the heaps become one lexsort + dense scatter.

def test_map_union_grouped(env):
    runner, _ = env
    got = runner.execute(
        "select g, map_union(m) from (select g, map_agg(k, v) m from "
        "(values (1,1,10),(1,2,20),(2,3,30),(2,4,40),(9,5,50)) t(g,k,v)"
        " group by g, k) group by g order by g").rows
    assert got == [(1, {1: 10, 2: 20}), (2, {3: 30, 4: 40}), (9, {5: 50})]


def test_map_union_global_and_null_maps(env):
    runner, _ = env
    (m,) = runner.execute(
        "select map_union(m) from (select map_agg(k, v) m from (values "
        "(1,10),(2,20)) t(k,v) group by k)").rows[0]
    assert m == {1: 10, 2: 20}
    # NULL maps are skipped; a group of only-NULL maps yields NULL
    got = runner.execute(
        "select a.g, map_union(b.m) from (values (1),(2)) a(g) left join "
        "(select 1 g, map_agg(k, v) m from (values (1,10),(2,20)) t(k,v)"
        " group by 1) b on a.g = b.g group by a.g order by a.g").rows
    assert got == [(1, {1: 10, 2: 20}), (2, None)]


def test_max_n_min_n_grouped(env):
    runner, _ = env
    got = runner.execute(
        "select g, max(x, 2), min(x, 2) from (values "
        "(1,5),(1,3),(1,9),(1,1),(2,7)) t(g,x) group by g order by g"
    ).rows
    assert got == [(1, [9, 5], [1, 3]), (2, [7], [7])]


def test_max_n_nulls_and_count_cap(env):
    runner, _ = env
    got = runner.execute(
        "select max(x, 3) from (values (1),(null),(4),(2),(null)) t(x)"
    ).rows
    assert got == [([4, 2, 1],)]


def test_max_n_vs_numpy_over_splits(env):
    runner, _ = env
    prices = sorted(
        (r[0] for r in runner.execute(
            "select l_extendedprice from lineitem").rows), reverse=True)
    (top,) = runner.execute(
        "select max(l_extendedprice, 5) from lineitem").rows[0]
    assert [round(float(v), 2) for v in top] == [round(float(v), 2) for v in prices[:5]]
    got = runner.execute(
        "select l_returnflag, min(l_extendedprice, 3) from lineitem "
        "group by 1 order by 1").rows
    import collections

    per = collections.defaultdict(list)
    for f, p in runner.execute(
            "select l_returnflag, l_extendedprice from lineitem").rows:
        per[f].append(p)
    for flag, arr in got:
        want = sorted(per[flag])[:3]
        assert [round(float(v), 2) for v in arr] == [round(float(v), 2) for v in want]


def test_max_by_n_min_by_n(env):
    runner, _ = env
    got = runner.execute(
        "select g, max_by(x, y, 2), min_by(x, y, 2) from (values "
        "(1, 100, 1.0), (1, 200, 3.0), (1, 300, 2.0), (2, 5, 9.0)) "
        "t(g, x, y) group by g order by g").rows
    assert got == [(1, [200, 300], [100, 300]), (2, [5], [5])]


def test_max_by_n_null_value_slot(env):
    runner, _ = env
    (arr,) = runner.execute(
        "select max_by(x, y, 2) from (values (null, 9), (7, 1)) t(x, y)"
    ).rows[0]
    assert arr == [None, 7]


def test_topn_binder_errors(env):
    runner, _ = env
    for sql in ("select max(x, 0) from (values (1)) t(x)",
                "select max(x, 100000) from (values (1)) t(x)",
                "select min(x, y) from (values (1, 2)) t(x, y)",
                "select map_union(x) from (values (1)) t(x)"):
        with pytest.raises(Exception):
            runner.execute(sql)


def test_max_n_many_groups_merge_path(env):
    """> SMALL_SEG_LIMIT groups exercises the sort-ctx segment path in
    the grouped merge, where the flattened top-n lanes must NOT reuse
    the row-length sort ctx (code-review regression)."""
    runner, _ = env
    got = runner.execute(
        "select l_orderkey, max(l_extendedprice, 2) from lineitem "
        "where l_orderkey <= 2000 group by 1 order by 1").rows
    import collections

    per = collections.defaultdict(list)
    for k, p in runner.execute(
            "select l_orderkey, l_extendedprice from lineitem "
            "where l_orderkey <= 2000").rows:
        per[k].append(float(p))
    assert len(got) > 128
    for key, arr in got:
        want = sorted(per[key], reverse=True)[:2]
        assert [round(float(v), 2) for v in arr] == \
            [round(v, 2) for v in want], key


def test_map_union_rejects_multimap_and_hll(env):
    runner, _ = env
    for sql in (
            "select map_union(m) from (select multimap_agg(k, v) m from "
            "(values (1, 10), (1, 11)) t(k, v)) s",
            "select map_union(m) from (select approx_set(k) m from "
            "(values (1), (2)) t(k)) s"):
        with pytest.raises(Exception):
            runner.execute(sql)


def test_map_union_of_empty_maps_is_empty_map(env):
    """A group whose maps are all EMPTY (not NULL) unions to an empty
    map, not NULL (code-review regression: validity tracks rows, not
    entries)."""
    runner, _ = env
    (m,) = runner.execute(
        "select map_union(m) from (select map(slice(array[1], 1, 0), "
        "slice(array[10], 1, 0)) m) t").rows[0]
    assert m == {}
