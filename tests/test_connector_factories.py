"""Every built-in connector kind constructs from catalog property
files (the ConnectorFactory registry behind etc/catalog/*.properties;
reference: server/PluginManager + each connector's factory class)."""

import json
import os

import numpy as np
import pytest

from presto_tpu.config import _BUILTIN_CONNECTORS, _make_connector


def test_every_builtin_kind_constructs(tmp_path):
    from presto_tpu.connectors.remote import TableServiceServer
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.page import Page
    from presto_tpu.storage.pcf import write_pcf
    from presto_tpu.storage.rgf import write_rgf
    from presto_tpu.types import BIGINT

    page = Page.from_arrays([np.arange(10, dtype=np.int64)], [BIGINT])
    write_pcf(str(tmp_path / "pcfroot_t.pcf"), [("k", BIGINT)], [page])
    os.makedirs(tmp_path / "pcfroot", exist_ok=True)
    os.rename(tmp_path / "pcfroot_t.pcf", tmp_path / "pcfroot" / "t.pcf")
    os.makedirs(tmp_path / "rgfroot", exist_ok=True)
    write_rgf(str(tmp_path / "rgfroot" / "t.rgf"), [("k", BIGINT)], [page])
    csv_path = tmp_path / "data.csv"
    csv_path.write_text("1,a\n2,b\n")
    (tmp_path / "lf.json").write_text(json.dumps([
        {"name": "t", "path": str(csv_path), "format": "csv",
         "schema": [["n", "bigint"], ["s", "varchar"]]}]))
    (tmp_path / "stream.json").write_text(json.dumps(
        {"events": {"format": "json", "schema": [["n", "bigint"]]}}))
    import sqlite3

    db = sqlite3.connect(str(tmp_path / "db.sqlite"))
    db.execute("CREATE TABLE t (k INTEGER)")
    db.commit()
    db.close()
    svc = TableServiceServer({"tpch": Tpch(sf=0.001, split_rows=512)}).start()
    try:
        props = {
            "tpch": {"tpch.scale-factor": "0.001"},
            "tpcds": {"tpcds.scale-factor": "0.001"},
            "memory": {},
            "blackhole": {},
            "metrics": {},
            "jdbc": {"jdbc.path": str(tmp_path / "db.sqlite")},
            "localfile": {"localfile.catalog": str(tmp_path / "lf.json")},
            "pcf": {"pcf.root": str(tmp_path / "pcfroot")},
            "rgf": {"rgf.root": str(tmp_path / "rgfroot")},
            "warehouse": {"warehouse.root": str(tmp_path / "wh")},
            "shardstore": {"shardstore.root": str(tmp_path / "ss"),
                           "shardstore.nodes": "a,b"},
            "remote": {"remote.uri": svc.uri},
            "stream": {"stream.root": str(tmp_path / "log"),
                       "stream.table-descriptions":
                           str(tmp_path / "stream.json")},
            "kv": {"kv.path": str(tmp_path / "kv.db"),
                   "kv.table-descriptions": str(tmp_path / "stream.json")},
        }
        # http needs a live catalog URI; serve one through the table
        # service host? — skipped here, constructor covered in
        # test_external_connectors
        for kind in _BUILTIN_CONNECTORS:
            if kind == "http":
                continue
            conn = _make_connector(kind, props[kind])
            names = conn.table_names()
            assert isinstance(names, list), kind
    finally:
        svc.stop()
    with pytest.raises(ValueError):
        _make_connector("nope", {})
