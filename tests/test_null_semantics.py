"""ANSI three-valued IN / NOT IN, ALL/ANY edge semantics.

Reference analogs: operator/HashSemiJoinOperator.java:32 (NULL-aware
semi join: the membership test is NULL for an unmatched probe whose
key is NULL or when the build side holds a NULL key) and the
QuantifiedComparison rewriter's count-based ALL/ANY expansion (ALL
over an empty subquery is TRUE, ANY over empty is FALSE).

Expected values are cross-checked against sqlite3, which implements
ANSI IN/NOT IN three-valued logic.
"""

import sqlite3

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import QueryRunner


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("mem", MemoryConnector(), writable=True)
    r = QueryRunner(catalog)
    # t: values 1..4 plus NULL; s_null holds a NULL; s_clean does not
    r.execute("create table t as select * from (values 1, 2, 3, 4, "
              "null) v(x)")
    r.execute("create table s_clean as select * from (values 2, 3) v(y)")
    r.execute("create table s_null as select * from "
              "(values 2, null) v(y)")
    r.execute("create table s_empty as select y from s_clean where y < 0")
    return r


def nsort(rows):
    return sorted(rows, key=lambda r: tuple((v is None, v) for v in r))


def sqlite_rows(sql):
    con = sqlite3.connect(":memory:")
    con.execute("create table t(x)")
    con.executemany("insert into t values (?)", [(1,), (2,), (3,), (4,),
                                                 (None,)])
    con.execute("create table s_clean(y)")
    con.executemany("insert into s_clean values (?)", [(2,), (3,)])
    con.execute("create table s_null(y)")
    con.executemany("insert into s_null values (?)", [(2,), (None,)])
    con.execute("create table s_empty(y)")
    return nsort(con.execute(sql).fetchall())


@pytest.mark.parametrize("sql", [
    "select x from t where x in (select y from s_clean)",
    "select x from t where x not in (select y from s_clean)",
    "select x from t where x in (select y from s_null)",
    "select x from t where x not in (select y from s_null)",
    "select x from t where x in (select y from s_empty)",
    "select x from t where x not in (select y from s_empty)",
    "select x from t where not (x in (select y from s_null))",
    "select x from t where not (x not in (select y from s_clean))",
])
def test_in_not_in_vs_sqlite(runner, sql):
    assert nsort(runner.execute(sql).rows) == sqlite_rows(sql)


def test_not_in_with_build_null_is_empty(runner):
    # x NOT IN {2, NULL}: never TRUE for any x
    assert runner.execute(
        "select x from t where x not in (select y from s_null)").rows == []


def test_not_in_empty_keeps_all_rows(runner):
    rows = sorted(runner.execute(
        "select x from t where x not in (select y from s_empty)").rows,
        key=lambda r: (r[0] is None, r[0]))
    assert rows == [(1,), (2,), (3,), (4,), (None,)]


def test_in_mark_join_three_valued(runner):
    """IN under OR lowers to a mark join; the mark must be
    three-valued so the OR combines per Kleene logic."""
    # x IN s_null OR x = 1: row 1 via the disjunct, row 2 via the IN;
    # rows 3/4 have IN = NULL (build holds NULL) so NULL OR FALSE drops
    assert sorted(runner.execute(
        "select x from t where x in (select y from s_null) or x = 1"
    ).rows) == [(1,), (2,)]
    # NOT over the mark: NOT(NULL) is NULL, so only the definite
    # non-member with no NULL uncertainty survives — none here
    assert runner.execute(
        "select x from t where not (x in (select y from s_null)) "
        "and x is not null").rows == []
    # IN over empty is FALSE even for the NULL probe: NOT keeps all
    rows = runner.execute(
        "select x from t where not (x in (select y from s_empty)) "
        "or x = -1").rows
    assert len(rows) == 5


def test_all_over_empty_is_true(runner):
    rows = sorted(runner.execute(
        "select x from t where x < all (select y from s_empty)").rows,
        key=lambda r: (r[0] is None, r[0]))
    assert rows == [(1,), (2,), (3,), (4,), (None,)]  # vacuous truth


def test_any_over_empty_is_false(runner):
    assert runner.execute(
        "select x from t where x < any (select y from s_empty)").rows == []


def test_all_with_nulls_unknown(runner):
    # x < ALL {2, NULL}: 1 < 2 TRUE but 1 < NULL unknown -> UNKNOWN (drop)
    assert runner.execute(
        "select x from t where x < all (select y from s_null)").rows == []
    # definite miss stays FALSE regardless of NULLs (2 < 2, 3 < 2,
    # 4 < 2 all FALSE), so NOT keeps those rows
    assert sorted(runner.execute(
        "select x from t where not (x < all (select y from s_null))"
    ).rows) == [(2,), (3,), (4,)]


def test_any_with_nulls(runner):
    # x > ANY {2, NULL}: 3 > 2 TRUE; 1 > 2 FALSE and 1 > NULL unknown -> UNKNOWN
    assert sorted(runner.execute(
        "select x from t where x > any (select y from s_null)").rows) == [
        (3,), (4,)]
    # the FALSE-with-nulls case must NOT surface under NOT either
    assert runner.execute(
        "select x from t where not (x > any (select y from s_null))"
    ).rows == []


def test_all_any_clean_comparisons(runner):
    assert sorted(runner.execute(
        "select x from t where x >= all (select y from s_clean)").rows) == [
        (3,), (4,)]
    assert sorted(runner.execute(
        "select x from t where x <= any (select y from s_clean)").rows) == [
        (1,), (2,), (3,)]
    assert runner.execute(
        "select x from t where x = all (select y from s_clean)").rows == []
    assert runner.execute(
        "select x from t where x = all (select y from s_clean "
        "where y = 2)").rows == [(2,)]


def test_neq_any(runner):
    # x <> ANY {2, 3}: TRUE unless the set is all-equal to x
    assert sorted(runner.execute(
        "select x from t where x <> any (select y from s_clean)").rows) == [
        (1,), (2,), (3,), (4,)]
    assert sorted(runner.execute(
        "select x from t where x <> any (select y from s_clean "
        "where y = 2)").rows) == [(1,), (3,), (4,)]
    # over empty: FALSE (no element differs)
    assert runner.execute(
        "select x from t where x <> any (select y from s_empty)").rows == []
