import numpy as np
import pytest

from presto_tpu.connectors.tpch import (
    CURRENT_DATE,
    MAX_ORDER_DATE,
    MIN_ORDER_DATE,
    SCHEMAS,
    Tpch,
)


@pytest.fixture(scope="module")
def tpch():
    return Tpch(sf=0.01)


def test_row_counts(tpch):
    assert tpch.row_count("region") == 5
    assert tpch.row_count("nation") == 25
    assert tpch.row_count("customer") == 1500
    assert tpch.row_count("orders") == 15000
    assert tpch.row_count("part") == 2000
    # lineitem ~4x orders
    n = tpch.row_count("lineitem")
    assert 15000 * 1 <= n <= 15000 * 7
    assert abs(n / 15000 - 4.0) < 0.2


def test_determinism(tpch):
    a = tpch.generate_split("lineitem", 0)
    b = Tpch(sf=0.01).generate_split("lineitem", 0)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k])


def test_value_domains(tpch):
    li = tpch.generate_split("lineitem", 0)
    assert li["l_quantity"].min() >= 100 and li["l_quantity"].max() <= 5000
    assert li["l_discount"].min() >= 0 and li["l_discount"].max() <= 10
    assert li["l_tax"].min() >= 0 and li["l_tax"].max() <= 8
    assert (li["l_shipdate"] > MIN_ORDER_DATE).all()
    assert (li["l_receiptdate"] > li["l_shipdate"]).all()
    o = tpch.generate_split("orders", 0)
    assert o["o_orderdate"].min() >= MIN_ORDER_DATE
    assert o["o_orderdate"].max() <= MAX_ORDER_DATE
    # linestatus consistent with shipdate
    assert ((li["l_linestatus"] == 1) == (li["l_shipdate"] > CURRENT_DATE)).all()


def test_referential_integrity(tpch):
    li = tpch.generate_split("lineitem", 0)
    o = tpch.generate_split("orders", 0)
    assert set(np.unique(li["l_orderkey"])) == set(np.unique(o["o_orderkey"]))
    assert li["l_partkey"].max() <= tpch.n_parts
    assert li["l_suppkey"].max() <= tpch.n_suppliers
    assert o["o_custkey"].max() <= tpch.n_customers
    ps = tpch.generate_split("partsupp", 0)
    assert ps["ps_suppkey"].min() >= 1 and ps["ps_suppkey"].max() <= tpch.n_suppliers
    # each part has 4 distinct suppliers
    assert len(set(ps["ps_suppkey"][:4])) == 4


def test_totalprice_consistency(tpch):
    o = tpch.generate_split("orders", 0)
    li = tpch.generate_split("lineitem", 0)
    k = o["o_orderkey"][7]
    lines = li["l_orderkey"] == k
    charge = (
        li["l_extendedprice"][lines]
        * (100 + li["l_tax"][lines])
        * (100 - li["l_discount"][lines])
    ) // 10000
    assert charge.sum() == o["o_totalprice"][7]


def test_dictionaries(tpch):
    d = tpch.dictionary_for("lineitem", "l_shipmode")
    assert "AIR" in d.values and len(d) == 7
    names = tpch.dictionary_for("customer", "c_name")
    assert names.decode(np.array([0]))[0] == "Customer#000000001"
    ptype = tpch.dictionary_for("part", "p_type")
    assert len(ptype) == 150
    lut = ptype.lut(lambda s: s.startswith("PROMO"))
    assert lut.sum() == 25
    phone = tpch.dictionary_for("customer", "c_phone")
    v = phone.decode(np.array([5]))[0]
    assert len(v.split("-")) == 4 and 10 <= int(v.split("-")[0]) <= 34


def test_pages(tpch):
    page = tpch.page_for_split("nation", 0)
    rows = page.to_pylist()
    assert len(rows) == 25
    assert rows[6][1] == "FRANCE" and rows[6][2] == 3
    # lineitem page types decode
    lp = tpch.page_for_split("lineitem", 0)
    r0 = lp.to_pylist()[0]
    schema = [n for n, _ in SCHEMAS["lineitem"]]
    row = dict(zip(schema, r0))
    assert row["l_returnflag"] in ("A", "N", "R")
    from decimal import Decimal

    assert isinstance(row["l_quantity"], Decimal) and 1 <= row["l_quantity"] <= 50


def test_split_alignment():
    t = Tpch(sf=0.01, split_rows=4096)
    assert t.num_splits("orders") == 4  # 15000 / 4096
    total = 0
    seen = set()
    for i in range(t.num_splits("lineitem")):
        cols = t.generate_split("lineitem", i)
        total += len(cols["l_orderkey"])
        keys = set(np.unique(cols["l_orderkey"]))
        assert not (keys & seen)  # order-aligned: no key spans splits
        seen |= keys
    assert total == t.row_count("lineitem")
