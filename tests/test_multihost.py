"""Multi-host distributed execution tests: coordinator + N worker HTTP
servers in one process.

Reference analog: ``DistributedQueryRunner.java:69`` (one coordinator +
N TestingPrestoServers in one JVM on localhost ports, full protocol
end-to-end) including worker-failure behavior — with the improvement
that leaf fragments are rescheduled instead of failing the query."""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.parallel.multihost import MultiHostRunner
from presto_tpu.runner import QueryRunner
from presto_tpu.server.worker import WorkerServer

from tests.tpch_queries import QUERIES


def make_catalog():
    catalog = Catalog()
    catalog.register("tpch", Tpch(sf=0.005, split_rows=2048))
    return catalog


@pytest.fixture(scope="module")
def cluster():
    workers = [WorkerServer(make_catalog()) for _ in range(3)]
    for w in workers:
        w.start()
    catalog = make_catalog()
    local = QueryRunner(catalog)
    multi = MultiHostRunner(catalog, [w.uri for w in workers])
    yield local, multi, workers
    for w in workers:
        try:
            w.stop()
        except Exception:
            pass


def _key(row):
    return tuple(round(v, 6) if isinstance(v, float) else v for v in row)


def _check(local, multi, sql):
    expected = local.executor.run(local.plan(sql)).rows
    actual = multi.run(local.binder.plan(sql)).rows
    assert len(actual) == len(expected)
    for a, e in zip(sorted(actual, key=_key), sorted(expected, key=_key)):
        for va, ve in zip(a, e):
            if isinstance(va, float):
                assert va == pytest.approx(ve, rel=1e-12), (a, e)
            else:
                assert va == ve, (a, e)


def test_multihost_q1(cluster):
    local, multi, _ = cluster
    _check(local, multi, QUERIES[1])


def test_multihost_q6(cluster):
    local, multi, _ = cluster
    _check(local, multi, QUERIES[6])


def test_multihost_q3_joins(cluster):
    local, multi, _ = cluster
    _check(local, multi, QUERIES[3])


def test_two_stage_exchange_no_coordinator_merge(cluster):
    """Grouped aggregation with >=2 workers must run the worker-to-
    worker partitioned exchange: partial states flow stage-1 -> stage-2
    between workers and the coordinator only drains the root stage
    (ExchangeOperator.java:36 + PartitionedOutputBuffer.java analog).
    The coordinator-merge fallback must NOT be used."""
    local, multi, workers = cluster
    sql = ("SELECT o_orderpriority, count(*), sum(o_totalprice) "
           "FROM orders GROUP BY o_orderpriority")
    original = multi._run_agg_coordinator_merge

    def fail_loudly(*a, **kw):
        raise AssertionError("coordinator-merge fallback used; the "
                             "two-stage exchange should have handled this")

    multi._run_agg_coordinator_merge = fail_loudly
    try:
        _check(local, multi, sql)
    finally:
        multi._run_agg_coordinator_merge = original


def test_two_stage_capacity_retry(cluster):
    """A max_groups far below the true group count must be detected at
    the exchange boundary (producer-side truncation check) and retried
    with doubled capacity until exact — never silently truncated."""
    from presto_tpu.planner.plan import AggregationNode

    local, multi, _ = cluster
    sql = ("SELECT o_custkey, count(*) c FROM orders GROUP BY o_custkey")
    expected = local.executor.run(local.plan(sql)).rows
    assert len(expected) > 8
    plan = local.binder.plan(sql)

    def shrink(node):
        if isinstance(node, AggregationNode):
            node.max_groups = 4
        for s in node.sources:
            shrink(s)

    shrink(plan)
    actual = multi.run(plan).rows
    assert sorted(actual) == sorted(expected)


def test_worker_failure_reschedules(cluster):
    """Kill one worker: its splits must be re-run on survivors and the
    result stay exact (beyond-reference: the reference fails the query
    on task failure, SURVEY.md §2.2)."""
    local, multi, workers = cluster
    victim = workers[0]
    victim.stop()
    try:
        _check(local, multi, QUERIES[6])
        _check(local, multi, QUERIES[1])
    finally:
        pass  # victim stays down; other tests use ping-based liveness


def test_task_serde_roundtrip():
    """Fragment + page wire formats round-trip exactly."""
    import numpy as np

    from presto_tpu.server.serde import (
        deserialize_page, plan_from_json, plan_to_json, serialize_page,
    )

    catalog = make_catalog()
    runner = QueryRunner(catalog)
    plan = runner.plan("select l_orderkey, l_quantity from lineitem where l_quantity < 10")
    d = plan_to_json(plan)
    plan2 = plan_from_json(d, catalog)
    r1 = runner.executor.run(plan)
    r2 = runner.executor.run(plan2)
    assert sorted(r1.rows) == sorted(r2.rows)

    page = next(runner.executor._pages(plan))
    raw = serialize_page(page)
    back = deserialize_page(raw)
    assert int(np.asarray(back.num_rows())) == int(np.asarray(page.num_rows()))


def test_task_failure_is_not_worker_failure():
    """A deterministic query error raises TaskFailed without retries or
    marking the worker dead (ContinuousTaskStatusFetcher analog)."""
    import numpy as np
    import pytest

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.page import Page
    from presto_tpu.parallel.multihost import TaskFailed, WorkerClient
    from presto_tpu.planner.plan import TableScanNode
    from presto_tpu.server.serde import plan_to_json
    from presto_tpu.server.worker import WorkerServer
    from presto_tpu.types import BIGINT

    mem = MemoryConnector()
    mem.create_table(
        "t", [("x", BIGINT)],
        [Page.from_arrays([np.arange(3, dtype=np.int64)], [BIGINT])])
    cat = Catalog()
    cat.register("mem", mem)
    w = WorkerServer(cat)
    w.start()
    try:
        handle = cat.resolve("t")
        good = plan_to_json(TableScanNode(handle, [0]))
        bad = dict(good, table="missing_table")
        client = WorkerClient(w.uri, timeout=20.0)
        with pytest.raises(TaskFailed):
            client.run_fragment(bad)
        assert client.alive  # the worker is fine; the query was not
        # and the worker still serves good fragments afterwards
        assert client.run_fragment(good)
    finally:
        w.stop()


def test_multihost_chain_without_aggregation():
    """Non-aggregate plans fan leaf fragments over workers; the sorted
    tail runs at the coordinator over the gathered pages."""
    from presto_tpu.parallel.multihost import MultiHostRunner
    from presto_tpu.server.worker import WorkerServer

    catalog = make_catalog()
    workers = [WorkerServer(catalog) for _ in range(2)]
    for w in workers:
        w.start()
    try:
        from presto_tpu.runner import QueryRunner

        r = QueryRunner(catalog)
        mh = MultiHostRunner(catalog, [w.uri for w in workers])
        for sql in [
            "SELECT l_orderkey, l_quantity FROM lineitem "
            "WHERE l_quantity > 45 ORDER BY l_orderkey, l_quantity, "
            "l_extendedprice LIMIT 25",
            "SELECT o_orderkey, o_totalprice FROM orders "
            "WHERE o_orderpriority = '1-URGENT' ORDER BY o_orderkey LIMIT 10",
        ]:
            local = r.execute(sql).rows
            assert local
            got = mh._run_distributed(r.plan(sql)).rows
            assert got == local, sql
    finally:
        for w in workers:
            w.stop()


# ---------------------------------------------------------------------------
# cross-host (DCN) repartitioned join: both join sides hash-partition
# across the HTTP workers (VERDICT r3 next-round item 5)
# ---------------------------------------------------------------------------

def test_partitioned_join_q3_across_workers(cluster):
    """Q3 with broadcast_threshold=0: the orders build (and the
    lineitem probe) hash-partition across 3 workers; stage-2 workers
    pull their key partition of BOTH sides, join, and partially
    aggregate; the coordinator merges K partials."""
    local, _, workers = cluster
    catalog = make_catalog()
    multi = MultiHostRunner(catalog, [w.uri for w in workers],
                            broadcast_threshold=0)
    sql = QUERIES[3]
    # the shuffle-join path must actually engage
    plan = local.binder.plan(sql)
    from presto_tpu.planner.plan import AggregationNode

    node = plan
    while not isinstance(node, AggregationNode):
        node = node.source
    join = multi._partitionable_join(node.source)
    assert join is not None, "Q3's join must qualify for repartitioning"
    # the shuffle path must ANSWER the query, not silently fall back
    def boom(*a, **k):
        raise AssertionError("fell back off the partitioned-join path")
    multi._run_agg_two_stage = boom
    multi._run_agg_coordinator_merge = boom
    _check(local, multi, sql)


def test_partitioned_join_matches_broadcast_results(cluster):
    """The same join answered by the broadcast tier and the shuffle
    tier must agree (two independent distributed paths)."""
    local, _, workers = cluster
    catalog = make_catalog()
    part = MultiHostRunner(catalog, [w.uri for w in workers],
                           broadcast_threshold=0)
    bcast = MultiHostRunner(catalog, [w.uri for w in workers])  # default
    sql = ("SELECT o_orderpriority, count(*) AS c, sum(l_extendedprice) AS s "
           "FROM lineitem, orders WHERE l_orderkey = o_orderkey "
           "AND l_quantity < 30 GROUP BY o_orderpriority "
           "ORDER BY o_orderpriority")
    _check(local, part, sql)
    got_part = part.run(local.binder.plan(sql)).rows
    got_bcast = bcast.run(local.binder.plan(sql)).rows
    assert got_part == got_bcast


def test_partitioned_join_survives_capacity_retry(cluster):
    """Undersized group capacity on stage-2 workers triggers the
    GroupCapacityExceeded retry protocol across the shuffle."""
    local, _, workers = cluster
    catalog = make_catalog()
    multi = MultiHostRunner(catalog, [w.uri for w in workers],
                            broadcast_threshold=0)
    sql = ("SELECT o_custkey, count(*) AS c FROM orders, lineitem "
           "WHERE l_orderkey = o_orderkey GROUP BY o_custkey")
    _check(local, multi, sql)


# ---------------------------------------------------------------------------
# r5: generalized stage-DAG at the DCN tier (lower_stages over HTTP
# workers — the decomposition the mesh tier runs, parallel/dist.py)
# ---------------------------------------------------------------------------

def test_multilevel_agg_both_stages_distributed(cluster):
    """Agg over agg: the inner aggregation distributes over scan
    splits; the outer distributes over the RE-CHUNKED materialized
    inner output (serde "pre" fragments).  min_stage_rows=0 so the
    tiny test table still decomposes (the dryrun's setting)."""
    local, _multi, workers = cluster
    multi = MultiHostRunner(make_catalog(), [w.uri for w in workers])
    multi.min_stage_rows = 0
    sql = ("SELECT max(c) AS mx, min(ok) AS mn FROM "
           "(SELECT o_custkey AS ok, count(*) AS c FROM orders "
           "GROUP BY o_custkey)")
    _check(local, multi, sql)
    assert multi.last_stage_count >= 2


def test_union_of_chains_with_outer_agg(cluster):
    local, multi, _ = cluster
    sql = ("SELECT count(*) AS n, sum(k) AS s FROM ("
           "SELECT o_orderkey AS k FROM orders WHERE o_orderkey % 2 = 0 "
           "UNION ALL "
           "SELECT l_orderkey AS k FROM lineitem WHERE l_linenumber = 1)")
    _check(local, multi, sql)
    assert multi.last_stage_count >= 2


def test_tpcds_q7_multihost(cluster):
    """TPC-DS Q7 (star join + agg + TopN) end-to-end over 3 HTTP
    workers — the mesh tier's flagship stage-DAG shape, now at DCN."""
    _local, _multi, workers = cluster
    from presto_tpu.connectors.tpcds import Tpcds

    def ds_catalog():
        c = Catalog()
        c.register("tpcds", Tpcds(sf=0.01, split_rows=2048))
        return c

    ds_workers = [WorkerServer(ds_catalog()) for _ in range(3)]
    for w in ds_workers:
        w.start()
    try:
        local = QueryRunner(ds_catalog())
        multi = MultiHostRunner(ds_catalog(), [w.uri for w in ds_workers])
        from tests.tpcds_queries import QUERIES as DS

        expected = local.executor.run(local.plan(DS[7])).rows
        actual = multi.run(local.binder.plan(DS[7])).rows
        assert len(actual) == len(expected)
        for a, e in zip(actual, expected):  # ORDER BY: positional
            for va, ve in zip(a, e):
                if isinstance(va, float):
                    assert va == pytest.approx(ve, rel=1e-9), (a, e)
                else:
                    assert va == ve, (a, e)
        assert multi.last_stage_count >= 1
    finally:
        for w in ds_workers:
            try:
                w.stop()
            except Exception:
                pass


def test_topn_ships_per_shard_bound(cluster):
    """ORDER BY ... LIMIT n over a chain: each worker truncates to n
    before the gather, so the coordinator pulls O(workers x n) rows,
    not the full selectivity (per-shard bound at the DCN tier)."""
    local, multi, workers = cluster
    sql = ("SELECT l_orderkey, l_extendedprice FROM lineitem "
           "WHERE l_quantity > 10 "
           "ORDER BY l_extendedprice DESC, l_orderkey LIMIT 5")
    expected = local.executor.run(local.plan(sql)).rows
    actual = multi.run(local.binder.plan(sql)).rows
    assert actual == expected  # ORDER BY: positional comparison
    assert 0 < multi.last_gather_rows <= len(workers) * 5


def test_limit_ships_per_shard_bound(cluster):
    local, multi, workers = cluster
    sql = "SELECT l_orderkey FROM lineitem WHERE l_quantity > 10 LIMIT 7"
    actual = multi.run(local.binder.plan(sql)).rows
    assert len(actual) == 7
    assert 0 < multi.last_gather_rows <= len(workers) * 7


def test_fallback_counted_and_reason_recorded(cluster):
    """A MultiHostUnsupported local fallback must be LOUD: counted and
    reason-tagged (VERDICT weak #8 — the silent catch hid that queries
    never left the coordinator)."""
    local, multi, _ = cluster
    before = multi.fallback_count
    # evaluate_classifier_predictions is pinned local-only, so this
    # always exercises the fallback path regardless of planner growth
    plan = local.binder.plan(
        "SELECT count(*) FROM (SELECT n_nationkey FROM nation) t")
    from presto_tpu.parallel.multihost import MultiHostUnsupported

    orig = multi._run_distributed
    try:
        def raising(p, qstats=None):
            raise MultiHostUnsupported("forced for the fallback test")
        multi._run_distributed = raising
        res = multi.run(plan)
    finally:
        multi._run_distributed = orig
    assert res.rows == [(25,)]
    assert multi.fallback_count == before + 1
    assert "forced for the fallback test" in multi.last_fallback_reason


def test_distributed_run_clears_stale_fallback_reason(cluster):
    local, multi, _ = cluster
    multi.last_fallback_reason = "stale"
    _check(local, multi, "SELECT sum(l_quantity) FROM lineitem")
    assert multi.last_fallback_reason is None
