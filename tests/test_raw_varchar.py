"""Raw (non-dictionary) VARCHAR: fixed-width byte-matrix columns with
device comparisons/substr/concat and host-callback LIKE/regex —
unbounded cardinality text without dictionaries.

Reference analog: spi/block/VariableWidthBlock.java +
type/VarcharOperators.java byte comparisons."""

import random
import re

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.page import Page
from presto_tpu.runner import QueryRunner
from presto_tpu.types import BIGINT, VarcharType

W = 24
T = VarcharType(W, raw=True)

random.seed(3)
WORDS = ["alpha", "Bravo", "charlie", "delta-9", "Echo", "fox trot", ""]
STRINGS = ["%s %s%d" % (random.choice(WORDS), random.choice(WORDS), i % 97)
           for i in range(800)]  # high cardinality, duplicates across mod-97


@pytest.fixture(scope="module")
def runner():
    mem = MemoryConnector()
    page = Page.from_arrays(
        [np.arange(len(STRINGS), dtype=np.int64), STRINGS],
        [BIGINT, T],
    )
    mem.create_table("txt", [("id", BIGINT), ("s", T)], [page])
    page2 = Page.from_arrays(
        [[s for s in set(STRINGS)][:100],
         np.arange(100, dtype=np.int64)],
        [T, BIGINT],
    )
    mem.create_table("lookup", [("k", T), ("v", BIGINT)], [page2])
    catalog = Catalog()
    catalog.register("mem", mem)
    return QueryRunner(catalog)


def test_roundtrip(runner):
    rows = runner.execute("select id, s from txt order by id limit 10").rows
    for i, s in rows:
        assert s == STRINGS[i]


def test_eq_and_order_filters(runner):
    target = STRINGS[5]
    n = sum(1 for s in STRINGS if s == target)
    assert runner.execute(
        f"select count(*) from txt where s = '{target}'").rows == [(n,)]
    n_lt = sum(1 for s in STRINGS if s < "charlie")
    assert runner.execute(
        "select count(*) from txt where s < 'charlie'").rows == [(n_lt,)]
    n_in = sum(1 for s in STRINGS if s in (STRINGS[0], STRINGS[1]))
    assert runner.execute(
        f"select count(*) from txt where s in ('{STRINGS[0]}', '{STRINGS[1]}')"
    ).rows == [(n_in,)]


def test_col_col_compare(runner):
    n = sum(1 for s in STRINGS if s[:4] == s[:4])  # all
    got = runner.execute(
        "select count(*) from txt where substr(s, 1, 4) = substr(s, 1, 4)").rows
    assert got == [(n,)]


def test_like_and_regex_host_fallback(runner):
    n_like = sum(1 for s in STRINGS if s.startswith("alpha"))
    assert runner.execute(
        "select count(*) from txt where s like 'alpha%'").rows == [(n_like,)]
    rx = re.compile(r"[0-9][0-9]$")
    n_rx = sum(1 for s in STRINGS if rx.search(s))
    assert runner.execute(
        "select count(*) from txt where regexp_like(s, '[0-9][0-9]$')"
    ).rows == [(n_rx,)]
    n_sw = sum(1 for s in STRINGS if s.startswith("Echo"))
    assert runner.execute(
        "select count(*) from txt where starts_with(s, 'Echo')").rows == [(n_sw,)]


def test_length_substr_upper(runner):
    rows = runner.execute(
        "select id, length(s), substr(s, 2, 3), upper(s) from txt"
        " where id < 30 order by id").rows
    for i, ln, sub, up in rows:
        assert ln == len(STRINGS[i].encode())
        assert sub == STRINGS[i][1:4]
        assert up == STRINGS[i].upper()


def test_host_transform_callback(runner):
    rows = runner.execute(
        "select id, trim(s), replace(s, ' ', '_') from txt"
        " where id < 20 order by id").rows
    for i, tr, rep in rows:
        assert tr == STRINGS[i].strip()
        assert rep == STRINGS[i].replace(" ", "_")[:W]


def test_multi_column_concat(runner):
    rows = runner.execute(
        "select id, s || '#' || s from txt where id < 10 order by id").rows
    for i, c in rows:
        assert c == (STRINGS[i] + "#" + STRINGS[i])[: 2 * W + 1]


def test_group_by_raw(runner):
    got = dict(runner.execute("select s, count(*) from txt group by s").rows)
    want = {}
    for s in STRINGS:
        want[s] = want.get(s, 0) + 1
    assert got == want


def test_join_on_raw(runner):
    got = runner.execute(
        "select count(*) from txt, lookup where s = k").rows[0][0]
    keys = set([s for s in set(STRINGS)][:100])
    want = sum(1 for s in STRINGS if s in keys)
    assert got == want


def test_order_by_raw(runner):
    rows = runner.execute("select s from txt order by s, id").rows
    assert [r[0] for r in rows] == sorted(STRINGS)


def test_distinct_and_approx_distinct(runner):
    exact = len(set(STRINGS))
    assert runner.execute(
        "select count(distinct s) from txt").rows == [(exact,)]
    approx = runner.execute("select approx_distinct(s) from txt").rows[0][0]
    assert abs(approx - exact) <= max(0.05 * exact, 2)


def test_min_max_raw_supported(runner):
    rows = runner.execute("select min(s), max(s) from txt").rows
    assert rows == [(min(STRINGS), max(STRINGS))]
    # two-argument extremes over raw strings remain out of scope
    with pytest.raises(Exception, match="raw varchar"):
        runner.execute("select max_by(s, id) from txt")


def test_case_coalesce_with_raw(runner):
    rows = runner.execute(
        "select id, case when id < 5 then s else 'other' end,"
        " coalesce(nullif(s, 'alpha alpha0'), 'was-alpha') from txt"
        " where id < 10 order by id").rows
    for i, c, co in rows:
        assert c == (STRINGS[i] if i < 5 else "other")
        assert co == ("was-alpha" if STRINGS[i] == "alpha alpha0" else STRINGS[i])


def test_greatest_least_raw(runner):
    rows = runner.execute(
        "select id, greatest(s, 'charlie'), least(s, 'charlie') from txt"
        " where id < 30 order by id").rows
    for i, g, l in rows:
        assert g == max(STRINGS[i], "charlie")
        assert l == min(STRINGS[i], "charlie")


def test_serde_roundtrip_raw(runner):
    from presto_tpu.server.serde import deserialize_page, serialize_page

    conn = runner.catalog.connector("mem")
    page = conn.page_for_split("txt", 0)
    back = deserialize_page(serialize_page(page))
    assert back.blocks[1].type.is_raw_string
    assert back.to_pylist() == page.to_pylist()


def test_columnfile_roundtrip_raw(runner, tmp_path):
    from presto_tpu.storage.columnfile import FileConnector, write_table

    conn = runner.catalog.connector("mem")
    write_table(str(tmp_path), "txt", conn.schema("txt"),
                [conn.page_for_split("txt", 0)])
    fc = FileConnector(str(tmp_path))
    t = dict(fc.schema("txt"))["s"]
    assert t.is_raw_string and t.precision == W
    assert fc.page_for_split("txt", 0).to_pylist() == \
        conn.page_for_split("txt", 0).to_pylist()


def test_raw_varchar_min_max():
    """Lexicographic min/max via order-preserving int64 lane packing
    (PagesIndex VARCHAR comparator role)."""
    import numpy as np

    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.memory import MemoryConnector
    from presto_tpu.page import Page
    from presto_tpu.runner import QueryRunner
    from presto_tpu.types import BIGINT, VarcharType

    rt = VarcharType(12, raw=True)
    mem = MemoryConnector()
    mem.create_table(
        "mt", [("g", BIGINT), ("s", rt)],
        [Page.from_arrays(
            [np.array([1, 1, 2, 2, 1]),
             ["banana", "apple", "zebra", "aardvark", None]],
            [BIGINT, rt],
            valids=[None, np.array([True, True, True, True, False])]),
         Page.from_arrays([np.array([2, 1]), ["yak", "cherry"]], [BIGINT, rt])])
    cat = Catalog()
    cat.register("mem", mem)
    r = QueryRunner(cat)
    assert r.execute("SELECT g, min(s), max(s) FROM mt GROUP BY g ORDER BY g").rows == [
        (1, "apple", "cherry"), (2, "aardvark", "zebra")]
    assert r.execute("SELECT min(s), max(s) FROM mt").rows == [("aardvark", "zebra")]
    # all-NULL group -> NULL; '' sorts before any letter
    assert r.execute("SELECT min(s) FROM mt WHERE g = 3").rows == [(None,)]


def test_pack_lanes_roundtrip_and_order():
    import numpy as np

    from presto_tpu.ops.rawstring import encode_strings, pack_lanes, unpack_lanes

    vals = ["", "a", "ab", "b", "zzzzzzzzzzzzzzzzzzzzzzzz", "Z", "0"]
    data = encode_strings(vals, 24)
    lanes = np.asarray(pack_lanes(data))
    back = np.asarray(unpack_lanes(lanes, 24))
    assert (back == data).all()
    # lane tuple order == byte order
    order = sorted(range(len(vals)), key=lambda i: tuple(lanes[i]))
    assert [vals[i] for i in order] == sorted(vals)


# -- Unicode on the raw device path (r4): substr counts UTF-8 chars,
# -- case mapping covers ASCII + Latin-1 without corrupting sequences

UNI = ["héllo wörld", "ÀÉÎÕÜ mixed", "naïve café", "ascii only",
       "öß and þorn", "日本語テスト", ""]


@pytest.fixture(scope="module")
def uni_runner():
    mem = MemoryConnector()
    t = VarcharType(32, raw=True)
    page = Page.from_arrays(
        [np.arange(len(UNI), dtype=np.int64), UNI], [BIGINT, t])
    mem.create_table("uni", [("id", BIGINT), ("s", t)], [page])
    catalog = Catalog()
    catalog.register("mem", mem)
    return QueryRunner(catalog)


def test_substr_counts_characters(uni_runner):
    rows = uni_runner.execute(
        "select id, substr(s, 2, 4) from uni order by id").rows
    got = {i: s for i, s in rows}
    for i, s in enumerate(UNI):
        assert got[i] == s[1:5], (s, got[i])


def test_substr_no_length_suffix(uni_runner):
    rows = uni_runner.execute(
        "select id, substr(s, 3) from uni order by id").rows
    got = {i: s for i, s in rows}
    for i, s in enumerate(UNI):
        assert got[i] == s[2:], (s, got[i])


def test_upper_lower_latin1(uni_runner):
    rows = uni_runner.execute(
        "select id, upper(s), lower(s) from uni order by id").rows
    for i, up, lo in rows:
        s = UNI[i]
        # python casing restricted to chars whose upper/lower stays
        # one char in Latin-1 (ß→SS and ÿ→Ÿ are documented deviations)
        want_up = "".join(
            c.upper() if c.upper() != "SS" and ord(c) != 0xFF
            and len(c.upper()) == 1 and ord(c.upper()) < 0x100 else c
            for c in s)
        want_lo = "".join(
            c.lower() if len(c.lower()) == 1 and ord(c.lower()) < 0x100
            else c for c in s)
        assert up == want_up, (s, up, want_up)
        assert lo == want_lo, (s, lo, want_lo)
