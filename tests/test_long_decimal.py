"""Long DECIMAL (precision 19-36): two-limb base-10^18 arithmetic
end-to-end through the SQL surface, exactness-checked against python
ints/Decimal.

Reference analog: spi/type/Decimals.java + UnscaledDecimal128Arithmetic
and TestDecimalOperators (128-bit add/sub/compare/aggregate)."""

import random
from decimal import Decimal

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.page import Page
from presto_tpu.runner import QueryRunner
from presto_tpu.types import BIGINT, DecimalType

SCALE = 4
T = DecimalType(30, SCALE)  # long: 30 digits, scale 4

random.seed(11)
VALUES = [random.randint(-10**28, 10**28) for _ in range(500)] + [
    0, 1, -1, 10**18, -(10**18), 10**27,
]


@pytest.fixture(scope="module")
def runner():
    mem = MemoryConnector()
    page = Page.from_arrays(
        [np.arange(len(VALUES), dtype=np.int64), VALUES],
        [BIGINT, T],
    )
    mem.create_table("big", [("id", BIGINT), ("x", T)], [page])
    catalog = Catalog()
    catalog.register("mem", mem)
    return QueryRunner(catalog)


def as_exact(v: int) -> Decimal:
    """Exact expected value: results are decimal.Decimal now, so the
    headline exactness claims compare with == (no float tolerance).
    High-precision context: scaleb must not round 30+ digit values to
    the default 28-significant-digit context (r5: the engine itself
    became exact past 28 digits, exposing the helper's rounding)."""
    import decimal

    with decimal.localcontext() as ctx:
        ctx.prec = 50
        return Decimal(v).scaleb(-SCALE)


def test_roundtrip_and_filter(runner):
    rows = runner.execute("select count(*) from big").rows
    assert rows == [(len(VALUES),)]
    n_pos = sum(1 for v in VALUES if v > 0)
    assert runner.execute("select count(*) from big where x > 0").rows == [(n_pos,)]
    # compare against a long literal with full precision
    thresh = 10**27  # scaled; literal below has 23 int digits + 4 frac
    lit = "1" + "0" * 22 + ".0000"
    n_gt = sum(1 for v in VALUES if v > int(lit.replace(".", "")))
    assert runner.execute(
        f"select count(*) from big where x > {lit}").rows == [(n_gt,)]


def test_exact_sum(runner):
    """The headline: sums beyond int64/float53 stay exact."""
    got = runner.execute("select sum(x) from big").rows[0][0]
    exact = sum(VALUES)
    assert got == as_exact(exact)
    # the underlying value is exact: compare through the plan output page
    from presto_tpu.sql.binder import Binder

    plan = Binder(runner.catalog).plan("select sum(x) from big")
    page = runner.executor.run_to_page(plan)
    from presto_tpu.ops.decimal128 import decode_py

    limbs = np.asarray(page.blocks[0].data)[:1]
    assert decode_py(limbs)[0] == exact


def test_add_sub_mul_between_long_and_short(runner):
    rows = runner.execute(
        "select id, x + 1.5, x - x, x + x from big where id < 5 order by id").rows
    for (i, plus, zero, double) in rows:
        v = VALUES[i]
        assert zero == 0.0
        assert plus == as_exact(v + 15000)
        assert double == as_exact(2 * v)


def test_short_mul_overflow_via_cast(runner):
    """cast to a long decimal makes 18+18-digit products exact."""
    got = runner.execute(
        "select sum(cast(x as decimal(36, 4))) from big where id < 100").rows[0][0]
    exact = sum(VALUES[:100])
    assert got == as_exact(exact)


def test_min_max_avg(runner):
    got = runner.execute("select min(x), max(x), avg(x) from big").rows[0]
    assert got[0] == as_exact(min(VALUES))
    assert got[1] == as_exact(max(VALUES))
    # r4: avg(decimal) keeps the decimal scale, rounded HALF_UP
    # (reference DecimalAverageAggregation semantics)
    import decimal as _dec

    exact = (Decimal(sum(VALUES)) / len(VALUES)).quantize(
        Decimal(1), rounding=_dec.ROUND_HALF_UP).scaleb(-SCALE)
    assert got[2] == exact


def test_grouped_long_sum(runner):
    got = dict(runner.execute(
        "select mod(id, 7), sum(x) from big group by mod(id, 7)").rows)
    for k in range(7):
        exact = sum(v for i, v in enumerate(VALUES) if i % 7 == k)
        assert got[k] == as_exact(exact), k


def test_case_and_null_handling(runner):
    got = runner.execute(
        "select sum(case when x > 0 then x end) from big").rows[0][0]
    exact = sum(v for v in VALUES if v > 0)
    assert got == as_exact(exact)


def test_long_decimal_key_rejected(runner):
    with pytest.raises(Exception, match="long-decimal"):
        runner.execute("select x, count(*) from big group by x")


def test_long_decimal_order_by(runner):
    # limb matrices sort via per-limb stable radix passes (ops/sort):
    # the canonical limb form is value order, so multi-limb ORDER BY is
    # exact in both directions
    got = [r[1] for r in runner.execute(
        "select id, x from big order by x, id limit 40").rows]
    assert got == [as_exact(v) for v in sorted(VALUES)[:40]]
    got = [r[1] for r in runner.execute(
        "select id, x from big order by x desc, id limit 40").rows]
    assert got == [as_exact(v) for v in sorted(VALUES, reverse=True)[:40]]


def test_cast_down_to_short(runner):
    # only values that fit p=18 post-cast (narrowing overflow wraps,
    # like short-decimal arithmetic overflow)
    rows = runner.execute(
        "select id, cast(x as decimal(18, 2)) from big"
        " where x between -999999999999.0 and 999999999999.0 order by id").rows
    assert rows  # the fixed sentinel values 0/1/-1 qualify
    for i, v in rows:
        assert v == Decimal(VALUES[i] // 100).scaleb(-2)


def test_review_edge_semantics(runner):
    """neg canonical form, abs/sign, greatest/least, double compare,
    exact bigint cast, long x short products, coalesce supertype."""
    # unary minus keeps compare order (canonical limbs)
    n = runner.execute(
        "select count(*) from big where -x < x").rows[0][0]
    assert n == sum(1 for v in VALUES if -v < v)
    # abs / sign
    rows = runner.execute(
        "select id, abs(x), sign(x) from big where id < 20 order by id").rows
    for i, av, sv in rows:
        assert av == as_exact(abs(VALUES[i]))
        assert sv == (VALUES[i] > 0) - (VALUES[i] < 0)
    # greatest/least across long values
    rows = runner.execute(
        "select id, greatest(x, 0.0000), least(x, 0.0000) from big"
        " where id < 20 order by id").rows
    for i, g, l in rows:
        assert g == as_exact(max(VALUES[i], 0))
        assert l == as_exact(min(VALUES[i], 0))
    # compare vs double goes through double space (fractions kept)
    n = runner.execute(
        "select count(*) from big where x < 0.5e0").rows[0][0]
    assert n == sum(1 for v in VALUES if float(as_exact(v)) < 0.5)
    # exact bigint narrowing (above 2^53)
    got = runner.execute(
        "select cast(cast(123456789012345678.0000 as decimal(36, 4)) as bigint)"
    ).rows[0][0]
    assert got == 123456789012345678
    # long x short product exact at full width
    got = runner.execute(
        "select sum(x * 3) from big").rows[0][0]
    assert got == as_exact(3 * sum(VALUES))
    # coalesce keeps the long representation
    got = runner.execute(
        "select sum(coalesce(x, 0.0000)) from big").rows[0][0]
    assert got == as_exact(sum(VALUES))
    # round() on long decimals fails loudly instead of silently wrong
    with pytest.raises(Exception, match="long decimal"):
        runner.execute("select round(x) from big")


def test_serde_roundtrip(runner):
    from presto_tpu.server.serde import deserialize_page, serialize_page

    conn = runner.catalog.connector("mem")
    page = conn.page_for_split("big", 0)
    back = deserialize_page(serialize_page(page))
    a = page.to_pylist(decode_strings=False)
    b = back.to_pylist(decode_strings=False)
    assert a == b
