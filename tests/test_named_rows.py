"""Named ROW fields + field access.

Reference analogs: spi/type/RowType.java (named RowFields),
sql/tree/DereferenceExpression.java (row-field dereference), CAST to
ROW(name type, ...).  Device layout: rows are dense (capacity, nfields)
matrices; a naming-only cast is a retype, a converting cast rebuilds
the matrix from converted field slices.
"""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import QueryRunner


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("mem", MemoryConnector(), writable=True)
    r = QueryRunner(catalog)
    r.execute("create table pts as select "
              "cast(row(x, y) as row(x bigint, y bigint)) as p from "
              "(values (1, 10), (2, 20), (3, 30)) t(x, y)")
    return r


def test_cast_and_field_access(runner):
    assert runner.execute(
        "select cast(row(1, 2) as row(x bigint, y bigint)).x").rows == [(1,)]
    assert runner.execute(
        "select cast(row(1, 2.5) as row(a bigint, b double)).b + 1"
    ).rows == [(3.5,)]


def test_field_access_on_column(runner):
    assert sorted(runner.execute("select p.y from pts").rows) == [
        (10,), (20,), (30,)]
    assert sorted(runner.execute(
        "select p.x + p.y from pts where p.x >= 2").rows) == [(22,), (33,)]


def test_table_qualified_field_access(runner):
    assert runner.execute(
        "select t.p.y from pts t where t.p.x = 3").rows == [(30,)]


def test_row_in_group_by_expression(runner):
    rows = sorted(runner.execute(
        "select p.x % 2 as odd, sum(p.y) from pts group by 1").rows)
    assert rows == [(0, 20), (1, 40)]


def test_unknown_field_errors(runner):
    with pytest.raises(Exception, match="field"):
        runner.execute("select p.z from pts")


def test_unnamed_row_field_access_errors(runner):
    with pytest.raises(Exception, match="named"):
        runner.execute("select r.q from (select row(1, 2) as r) t")


def test_row_cast_arity_mismatch(runner):
    with pytest.raises(Exception, match="arity"):
        runner.execute("select cast(row(1, 2) as row(x bigint))")
