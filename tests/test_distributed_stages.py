"""Distributed window / sort / union stages on the 8-device mesh and
the multi-host HTTP tier, plus the EXPLAIN (TYPE DISTRIBUTED) plan
shapes for their exchanges.

Reference analogs: AddExchanges partitioning WindowNode on its
PARTITION BY (FIXED_HASH window fragments), MergeOperator.java:45
(distributed sort = per-stage sort + consumer merge), and concurrent
UNION source fragments draining one exchange."""

import numpy as np
import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.parallel.dist import DistributedRunner, make_mesh
from presto_tpu.runner import QueryRunner

WINDOW_SQL = ("SELECT o_custkey, o_totalprice, "
              "sum(o_totalprice) OVER (PARTITION BY o_custkey) "
              "FROM orders")
WINDOW_ORDERED_SQL = (
    "SELECT o_custkey, o_orderkey, "
    "rank() OVER (PARTITION BY o_custkey ORDER BY o_totalprice DESC), "
    "sum(o_totalprice) OVER (PARTITION BY o_custkey ORDER BY o_orderkey) "
    "FROM orders")
ORDER_BY_SQL = ("SELECT l_orderkey, l_extendedprice, l_shipdate "
                "FROM lineitem "
                "ORDER BY l_extendedprice DESC, l_orderkey, l_linenumber")
UNION_SQL = ("SELECT o_orderkey FROM orders "
             "UNION ALL SELECT o_orderkey FROM orders "
             "UNION ALL SELECT l_orderkey FROM lineitem")
UNION_MIXED_SQL = ("SELECT l_returnflag x FROM lineitem "
                   "UNION ALL SELECT o_orderstatus FROM orders")


@pytest.fixture(scope="module")
def env():
    tpch = Tpch(sf=0.01, split_rows=4096)
    catalog = Catalog()
    catalog.register("tpch", tpch)
    local = QueryRunner(catalog)
    dist = DistributedRunner(catalog, make_mesh(8))
    # exercise multi-stage streaming on every input size (the CI leg's
    # distributed_min_stage_rows=0 contract)
    dist.min_stage_rows = 0
    return local, dist


def _key(row):
    return tuple(round(v, 6) if isinstance(v, float) else v for v in row)


def _check(local, dist, sql, ordered=False, min_stages=1):
    expected = local.executor.run(local.plan(sql)).rows
    out = dist.run(local.plan(sql))
    assert out.dist_fallback is None, out.dist_fallback
    assert out.dist_stages >= min_stages
    actual = out.rows
    assert len(actual) == len(expected)
    pairs = (zip(actual, expected) if ordered else
             zip(sorted(actual, key=_key), sorted(expected, key=_key)))
    for a, e in pairs:
        for va, ve in zip(a, e):
            if isinstance(va, float):
                assert va == pytest.approx(ve, rel=1e-9), f"{a} != {e}"
            else:
                assert va == ve, f"{a} != {e}"


# ---------------------------------------------------------------------------
# mesh tier (parallel/dist.py)
# ---------------------------------------------------------------------------

def test_mesh_window_partition_agg(env):
    local, dist = env
    _check(local, dist, WINDOW_SQL, min_stages=1)


def test_mesh_window_with_order(env):
    local, dist = env
    _check(local, dist, WINDOW_ORDERED_SQL, min_stages=1)


def test_mesh_large_order_by_exact_order(env):
    local, dist = env
    _check(local, dist, ORDER_BY_SQL, ordered=True, min_stages=1)


def test_mesh_union_three_legs(env):
    local, dist = env
    _check(local, dist, UNION_SQL, min_stages=3)


def test_mesh_union_merged_dictionaries(env):
    """Legs with different varchar dictionaries ride per-leg code
    offsets through the exchange."""
    local, dist = env
    _check(local, dist, UNION_MIXED_SQL, min_stages=2)


def test_mesh_window_then_order_by(env):
    """A window stage feeding a sort stage: two streamed breaker
    stages in one plan."""
    local, dist = env
    sql = ("SELECT o_custkey, r FROM ("
           "SELECT o_custkey, sum(o_totalprice) "
           "OVER (PARTITION BY o_custkey) r FROM orders) "
           "ORDER BY r DESC, o_custkey")
    _check(local, dist, sql, ordered=True, min_stages=2)


def test_mesh_streaming_toggle_same_result(env):
    local, dist = env
    expected = local.executor.run(local.plan(ORDER_BY_SQL)).rows
    try:
        dist.exchange_streaming = False
        out = dist.run(local.plan(ORDER_BY_SQL))
    finally:
        dist.exchange_streaming = True
    assert out.rows == expected


def test_sort_stays_glue_over_small_intermediates(env):
    """ORDER BY over a below-threshold materialized intermediate keeps
    the coordinator-glue path (min_stage_rows gate)."""
    local, _ = env
    from presto_tpu.parallel.fragment import explain_distributed

    sql = ("SELECT l_returnflag, sum(l_quantity) q FROM lineitem "
           "GROUP BY l_returnflag ORDER BY q")
    text = explain_distributed(local.plan(sql))  # default min_stage_rows
    # the aggregation distributes; the tiny sort is a SINGLE coordinator
    # fragment (glue), not a distributed merge stage
    assert "root=AggregationNode" in text
    assert "via merge[" not in text
    assert "[SINGLE] => output [SINGLE] via gather root=SortNode" in text


# ---------------------------------------------------------------------------
# EXPLAIN (TYPE DISTRIBUTED) plan shapes
# ---------------------------------------------------------------------------

def test_explain_window_shows_hash_exchange(env):
    local, _ = env
    from presto_tpu.parallel.fragment import explain_distributed

    text = explain_distributed(local.plan(WINDOW_SQL), min_stage_rows=0)
    assert text.startswith("FRAGMENTED: yes")
    assert "root=WindowNode" in text
    assert "via hash[o_custkey]" in text  # partition keys on the edge


def test_explain_order_by_shows_merge_exchange(env):
    local, _ = env
    from presto_tpu.parallel.fragment import explain_distributed

    text = explain_distributed(local.plan(ORDER_BY_SQL), min_stage_rows=0)
    assert text.startswith("FRAGMENTED: yes")
    assert "root=SortNode" in text
    assert "via merge[" in text  # sorted-run merge edge


def test_explain_union_shows_concurrent_legs(env):
    local, _ = env
    from presto_tpu.parallel.fragment import explain_distributed

    text = explain_distributed(local.plan(UNION_SQL), min_stage_rows=0)
    assert text.startswith("FRAGMENTED: yes (3 mesh stages)")
    assert "via union" in text
    assert text.count("via gather root=ProjectNode") == 3  # one per leg


def test_explain_agrees_with_execution(env):
    """The simulated decomposition and the executed one count the same
    stages for every breaker shape."""
    local, dist = env
    from presto_tpu.parallel.fragment import fragment_plan

    for sql in (WINDOW_SQL, ORDER_BY_SQL, UNION_SQL):
        frags = fragment_plan(local.plan(sql), min_stage_rows=0)
        out = dist.run(local.plan(sql))
        assert frags.mesh_stages == out.dist_stages, sql


# ---------------------------------------------------------------------------
# multi-host tier (parallel/multihost.py over HTTP workers)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def dqr():
    from presto_tpu.testing import DistributedQueryRunner

    rig = DistributedQueryRunner(n_workers=2, sf=0.01, split_rows=4096)
    rig.multihost.min_stage_rows = 0
    yield rig
    rig.close()


def _check_mh(dqr, sql, ordered=False, min_stages=1):
    local = dqr.runner
    expected = local.executor.run(local.plan(sql)).rows
    out = dqr.multihost.run(local.plan(sql))
    assert out.dist_fallback is None, out.dist_fallback
    assert out.dist_stages >= min_stages
    actual = out.rows
    assert len(actual) == len(expected)
    pairs = (zip(actual, expected) if ordered else
             zip(sorted(actual, key=_key), sorted(expected, key=_key)))
    for a, e in pairs:
        for va, ve in zip(a, e):
            if isinstance(va, float):
                assert va == pytest.approx(ve, rel=1e-9), f"{a} != {e}"
            else:
                assert va == ve, f"{a} != {e}"


def test_multihost_window_two_stage_shuffle(dqr):
    _check_mh(dqr, WINDOW_SQL)


def test_multihost_window_with_order(dqr):
    _check_mh(dqr, WINDOW_ORDERED_SQL)


def test_multihost_order_by_merge(dqr):
    _check_mh(dqr, ORDER_BY_SQL, ordered=True)


def test_multihost_union_concurrent_legs(dqr):
    _check_mh(dqr, UNION_SQL, min_stages=3)


def test_multihost_union_merged_dictionaries(dqr):
    _check_mh(dqr, UNION_MIXED_SQL, min_stages=2)


def test_multihost_window_degrades_with_one_worker(dqr):
    """With a single live worker the two-stage shuffle is pointless:
    the stage degrades to a distributed source gather + coordinator
    window, still oracle-correct."""
    from presto_tpu.parallel.multihost import MultiHostRunner

    local = dqr.runner
    mh1 = MultiHostRunner(dqr.catalog, [dqr.workers[0].uri])
    mh1.min_stage_rows = 0
    expected = local.executor.run(local.plan(WINDOW_SQL)).rows
    out = mh1.run(local.plan(WINDOW_SQL))
    assert out.dist_fallback is None
    assert sorted(out.rows, key=_key) == sorted(expected, key=_key)
