"""Iterative rule-based optimizer + pattern matching.

Reference analogs: presto-matching (Pattern/Match) and
sql/planner/iterative/IterativeOptimizer.java with its rule set.
"""

import numpy as np

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.expr.ir import Call, ColumnRef, Literal
from presto_tpu.matching import Capture, Pattern
from presto_tpu.page import Page
from presto_tpu.planner.iterative import (
    DEFAULT_RULES, EvaluateConstantFilter, IterativeOptimizer, MergeLimits,
)
from presto_tpu.planner.plan import (
    FilterNode, LimitNode, OutputNode, ProjectNode, TableScanNode, ValuesNode,
)
from presto_tpu.runner import QueryRunner
from presto_tpu.types import BIGINT, BOOLEAN, DOUBLE


def make_runner():
    mem = MemoryConnector()
    mem.create_table(
        "t", [("a", BIGINT), ("b", DOUBLE)],
        [Page.from_arrays([np.arange(10), np.arange(10) * 1.5],
                          [BIGINT, DOUBLE])])
    cat = Catalog()
    cat.register("mem", mem)
    return QueryRunner(cat)


def _walk(node):
    yield node
    for s in node.sources:
        yield from _walk(s)


# ---------------------------------------------------------------------------
# pattern matching
# ---------------------------------------------------------------------------

def test_pattern_type_and_predicate():
    n = LimitNode(ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)]), 5)
    assert Pattern.type_of(LimitNode).match(n)
    assert Pattern.type_of(FilterNode).match(n) is None
    assert Pattern.type_of(LimitNode).where(lambda x: x.count > 3).match(n)
    assert Pattern.type_of(LimitNode).where(lambda x: x.count > 9).match(n) is None


def test_pattern_sources_and_capture():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)])
    n = LimitNode(LimitNode(src, 3), 5)
    cap = Capture("inner")
    m = Pattern.type_of(LimitNode).with_sources(
        Pattern.type_of(LimitNode).captured_as(cap)).match(n)
    assert m is not None and m.get(cap) is n.source


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def test_merge_limits_rule():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(i,) for i in range(10)])
    n = LimitNode(LimitNode(src, 3), 7)
    out = IterativeOptimizer([MergeLimits()]).optimize(n)
    assert isinstance(out, LimitNode) and out.count == 3
    assert not isinstance(out.source, LimitNode)


def test_constant_false_filter_becomes_empty_values():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)])
    n = FilterNode(src, Literal(type=BOOLEAN, value=False))
    out = IterativeOptimizer([EvaluateConstantFilter()]).optimize(n)
    assert isinstance(out, ValuesNode) and out.rows == []


def test_constant_true_filter_removed():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)])
    n = FilterNode(src, Literal(type=BOOLEAN, value=True))
    out = IterativeOptimizer([EvaluateConstantFilter()]).optimize(n)
    assert out is src


# ---------------------------------------------------------------------------
# end-to-end through the engine
# ---------------------------------------------------------------------------

def test_nested_projections_collapse():
    r = make_runner()
    plan = r.plan("SELECT y + 1 FROM (SELECT a + 1 AS y FROM (SELECT a FROM t))")
    projects = [n for n in _walk(plan) if isinstance(n, ProjectNode)]
    # nested single-use projections inline into few nodes
    assert len(projects) <= 2
    assert r.execute(
        "SELECT y + 1 FROM (SELECT a + 1 AS y FROM (SELECT a FROM t)) "
        "ORDER BY 1 LIMIT 2").rows == [(2,), (3,)]


def test_filter_pushes_through_project():
    r = make_runner()
    plan = r.plan("SELECT y FROM (SELECT a + 1 AS y FROM t) WHERE y > 5")
    # after pushdown, no FilterNode sits directly on a ProjectNode
    for n in _walk(plan):
        if isinstance(n, FilterNode):
            assert not isinstance(n.source, ProjectNode)
    assert r.execute("SELECT y FROM (SELECT a + 1 AS y FROM t) WHERE y > 5 "
                     "ORDER BY y").rows == [(6,), (7,), (8,), (9,), (10,)]


def test_default_rules_preserve_correctness():
    r = make_runner()
    rows = r.execute(
        "SELECT a, b FROM (SELECT a, b FROM t WHERE a >= 2) "
        "WHERE a < 5 ORDER BY a LIMIT 10").rows
    assert rows == [(2, 3.0), (3, 4.5), (4, 6.0)]
