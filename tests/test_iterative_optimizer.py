"""Iterative rule-based optimizer + pattern matching.

Reference analogs: presto-matching (Pattern/Match) and
sql/planner/iterative/IterativeOptimizer.java with its rule set.
"""

import numpy as np

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.expr.ir import Call, ColumnRef, Literal
from presto_tpu.matching import Capture, Pattern
from presto_tpu.page import Page
from presto_tpu.planner.iterative import (
    DEFAULT_RULES, EvaluateConstantFilter, IterativeOptimizer, MergeLimits,
)
from presto_tpu.planner.plan import (
    FilterNode, LimitNode, OutputNode, ProjectNode, TableScanNode, ValuesNode,
)
from presto_tpu.runner import QueryRunner
from presto_tpu.types import BIGINT, BOOLEAN, DOUBLE


def make_runner():
    mem = MemoryConnector()
    mem.create_table(
        "t", [("a", BIGINT), ("b", DOUBLE)],
        [Page.from_arrays([np.arange(10), np.arange(10) * 1.5],
                          [BIGINT, DOUBLE])])
    cat = Catalog()
    cat.register("mem", mem)
    return QueryRunner(cat)


def _walk(node):
    yield node
    for s in node.sources:
        yield from _walk(s)


# ---------------------------------------------------------------------------
# pattern matching
# ---------------------------------------------------------------------------

def test_pattern_type_and_predicate():
    n = LimitNode(ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)]), 5)
    assert Pattern.type_of(LimitNode).match(n)
    assert Pattern.type_of(FilterNode).match(n) is None
    assert Pattern.type_of(LimitNode).where(lambda x: x.count > 3).match(n)
    assert Pattern.type_of(LimitNode).where(lambda x: x.count > 9).match(n) is None


def test_pattern_sources_and_capture():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)])
    n = LimitNode(LimitNode(src, 3), 5)
    cap = Capture("inner")
    m = Pattern.type_of(LimitNode).with_sources(
        Pattern.type_of(LimitNode).captured_as(cap)).match(n)
    assert m is not None and m.get(cap) is n.source


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------

def test_merge_limits_rule():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(i,) for i in range(10)])
    n = LimitNode(LimitNode(src, 3), 7)
    out = IterativeOptimizer([MergeLimits()]).optimize(n)
    assert isinstance(out, LimitNode) and out.count == 3
    assert not isinstance(out.source, LimitNode)


def test_constant_false_filter_becomes_empty_values():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)])
    n = FilterNode(src, Literal(type=BOOLEAN, value=False))
    out = IterativeOptimizer([EvaluateConstantFilter()]).optimize(n)
    assert isinstance(out, ValuesNode) and out.rows == []


def test_constant_true_filter_removed():
    src = ValuesNode(names=["x"], types=[BIGINT], rows=[(1,)])
    n = FilterNode(src, Literal(type=BOOLEAN, value=True))
    out = IterativeOptimizer([EvaluateConstantFilter()]).optimize(n)
    assert out is src


# ---------------------------------------------------------------------------
# end-to-end through the engine
# ---------------------------------------------------------------------------

def test_nested_projections_collapse():
    r = make_runner()
    plan = r.plan("SELECT y + 1 FROM (SELECT a + 1 AS y FROM (SELECT a FROM t))")
    projects = [n for n in _walk(plan) if isinstance(n, ProjectNode)]
    # nested single-use projections inline into few nodes
    assert len(projects) <= 2
    assert r.execute(
        "SELECT y + 1 FROM (SELECT a + 1 AS y FROM (SELECT a FROM t)) "
        "ORDER BY 1 LIMIT 2").rows == [(2,), (3,)]


def test_filter_pushes_through_project():
    r = make_runner()
    plan = r.plan("SELECT y FROM (SELECT a + 1 AS y FROM t) WHERE y > 5")
    # after pushdown, no FilterNode sits directly on a ProjectNode
    for n in _walk(plan):
        if isinstance(n, FilterNode):
            assert not isinstance(n.source, ProjectNode)
    assert r.execute("SELECT y FROM (SELECT a + 1 AS y FROM t) WHERE y > 5 "
                     "ORDER BY y").rows == [(6,), (7,), (8,), (9,), (10,)]


def test_default_rules_preserve_correctness():
    r = make_runner()
    rows = r.execute(
        "SELECT a, b FROM (SELECT a, b FROM t WHERE a >= 2) "
        "WHERE a < 5 ORDER BY a LIMIT 10").rows
    assert rows == [(2, 3.0), (3, 4.5), (4, 6.0)]


# ---------------------------------------------------------------------------
# round-4 rules (RuleTester-style plan-shape assertions +
# end-to-end result checks)
# ---------------------------------------------------------------------------

import pytest
from presto_tpu.connectors.tpch import Tpch


@pytest.fixture(scope="module")
def runner():
    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001, split_rows=256))
    return QueryRunner(cat)


def _find(plan, kind):
    out = []

    def walk(n):
        if isinstance(n, kind):
            out.append(n)
        for s in n.sources:
            walk(s)

    walk(plan)
    return out


def test_push_limit_into_table_scan(runner):
    from presto_tpu.planner.plan import LimitNode, TableScanNode

    plan = runner.plan("SELECT o_orderkey + 1 AS k FROM orders LIMIT 7")
    scans = _find(plan, TableScanNode)
    assert scans and scans[0].limit == 7  # pushed into the scan
    assert _find(plan, LimitNode)  # the exact cut stays above
    assert len(runner.execute(
        "SELECT o_orderkey + 1 AS k FROM orders LIMIT 7").rows) == 7


def test_limit_not_pushed_through_filter(runner):
    from presto_tpu.planner.plan import TableScanNode

    plan = runner.plan(
        "SELECT o_orderkey FROM orders WHERE o_custkey = 5 LIMIT 3")
    scans = _find(plan, TableScanNode)
    assert scans and scans[0].limit is None  # filters change row counts


def test_remove_redundant_distinct_over_aggregation(runner):
    from presto_tpu.planner.plan import AggregationNode

    sql = ("SELECT DISTINCT o_custkey, c FROM "
           "(SELECT o_custkey, count(*) AS c FROM orders GROUP BY o_custkey)")
    plan = runner.plan(sql)
    aggs = [a for a in _find(plan, AggregationNode) if a.aggs]
    distincts = [a for a in _find(plan, AggregationNode) if not a.aggs]
    assert len(aggs) == 1 and not distincts  # the DISTINCT was elided
    got = sorted(runner.execute(sql).rows)
    want = sorted(runner.execute(
        "SELECT o_custkey, count(*) AS c FROM orders "
        "GROUP BY o_custkey").rows)
    assert got == want


def test_distinct_kept_when_not_provably_unique(runner):
    from presto_tpu.planner.plan import AggregationNode

    sql = "SELECT DISTINCT o_orderpriority FROM orders"
    plan = runner.plan(sql)
    distincts = [a for a in _find(plan, AggregationNode) if not a.aggs]
    assert distincts  # priorities repeat: the distinct must survive
    assert len(runner.execute(sql).rows) == 5


def test_distinct_removed_on_primary_key_scan(runner):
    from presto_tpu.planner.plan import AggregationNode

    sql = "SELECT DISTINCT o_orderkey, o_custkey FROM orders"
    plan = runner.plan(sql)
    distincts = [a for a in _find(plan, AggregationNode) if not a.aggs]
    assert not distincts  # o_orderkey is the primary key
    want = runner.execute("SELECT count(*) FROM orders").rows[0][0]
    assert len(runner.execute(sql).rows) == want


def test_quantified_comparisons_match_explicit_forms(runner):
    got = runner.execute(
        "SELECT count(*) FROM orders WHERE o_totalprice > ALL "
        "(SELECT o_totalprice FROM orders WHERE o_custkey = 5)").rows
    want = runner.execute(
        "SELECT count(*) FROM orders WHERE o_totalprice > "
        "(SELECT max(o_totalprice) FROM orders WHERE o_custkey = 5)").rows
    assert got == want
    got_any = runner.execute(
        "SELECT count(*) FROM orders WHERE o_custkey = ANY "
        "(SELECT c_custkey FROM customer WHERE c_acctbal > 9000.0)").rows
    want_any = runner.execute(
        "SELECT count(*) FROM orders WHERE o_custkey IN "
        "(SELECT c_custkey FROM customer WHERE c_acctbal > 9000.0)").rows
    assert got_any == want_any


def test_correlated_in_matches_exists(runner):
    got = runner.execute(
        "SELECT count(*) FROM orders o WHERE o_orderkey IN "
        "(SELECT l_orderkey FROM lineitem WHERE l_suppkey = o.o_custkey)").rows
    want = runner.execute(
        "SELECT count(*) FROM orders o WHERE EXISTS "
        "(SELECT 1 FROM lineitem WHERE l_orderkey = o.o_orderkey "
        " AND l_suppkey = o.o_custkey)").rows
    assert got == want
    got_not = runner.execute(
        "SELECT count(*) FROM orders o WHERE o_orderkey NOT IN "
        "(SELECT l_orderkey FROM lineitem WHERE l_suppkey = o.o_custkey)").rows
    total = runner.execute("SELECT count(*) FROM orders").rows
    assert got_not[0][0] == total[0][0] - got[0][0]


# ---------------------------------------------------------------------------
# round-4 rule batch
# ---------------------------------------------------------------------------

def test_zero_limit_collapses_to_empty_values(runner):
    plan = runner.plan("SELECT o_orderkey FROM orders LIMIT 0")
    assert not _find(plan, TableScanNode)  # scan never compiles
    vals = _find(plan, ValuesNode)
    assert vals and vals[0].rows == []
    assert runner.execute("SELECT o_orderkey FROM orders LIMIT 0").rows == []


def test_empty_propagates_through_join_and_agg(runner):
    from presto_tpu.planner.plan import AggregationNode, JoinNode

    sql = ("SELECT o_orderpriority, count(*) FROM orders, customer "
           "WHERE o_custkey = c_custkey AND 1 = 0 GROUP BY o_orderpriority")
    plan = runner.plan(sql)
    assert not _find(plan, JoinNode)
    assert not _find(plan, TableScanNode)
    assert runner.execute(sql).rows == []
    # global aggregation over empty still returns its single row
    assert runner.execute(
        "SELECT count(*) FROM orders WHERE 1 = 0").rows == [(0,)]


def test_simplify_boolean_identities(runner):
    # (pred AND true) OR false -> pred: one plain comparison survives
    sql = ("SELECT count(*) FROM orders "
           "WHERE (o_orderkey > 100 AND 1 = 1) OR 1 = 2")
    plan = runner.plan(sql)
    filters = _find(plan, FilterNode)
    preds = [f.predicate for f in filters]
    assert all("or" != getattr(p, "fn", None) for p in preds), preds
    want = runner.execute(
        "SELECT count(*) FROM orders WHERE o_orderkey > 100").rows
    assert runner.execute(sql).rows == want


def test_prune_order_by_in_aggregation(runner):
    from presto_tpu.planner.plan import SortNode

    sql = ("SELECT o_orderpriority, count(*) FROM "
           "(SELECT * FROM orders ORDER BY o_totalprice) "
           "GROUP BY o_orderpriority")
    plan = runner.plan(sql)
    assert not _find(plan, SortNode)
    # order-sensitive aggregate keeps the sort
    sql2 = ("SELECT max_by(o_orderkey, o_totalprice) FROM "
            "(SELECT * FROM orders ORDER BY o_totalprice)")
    assert _find(runner.plan(sql2), SortNode)


def test_topn_pushes_through_project(runner):
    from presto_tpu.planner.plan import ProjectNode, TopNNode

    sql = ("SELECT o_orderkey * 2 AS k2, o_totalprice FROM orders "
           "ORDER BY o_totalprice DESC LIMIT 5")
    plan = runner.plan(sql)
    found = _find(plan, TopNNode)
    assert found
    # the TopN bound applies below the doubling projection

    def above(node, kind):
        for s in node.sources:
            if isinstance(s, kind) or above(s, kind):
                return True
        return False

    projs = _find(plan, ProjectNode)
    assert any(above(p, TopNNode) for p in projs) or not projs
    rows = runner.execute(sql).rows
    assert len(rows) == 5
    assert rows == sorted(rows, key=lambda r: -r[1])


def test_filter_through_union(runner):
    from presto_tpu.planner.plan import UnionNode

    sql = ("SELECT count(*) FROM ("
           "SELECT o_orderkey AS k FROM orders "
           "UNION ALL SELECT l_orderkey AS k FROM lineitem) "
           "WHERE k < 100")
    plan = runner.plan(sql)
    unions = _find(plan, UnionNode)
    assert unions
    # every arm is filtered (or reduced below a filter)
    for arm in unions[0].inputs:
        kinds = {type(n).__name__ for n in _walk(arm)}
        assert "FilterNode" in kinds or "ValuesNode" in kinds, kinds
    lhs = runner.execute(sql).rows
    want = [(runner.execute(
        "SELECT count(*) FROM orders WHERE o_orderkey < 100").rows[0][0]
        + runner.execute(
        "SELECT count(*) FROM lineitem WHERE l_orderkey < 100").rows[0][0],)]
    assert lhs == want


def test_count_literal_becomes_count_star(runner):
    from presto_tpu.planner.plan import AggregationNode

    plan = runner.plan("SELECT count(1) FROM orders")
    aggs = _find(plan, AggregationNode)
    assert aggs and aggs[0].aggs[0].fn == "count_star"
    assert runner.execute("SELECT count(1) FROM orders").rows == \
        runner.execute("SELECT count(*) FROM orders").rows
    # count(NULL) is 0, not count(*)
    assert runner.execute("SELECT count(NULL) FROM orders").rows == [(0,)]


# ---------------------------------------------------------------------------
# round-4b rules
# ---------------------------------------------------------------------------

def test_merge_limit_with_topn(runner):
    from presto_tpu.planner.plan import TopNNode

    plan = runner.plan(
        "SELECT * FROM (SELECT n_name FROM nation ORDER BY n_name "
        "LIMIT 10) LIMIT 3")
    topns = _find(plan, TopNNode)
    assert topns and all(t.count == 3 for t in topns)
    assert not _find(plan, LimitNode)
    rows = runner.execute(
        "SELECT * FROM (SELECT n_name FROM nation ORDER BY n_name "
        "LIMIT 10) LIMIT 3").rows
    assert [r[0] for r in rows] == sorted(
        r for (r,) in runner.execute("SELECT n_name FROM nation").rows)[:3]


def test_push_topn_through_union(runner):
    from presto_tpu.planner.plan import TopNNode, UnionNode

    sql = ("SELECT n_nationkey FROM nation UNION ALL "
           "SELECT r_regionkey FROM region ORDER BY 1 DESC LIMIT 4")
    plan = runner.plan(sql)
    unions = _find(plan, UnionNode)
    assert unions
    for u in unions:
        for arm in u.inputs:
            # the planted per-arm TopN may sit below the arm projection
            arm_topns = _find(arm, TopNNode)
            assert arm_topns and all(t.count == 4 for t in arm_topns)
    keys = sorted([r[0] for r in runner.execute(
        "SELECT n_nationkey FROM nation").rows] + [r[0] for r in
        runner.execute("SELECT r_regionkey FROM region").rows],
        reverse=True)
    assert [r[0] for r in runner.execute(sql).rows] == keys[:4]


def test_push_limit_through_row_preserving(runner):
    from presto_tpu.planner.plan import CrossSingleNode, JoinNode

    def probe_has_limit(n):
        while not isinstance(n, LimitNode):
            if not n.sources:
                return False
            n = n.sources[0]
        return True

    # scalar-subquery cross product: one output row per probe row
    sql = ("SELECT n_name, (SELECT max(r_regionkey) FROM region) "
           "FROM nation LIMIT 5")
    plan = runner.plan(sql)
    crosses = _find(plan, CrossSingleNode)
    assert crosses and any(probe_has_limit(c.left) for c in crosses)
    assert len(runner.execute(sql).rows) == 5

    # left join with a unique (primary-key) build side
    sql2 = ("SELECT n_name, r_name FROM nation LEFT JOIN region "
            "ON n_regionkey = r_regionkey LIMIT 7")
    plan2 = runner.plan(sql2)
    joins = [j for j in _find(plan2, JoinNode)
             if j.kind == "left" and j.unique_build]
    assert joins and any(probe_has_limit(j.left) for j in joins)
    assert len(runner.execute(sql2).rows) == 7


def test_prune_count_aggregation_over_scalar(runner):
    from presto_tpu.planner.plan import AggregationNode

    sql = "SELECT count(*) FROM (SELECT max(n_nationkey) FROM nation)"
    plan = runner.plan(sql)
    assert not _find(plan, AggregationNode)
    assert runner.execute(sql).rows == [(1,)]


def test_gather_and_merge_windows(runner):
    from presto_tpu.planner.plan import WindowNode

    sql = ("SELECT n_name, "
           "rank() OVER (PARTITION BY n_regionkey ORDER BY n_name), "
           "row_number() OVER (PARTITION BY n_regionkey ORDER BY n_name) "
           "FROM nation")
    plan = runner.plan(sql)
    windows = _find(plan, WindowNode)
    assert len(windows) == 1 and len(windows[0].funcs) == 2
    rows = runner.execute(sql).rows
    assert len(rows) == 25
    for _, rk, rn in rows:
        assert rk <= rn


def test_windows_not_merged_when_specs_differ(runner):
    from presto_tpu.planner.plan import WindowNode

    plan = runner.plan(
        "SELECT n_name, "
        "rank() OVER (PARTITION BY n_regionkey ORDER BY n_name), "
        "rank() OVER (ORDER BY n_name) FROM nation")
    assert len(_find(plan, WindowNode)) == 2


def test_prune_union_columns(runner):
    from presto_tpu.planner.plan import UnionNode

    sql = ("SELECT k FROM (SELECT n_nationkey k, n_name, n_comment "
           "FROM nation UNION ALL SELECT r_regionkey, r_name, r_comment"
           " FROM region) WHERE k < 2")
    plan = runner.plan(sql)
    unions = _find(plan, UnionNode)
    # the column selection moved into the arms: every union emits only
    # the single surviving channel
    assert unions and all(len(u.channels) == 1 for u in unions)
    got = sorted(r[0] for r in runner.execute(sql).rows)
    assert got == [0, 0, 1, 1]


def test_sample_rules(runner):
    from presto_tpu.planner.plan import TableScanNode, ValuesNode

    plan0 = runner.plan("select n_name from nation tablesample bernoulli (0)")
    assert not _find(plan0, TableScanNode)
    assert runner.execute(
        "select count(*) from nation tablesample bernoulli (0)"
    ).rows == [(0,)]
    plan100 = runner.plan(
        "select n_name from nation tablesample bernoulli (100)")
    scans = _find(plan100, TableScanNode)
    assert scans and all(s.sample is None for s in scans)
    assert runner.execute(
        "select count(*) from nation tablesample bernoulli (100)"
    ).rows == [(25,)]


def test_remove_unreferenced_scalar_apply(runner):
    from presto_tpu.planner.plan import CrossSingleNode

    # the scalar subquery's value is never selected -> apply dropped
    plan = runner.plan(
        "select n_name from (select n_name, (select max(r_regionkey) "
        "from region) m from nation)")
    assert not _find(plan, CrossSingleNode)
    # still present when referenced
    plan2 = runner.plan(
        "select n_name, m from (select n_name, (select max(r_regionkey)"
        " from region) m from nation)")
    assert _find(plan2, CrossSingleNode)
