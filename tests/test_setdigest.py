"""KMV set digests: make_set_digest / merge_set_digest +
jaccard_index / intersection_cardinality / hash_counts / cardinality.

Reference analogs: type/setdigest/BuildSetDigestAggregation.java,
MergeSetDigestAggregation.java, SetDigestFunctions.java.  The TPU
re-design is a KMV (k-minimum-values) sketch — K smallest 64-bit hashes
with per-hash counts in the fixed-slot map layout — so construction and
union are one dedup-relane kernel and all estimators are vector math.
Below K distinct values everything here is EXACT, which the tests use.
"""

import pytest

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.memory import MemoryConnector
from presto_tpu.runner import QueryRunner


@pytest.fixture(scope="module")
def runner():
    catalog = Catalog()
    catalog.register("mem", MemoryConnector(), writable=True)
    r = QueryRunner(catalog)
    # a: 1..20 with duplicates of 1..5; b: 11..30
    r.execute("create table ta as select x % 20 + 1 as v from "
              "(values 0,1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17,18,19,"
              "20,21,22,23,24) t(x)")
    r.execute("create table tb as select x + 11 as v from "
              "(values 0,1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17,18,19) "
              "t(x)")
    r.execute("create table grp as select * from (values "
              "(1, 10), (1, 10), (1, 20), (2, 30), (2, 30), (2, 30)) "
              "t(g, v)")
    return r


def test_cardinality_exact_below_k(runner):
    assert runner.execute(
        "select cardinality(make_set_digest(v)) from ta").rows == [(20,)]
    assert runner.execute(
        "select cardinality(make_set_digest(v)) from tb").rows == [(20,)]


def test_grouped_digests(runner):
    rows = dict(runner.execute(
        "select g, cardinality(make_set_digest(v)) from grp group by g"
    ).rows)
    assert rows == {1: 2, 2: 1}


def test_hash_counts_multiplicities(runner):
    """hash_counts keeps per-hash multiplicities: summing the counts
    recovers the row count."""
    res = runner.execute(
        "select map_values(hash_counts(make_set_digest(v))) from grp")
    vals = res.rows[0][0]
    assert sorted(x for x in vals if x is not None) == [1, 2, 3]


def test_merge_set_digest(runner):
    """merge_set_digest unions digests built per group."""
    sql = ("select cardinality(merge_set_digest(d)) from "
           "(select g, make_set_digest(v) as d from grp group by g)")
    assert runner.execute(sql).rows == [(3,)]


def test_jaccard_and_intersection(runner):
    """|ta| = 20 (1..20), |tb| = 20 (11..30), overlap = 10 (11..20):
    jaccard = 10/30, intersection = 10 — exact below K=64."""
    sql = ("select jaccard_index(da, db), intersection_cardinality(da, db) "
           "from (select make_set_digest(v) as da from ta), "
           "(select make_set_digest(v) as db from tb)")
    j, ic = runner.execute(sql).rows[0]
    assert j == pytest.approx(10 / 30, abs=1e-9)
    assert ic == 10


def test_disjoint_and_identical(runner):
    sql = ("select jaccard_index(da, db), intersection_cardinality(da, db) "
           "from (select make_set_digest(v) as da from ta), "
           "(select make_set_digest(v - 1000) as db from ta)")
    j, ic = runner.execute(sql).rows[0]
    assert j == 0.0 and ic == 0
    sql2 = ("select jaccard_index(da, db) "
            "from (select make_set_digest(v) as da from ta), "
            "(select make_set_digest(v) as db from ta)")
    assert runner.execute(sql2).rows[0][0] == pytest.approx(1.0)


def test_cardinality_estimate_beyond_k(runner):
    """Past K=64 slots the KMV estimator takes over: a 1000-distinct
    input must estimate within ~25% (K=64 gives ~12% stderr)."""
    runner.execute("create table big as select x1 * 100 + x2 as v from "
                   "(values 0,1,2,3,4,5,6,7,8,9) a(x1), "
                   "(values 0,1,2,3,4,5,6,7,8,9,10,11,12,13,14,15,16,17,18,"
                   "19,20,21,22,23,24,25,26,27,28,29,30,31,32,33,34,35,36,"
                   "37,38,39,40,41,42,43,44,45,46,47,48,49,50,51,52,53,54,"
                   "55,56,57,58,59,60,61,62,63,64,65,66,67,68,69,70,71,72,"
                   "73,74,75,76,77,78,79,80,81,82,83,84,85,86,87,88,89,90,"
                   "91,92,93,94,95,96,97,98,99) b(x2)")
    est = runner.execute(
        "select cardinality(make_set_digest(v)) from big").rows[0][0]
    assert 750 <= est <= 1250, est


def test_digest_distributed_states(runner):
    """Digest states merge exactly across partial pages (the split
    boundary path): same answer with a 2-row split capacity."""
    from presto_tpu.runner import QueryRunner as QR
    from presto_tpu.session import Session

    s = Session()
    s.set("split_capacity", "4")
    r2 = QR(runner.catalog, session=s)
    rows = dict(r2.execute(
        "select g, cardinality(make_set_digest(v)) from grp group by g"
    ).rows)
    assert rows == {1: 2, 2: 1}
