"""Round-4b scalar breadth: bitwise/numeric device functions and the
datetime LUT/domain-dictionary family.

Reference analogs: operator/scalar/BitwiseFunctions.java,
MathFunctions.java (NAN/INFINITY), DateTimeFunctions.java
(date_format/date_parse/week/year_of_week/last_day_of_month),
VarbinaryFunctions.java (crc32/xxhash64/to_utf8 — here computed over
dictionary values host-side, one device gather).
"""

import datetime

import numpy as np
import pytest

EPOCH = datetime.date(1970, 1, 1)


def _d(days):
    """DATE channels materialize as epoch-day ints (engine convention)."""
    return EPOCH + datetime.timedelta(days=int(days))

from presto_tpu.catalog import Catalog
from presto_tpu.connectors.tpch import Tpch
from presto_tpu.runner import QueryRunner


@pytest.fixture(scope="module")
def runner():
    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001, split_rows=4096))
    return QueryRunner(cat)


def one(runner, sql):
    return runner.execute(sql).rows[0][0]


# ---------------------------------------------------------------------------
# bitwise / numeric
# ---------------------------------------------------------------------------

def test_bitwise_scalars(runner):
    assert one(runner, "select bitwise_and(19, 25)") == 19 & 25
    assert one(runner, "select bitwise_or(19, 25)") == 19 | 25
    assert one(runner, "select bitwise_xor(19, 25)") == 19 ^ 25
    assert one(runner, "select bitwise_not(19)") == ~19
    assert one(runner, "select bitwise_shift_left(1, 5, 64)") == 32
    assert one(runner, "select bitwise_shift_right(-8, 2, 64)") \
        == ((-8) % (1 << 64)) >> 2
    assert one(runner, "select bitwise_shift_left(7, 2, 4)") == (7 << 2) % 16


def test_bit_count(runner):
    assert one(runner, "select bit_count(7, 64)") == 3
    assert one(runner, "select bit_count(-1, 64)") == 64
    assert one(runner, "select bit_count(-1, 8)") == 8
    assert one(runner, "select bit_count(9, 64)") == 2


def test_bitwise_over_column(runner):
    rows = runner.execute(
        "select o_orderkey, bitwise_and(o_orderkey, 255), "
        "bitwise_xor(o_orderkey, 7) from orders limit 100").rows
    for k, a, x in rows:
        assert a == k & 255 and x == k ^ 7


def test_nan_infinity(runner):
    assert one(runner, "select is_nan(nan())") is True
    assert one(runner, "select is_infinite(infinity())") is True
    assert one(runner, "select is_infinite(1.5)") is False
    assert one(runner, "select infinity() > 1e300") is True


def test_from_base(runner):
    assert one(runner, "select from_base('ff', 16)") == 255
    assert one(runner, "select from_base('-101', 2)") == -5
    assert one(runner, "select from_base('z', 36)") == 35


def test_to_base(runner):
    assert one(runner, "select to_base(255, 16)") == "ff"
    assert one(runner, "select to_base(-5, 2)") == "-101"
    assert one(runner, "select to_base(0, 8)") == "0"


def test_crc32_xxhash64(runner):
    import zlib

    assert one(runner, "select crc32(to_utf8('presto'))") \
        == zlib.crc32(b"presto")
    # xxhash64 of empty-seed spec vector (xxHash reference value)
    assert one(runner, "select xxhash64(to_utf8(''))") \
        == 0xEF46DB3751D8E999 - (1 << 64)
    got = runner.execute(
        "select n_name, crc32(to_utf8(n_name)) from nation").rows
    for name, c in got:
        assert c == zlib.crc32(name.encode())


# ---------------------------------------------------------------------------
# datetime
# ---------------------------------------------------------------------------

def test_iso_week_functions(runner):
    rows = runner.execute(
        "select o_orderdate, week(o_orderdate), week_of_year(o_orderdate),"
        " year_of_week(o_orderdate), yow(o_orderdate) "
        "from orders limit 300").rows
    for d, w, w2, yw, yw2 in rows:
        iso = _d(d).isocalendar()
        assert w == w2 == iso[1], d
        assert yw == yw2 == iso[0], d


def test_last_day_of_month(runner):
    assert _d(one(runner, "select last_day_of_month(date '2020-02-10')")) \
        == datetime.date(2020, 2, 29)
    assert _d(one(runner, "select last_day_of_month(date '2021-12-31')")) \
        == datetime.date(2021, 12, 31)
    rows = runner.execute(
        "select o_orderdate, last_day_of_month(o_orderdate) "
        "from orders limit 200").rows
    for di, ld in rows:
        d = _d(di)
        nxt = datetime.date(d.year + (d.month == 12), d.month % 12 + 1, 1)
        assert _d(ld) == nxt - datetime.timedelta(days=1)


def test_date_format(runner):
    assert one(runner,
               "select date_format(date '1995-03-04', '%Y-%m-%d')") \
        == "1995-03-04"
    rows = runner.execute(
        "select o_orderdate, date_format(o_orderdate, '%d/%m/%Y') "
        "from orders limit 200").rows
    for d, fs in rows:
        assert fs == _d(d).strftime("%d/%m/%Y")


def test_date_parse_and_iso8601(runner):
    assert one(runner, "select date_parse('1995-03-04', '%Y-%m-%d')") \
        == datetime.datetime(1995, 3, 4)
    assert one(runner,
               "select date_parse('04/03/1995 13:30:15', "
               "'%d/%m/%Y %H:%i:%s')") \
        == datetime.datetime(1995, 3, 4, 13, 30, 15)
    assert _d(one(runner, "select from_iso8601_date('2001-08-22')")) \
        == datetime.date(2001, 8, 22)
    # over a dictionary varchar column
    rows = runner.execute(
        "select s, date_parse(s, '%Y-%m-%d') from (values ('1999-01-08'),"
        " ('2020-02-29')) t(s)").rows
    for s, ts in rows:
        assert ts == datetime.datetime.strptime(s, "%Y-%m-%d")


def test_day_of_month_aliases(runner):
    rows = runner.execute(
        "select o_orderdate, day_of_month(o_orderdate), doy(o_orderdate),"
        " dow(o_orderdate) from orders limit 100").rows
    for di, dom, doy, dow in rows:
        d = _d(di)
        assert dom == d.day
        assert doy == d.timetuple().tm_yday
        assert dow == d.isoweekday()


def test_null_arguments_null_out(runner):
    """NULL in any argument is NULL out, never a crash (code-review
    regression)."""
    for sql in (
            "select levenshtein_distance('abc', null)",
            "select hamming_distance('abc', null)",
            "select from_base('ff', null)",
            "select from_base(null, 16)",
            "select date_parse('1995-01-01', null)",
            "select to_base(null, 16)",
            "select chr(null)",
            "select replace('abc', null)",
            "select n2 from (select levenshtein_distance(n_name, null) n2 "
            "from nation limit 1) t"):
        assert runner.execute(sql).rows[0][0] is None, sql


def test_shift_wraps_like_java(runner):
    assert one(runner, "select bitwise_shift_left(1, 64, 64)") == 1
    assert one(runner, "select bitwise_shift_left(1, 65, 64)") == 2
    assert one(runner, "select bitwise_shift_right(8, 1, 64)") == 4


def test_hamming_unequal_returns_null(runner):
    assert one(runner, "select hamming_distance('ab', 'abc')") is None


def test_date_parse_exact_micros(runner):
    got = one(runner, "select date_parse('2017-08-01 13:30:15', "
                      "'%Y-%m-%d %H:%i:%s')")
    assert got == datetime.datetime(2017, 8, 1, 13, 30, 15)


def test_levenshtein_over_column(runner):
    rows = runner.execute(
        "select n_name, levenshtein_distance(n_name, 'FRANCE'), "
        "levenshtein_distance('FRANCE', n_name) from nation").rows

    def lev(a, b):
        import numpy as _np

        m = _np.zeros((len(a) + 1, len(b) + 1), dtype=int)
        m[:, 0] = range(len(a) + 1)
        m[0, :] = range(len(b) + 1)
        for i in range(1, len(a) + 1):
            for j in range(1, len(b) + 1):
                m[i, j] = min(m[i - 1, j] + 1, m[i, j - 1] + 1,
                              m[i - 1, j - 1] + (a[i - 1] != b[j - 1]))
        return int(m[-1, -1])

    for name, d1, d2 in rows:
        assert d1 == d2 == lev(name, "FRANCE")


def test_string_transform_breadth(runner):
    assert one(runner, "select translate('abcd', 'abc', 'xy')") == "xyd"
    assert one(runner, "select soundex('Robert')" ) == "R163"
    assert one(runner, "select upper('x')") == "X"  # literal fold fixed
    rows = runner.execute(
        "select n_name, translate(n_name, 'AEIOU', 'aeiou'), "
        "soundex(n_name) from nation").rows
    for name, tr, sx in rows:
        assert tr == name.translate(str.maketrans("AEIOU", "aeiou"))
        assert len(sx) == 4 and sx[0] == name[0].upper()


def test_translate_first_occurrence_wins(runner):
    assert one(runner, "select translate('a', 'aa', 'xy')") == "x"


def test_nonpadded_format_codes(runner):
    assert one(runner,
               "select date_format(date '2020-07-05', '%c/%e')") == "7/5"
    assert one(runner, "select date_parse('7/5/2020', '%c/%e/%Y')") \
        == datetime.datetime(2020, 7, 5)


def test_null_first_argument_distance(runner):
    assert runner.execute(
        "select levenshtein_distance(null, n_name) from nation limit 1"
    ).rows[0][0] is None


def test_chr_out_of_range_is_bind_error(runner):
    with pytest.raises(Exception) as ei:
        runner.execute("select chr(1114112)")
    assert "chr" in str(ei.value)


def test_try_and_string_casts(runner):
    """TRY is the identity: trappable errors already yield NULL
    (DesugarTryExpression role); varchar->number casts parse via the
    dictionary LUT with NULL on failure."""
    assert one(runner, "select try(1/0)") is None
    assert one(runner, "select try(cast('abc' as bigint))") is None
    assert one(runner, "select cast('42' as bigint)") == 42
    assert one(runner, "select cast('2.5' as double)") == 2.5
    assert one(runner, "select cast('abc' as bigint)") is None
    rows = runner.execute(
        "select n_name, cast(n_name as bigint) from nation limit 5").rows
    assert all(v is None for _, v in rows)
    # numeric-looking dictionary values parse
    assert runner.execute(
        "select cast(s as bigint) from (values ('7'), ('x')) t(s)"
    ).rows == [(7,), (None,)]


def test_string_cast_strictness_and_overflow(runner):
    """Review regressions: out-of-int64-range strings are NULL (never
    OverflowError), python-only syntax ('1_0', padding) is rejected."""
    assert one(runner, "select cast('99999999999999999999' as bigint)") \
        is None
    assert one(runner,
               "select try(cast('99999999999999999999' as bigint))") is None
    assert runner.execute(
        "select cast(s as bigint) from (values "
        "('99999999999999999999'), ('7')) t(s)").rows == [(None,), (7,)]
    assert one(runner, "select cast('1_0' as bigint)") is None
    assert one(runner, "select cast(' 7 ' as bigint)") is None
    assert one(runner, "select cast('1.5e3' as double)") == 1500.0
    assert one(runner, "select cast('Infinity' as double)") \
        == float("inf")
    assert one(runner, "select cast('1_0.5' as double)") is None


# ---------------------------------------------------------------------------
# second scalar batch: URL codecs, JSON normalization, Joda-pattern
# datetime formatting, hash hex forms, position/substring forms
# ---------------------------------------------------------------------------

def test_url_codecs(runner):
    import urllib.parse

    # form-urlencoded (URLEncoder): space -> '+', '*' '-' '.' '_' bare
    assert one(runner, "select url_encode('a b&c=d')") == "a+b%26c%3Dd"
    assert one(runner, "select url_encode('x*-._y')") == "x*-._y"
    assert one(runner, "select url_decode('a+b%26c')") == "a b&c"
    assert one(runner, "select url_decode('a%20b')") == "a b"
    rows = runner.execute(
        "select n_name, url_encode(n_name) from nation").rows
    for name, ue in rows:
        assert ue == urllib.parse.quote_plus(name, safe="*-._")


def test_json_normalization_and_size(runner):
    assert one(runner, "select json_parse('[1, 2]')") == "[1,2]"
    assert one(runner, "select json_parse('nope')") is None
    assert one(runner,
               "select json_format(json_extract('{\"a\":[1,2]}', '$.a'))") \
        == "[1,2]"
    assert one(runner, "select json_size('{\"a\":[1,2,3]}', '$.a')") == 3
    assert one(runner, "select json_size('{\"a\":{\"b\":1}}', '$.a')") == 1
    assert one(runner, "select json_size('{\"a\":5}', '$.a')") == 0
    assert one(runner, "select json_size('{\"a\":5}', '$.x')") is None


def test_datetime_name_functions(runner):
    import datetime as _dt

    assert one(runner, "select to_iso8601(date '2020-01-02')") == "2020-01-02"
    assert one(runner, "select day_name(date '2020-01-02')") == "Thursday"
    assert one(runner, "select month_name(date '2020-01-02')") == "January"
    assert one(runner,
               "select format_datetime(date '2020-01-02', 'd MMM yyyy')") \
        == "2 Jan 2020"
    rows = runner.execute(
        "select o_orderdate, day_name(o_orderdate), "
        "format_datetime(o_orderdate, 'yyyy/MM') from orders limit 100").rows
    for di, dn, fm in rows:
        d = _d(di)
        assert dn == d.strftime("%A")
        assert fm == d.strftime("%Y/%m")


def test_hash_hex_forms(runner):
    import hashlib

    for algo in ("md5", "sha1", "sha256"):
        got = one(runner, f"select to_hex({algo}(to_utf8('presto')))")
        assert got == getattr(hashlib, algo)(b"presto").hexdigest().upper()
    rows = runner.execute(
        "select n_name, to_hex(md5(to_utf8(n_name))) from nation").rows
    for name, h in rows:
        assert h == hashlib.md5(name.encode()).hexdigest().upper()


def test_position_and_concat_ws(runner):
    assert one(runner, "select position('b' in 'abc')") == 2
    assert one(runner, "select position('z' in 'abc')") == 0
    assert one(runner, "select concat_ws('-', 'a', 'b', 'c')") == "a-b-c"
    assert one(runner, "select substring('hello', 2, 3)") == "ell"
    rows = runner.execute(
        "select n_name, position('AN' in n_name) from nation").rows
    for name, p in rows:
        assert p == name.find("AN") + 1


def test_hex_uppercase_and_position_concat(runner):
    """Review regressions: to_hex is uppercase (BaseEncoding.base16);
    position operands accept ||; Joda '' quoting."""
    import hashlib

    assert one(runner, "select to_hex(md5(to_utf8('presto')))") \
        == hashlib.md5(b"presto").hexdigest().upper()
    assert one(runner, "select position('b' || 'c' in 'abcd')") == 2
    assert one(runner,
               "select format_datetime(date '2020-01-02', 'yyyy''''MM')") \
        == "2020'01"


# ---------------------------------------------------------------------------
# value-equality over duplicate-valued derived dictionaries
# (pre-existing engine bug surfaced by date_format/day_name: substr,
# date_format etc. map MANY codes to one value, and grouping, DISTINCT,
# joins, window partitions and exchange routing must follow VALUES)
# ---------------------------------------------------------------------------

def test_group_by_derived_dictionary_merges_values(runner):
    import collections

    rows = runner.execute(
        "select substr(c_phone, 1, 2), count(*) from customer "
        "group by 1 order by 1").rows
    per = collections.Counter(
        p[:2] for (p,) in runner.execute(
            "select c_phone from customer").rows)
    assert dict(rows) == dict(per)
    assert runner.execute(
        "select count(distinct substr(c_phone, 1, 2)) from customer"
    ).rows == [(len(per),)]


def test_group_by_day_name_merges_dates(runner):
    import collections

    got = dict(runner.execute(
        "select day_name(o_orderdate), count(*) from orders group by 1"
    ).rows)
    per = collections.Counter(
        _d(d).strftime("%A") for (d,) in runner.execute(
            "select o_orderdate from orders").rows)
    assert got == dict(per)


def test_join_on_derived_dictionary_value_equality(runner):
    rows = runner.execute(
        "select count(*) from (select distinct substr(c_phone, 1, 2) p "
        "from customer) a join (select distinct substr(c_phone, 1, 2) p "
        "from customer) b on a.p = b.p").rows
    want = runner.execute(
        "select count(distinct substr(c_phone, 1, 2)) from customer"
    ).rows
    assert rows == want


def test_window_partition_by_derived_dictionary(runner):
    rows = runner.execute(
        "select substr(c_phone, 1, 2) p, count(*) over "
        "(partition by substr(c_phone, 1, 2)) from customer").rows
    import collections

    per = collections.Counter(p for p, _ in rows)
    for p, c in rows:
        assert c == per[p], p


def test_review_fixes_round2(runner):
    assert one(runner, "select json_size('{\"a\":null}', '$.a')") == 0
    assert one(runner, "select json_size('{\"a\":1}', '$.b')") is None
    with pytest.raises(Exception):
        runner.execute("select format_datetime(date '2020-01-02', 'D')")
    with pytest.raises(Exception):
        runner.execute(
            "select to_iso8601(date_parse('2020-01-02', '%Y-%m-%d'))")


def test_split(runner):
    """split(s, delim) -> ARRAY(varchar) via a derived parts dictionary
    + one (codes, 1+cap) LUT gather (StringFunctions.java#split)."""
    assert one(runner, "select split('a,b,c', ',')") == ["a", "b", "c"]
    assert one(runner, "select split('a,,c', ',')") == ["a", "", "c"]
    assert one(runner, "select split('abc', 'x')") == ["abc"]
    assert one(runner, "select split('a,b,c', ',')[2]") == "b"
    rows = runner.execute(
        "select c_phone, split(c_phone, '-'), split(c_phone, '-')[1] "
        "from customer").rows
    for p, parts, cc in rows:
        assert parts == p.split("-")
        assert cc == p.split("-")[0]
    got = dict(runner.execute(
        "select split(c_phone, '-')[1], count(*) from customer "
        "group by 1").rows)
    import collections

    per = collections.Counter(p.split("-")[0] for (p,) in runner.execute(
        "select c_phone from customer").rows)
    assert got == dict(per)


def test_split_limit_semantics(runner):
    """Limit keeps the remainder in the last element; bad limits and
    empty delimiters are bind errors (review regressions)."""
    assert one(runner, "select split('a.b.c', '.', 2)") == ["a", "b.c"]
    assert one(runner, "select split('a,b,c,d,e,f,g,h,i,j', ',')") \
        == ["a", "b", "c", "d", "e", "f", "g", "h,i,j"]
    for bad in ("select split('a,b', ',', 0)",
                "select split('a,b', ',', -1)",
                "select split('abc', '')"):
        with pytest.raises(Exception):
            runner.execute(bad)
    assert one(runner, "select url_encode('~')") == "%7E"


# ---------------------------------------------------------------------------
# window IGNORE NULLS (WindowOperator null-treatment clause)
# ---------------------------------------------------------------------------

def test_window_ignore_nulls(runner):
    rows = runner.execute(
        "select i, lag(v) ignore nulls over (order by i), "
        "lead(v) ignore nulls over (order by i), "
        "first_value(v) ignore nulls over (order by i), "
        "last_value(v) ignore nulls over (order by i), "
        "lag(v, 2) ignore nulls over (order by i), "
        "nth_value(v, 2) ignore nulls over (order by i) "
        "from (values (1, 10), (2, null), (3, 30), (4, null), (5, 50)) "
        "t(i, v) order by i").rows
    assert rows == [
        (1, None, 30, 10, 10, None, None),
        (2, 10, 30, 10, 10, None, None),
        (3, 10, 50, 10, 30, None, 30),
        (4, 30, 50, 10, 30, 10, 30),
        (5, 30, None, 10, 50, 10, 30),
    ]


def test_window_ignore_nulls_partitioned(runner):
    rows = runner.execute(
        "select g, i, lag(v) ignore nulls over "
        "(partition by g order by i) from (values "
        "(1, 1, null), (1, 2, 12), (1, 3, null), (1, 4, 14), "
        "(2, 1, 21), (2, 2, null), (2, 3, 23)) t(g, i, v) "
        "order by g, i").rows
    assert rows == [
        (1, 1, None), (1, 2, None), (1, 3, 12), (1, 4, 12),
        (2, 1, None), (2, 2, 21), (2, 3, 21),
    ]


def test_respect_nulls_is_default(runner):
    rows = runner.execute(
        "select lag(v) respect nulls over (order by i) from (values "
        "(1, 10), (2, null), (3, 30)) t(i, v) order by i").rows
    assert rows == [(None,), (10,), (None,)]


def test_ignore_nulls_rejected_on_rank(runner):
    with pytest.raises(Exception):
        runner.execute(
            "select rank() ignore nulls over (order by n_name) from nation")


def test_ignore_nulls_review_regressions(runner):
    # offset 0 returns the CURRENT row's value even under IGNORE NULLS
    rows = runner.execute(
        "select lag(v, 0) ignore nulls over (order by i) from (values "
        "(1, 10), (2, null), (3, 30)) t(i, v) order by i").rows
    assert rows == [(10,), (None,), (30,)]
    # IGNORE NULLS without OVER is rejected, not silently dropped
    with pytest.raises(Exception):
        runner.execute("select sum(n_nationkey) ignore nulls from nation")
    # a bare alias named 'ignore' still parses
    assert runner.execute(
        "select count(*) ignore from nation").rows == [(25,)]


# ---------------------------------------------------------------------------
# first-class ROW values (spi/type/RowType.java subset)
# ---------------------------------------------------------------------------

def test_row_type_first_class(runner):
    assert one(runner, "select row(1, 2.5)") == (1, 2.5)
    assert one(runner, "select row(1, 2.5)[2]") == 2.5
    assert one(runner, "select row(1, null)") == (1, None)
    assert one(runner, "select row(1, null)[2]") is None
    rows = runner.execute(
        "select row(o_orderkey, o_custkey), "
        "row(o_orderkey, o_custkey)[1] from orders limit 3").rows
    for tup, k in rows:
        assert tup[0] == k and len(tup) == 2
    # derived expressions inside fields
    assert one(runner,
               "select row(1 + 1, o_orderkey * 2)[2] from orders "
               "where o_orderkey = 3") == 6


def test_row_type_errors(runner):
    for sql in ("select row(n_name, 1) from nation",   # string field
                "select row(1, 2)[3]",                  # out of range
                "select row(1, 2)[0]"):
        with pytest.raises(Exception):
            runner.execute(sql)


def test_row_review_regressions(runner):
    # REAL fields ride a float lane (no int truncation)
    assert one(runner, "select row(cast(1.5 as real))[1]") == 1.5
    # row() comparisons desugar pairwise, both constructor forms
    assert one(runner, "select count(*) from nation where "
               "row(n_regionkey, 1) = row(1, 1)") == 5
    assert one(runner, "select row(1, 2) = row(1, 2)") is True
    assert one(runner, "select row(1, 2) <> (1, 3)") is True


def test_row_in_and_real_decode(runner):
    """Review regressions: row() form in IN lists; REAL tuple decode."""
    assert one(runner, "select row(1, 2) in (row(1, 2), row(3, 4))") is True
    assert one(runner, "select row(1, 5) in (row(1, 2), row(3, 4))") in (
        False, None)
    assert one(runner, "select row(cast(1.5 as real))") == (1.5,)


def test_show_stats_and_explain_validate(runner):
    """SHOW STATS FOR t (ShowStats.java / ShowStatsRewrite shape) and
    EXPLAIN (TYPE VALIDATE)."""
    res = runner.execute("show stats for orders")
    assert res.names[0] == "column_name" and res.names[-1] == "row_count"
    summary = res.rows[-1]
    assert summary[0] is None and summary[-1] == 1500.0
    by_col = {r[0]: r for r in res.rows[:-1]}
    assert by_col["o_orderkey"][1] == 1500.0  # pk: ndv == rows
    res = runner.execute(
        "explain (type validate) select count(*) from orders")
    assert res.rows[0][0] is True
    assert res.rows[0][1].startswith("optimizer:")
    with pytest.raises(Exception):
        runner.execute("explain (type validate) select nope from orders")


def test_show_stats_logical_values(runner):
    """Stats print LOGICAL values, not device representation (review
    regression: epoch days, dictionary codes, scaled decimal ints)."""
    rows = {r[0]: r for r in runner.execute(
        "show stats for lineitem").rows if r[0]}
    lo, hi = rows["l_shipdate"][2], rows["l_shipdate"][3]
    assert lo.startswith("199") and hi.startswith("199")  # ISO dates
    q = rows["l_quantity"]
    assert float(q[2]) >= 1.0 and float(q[3]) <= 51.0  # descaled
    assert q[1] is None or q[1] <= 60  # no 10^scale inflation
    flags = {r[0]: r for r in runner.execute(
        "show stats for orders").rows if r[0]}
    st = flags["o_orderstatus"]
    assert st[2] in (None, "F", "O", "P")  # values, never codes


def test_reset_session_and_show_create(runner):
    defaults = {r[0]: r[2] for r in runner.execute("show session").rows}
    # flip AWAY from the default so a no-op reset cannot pass
    runner.execute("set session jit = false")
    vals = {r[0]: r[1] for r in runner.execute("show session").rows}
    assert vals["jit"] != defaults["jit"]
    runner.execute("reset session jit")
    vals = {r[0]: r[1] for r in runner.execute("show session").rows}
    assert str(vals["jit"]) == str(defaults["jit"])
    assert runner.executor.jit  # the executor rebuilt with the default
    (ddl,) = runner.execute("show create table nation").rows[0]
    assert ddl.startswith("CREATE TABLE nation") and "n_name varchar" in ddl
    with pytest.raises(Exception):
        runner.execute("reset session not_a_property")


def test_try_cast(runner):
    assert runner.execute(
        "select try_cast('abc' as bigint), try_cast('7' as bigint), "
        "try_cast('2.5' as double)").rows == [(None, 7, 2.5)]
    assert runner.execute(
        "select count(*) from nation where try_cast(n_name as bigint) "
        "is null").rows == [(25,)]


def test_describe_input_output_and_current_user(runner):
    runner.execute(
        "prepare qd from select n_name, n_nationkey + ? as k from "
        "nation where n_nationkey = ?")
    assert runner.execute("describe output qd").rows == [
        ("n_name", "varchar"), ("k", "bigint")]
    assert runner.execute("describe input qd").rows == [
        (0, "unknown"), (1, "unknown")]
    with pytest.raises(Exception):
        runner.execute("describe output nope")
    assert runner.execute("select current_user").rows == [("presto",)]
    runner.execute("deallocate prepare qd")


def test_describe_output_respects_access_control(runner):
    """DESCRIBE OUTPUT must not leak schema of denied tables (review
    regression: it binds a plan, so it checks access like EXECUTE)."""
    from presto_tpu.catalog import Catalog
    from presto_tpu.connectors.tpch import Tpch
    from presto_tpu.runner import QueryRunner
    from presto_tpu.security import RuleBasedAccessControl
    from presto_tpu.session import Session

    cat = Catalog()
    cat.register("tpch", Tpch(sf=0.001, split_rows=4096))
    ac = RuleBasedAccessControl(
        [("analyst", "region", True, False),
         ("analyst", "*", False, False)])
    r = QueryRunner(cat, session=Session(user="analyst"),
                    access_control=ac)
    r.execute("prepare qa from select n_name from nation")
    with pytest.raises(Exception) as ei:
        r.execute("describe output qa")
    assert "denied" in str(ei.value).lower() or "access" in str(ei.value).lower()
